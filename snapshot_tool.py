#!/usr/bin/env python
"""Offline snapshot replay — ref ``cmd/snapshot-tool/main.go:30-90``.

Usage:
    python snapshot_tool.py dump OUT.json[.gz]        # synthetic demo dump
    python snapshot_tool.py replay SNAP.json[.gz]     # one cycle, print commits
    python snapshot_tool.py replay STREAM.json[.gz]   # twin stream: oracle replay
    python snapshot_tool.py record OUT --url BASE     # pull /debug/twin stream
    python snapshot_tool.py record OUT --family F [--seed N] [--scale X]

``replay`` on a cluster snapshot loads it, runs exactly one scheduling
cycle with the default config, and prints the commit set (bind requests
+ evictions) as JSON lines — deterministic for a given file.  On a
kai-twin stream file (``format: kai-twin-stream``) it instead replays
the whole stream through the differential oracle and prints the
verdict; exit code 1 on any digest divergence.

``record`` captures a stream: ``--url`` pulls the live recorder's
stream from a running server's ``GET /debug/twin?stream=1``;
``--family`` generates one synthetically from a fuzzer family.
"""
from __future__ import annotations

import json
import sys


def _dump(path: str) -> None:
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.runtime.snapshot import save
    from kai_scheduler_tpu.state import make_cluster

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=8, node_accel=8.0, num_gangs=8, tasks_per_gang=2)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    save(cluster, path)
    print(f"wrote synthetic snapshot to {path}")


def _replay_stream(path: str) -> int:
    from kai_scheduler_tpu.twin import replay as twin_replay
    from kai_scheduler_tpu.twin import stream as twin_stream

    stream = twin_stream.read_stream(path)
    verdict = twin_replay.oracle(stream)
    print(json.dumps({
        "kind": "TwinOracle", "ok": verdict["ok"],
        "checks": verdict["checks"],
        "divergences": len(verdict["divergences"]),
        "events_applied": verdict["replay"]["events_applied"],
        "cycles": verdict["replay"]["cycles"],
    }, sort_keys=True))
    for d in verdict["divergences"]:
        print(json.dumps({"kind": "Divergence", "detail": d},
                         sort_keys=True))
    # throughput goes to stderr so stdout stays byte-identical
    print(json.dumps({"events_per_s": verdict["replay"]["events_per_s"]}),
          file=sys.stderr)
    return 0 if verdict["ok"] else 1


def _replay(path: str) -> int:
    from kai_scheduler_tpu.twin import stream as twin_stream

    # sniff the format field: a twin stream replays through the oracle,
    # anything else stays the classic one-cycle snapshot replay
    doc = twin_stream.read_doc(path)
    if isinstance(doc, dict) and doc.get("format") == twin_stream.FORMAT:
        return _replay_stream(path)

    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.snapshot import load

    cluster = load(path)
    result = Scheduler().run_once(cluster)
    for br in result.bind_requests:
        print(json.dumps({
            "kind": "BindRequest", "pod": br.pod_name,
            "node": br.selected_node,
            "type": br.received_resource_type.value,
            "accel_count": br.received_accel_count,
            "accel_portion": br.received_accel_portion,
        }, sort_keys=True))
    for ev in result.evictions:
        print(json.dumps({
            "kind": "Eviction", "pod": ev.pod_name, "group": ev.group,
            "move_to": ev.move_to,
        }, sort_keys=True))
    # timings go to stderr so stdout stays byte-identical across replays
    print(json.dumps({
        "kind": "Summary",
        "bind_requests": len(result.bind_requests),
        "evictions": len(result.evictions),
    }, sort_keys=True))
    print(json.dumps({k: round(v, 4)
                      for k, v in result.action_seconds.items()}),
          file=sys.stderr)
    return 0


def _record(out: str, opts: dict) -> int:
    from kai_scheduler_tpu.twin import stream as twin_stream

    if opts.get("url"):
        import urllib.request
        with urllib.request.urlopen(
                opts["url"].rstrip("/") + "/debug/twin?stream=1") as r:
            doc = json.loads(r.read())
        stream_doc = doc.get("stream")
        if not stream_doc:
            print("server has no recorded stream "
                  "(twinRecord: false?)", file=sys.stderr)
            return 1
        stream = twin_stream.Stream.from_doc(stream_doc)
    elif opts.get("family"):
        from kai_scheduler_tpu.twin import fuzz
        stream = fuzz.generate(opts["family"],
                               seed=int(opts.get("seed", 0)),
                               scale=float(opts.get("scale", 1.0)))
    else:
        print("record needs --url BASE or --family NAME",
              file=sys.stderr)
        return 2
    twin_stream.write_stream(stream, out)
    print(f"wrote twin stream ({len(stream.events)} events) to {out}")
    return 0


def main(argv: list[str]) -> int:
    args = argv[1:]
    if not args or args[0] not in ("dump", "replay", "record"):
        print(__doc__, file=sys.stderr)
        return 2
    cmd, args = args[0], args[1:]
    if cmd in ("dump", "replay"):
        if len(args) != 1:
            print(__doc__, file=sys.stderr)
            return 2
        if cmd == "dump":
            _dump(args[0])
            return 0
        return _replay(args[0])
    # record OUT [--url BASE | --family NAME [--seed N] [--scale X]]
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    out, opts = args[0], {}
    it = iter(args[1:])
    for flag in it:
        if not flag.startswith("--"):
            print(__doc__, file=sys.stderr)
            return 2
        opts[flag[2:]] = next(it, "")
    return _record(out, opts)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))

#!/usr/bin/env python
"""Offline snapshot replay — ref ``cmd/snapshot-tool/main.go:30-90``.

Usage:
    python snapshot_tool.py dump OUT.json[.gz]        # synthetic demo dump
    python snapshot_tool.py replay SNAP.json[.gz]     # one cycle, print commits

``replay`` loads a cluster snapshot, runs exactly one scheduling cycle
against it with the default config, and prints the commit set (bind
requests + evictions) as JSON lines — deterministic for a given file.
"""
from __future__ import annotations

import json
import sys


def _dump(path: str) -> None:
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.runtime.snapshot import save
    from kai_scheduler_tpu.state import make_cluster

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=8, node_accel=8.0, num_gangs=8, tasks_per_gang=2)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    save(cluster, path)
    print(f"wrote synthetic snapshot to {path}")


def _replay(path: str) -> None:
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.snapshot import load

    cluster = load(path)
    result = Scheduler().run_once(cluster)
    for br in result.bind_requests:
        print(json.dumps({
            "kind": "BindRequest", "pod": br.pod_name,
            "node": br.selected_node,
            "type": br.received_resource_type.value,
            "accel_count": br.received_accel_count,
            "accel_portion": br.received_accel_portion,
        }, sort_keys=True))
    for ev in result.evictions:
        print(json.dumps({
            "kind": "Eviction", "pod": ev.pod_name, "group": ev.group,
            "move_to": ev.move_to,
        }, sort_keys=True))
    # timings go to stderr so stdout stays byte-identical across replays
    print(json.dumps({
        "kind": "Summary",
        "bind_requests": len(result.bind_requests),
        "evictions": len(result.evictions),
    }, sort_keys=True))
    print(json.dumps({k: round(v, 4)
                      for k, v in result.action_seconds.items()}),
          file=sys.stderr)


def main(argv: list[str]) -> int:
    if len(argv) != 3 or argv[1] not in ("dump", "replay"):
        print(__doc__, file=sys.stderr)
        return 2
    (_dump if argv[1] == "dump" else _replay)(argv[2])
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))

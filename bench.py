"""Benchmark harness — prints ONE JSON line for the driver.

Measures the full compiled scheduling step (DRF division + gang-allocate
scan) at BASELINE.json config-3 scale by default (2k nodes, 1k gangs × 8
pods — the gang all-or-nothing benchmark).  Override with env vars
BENCH_NODES / BENCH_GANGS / BENCH_TASKS / BENCH_ITERS.

``vs_baseline``: the reference publishes no absolute numbers
(BASELINE.md); its implied budget is the default 1 s schedule-period a
cycle must fit in (``cmd/scheduler/app/options/options.go:33``).  We
report p99 cycle latency and set ``vs_baseline = 1000 ms / p99 ms`` —
how many reference cycle budgets fit in one of ours (higher is better).
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np


def main() -> None:
    quick = "--quick" in sys.argv
    num_nodes = int(os.environ.get("BENCH_NODES", 200 if quick else 2000))
    num_gangs = int(os.environ.get("BENCH_GANGS", 100 if quick else 1000))
    tasks = int(os.environ.get("BENCH_TASKS", 4 if quick else 8))
    iters = int(os.environ.get("BENCH_ITERS", 3 if quick else 20))

    from kai_scheduler_tpu.ops import drf
    from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
    from kai_scheduler_tpu.state import build_snapshot, make_cluster

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, node_cpu=256.0, node_mem=1024.0,
        num_gangs=num_gangs, tasks_per_gang=tasks,
        num_departments=4, queues_per_department=4)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)

    num_levels = 2
    config = AllocateConfig(dynamic_order=False)

    @jax.jit
    def cycle(state):
        fair_share = drf.set_fair_share(state, num_levels=num_levels)
        st = state.replace(queues=state.queues.replace(fair_share=fair_share))
        res = allocate(st, fair_share, num_levels=num_levels, config=config)
        return res.placements, res.allocated

    # compile (excluded from timing, like the reference's warm informer cache)
    placements, allocated = jax.block_until_ready(cycle(state))
    placed_pods = int((np.asarray(placements) >= 0).sum())

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(cycle(state))
        times.append(time.perf_counter() - t0)
    p99_ms = float(np.percentile(np.asarray(times), 99) * 1e3)

    print(json.dumps({
        "metric": (f"sched-cycle p99 latency ({num_nodes} nodes x "
                   f"{num_gangs} gangs x {tasks} pods, "
                   f"{placed_pods} pods placed)"),
        "value": round(p99_ms, 3),
        "unit": "ms",
        "vs_baseline": round(1000.0 / max(p99_ms, 1e-9), 3),
    }))


if __name__ == "__main__":
    main()

"""Benchmark harness — prints ONE JSON line for the driver.

Headline (default): the BASELINE.json north star — full compiled
scheduling step (DRF division + gang allocate) at **10k nodes × 50k
pending pods**, p99 cycle latency against the driver's 50 ms bar
(``vs_baseline = 50 ms / p99`` — 1.0 means the bar is met).

``BENCH_CONFIG`` selects the other BASELINE configs:

  1 fairshare   100 nodes / 500 pods, 2-level DRF division
  2 scoring     1k nodes × 5k single-accel pods (dense score path)
  3 gang        2k nodes, 1k gangs × 8 pods (all-or-nothing)
  4 topology    5k nodes, 3-level tree, rack-constrained gangs
  5 reclaim     10k nodes × 50k pods, over-quota victim search
  preempt       512 queues × 1 boosted preemptor @ 10k nodes (the
                sparse victim-wavefront hot path; quick alias of
                preempt_many_queues)
  phases        kai-trace per-phase cycle attribution (snapshot/upload/
                solve-dispatch/device-wait/host-decode/commit) @ 10k
                nodes × 50k pods, 1% journaled churn
  frag          kai-pulse fragmentation scenario: 10k nodes, 70k
                running fillers strand 10k single devices across 40
                racks; a rack-required 256-pod gang is unplaceable
                until a rack frees — measures analytics overhead and
                the gauge's predictive drop
  resident      kai-resident device-resident state @ 10k nodes × 50k
                pods, 1% churn: per-cycle p99 with ONE fused dispatch
                + ONE packed-delta upload vs the classic patch-ship
                twin (delta bytes/cycle, dispatches/cycle, phase
                shares)
  storm         kai-intake traffic storm: a 1M-event pod create/delete
                burst (BENCH_STORM_EVENTS overrides) through the async
                multi-lane router while cycles keep running — sustained
                ingest events/s, cycle p99 under storm vs quiescent,
                coalesce p99, and the deliberate-overload shed fraction
  headline      10k nodes × 50k pods allocate
  e2e/e2e_alloc full cycle (snapshot→actions→commit), saturated /
                allocate-heavy shapes
  full          (default) headline to stdout with every other BASELINE
                config and the unpipelined per-cycle p99 folded into the
                same JSON line's "extra" field — the driver artifact
  all           run everything; extra lines to stderr, headline to stdout

``--compare PREV.json`` folds benchstat-style per-config deltas vs a
previous artifact into ``extra.vs_prev`` (and prints them to stderr).

Measured through the *default* semantic path: Session.open's auto-tuned
config (dynamic ordering, prefilter + signature skip on), kernels jitted
once and timed over BENCH_ITERS repetitions.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _p99(times: list[float]) -> float:
    import numpy as np
    return float(np.percentile(np.asarray(times), 99) * 1e3)


#: dispatches per timed batch: the CI TPU is reached through a tunnel
#: whose completion-notification latency (~50 ms) would otherwise
#: dominate a per-call sync measurement; a production scheduler runs
#: cycles back-to-back on a local chip, so per-cycle latency is measured
#: as pipelined batches (dispatch K, sync once, divide) and p99 is taken
#: over batches.
PIPELINE = int(os.environ.get("BENCH_PIPELINE", "5"))

#: The harness link intermittently serves a RESULT CACHE keyed on the
#: (program, input values) pair: re-dispatching a compiled program on
#: byte-identical inputs can return in ~0.1 ms without executing —
#: observed bimodally (the same 50k-pod cycle measured 0.07 ms and
#: ~50 ms minutes apart, across fresh processes, so the key is content-
#: based).  Every timed dispatch therefore consumes a GLOBALLY UNIQUE
#: pre-uploaded epsilon scalar that rides the kernel's OUTPUT (never an
#: input — perturbing solver inputs can shift loop trip counts, see
#: bench_fairshare) so no two dispatches in the whole bench run share a
#: cache key and the device genuinely executes each one.
_eps_buffers: list = []
_eps_next = 0
#: per-PROCESS salt: the cache is content-keyed and persists across
#: processes, so a counter restarting at 0 every run would replay the
#: exact (program, inputs) pairs of the previous run and hit the cache
#: after all.  eps rides outputs only, so magnitude is irrelevant —
#: but the sequence must stay f32-DISTINCT, so the salt is bounded
#: (ulp(1000) ≈ 6e-5 < the 1e-3 step)
_eps_salt = time.time() % 1000.0


def _reserve_eps(n: int) -> None:
    """Pre-upload at least ``n`` unused epsilon scalars so the timing
    loops never pay the H2D mid-measurement."""
    import jax
    import jax.numpy as jnp
    missing = _eps_next + n - len(_eps_buffers)
    if missing > 0:
        base = len(_eps_buffers)
        block = [jnp.float32(_eps_salt + (base + i) * 1e-3)
                 for i in range(max(missing, 512))]
        jax.block_until_ready(block)
        _eps_buffers.extend(block)


def _next_eps():
    """Next never-before-used epsilon device scalar."""
    global _eps_next
    _reserve_eps(1)
    buf = _eps_buffers[_eps_next]
    _eps_next += 1
    return buf


def _time(fn, iters: int, pipeline: int | None = None) -> float:
    """``fn`` must consume ``_next_eps()`` (or otherwise vary its input
    values per call, as the e2e benches do by mutating real state) so
    the link's result cache cannot short-circuit execution."""
    import jax
    pipeline = PIPELINE if pipeline is None else pipeline
    _reserve_eps(iters * pipeline + 1)
    jax.block_until_ready(fn())  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready([fn() for _ in range(pipeline)])
        times.append((time.perf_counter() - t0) / pipeline)
    return _p99(times)


def _time_double_buffered(fn, iters: int) -> float:
    """Per-cycle p99 with ONE cycle in flight: dispatch cycle N+1, then
    gather cycle N — the deployable double-buffered cycle loop (the host
    prepares/commits cycle N while the device already solves N+1), which
    hides the device-link round trip behind the next solve without
    batching more than one cycle ahead."""
    import jax
    _reserve_eps(iters + 2)
    prev = fn()
    jax.block_until_ready(prev)  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        nxt = fn()               # dispatch N+1 (async)
        jax.block_until_ready(prev)   # gather N
        prev = nxt
        times.append(time.perf_counter() - t0)
    jax.block_until_ready(prev)
    return _p99(times)


def _session(**kw):
    from kai_scheduler_tpu.framework.session import Session
    from kai_scheduler_tpu.state import make_cluster
    nodes, queues, groups, pods, topo = make_cluster(**kw)
    return Session.open(nodes, queues, groups, pods, topo)


def bench_fairshare(iters: int) -> dict:
    import functools

    import jax

    from kai_scheduler_tpu.ops import drf
    ses = _session(num_nodes=100, node_accel=8.0, num_gangs=250,
                   tasks_per_gang=2, num_departments=2,
                   queues_per_department=4)

    @jax.jit
    def run(state, e):
        # the eps perturbs the DIVIDEND (cluster totals) — request and
        # limit predicates stay untouched so the water-fill's satisfied
        # sets cannot oscillate (perturbing `request` measured a
        # 19-second loop blowup), while the solve subgraph still sees a
        # distinct input every dispatch (see the cycle benches)
        state = state.replace(nodes=state.nodes.replace(
            allocatable=state.nodes.allocatable + e * 1e-10))
        return drf.set_fair_share(state, num_levels=2) + e

    p99 = _time(lambda: run(ses.state, _next_eps()), iters)
    return {"metric": "DRF fair-share division p99 (100 nodes, 500 pods)",
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def _allocate_bench(name: str, iters: int, pipeline: int | None = None,
                    _reuse=None, double_buffer: bool = False, **kw) -> dict:
    import functools

    import jax
    import numpy as np

    from kai_scheduler_tpu.ops import drf
    from kai_scheduler_tpu.ops.allocate import allocate
    ses = _reuse if _reuse is not None else _session(**kw)
    num_levels = ses.config.num_levels
    config = ses.config.allocate

    @functools.partial(jax.jit, static_argnames=())
    def cycle(state, e):
        # e (≤ ~5e-10 once scaled, far below the 1e-6 fit-test EPS)
        # perturbs a SOLVE input: the link's result cache was observed
        # to serve the solve subgraph separately, so an output-only
        # eps does not force execution of the part being measured
        state = state.replace(nodes=state.nodes.replace(
            free=state.nodes.free + e * 1e-10))
        fair_share = drf.set_fair_share(state, num_levels=num_levels)
        st = state.replace(
            queues=state.queues.replace(fair_share=fair_share))
        res = allocate(st, fair_share, num_levels=num_levels, config=config)
        return res.placements, res.allocated, e + 1.0

    placements, _, _ = jax.block_until_ready(cycle(ses.state, _next_eps()))
    placed = int((np.asarray(placements) >= 0).sum())
    if double_buffer:
        p99 = _time_double_buffered(lambda: cycle(ses.state, _next_eps()),
                                    max(iters * 3, 8))
    else:
        p99 = _time(lambda: cycle(ses.state, _next_eps()), iters,
                    pipeline=pipeline)
    total = int(np.asarray(ses.state.gangs.task_valid).sum())
    return {"metric": f"{name} ({placed}/{total} pods placed)",
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def bench_scoring(iters: int) -> dict:
    return _allocate_bench(
        "sched-cycle p99, scoring: 1k nodes x 5k single-accel pods", iters,
        num_nodes=1000, node_accel=8.0, num_gangs=5000, tasks_per_gang=1)


def bench_gang(iters: int) -> dict:
    return _allocate_bench(
        "sched-cycle p99, gang: 2k nodes x 1k gangs x 8 pods", iters,
        num_nodes=2000, node_accel=8.0, num_gangs=1000, tasks_per_gang=8)


def bench_topology(iters: int) -> dict:
    return _allocate_bench(
        "sched-cycle p99, topology: 5k nodes, 3-level tree, "
        "rack-required gangs", iters,
        num_nodes=5000, node_accel=8.0, num_gangs=2500, tasks_per_gang=8,
        topology_levels=(8, 16), required_level="topo/level1")


def bench_headline(iters: int) -> dict:
    return _allocate_bench(
        "sched-cycle p99 @ 10k nodes x 50k pending pods", iters,
        num_nodes=10_000, node_accel=8.0, num_gangs=6250, tasks_per_gang=8)


def bench_headline_full(iters: int) -> dict:
    """The driver's default: the headline number, with every other
    BASELINE config AND the honest unpipelined per-cycle p99 folded
    into the same JSON line (VERDICT r2 items 3 + 10: all five configs
    in one artifact, tail latency without batch averaging)."""
    ses = _session(num_nodes=10_000, node_accel=8.0, num_gangs=6250,
                   tasks_per_gang=8)
    out = _allocate_bench(
        "sched-cycle p99 @ 10k nodes x 50k pending pods", iters,
        _reuse=ses)
    extra = {}
    for name, fn in (("fairshare", bench_fairshare),
                     ("scoring", bench_scoring),
                     ("gang", bench_gang),
                     ("topology", bench_topology),
                     ("reclaim", bench_reclaim),
                     ("preempt_many_queues", bench_preempt_many_queues),
                     ("churn", bench_churn),
                     ("phases", bench_phases),
                     ("frag", bench_frag),
                     ("resident", bench_resident),
                     # bounded storm in the artifact row; the
                     # standalone BENCH_CONFIG=storm run does the full
                     # 1M-event burst
                     ("storm", lambda it: bench_storm(
                         it, events=250_000))):
        try:
            r = fn(max(3, iters // 2))
            unit = r.get("unit", "ms")
            extra[name] = {"value": r["value"], "unit": unit,
                           "vs_baseline": r["vs_baseline"],
                           "metric": r["metric"]}
            if unit == "ms":
                # legacy column name — cross-artifact p99 comparisons
                # (and --compare) read this; non-latency configs (storm
                # events/s) must NOT masquerade as a latency
                extra[name]["p99_ms"] = r["value"]
            if r.get("extra"):
                extra[name]["extra"] = r["extra"]
        except Exception as exc:  # noqa: BLE001 — one config must not
            extra[name] = {"error": str(exc)[:200]}  # sink the artifact
    # honest tails, same session and compiled cycle as the headline:
    # - sync_p99_ms: dispatch + sync per cycle, nothing in flight
    # - p99_ms: ONE cycle in flight (dispatch N+1, then gather N) — the
    #   deployable double-buffered loop
    # Both pay the harness link's per-sync completion-notification
    # constant: any program past the execute-RPC inline window costs a
    # fixed ~70-80 ms to OBSERVE completion, charged per gather even
    # when the device finished earlier (bulk-dispatching K cycles and
    # gathering one by one shows inter-completion gaps of that size
    # while K distinct-input cycles dispatched together finish in
    # pipelined-rate wall time — measured r4; no server-side result
    # caching, distinct-input and identical-input pipelined rates
    # match).  link_notification_ms derives that constant as
    # sync - pipelined of the SAME compiled cycle;
    # local_chip_estimate_ms is the pipelined (link-amortized) solve —
    # what a per-cycle sync costs on a chip without the CI tunnel.
    try:
        r1 = _allocate_bench("per-cycle", max(3, iters // 2),
                             pipeline=1, _reuse=ses)
        rdb = _allocate_bench("per-cycle-db", max(3, iters // 2),
                              _reuse=ses, double_buffer=True)
        floor = _measure_link_floor(
            max(3, iters // 2),
            shape=tuple(ses.state.gangs.task_valid.shape))
        extra["headline_per_cycle"] = {
            # HEADLINE NUMBERS — raw measured p99 through the harness
            # link, nothing subtracted:
            "p99_ms": rdb["value"],
            "sync_p99_ms": r1["value"],
            **floor,
            # ESTIMATES — floor-subtracted derivations whose null-kernel
            # calibration (tiny fixed-shape outputs, no state-sized
            # args) may not match the real cycle's dispatch/transfer
            # profile; treat as indicative, never as the headline
            "local_chip_estimate_ms": round(
                max(0.0, r1["value"] - floor["measured_link_floor_ms"]),
                1),
            "local_chip_pipelined_estimate_ms": round(
                max(0.0, out["value"] - floor["link_dispatch_ms"]), 1),
            "vs_baseline_local_chip_estimate": round(
                50.0 / max(out["value"] - floor["link_dispatch_ms"],
                           1e-9), 2),
            "note": ("p99_ms: double-buffered (dispatch N+1, gather N); "
                     "sync_p99_ms: nothing in flight.  Both are RAW "
                     "measured p99 and are the headline numbers.  The "
                     "link floor is MEASURED with a null kernel (zero "
                     "device work, commit-sized outputs, distinct "
                     "inputs so the link's result cache cannot serve "
                     "it): measured_link_floor_ms = null sync p99 (the "
                     "full per-sync constant: completion notification "
                     "+ dispatch RPC), link_dispatch_ms = null "
                     "pipelined p99 (the per-dispatch cost even "
                     "pipelined batches pay).  The *_estimate_* values "
                     "subtract that floor (sync - floor, and headline "
                     "pipelined - link_dispatch); the null kernel's "
                     "profile may not match the real cycle, so they "
                     "are ESTIMATES, not measurements")}
    except Exception as exc:  # noqa: BLE001
        extra["headline_per_cycle"] = {"error": str(exc)[:200]}
    out["extra"] = extra
    return out


def _measure_link_floor(iters: int, shape: tuple = (6250, 8)) -> dict:
    """Null-kernel calibration of the harness link's completion-
    notification constant (round-4 VERDICT item 3): a trivial jitted
    kernel producing commit-sized outputs (the cycle's [G, T] i32
    placements + [G] allocated shapes) is timed sync (nothing in
    flight) and pipelined.  The device work is ~zero either way, so
    their difference is the fixed per-sync cost of OBSERVING completion
    through the link — a transport constant a local chip does not pay.
    ``local_chip_estimate_ms`` is then derived as measured sync minus
    this measured floor instead of being asserted."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def null_cycle(x):
        return (jnp.zeros(shape, jnp.float32) + x,
                jnp.zeros(shape[:1], jnp.float32) + x)

    sync = _time(lambda: null_cycle(_next_eps()), max(3, iters),
                 pipeline=1)
    piped = _time(lambda: null_cycle(_next_eps()), max(3, iters))
    # null_sync is the FULL per-sync link constant (completion
    # notification + per-dispatch RPC); null_pipelined isolates the
    # per-dispatch component that even pipelined batches pay
    return {"null_sync_p99_ms": round(sync, 3),
            "null_pipelined_p99_ms": round(piped, 3),
            "measured_link_floor_ms": round(sync, 1),
            "link_dispatch_ms": round(piped, 1)}


def bench_reclaim(iters: int) -> dict:
    import functools

    import jax
    import numpy as np

    from kai_scheduler_tpu.ops.allocate import init_result
    from kai_scheduler_tpu.ops.victims import run_victim_action
    ses = _session(
        num_nodes=10_000, node_accel=8.0, num_gangs=6250, tasks_per_gang=8,
        running_fraction=0.5, queue_accel_quota=1000.0,
        partition_queues_by_running=True)
    num_levels = ses.config.num_levels
    config = ses.config.victims

    @functools.partial(jax.jit)
    def cycle(state, e):
        state = state.replace(nodes=state.nodes.replace(
            free=state.nodes.free + e * 1e-10))
        res = run_victim_action(
            state, state.queues.fair_share, init_result(state),
            num_levels=num_levels, mode="reclaim", config=config)
        return res.victim, res.allocated, e + 1.0

    victims, _, _ = jax.block_until_ready(cycle(ses.state, _next_eps()))
    n_vic = int(np.asarray(victims).sum())
    p99 = _time(lambda: cycle(ses.state, _next_eps()), iters)
    return {"metric": ("reclaim victim-search p99 @ 10k nodes x 50k pods "
                       f"({n_vic} victims)"),
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def bench_preempt_many_queues(iters: int) -> dict:
    """Preempt with ~512 queues each holding ONE boosted preemptor over
    a saturated cluster — the adversarial shape for the wavefront's
    single-queue-per-chunk batching (round-4 VERDICT weak 7): every
    chunk can serve at most one queue's preemptor, so per-chunk
    overheads dominate if the action degrades toward sequential."""
    import functools

    import jax
    import numpy as np

    from kai_scheduler_tpu.ops.allocate import init_result
    from kai_scheduler_tpu.ops.victims import run_victim_action
    ses = _session(
        num_nodes=10_000, node_accel=8.0, num_gangs=10_512,
        tasks_per_gang=8, running_fraction=10_000 / 10_512,
        num_departments=2, queues_per_department=256,
        pending_priority_boost=100)
    num_levels = ses.config.num_levels
    config = ses.config.victims

    @functools.partial(jax.jit)
    def cycle(state, e):
        state = state.replace(nodes=state.nodes.replace(
            free=state.nodes.free + e * 1e-10))
        res = run_victim_action(
            state, state.queues.fair_share, init_result(state),
            num_levels=num_levels, mode="preempt", config=config)
        return res.victim, res.allocated, e + 1.0

    victims, alloc, _ = jax.block_until_ready(
        cycle(ses.state, _next_eps()))
    n_vic = int(np.asarray(victims).sum())
    n_alloc = int(np.asarray(alloc).sum())
    p99 = _time(lambda: cycle(ses.state, _next_eps()), iters)
    return {"metric": ("preempt p99, 512 queues x 1 preemptor each @ "
                       f"10k nodes ({n_alloc} preemptors placed, "
                       f"{n_vic} victims)"),
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def _cost_model_peak_mb(sched) -> float | None:
    """kai-cost's peak-live-bytes model for the fused entry, traced at
    the scheduler's CURRENT snapshot shapes (analysis/costmodel.py) —
    a pure re-trace, no compile/dispatch; None when no snapshot has
    been built yet."""
    from kai_scheduler_tpu.analysis import costmodel
    snap = getattr(sched, "_snapshotter", None)
    state = getattr(snap, "_dev", None) if snap is not None else None
    if state is None:
        return None
    return costmodel.peak_mb_for_state(state).get("fused_pipeline")


def _comm_model_bytes_per_cycle(sched) -> int | None:
    """kai-comms' modeled cross-device collective bytes for the fused
    entry, traced at the scheduler's CURRENT snapshot shapes
    (analysis/comms.py) — a pure re-trace over ShapeDtypeStructs, no
    compile/dispatch; None when no snapshot has been built yet."""
    from kai_scheduler_tpu.analysis import comms
    snap = getattr(sched, "_snapshotter", None)
    state = getattr(snap, "_dev", None) if snap is not None else None
    if state is None:
        return None
    return comms.comm_bytes_for_state(state).get("fused_pipeline")


def _churn_cluster(cluster, rng, frac: float,
                   num_nodes: int = 10_000) -> None:
    """Journaled churn (evict half / rebind half / tick) through the
    mutation paths the cluster hub marks, so the incremental refresh
    can patch — shared by the churn and phases benches."""
    from kai_scheduler_tpu.apis import types as apis
    k = max(1, int(len(cluster.pods) * frac / 2))
    running = [p.name for p in cluster.pods.values()
               if p.status == apis.PodStatus.RUNNING][:k]
    for nm in running:
        cluster.evict_pod(nm)
    pending = [p for p in cluster.pods.values()
               if p.status == apis.PodStatus.PENDING][:k]
    for p in pending:
        try:
            cluster.bind_pod(p.name, f"node-{rng.integers(0, num_nodes)}")
        except RuntimeError:
            pass  # node full — the churn mix, not the refresh, varies
    cluster.tick()


def _wire_totals() -> dict:
    """Cumulative per-reason transfer-ledger aggregates (kai-wire)."""
    from kai_scheduler_tpu.runtime.wire_ledger import LEDGER
    return LEDGER.totals()["by_reason"]


def _wire_delta(before: dict, after: dict, cycles: int) -> dict:
    """Per-cycle (total, patch, redundant) bytes-on-the-wire between
    two ledger totals snapshots — the BENCH_r06+ wire columns."""
    def diff(field, reason=None):
        tot = 0
        for r, t in after.items():
            if reason is not None and r != reason:
                continue
            tot += t[field] - before.get(r, {}).get(field, 0)
        return tot

    n = max(1, cycles)
    return {
        "total": round(diff("bytes") / n),
        "patch": round(diff("bytes", "journal-patch") / n),
        "redundant": round(diff("redundant_bytes") / n),
        "redundant_patch": round(
            diff("redundant_bytes", "journal-patch") / n),
        "dispatches": round(diff("dispatches") / n, 2),
    }


def bench_churn(iters: int) -> dict:
    """Snapshot-refresh latency vs churn — the incremental snapshot
    engine (state/incremental.py) against the full ``build_snapshot``
    host pass at 10k nodes × 50k pods.  Cycle-to-cycle churn at
    production scale is a tiny fraction of the cluster, so the refresh
    should cost O(change): measured at 0.1% / 1% / 10% dirty pods per
    cycle (evictions + new binds + reap ticks) in the post-binder
    steady state (running pods carry concrete devices).  Headline value
    is the 1%-churn p99; ``vs_full`` > 1 means the patch path beats the
    full rebuild (the acceptance bar is ≥ 5x at ≤ 1%)."""
    import numpy as np

    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster
    from kai_scheduler_tpu.state.cluster_state import build_snapshot
    from kai_scheduler_tpu.state.incremental import IncrementalSnapshotter

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10_000, node_accel=8.0, num_gangs=6250,
        tasks_per_gang=8, running_fraction=0.5)
    cursor: dict = {}
    for p in pods:
        if p.status == apis.PodStatus.RUNNING:
            c = cursor.get(p.node, 0)
            p.accel_devices = [c]
            cursor[p.node] = c + 1
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    snap = IncrementalSnapshotter()
    snap.refresh(cluster, now=cluster.now)

    lists = cluster.snapshot_lists()
    full_times = []
    for _ in range(max(3, iters // 2)):
        t0 = time.perf_counter()
        build_snapshot(*lists, now=cluster.now)
        full_times.append(time.perf_counter() - t0)
    full_p99 = _p99(full_times)

    rng = np.random.default_rng(0)
    extra: dict = {"full_rebuild_p99_ms": round(full_p99, 1)}
    p99_1pct = None
    for frac, label in ((0.001, "0.1pct"), (0.01, "1pct"),
                        (0.10, "10pct")):
        times = []
        before = snap.stats.patched
        wire_before = _wire_totals()
        for _ in range(max(5, iters)):
            _churn_cluster(cluster, rng, frac)
            t0 = time.perf_counter()
            snap.refresh(cluster, now=cluster.now)
            times.append(time.perf_counter() - t0)
        p99 = _p99(times)
        extra[f"refresh_p99_ms_{label}"] = round(p99, 1)
        extra[f"speedup_vs_full_{label}"] = round(full_p99 / p99, 1)
        extra[f"patched_cycles_{label}"] = snap.stats.patched - before
        # kai-wire: measured bytes-on-the-wire per refresh (total /
        # patch-path / redundant re-uploaded-identical — the ROADMAP-1
        # invariant, 0 on the patch path), from the transfer-ledger
        # per-reason deltas over this label's cycles
        extra[f"wire_bytes_per_cycle_{label}"] = _wire_delta(
            wire_before, _wire_totals(), len(times))
        if label == "1pct":
            p99_1pct = p99
            extra["wire_bytes_per_cycle"] = \
                extra["wire_bytes_per_cycle_1pct"]
    extra["fallbacks"] = dict(snap.stats.fallbacks)
    return {"metric": ("incremental snapshot refresh p99 @ 1% churn, "
                       "10k nodes x 50k pods (vs "
                       f"{extra['full_rebuild_p99_ms']} ms full rebuild)"),
            "value": round(p99_1pct, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99_1pct, 1e-9), 3),
            "extra": extra}


def bench_phases(iters: int, *, num_nodes: int = 10_000,
                 num_gangs: int = 6250, tasks_per_gang: int = 8) -> dict:
    """Measured per-cycle phase attribution at the headline shape —
    the kai-trace breakdown (snapshot / upload / solve-dispatch /
    device-wait / host-decode / commit) of a full production cycle at
    10k nodes × 50k pods with 1% journaled churn per cycle, so the
    incremental snapshotter stays on the patch path and "upload" is the
    real changed-leaves transfer.  Phases are contiguous checkpoints on
    one clock (framework/scheduler.py), so they sum to the cycle wall
    time by construction; ``coverage`` reports that sum / measured wall
    (the acceptance bar is within 10%).  BENCH_r06+ records THIS
    measured attribution where earlier rounds could only subtract an
    estimated link-floor constant."""
    import numpy as np

    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
        tasks_per_gang=tasks_per_gang, running_fraction=0.5)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    sched = Scheduler()
    sched.run_once(cluster)  # compile + warm the incremental cache
    rng = np.random.default_rng(0)

    walls: list[float] = []
    acc: dict[str, list[float]] = {}
    wires: list[tuple[int, int, int, int]] = []
    an_dispatch: list[float] = []
    for _ in range(max(5, iters)):
        _churn_cluster(cluster, rng, 0.01, num_nodes)
        t0 = time.perf_counter()
        res = sched.run_once(cluster)
        walls.append(time.perf_counter() - t0)
        an_dispatch.append(res.analytics_seconds)
        for k, v in res.phase_seconds.items():
            acc.setdefault(k, []).append(v)
        # kai-wire per-cycle summary rides CycleResult.wire
        patch = res.wire["by_reason"].get("journal-patch", {})
        wires.append((res.wire["bytes"], patch.get("bytes", 0),
                      res.wire["redundant_bytes"],
                      patch.get("redundant_bytes", 0)))
    wall_mean = float(np.mean(walls))
    phases_ms = {k: round(float(np.mean(v)) * 1e3, 2)
                 for k, v in acc.items()}
    phase_sum = sum(float(np.mean(v)) for v in acc.values())
    wall_p99 = _p99(walls)
    snap = sched._snapshotter
    extra = {
        "phases_ms": phases_ms,
        "wall_mean_ms": round(wall_mean * 1e3, 2),
        "phase_sum_ms": round(phase_sum * 1e3, 2),
        # phases are contiguous checkpoints, so this is ~1.0 by
        # construction — reported so the artifact PROVES the 10% bar
        "coverage": round(phase_sum / max(wall_mean, 1e-12), 4),
        "snapshot_mode": (dict(snap.stats.last)
                          if snap is not None else {}),
        "patched_cycles": (snap.stats.patched
                           if snap is not None else 0),
        "fallbacks": (dict(snap.stats.fallbacks)
                      if snap is not None else {}),
        # measured bytes-on-the-wire per cycle next to the phase
        # attribution (total / patch-path / redundant) — redundant must
        # read 0 while cycles stay on the patch path (ROADMAP-1's soak
        # invariant, now measured in every BENCH_r06+ artifact)
        "wire_bytes_per_cycle": {
            "total": round(float(np.mean([w[0] for w in wires]))),
            "patch": round(float(np.mean([w[1] for w in wires]))),
            "redundant": round(float(np.mean([w[2] for w in wires]))),
            "redundant_patch": round(
                float(np.mean([w[3] for w in wires]))),
        },
        # kai-cost (analysis/costmodel.py): the fused entry's
        # liveness-model peak-live-bytes traced AT this bench shape —
        # the model-side HBM watermark printed beside the measured
        # wire/phase columns (BENCH_r08+; the tier-1 cross-validation
        # test pins the model's traffic ranking against measured
        # dispatch ordering at canonical shapes)
        "cost_model_peak_mb": _cost_model_peak_mb(sched),
        # kai-comms (analysis/comms.py): the fused entry's modeled
        # collective bytes per cycle at this bench shape, priced for
        # the 8-way virtual mesh — the next MULTICHIP artifact records
        # this column beside the measured per-device wall time so the
        # model's scaling fit can be checked against hardware
        "comm_model_bytes_per_cycle": _comm_model_bytes_per_cycle(
            sched),
        # kai-pulse rides every cycle here (analytics_every=1 default):
        # host dispatch cost of the analytics pass + the BENCH_r06+
        # cluster-health tracking columns from the last cycle
        "analytics_dispatch_ms": round(
            float(np.mean(an_dispatch)) * 1e3, 2),
        "analytics_pct_of_wall": round(
            float(np.mean(an_dispatch)) / max(wall_mean, 1e-12) * 100,
            2),
        "fragmentation": res.analytics.get(
            "fragmentation", {}).get("score"),
        "goodput": res.analytics.get("goodput"),
        "fairness_drift": res.analytics.get(
            "fairness", {}).get("drift_max"),
    }
    return {"metric": (f"cycle phase attribution p99 @ {num_nodes} "
                       f"nodes x {num_gangs * tasks_per_gang} pods, "
                       "1% churn (snapshot/upload/solve-dispatch/"
                       "device-wait/host-decode/commit)"),
            "value": round(wall_p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(wall_p99, 1e-9), 3),
            "extra": extra}


def bench_resident(iters: int, *, num_nodes: int = 10_000,
                   num_gangs: int = 6250, tasks_per_gang: int = 8) -> dict:
    """kai-resident (ops/resident.py) @ the headline shape with 1%
    journaled churn: the snapshot stays device-resident across cycles,
    patched cycles upload ONE packed journal delta and run the whole
    dispatch chain (delta apply → fair share → pipeline → analytics →
    packed commit) as ONE fused donated-state dispatch.  Measured
    against a classic patch-ship twin (same churn stream) so the
    artifact records the upload + device_wait share collapse ROADMAP
    item 1 calls for, plus delta bytes/cycle, dispatches/cycle, and
    the resident reused-vs-uploaded gauge pair."""
    import numpy as np

    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster

    def build():
        nodes, queues, groups, pods, topo = make_cluster(
            num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
            tasks_per_gang=tasks_per_gang, running_fraction=0.5)
        cursor: dict = {}
        for p in pods:
            if p.status == apis.PodStatus.RUNNING:
                c = cursor.get(p.node, 0)
                p.accel_devices = [c]
                cursor[p.node] = c + 1
        return Cluster.from_objects(nodes, queues, groups, pods, topo)

    def run(resident: bool):
        cluster = build()
        sched = Scheduler(SchedulerConfig(resident=resident))
        rng = np.random.default_rng(0)
        sched.run_once(cluster)  # cold full build + classic compiles
        # warm until the steady-state mode engages (the first resident
        # cycle compiles the fused entry — that must not be timed)
        want = "resident" if resident else "patched"
        for _ in range(4):
            _churn_cluster(cluster, rng, 0.01, num_nodes)
            sched.run_once(cluster)
            if sched._snapshotter.stats.last.get("mode") == want:
                break
        walls: list[float] = []
        acc: dict[str, list[float]] = {}
        deltas: list[int] = []
        dispatches = 0
        modes: dict[str, int] = {}
        reused = uploaded = 0
        cycles = max(5, iters)
        for _ in range(cycles):
            _churn_cluster(cluster, rng, 0.01, num_nodes)
            t0 = time.perf_counter()
            res = sched.run_once(cluster)
            walls.append(time.perf_counter() - t0)
            for k, v in res.phase_seconds.items():
                acc.setdefault(k, []).append(v)
            last = sched._snapshotter.stats.last
            modes[last["mode"]] = modes.get(last["mode"], 0) + 1
            deltas.append(int(last.get("bytes_shipped", 0)))
            dispatches += res.wire["dispatches"]
            reused = res.wire["resident_reused_bytes"]
            uploaded = res.wire["resident_uploaded_bytes"]
        phases = {k: round(float(np.mean(v)) * 1e3, 2)
                  for k, v in acc.items()}
        return {"p99_ms": _p99(walls), "phases_ms": phases,
                "modes": modes,
                "delta_bytes_per_cycle": round(float(np.mean(deltas))),
                "dispatches_per_cycle": round(dispatches / cycles, 2),
                "resident_reused_bytes": reused,
                "resident_uploaded_bytes": uploaded,
                "fallbacks": dict(sched._snapshotter.stats.fallbacks)}

    res_on = run(True)
    res_off = run(False)
    link_share = {
        "resident_upload_plus_wait_ms": round(
            res_on["phases_ms"].get("upload", 0.0)
            + res_on["phases_ms"].get("device_wait", 0.0), 2),
        "classic_upload_plus_wait_ms": round(
            res_off["phases_ms"].get("upload", 0.0)
            + res_off["phases_ms"].get("device_wait", 0.0), 2),
    }
    extra = {
        "resident": res_on,
        "classic_patch_twin": res_off,
        "speedup_vs_classic": round(
            res_off["p99_ms"] / max(res_on["p99_ms"], 1e-9), 2),
        **link_share,
    }
    return {"metric": (f"kai-resident cycle p99 @ {num_nodes} nodes x "
                       f"{num_gangs * tasks_per_gang} pods, 1% churn "
                       "(one fused dispatch + one packed-delta upload "
                       "per cycle; vs classic patch-ship twin "
                       f"{round(res_off['p99_ms'], 1)} ms)"),
            "value": round(res_on["p99_ms"], 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(res_on["p99_ms"], 1e-9), 3),
            "extra": extra}


def bench_storm(iters: int, *, num_nodes: int = 2000,
                num_gangs: int = 500, tasks_per_gang: int = 4,
                events: int | None = None) -> dict:
    """kai-intake traffic storm (ROADMAP item 3): a burst of pod
    create/delete mutations (default 1M events, ``BENCH_STORM_EVENTS``
    overrides) rides the async multi-lane router — hash-sharded
    bounded lanes, per-lane drain workers running the vectorized
    admission sweep, cycle-boundary coalesce into the hub journal —
    while scheduling cycles keep running against the same cluster.

    Columns: sustained ingest events/s (submit → drain → coalesce, the
    honest end-to-end clock including the final coalesce), cycle p99
    under storm vs quiescent, coalesce p99, and a deliberate-overload
    phase (tiny lanes, no drain headroom) proving the shed valve is
    nonzero and metered while memory stays bounded by the lane caps.

    Environment note: CPU container, GIL-shared producers/workers/cycle
    thread — the ingest figure is a floor, not a ceiling; the
    differential (storm == sequential classic path, bit-identical) is
    pinned by tests/test_intake_router.py, not re-proven here."""
    import threading

    from kai_scheduler_tpu.framework import metrics as _metrics
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.intake.router import IntakeConfig, IntakeRouter
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster

    events = int(events if events is not None
                 else os.environ.get("BENCH_STORM_EVENTS", 1_000_000))
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
        tasks_per_gang=tasks_per_gang, running_fraction=0.5)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    sched = Scheduler()
    for _ in range(3):  # compile every late-arriving entry (victim
        sched.run_once(cluster)  # paths, analytics, repack probes)
    # -- quiescent cycle p99 (no storm, same cluster/scheduler) ------
    quiescent = []
    for _ in range(max(5, iters)):
        t0 = time.perf_counter()
        sched.run_once(cluster)
        quiescent.append(time.perf_counter() - t0)
    q_p99 = _p99(quiescent)

    # -- the storm ---------------------------------------------------
    router = IntakeRouter(IntakeConfig(
        lanes=4, lane_capacity=1 << 17, batch=1024)).start()
    chunk = 500
    n_chunks = max(1, events // (2 * chunk))  # create + delete pairs
    producers = 2
    accepted = [0] * producers

    def produce(tid: int) -> None:
        for c in range(tid, n_chunks, producers):
            names = [f"storm-{c}-{i}" for i in range(chunk)]
            creates = [("upsert", "pods",
                        nm, {"name": nm, "group": f"storm-g{c % 64}",
                             "resources": {"accel": 1.0, "cpu": 1.0,
                                           "memory": 1.0}})
                       for nm in names]
            deletes = [("delete", "pods", nm, nm) for nm in names]
            for ops in (creates, deletes):
                out = router.submit_ops(ops)
                accepted[tid] += out["accepted"]
                while out["shed"]:  # bounded lanes: wait, don't drop
                    time.sleep(0.002)
                    out = router.submit_ops(out["shed_ops"])
                    accepted[tid] += out["accepted"]

    storm_cycles: list[float] = []
    coalesce_s: list[float] = []
    cycle_period = 0.25  # pace cycles like a schedule period — the
    t_start = time.perf_counter()  # storm streams between boundaries
    threads = [threading.Thread(target=produce, args=(t,), daemon=True)
               for t in range(producers)]
    for t in threads:
        t.start()
    next_cycle = t_start
    while any(t.is_alive() for t in threads):
        now = time.perf_counter()
        if now < next_cycle:
            time.sleep(min(0.01, next_cycle - now))
            continue
        next_cycle = now + cycle_period
        t0 = time.perf_counter()
        summary = router.coalesce(cluster)
        sched.run_once(cluster)
        storm_cycles.append(time.perf_counter() - t0)
        coalesce_s.append(summary["seconds"])
    for t in threads:
        t.join()
    router.drain_inline(timeout=120)
    final = router.coalesce(cluster)
    coalesce_s.append(final["seconds"])
    wall = time.perf_counter() - t_start
    router.stop()
    total_accepted = sum(accepted)
    health = router.health()
    ingest_eps = health["coalesced_events"] / max(wall, 1e-9)

    # -- deliberate overload: tiny lanes, no drain headroom ----------
    # metric check is a DELTA over this phase: the main storm already
    # incremented the process-global shed counter (producers overflow
    # + retry), so an absolute read could mask a metering regression
    shed_metric_before = (_metrics.intake_shed.value("0")
                          + _metrics.intake_shed.value("1"))
    shed_router = IntakeRouter(IntakeConfig(lanes=2, lane_capacity=2048))
    shed_submitted = 0
    for c in range(64):
        ops = [("upsert", "pods", f"over-{c}-{i}",
                {"name": f"over-{c}-{i}", "group": "over-g"})
               for i in range(500)]
        shed_submitted += len(ops)
        shed_router.submit_ops(ops)
    shed_health = shed_router.health()
    shed_frac = shed_health["shed"] / max(shed_submitted, 1)

    # quiescent boundary overhead: a coalesce with nothing staged is
    # what every cycle pays once the storm is over — it must be noise
    # (microseconds) next to the cycle itself, or intake would tax the
    # PR-11 resident steady state
    empty = []
    idle_router = IntakeRouter(IntakeConfig(lanes=4))
    for _ in range(50):
        t0 = time.perf_counter()
        idle_router.coalesce(cluster)
        empty.append(time.perf_counter() - t0)
    empty_us = round(_p99(empty) * 1000.0, 1)

    storm_p99 = _p99(storm_cycles) if storm_cycles else 0.0
    extra = {
        "events_requested": events,
        "events_accepted": total_accepted,
        "events_coalesced": health["coalesced_events"],
        "storm_wall_s": round(wall, 2),
        "ingest_events_per_s": round(ingest_eps),
        "quiescent_cycle_p99_ms": round(q_p99, 1),
        "storm_cycle_p99_ms": round(storm_p99, 1),
        "storm_cycles": len(storm_cycles),
        "coalesce_p99_ms": round(_p99(coalesce_s), 1),
        "empty_coalesce_p99_us": empty_us,
        "lane_rejected": health["rejected"],
        "overload_shed_fraction": round(shed_frac, 3),
        "overload_shed_events": shed_health["shed"],
        "overload_metered": (_metrics.intake_shed.value("0")
                             + _metrics.intake_shed.value("1")
                             - shed_metric_before) > 0,
        "environment_note": (
            "CPU-only container, GIL-shared producer/worker/cycle "
            "threads; ingest includes drain + admission + final "
            "coalesce.  Cycle p99 under storm includes the coalesce."),
    }
    return {"metric": (f"kai-intake sustained ingest @ {events} "
                       f"create/delete storm vs {num_nodes} nodes x "
                       f"{num_gangs * tasks_per_gang} pods cycling "
                       f"(quiescent cycle p99 {round(q_p99, 1)} ms, "
                       f"storm {round(storm_p99, 1)} ms)"),
            "value": round(ingest_eps),
            "unit": "events/s",
            # the ROADMAP-3 bar: >= 100k events/s sustained → >= 1.0
            "vs_baseline": round(ingest_eps / 100_000.0, 3),
            "extra": extra}


def _frag_cluster_10k(num_racks: int = 40, nodes_per_rack: int = 250,
                      node_accel: int = 8, fill: int = 7,
                      gang_pods: int = 256, preemptible: bool = False):
    """A fragmented 10k-node cluster (ROADMAP item 5's scenario,
    pre-staged): every node holds ``fill``/``node_accel`` devices of
    NON-preemptible fillers, so each rack strands ``nodes_per_rack``
    single free devices — a rack-required ``gang_pods``-pod gang is
    cluster-feasible (10k free devices) but unplaceable in any single
    rack until capacity consolidates."""
    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.runtime.cluster import Cluster
    level = "topo/rack"
    topo = apis.Topology(name="default",
                         levels=[level, "kubernetes.io/hostname"])
    nodes, pods, groups = [], [], []
    queues = [
        apis.Queue("fill", accel=apis.QueueResource(
            quota=float(num_racks * nodes_per_rack * fill))),
        apis.Queue("big", accel=apis.QueueResource(
            quota=float(gang_pods)))]
    for rack in range(num_racks):
        g = apis.PodGroup(
            f"fill-{rack}", queue="fill",
            min_member=nodes_per_rack * fill,
            preemptibility=(apis.Preemptibility.PREEMPTIBLE
                            if preemptible
                            else apis.Preemptibility.NON_PREEMPTIBLE),
            last_start_timestamp=0.0)
        groups.append(g)
        for j in range(nodes_per_rack):
            i = rack * nodes_per_rack + j
            name = f"node-{i}"
            nodes.append(apis.Node(
                name, apis.ResourceVec(node_accel, 64, 256),
                labels={level: f"rack-{rack}",
                        "kubernetes.io/hostname": name}))
            for t in range(fill):
                pods.append(apis.Pod(
                    f"fill-{i}-{t}", g.name, apis.ResourceVec(1, 1, 4),
                    status=apis.PodStatus.RUNNING, node=name))
    gang = apis.PodGroup(
        "big-gang", queue="big", min_member=gang_pods,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level=level))
    groups.append(gang)
    for t in range(gang_pods):
        pods.append(apis.Pod(f"big-{t}", "big-gang",
                             apis.ResourceVec(1, 1, 4)))
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def bench_frag(iters: int, **scale) -> dict:
    """kai-pulse fragmentation scenario @ 10k nodes / 70k running pods:
    a rack-required 256-pod gang is unplaceable while ~10k free devices
    sit stranded one-per-node across 40 racks.  Measures the full cycle
    p99 WITH the analytics pass against an analytics-off twin (the
    <10%-overhead acceptance bar), proves the fragmentation gauge is
    predictive (high while stranded, dropping once a rack frees), and —
    BENCH_r06+ — runs the kai-repack solver on a movable-filler twin
    (repack_solve_ms / migrations_per_unblocked_gang /
    cycles_to_unblock) plus a repack-off twin proving zero overhead and
    identical wire bytes while the trigger sits below threshold."""
    import numpy as np

    from kai_scheduler_tpu.binder import Binder
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    gang_pods = scale.get("gang_pods", 256)

    def timed_cycles(every: int, repack_enable: bool = True,
                     repack_threshold: float = 1.1):
        # repack idles through the timed loop: the threshold sits above
        # any possible score, so enabled-vs-disabled twins measure the
        # trigger's pure host overhead (the zero-overhead bar)
        cluster = _frag_cluster_10k(**scale)
        sched = Scheduler(SchedulerConfig(
            analytics_every=every, repack_enable=repack_enable,
            repack_frag_threshold=repack_threshold))
        res = sched.run_once(cluster)  # compile
        times, an_s, wire = [], [], []
        for _ in range(max(3, iters)):
            t0 = time.perf_counter()
            res = sched.run_once(cluster)
            times.append(time.perf_counter() - t0)
            an_s.append(res.analytics_seconds)
            wire.append(res.wire["bytes"])
        return _p99(times), float(np.mean(an_s)), res, sched, cluster, \
            wire

    p99_on, analytics_ms, res, sched, cluster, wire_on = \
        timed_cycles(every=1)
    analytics_ms *= 1e3
    p99_off, _, _, _, _, _ = timed_cycles(every=0)
    frag = res.analytics["fragmentation"]
    stranded = {
        "score": frag["score"],
        "largest_rack_unit_pods": frag["largest_rack_unit_pods"],
        "total_unit_pods": frag["total_unit_pods"],
        "rung256_cluster_feasible": [
            r["cluster_feasible"] for r in frag["gang_ladder"]
            if r["pods"] == 256][0],
        "rung256_rack_placeable": [
            r["rack_placeable"] for r in frag["gang_ladder"]
            if r["pods"] == 256][0],
    }
    # free one rack: evict 6 fillers on distinct rack-0 nodes so the
    # rack holds 256 whole devices, reap, rerun — the gang must place
    # and the gauge must drop
    for i in range(6):
        cluster.evict_pod(f"fill-{i}-0")
    cluster.tick()
    cluster.tick()
    res2 = sched.run_once(cluster)
    frag2 = res2.analytics["fragmentation"]

    # --- kai-repack columns (BENCH_r06+) ------------------------------
    # (a) zero-overhead twin: the headline run above is repack-ENABLED
    # with the gauge pinned below its threshold (repack_threshold=1.1),
    # so comparing it to a repack-DISABLED twin measures the trigger's
    # whole untriggered cost — wall time and wire bytes must match
    p99_rp_off, _, _, _, _, wire_off = timed_cycles(
        every=1, repack_enable=False)
    repack_off_twin = {
        "p99_ms_repack_idle": round(p99_on, 1),
        "p99_ms_repack_off": round(p99_rp_off, 1),
        "wire_bytes_identical": wire_off == wire_on,
    }
    # (b) proactive unblock: the SAME scenario with movable fillers and
    # consolidation excluded (isolating the proactive path) — cycles
    # from trigger firing to the 256-pod gang's placement
    rp_cluster = _frag_cluster_10k(preemptible=True, **scale)
    rp_sched = Scheduler(SchedulerConfig(
        actions=("allocate", "reclaim", "preempt", "stalegangeviction"),
        repack_frag_threshold=0.2, repack_trigger_cycles=2,
        repack_cooldown=4))
    binder = Binder()
    # warm the solver's compile cache at the production shapes (a
    # throwaway scheduler on a cluster copy, trigger tuned to fire on
    # its 2nd cycle) so the recorded repack_solve_ms is the
    # steady-state dispatch cost, not trace+XLA-compile of the
    # first-ever firing
    import copy
    warm_cluster = copy.deepcopy(rp_cluster)
    warm_sched = Scheduler(SchedulerConfig(
        actions=("allocate", "reclaim", "preempt", "stalegangeviction"),
        repack_frag_threshold=0.2, repack_trigger_cycles=1,
        repack_cooldown=0))
    warm_sched.run_once(warm_cluster)
    warm_sched.run_once(warm_cluster)
    fired = placed = None
    solve_ms = migrations = 0.0
    for cyc in range(1, 12):
        r = rp_sched.run_once(rp_cluster)
        if r.repack and fired is None:
            fired = cyc
            solve_ms = r.repack_seconds * 1e3
            migrations = r.repack["migrations_executed"]
        if sum(b.pod_name.startswith("big-")
               for b in r.bind_requests) >= gang_pods:
            placed = cyc
            break
        binder.reconcile(rp_cluster)
        rp_cluster.tick()
    repack_cols = {
        "repack_solve_ms": round(solve_ms, 2),
        "migrations_per_unblocked_gang": migrations,
        "cycles_to_unblock": (placed - fired
                              if placed and fired else None),
        "unblocked": bool(placed),
    }
    extra = {
        "p99_ms_analytics_off": round(p99_off, 1),
        "analytics_dispatch_ms": round(analytics_ms, 2),
        "analytics_overhead_pct": round(
            (p99_on - p99_off) / max(p99_off, 1e-9) * 100.0, 1),
        "stranded": stranded,
        "freed": {"score": frag2["score"],
                  "largest_rack_unit_pods":
                      frag2["largest_rack_unit_pods"],
                  "binds": len(res2.bind_requests)},
        # the BENCH_r06+ tracking columns
        "fragmentation": stranded["score"],
        "goodput": res.analytics["goodput"],
        "fairness_drift": res.analytics["fairness"]["drift_max"],
        "predictive": bool(
            stranded["score"] > frag2["score"]
            and len(res2.bind_requests) >= gang_pods),
        "repack": repack_cols,
        "repack_off_twin": repack_off_twin,
    }
    return {"metric": ("frag cycle p99 @ 10k nodes / 70k running pods, "
                       "256-pod rack-required gang stranded "
                       "(analytics ON; gauge "
                       f"{stranded['score']}→{frag2['score']} after "
                       "rack freed)"),
            "value": round(p99_on, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99_on, 1e-9), 3),
            "extra": extra}


def bench_e2e(iters: int) -> dict:
    """Full production cycle — snapshot → default action pipeline →
    commit, measured as ONE wall-clock number per cycle (the VERDICT r2
    gap: the kernel met the bar while the host path cost seconds).

    Runs on a SATURATED shape — running pods fill the cluster exactly
    (40k running pods x 1 accel = 10k nodes x 4), the 10k pending pods sit
    in under-served queues — so allocate fails capacity, reclaim finds
    real victims, and preempt/consolidation/stale all execute: the
    worst-case production cycle.  Cluster state is restored between
    cycles outside the timed region.  Reports the host/device split
    alongside p99.
    """
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10_000, node_accel=4.0, num_gangs=6250, tasks_per_gang=8,
        running_fraction=0.8, queue_accel_quota=1000.0,
        partition_queues_by_running=True)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    # restorable bits mutated by a cycle: pod status/devices, group flags
    pod_state = {p.name: (p.status, p.node, tuple(p.accel_devices))
                 for p in pods}
    grp_state = {g.name: (g.fit_failures, g.unschedulable, g.phase,
                          g.last_start_timestamp) for g in groups}

    def restore():
        cluster.bind_requests.clear()
        cluster.restarting.clear()
        for p in pods:
            st, nd, devs = pod_state[p.name]
            p.status, p.node, p.accel_devices = st, nd, list(devs)
        for g in groups:
            (g.fit_failures, g.unschedulable, g.phase,
             g.last_start_timestamp) = grp_state[g.name]

    import numpy as np
    sched = Scheduler()
    res = sched.run_once(cluster)  # compile
    times, opens, commits = [], [], []
    for _ in range(iters):
        restore()
        t0 = time.perf_counter()
        res = sched.run_once(cluster)
        times.append(time.perf_counter() - t0)
        opens.append(res.open_seconds)
        commits.append(res.commit_seconds)
    p99 = _p99(times)
    pipelined = int(np.asarray(res.tensors.pipelined).sum())
    return {"metric": ("END-TO-END cycle p99 @ 10k nodes x 50k pods, "
                       "saturated worst case (snapshot+actions+commit; "
                       f"{len(res.bind_requests)} binds, "
                       f"{pipelined} pipelined onto victim capacity, "
                       f"{len(res.evictions)} evictions; "
                       f"open {_p99(opens):.0f} ms, "
                       f"commit+sync {_p99(commits):.0f} ms)"),
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def bench_e2e_alloc(iters: int) -> dict:
    """Full cycle on the HEADLINE allocate shape (empty cluster, 50k
    pending) — isolates the host path (snapshot build + commit
    translation) around the allocate kernel; victim actions run but find
    nothing.  This is the shape VERDICT r2 measured at ~9 s host cost."""
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state import make_cluster
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10_000, node_accel=8.0, num_gangs=6250, tasks_per_gang=8)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    grp_state = {g.name: (g.fit_failures, g.unschedulable, g.phase,
                          g.last_start_timestamp) for g in groups}
    sched = Scheduler()
    res = sched.run_once(cluster)  # compile
    times, opens, commits = [], [], []
    for _ in range(iters):
        cluster.bind_requests.clear()
        for g in groups:
            (g.fit_failures, g.unschedulable, g.phase,
             g.last_start_timestamp) = grp_state[g.name]
        t0 = time.perf_counter()
        res = sched.run_once(cluster)
        times.append(time.perf_counter() - t0)
        opens.append(res.open_seconds)
        commits.append(res.commit_seconds)
    p99 = _p99(times)
    return {"metric": ("END-TO-END cycle p99 @ 10k nodes x 50k pending "
                       "pods, allocate-heavy (snapshot+actions+commit; "
                       f"{len(res.bind_requests)} binds; "
                       f"open {_p99(opens):.0f} ms, "
                       f"commit+sync {_p99(commits):.0f} ms)"),
            "value": round(p99, 3), "unit": "ms",
            "vs_baseline": round(50.0 / max(p99, 1e-9), 3)}


def bench_twin(iters: int) -> dict:
    """kai-twin replay throughput: a mid-size fuzz-generated stream
    driven through the twin replayer, raw (digest=False) vs through
    the full differential oracle — reports events/s and the oracle's
    digesting overhead."""
    from kai_scheduler_tpu.twin import fuzz, replay as twin_replay
    stream = fuzz.generate("diurnal", seed=0, scale=2.0)
    twin_replay.replay(stream, digest=False)  # compile
    raw_eps, oracle_eps = [], []
    ok = True
    for _ in range(max(1, iters // 3)):
        r = twin_replay.replay(stream, digest=False)
        raw_eps.append(r.events_per_s)
        v = twin_replay.oracle(stream)
        ok = ok and v["ok"]
        oracle_eps.append(
            (v["replay"]["events_per_s"] + v["verify"]["events_per_s"])
            / 2)
    raw = max(raw_eps)
    withd = max(oracle_eps)
    overhead_pct = 100.0 * (raw - withd) / max(raw, 1e-9)
    return {"metric": ("kai-twin replay events/s (raw, digest off) on "
                       f"a {len(stream.events)}-event diurnal stream; "
                       f"oracle overhead {overhead_pct:.1f}%, "
                       f"bit-exact={ok}"),
            "value": round(raw, 1), "unit": "events/s",
            "vs_baseline": round(raw / 1000.0, 3),
            "extra": {"twin": {
                "events": len(stream.events),
                "raw_events_per_s": round(raw, 1),
                "oracle_events_per_s": round(withd, 1),
                "oracle_overhead_pct": round(overhead_pct, 1),
                "oracle_ok": ok}}}


CONFIGS = {
    "1": bench_fairshare, "fairshare": bench_fairshare,
    "2": bench_scoring, "scoring": bench_scoring,
    "3": bench_gang, "gang": bench_gang,
    "4": bench_topology, "topology": bench_topology,
    "5": bench_reclaim, "reclaim": bench_reclaim,
    # quick single-config target for the victim-wavefront hot path
    # (BENCH_CONFIG=preempt — same config as the full artifact's
    # preempt_many_queues row)
    "preempt": bench_preempt_many_queues,
    "preempt_many_queues": bench_preempt_many_queues,
    "churn": bench_churn,
    "phases": bench_phases,
    "frag": bench_frag,
    "resident": bench_resident,
    "storm": bench_storm,
    "headline": bench_headline,
    "e2e": bench_e2e,
    "e2e_alloc": bench_e2e_alloc,
    "twin": bench_twin,
}


def _load_artifact(path: str) -> dict:
    """Read a previous driver artifact — either the raw JSON line or the
    driver's wrapper ({"parsed": {...}})."""
    with open(path) as f:
        doc = json.load(f)
    return doc.get("parsed", doc)


def _compare(cur: dict, prev_path: str) -> dict:
    """benchstat-style per-config deltas vs a previous artifact (ref the
    reference's `make benchstat` comparison across counts,
    ``Makefile:124-130``): negative delta_pct = faster.  Folded into the
    artifact's extra AND printed as a table to stderr."""
    prev = _load_artifact(prev_path)
    pe, ce = prev.get("extra", {}), cur.get("extra", {})
    single = os.environ.get("BENCH_CONFIG")
    if single in ("fairshare", "scoring", "gang", "topology", "reclaim",
                  "preempt", "preempt_many_queues", "churn",
                  "1", "2", "3", "4", "5"):
        # single-config run: compare ONLY against the matching prev row
        names = {"1": "fairshare", "2": "scoring", "3": "gang",
                 "4": "topology", "5": "reclaim",
                 "preempt": "preempt_many_queues"}
        name = names.get(single, single)
        return_rows = {name: (pe.get(name, {}).get("p99_ms"),
                              cur.get("value"))}
        rows = return_rows
    else:
        rows = {"headline": (prev.get("value"), cur.get("value"))}
        for name in ("fairshare", "scoring", "gang", "topology",
                     "reclaim"):
            rows[name] = (pe.get(name, {}).get("p99_ms"),
                          ce.get(name, {}).get("p99_ms"))
        pc = pe.get("headline_per_cycle", {})
        cc = ce.get("headline_per_cycle", {})
        rows["per_cycle"] = (pc.get("sync_p99_ms", pc.get("p99_ms")),
                             cc.get("sync_p99_ms", cc.get("p99_ms")))
    out = {}
    print(f"vs {os.path.basename(prev_path)}:", file=sys.stderr)
    for name, (p, c) in rows.items():
        if p is None or c is None:
            continue
        delta = (c - p) / p * 100.0 if p else 0.0
        out[name] = {"prev_ms": p, "cur_ms": c,
                     "delta_pct": round(delta, 1)}
        print(f"  {name:12s} {p:9.2f}ms -> {c:9.2f}ms  "
              f"{delta:+6.1f}%", file=sys.stderr)
    return out


def main() -> None:
    quick = "--quick" in sys.argv
    compare_to = None
    if "--compare" in sys.argv:
        compare_to = sys.argv[sys.argv.index("--compare") + 1]
    which = os.environ.get("BENCH_CONFIG",
                           "gang" if quick else "full")
    iters = int(os.environ.get("BENCH_ITERS", 3 if quick else 10))
    if which == "full":
        out = bench_headline_full(iters)
        if compare_to:
            out["extra"]["vs_prev"] = _compare(out, compare_to)
        print(json.dumps(out))
        return
    if which == "all":
        for name in ("fairshare", "scoring", "gang", "topology", "reclaim",
                     "e2e", "e2e_alloc"):
            print(json.dumps(CONFIGS[name](iters)), file=sys.stderr)
        print(json.dumps(bench_headline(iters)))
        return
    out = CONFIGS[which](iters)
    if compare_to:
        out.setdefault("extra", {})["vs_prev"] = _compare(out, compare_to)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

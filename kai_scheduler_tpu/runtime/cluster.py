"""In-memory cluster model — the framework's stand-in for the K8s API server.

Every reference component talks exclusively through the API server (CRDs
+ watches, SURVEY.md §1); this object is that hub for the TPU framework:
intake (podgrouper) writes PodGroups into it, the scheduler snapshots it,
the binder commits bindings back, controllers (queue/podgroup status)
derive status from it.  In a real deployment this is replaced by a thin
client layer; the scheduling semantics live entirely above it.

It deliberately mirrors the fake-cluster model the reference uses for its
action integration tests (``pkg/scheduler/test_utils/test_utils.go``) —
the same object doubles as the test harness, per SURVEY.md §4 tier 2.
"""
from __future__ import annotations

import dataclasses

from ..apis import types as apis
from ..intake import gate as _gate


@dataclasses.dataclass
class Cluster:
    """Mutable cluster document store, keyed by object name."""

    nodes: dict[str, apis.Node] = dataclasses.field(default_factory=dict)
    queues: dict[str, apis.Queue] = dataclasses.field(default_factory=dict)
    pod_groups: dict[str, apis.PodGroup] = dataclasses.field(default_factory=dict)
    pods: dict[str, apis.Pod] = dataclasses.field(default_factory=dict)
    topology: apis.Topology | None = None
    bind_requests: dict[str, apis.BindRequest] = dataclasses.field(default_factory=dict)
    #: DRA objects (ref populateDRAGPUs + SharedDRAManager state)
    resource_claims: dict[str, apis.ResourceClaim] = dataclasses.field(
        default_factory=dict)
    device_classes: dict[str, apis.DeviceClass] = dataclasses.field(
        default_factory=dict)
    #: storage objects (ref storage{class,claim} info structs)
    volume_claims: dict[str, apis.PersistentVolumeClaim] = dataclasses.field(
        default_factory=dict)
    storage_classes: dict[str, apis.StorageClass] = dataclasses.field(
        default_factory=dict)
    #: shared-device reservation registry (ref the reservation pods in
    #: kai-resource-reservation; see runtime/reservation.py)
    reservations: "object" = None
    #: mutation journal — every state change records dirty keys so the
    #: incremental snapshotter (state/incremental.py) refreshes
    #: proportional to churn instead of rebuilding per cycle (the
    #: API-watch role of the reference's cache layer, SURVEY §2.6)
    journal: "object" = None

    def __post_init__(self):
        if self.reservations is None:
            from .reservation import ReservationRegistry
            self.reservations = ReservationRegistry()
        if self.journal is None:
            from ..state.incremental import MutationJournal
            self.journal = MutationJournal()
    #: monotonic clock advanced by the simulation driver
    now: float = 0.0
    #: evicted pods whose workload controller will recreate them (the
    #: consolidation-move path) — on the next tick they return to PENDING
    #: instead of vanishing
    restarting: set[str] = dataclasses.field(default_factory=set)
    #: kai-twin recorder hook (``twin/stream.StreamRecorder``): when
    #: set, the shared intake applier mirrors every successfully
    #: applied event into the recorder's stream.  Deepcopied clusters
    #: drop the hook (the recorder's ``__deepcopy__`` returns None) so
    #: a profiling/differential twin never re-records its own replay.
    twin_recorder: "object" = None

    # -- intake -----------------------------------------------------------

    @classmethod
    def from_objects(cls, nodes, queues, pod_groups, pods, topology=None) -> "Cluster":
        c = cls(topology=topology)
        for n in nodes:
            c.nodes[n.name] = n
        for q in queues:
            c.queues[q.name] = q
        for g in pod_groups:
            c.pod_groups[g.name] = g
        for p in pods:
            c.pods[p.name] = p
        return c

    def submit(self, group: apis.PodGroup, pods: list[apis.Pod]) -> None:
        """Add a workload (PodGroup + its pods) — podgrouper output."""
        group.creation_timestamp = group.creation_timestamp or self.now
        if group.name in self.pod_groups:
            _gate.gang_touched(self.journal, group.name)
        else:
            _gate.gang_added(self.journal, group.name)
        self.pod_groups[group.name] = group
        for p in pods:
            p.creation_timestamp = p.creation_timestamp or self.now
            if p.name in self.pods:
                _gate.pod_touched(self.journal, p.name)
            else:
                _gate.pod_added(self.journal, p.name)
            self.pods[p.name] = p

    # -- views ------------------------------------------------------------

    def snapshot_lists(self):
        """Stable-ordered object lists for ``build_snapshot``.

        Pods with an in-flight (Pending) BindRequest are presented as
        BOUND on their selected node — the reference's snapshot does the
        same (``cache/cluster_info/cluster_info.go:323`` snapshotBindRequests)
        so the scheduler neither double-allocates their capacity nor
        re-schedules them while the binder retries.
        """
        pods: list[apis.Pod] = []
        for p in self.pods.values():
            br = self.bind_requests.get(p.name)
            if (p.status == apis.PodStatus.PENDING and br is not None
                    and br.phase == "Pending"):
                pods.append(dataclasses.replace(
                    p, status=apis.PodStatus.BOUND, node=br.selected_node))
            elif (p.status == apis.PodStatus.RELEASING and br is not None
                    and br.phase == "Pending"):
                # consolidation move in flight: the pod still occupies its
                # old node (releasing) AND holds a verified claim on the
                # rebind target — present both, so a cycle run before the
                # restart tick cannot steal the earmarked capacity.
                pods.append(p)
                pods.append(dataclasses.replace(
                    p, status=apis.PodStatus.BOUND, node=br.selected_node,
                    accel_devices=[]))
            else:
                pods.append(p)
        return (
            list(self.nodes.values()),
            list(self.queues.values()),
            list(self.pod_groups.values()),
            pods,
            self.topology,
        )

    def pods_of_group(self, group: str) -> list[apis.Pod]:
        return [p for p in self.pods.values() if p.group == group]

    def group_running_count(self, group: str) -> int:
        return sum(p.status in (apis.PodStatus.BOUND, apis.PodStatus.RUNNING)
                   for p in self.pods_of_group(group))

    # -- commit side (binder / evictor write-backs) -----------------------

    def create_bind_request(self, br: apis.BindRequest) -> None:
        self.bind_requests[br.pod_name] = br
        # a Pending BindRequest changes the pod's snapshot presentation
        _gate.pod_touched(self.journal, br.pod_name)

    def node_device_free(self, node_name: str) -> list[float]:
        """Free share per accel device on a node, from pods' recorded
        devices — the runtime equivalent of the reservation-pod device
        bookkeeping (``binder/binding/resourcereservation``)."""
        node = self.nodes[node_name]
        free = [1.0] * int(round(node.allocatable.accel))
        # devices held through allocated DRA claims are not free either
        for claim in self.resource_claims.values():
            if claim.node == node_name:
                for d in claim.devices:
                    if d < len(free):
                        free[d] = 0.0
        for pod in self.pods.values():
            if pod.node != node_name or pod.status not in (
                    apis.PodStatus.BOUND, apis.PodStatus.RUNNING,
                    apis.PodStatus.RELEASING):
                continue
            if pod.accel_portion > 0 or pod.accel_memory_gib > 0:
                share = (pod.accel_portion if pod.accel_portion > 0
                         else pod.accel_memory_gib
                         / max(node.accel_memory_gib, 1e-6))
                for d in pod.accel_devices[:1]:
                    if d < len(free):
                        free[d] = max(0.0, free[d] - share)
            else:
                for d in pod.accel_devices:
                    if d < len(free):
                        free[d] = 0.0
        return free

    def bind_pod(self, pod_name: str, node_name: str,
                 devices: list[int] | None = None) -> None:
        """pods/binding subresource equivalent; assigns concrete accel
        devices (the reference resolves these through the reservation
        pod's NVML-discovered UUID — here device indices are first-class).
        """
        pod = self.pods[pod_name]
        if node_name not in self.nodes:
            raise KeyError(f"node {node_name} not found")
        free = self.node_device_free(node_name)
        if pod.accel_portion > 0 or pod.accel_memory_gib > 0:
            node = self.nodes[node_name]
            share = (pod.accel_portion if pod.accel_portion > 0
                     else pod.accel_memory_gib
                     / max(node.accel_memory_gib, 1e-6))
            if devices:
                pod.accel_devices = devices[:1]
            else:  # first fitting device, matching the snapshot builder
                fits = [d for d, f in enumerate(free) if f >= share - 1e-6]
                pod.accel_devices = fits[:1]
            if not pod.accel_devices:
                raise RuntimeError(
                    f"no device on {node_name} fits share {share} for "
                    f"{pod_name}")  # binder rolls back + backs off
        else:
            k = int(round(pod.resources.accel))
            if k > 0 and not pod.accel_devices:
                fully = [d for d, f in enumerate(free) if f >= 1.0 - 1e-6]
                if len(fully) < k:
                    raise RuntimeError(
                        f"only {len(fully)} fully-free devices on "
                        f"{node_name}, {pod_name} needs {k}")
                pod.accel_devices = fully[:k]
        pod.node = node_name
        pod.status = apis.PodStatus.BOUND
        _gate.pod_touched(self.journal, pod_name)
        group = self.pod_groups.get(pod.group)
        if group is not None and group.last_start_timestamp is None:
            group.last_start_timestamp = self.now
            _gate.gang_touched(self.journal, group.name)

    def evict_pod(self, pod_name: str, restart: bool = False) -> None:
        """Eviction = delete pod; its resources become releasing until the
        next tick reaps it (matching the reference's deletion grace window).

        ``restart=True`` models the workload controller recreating the pod
        (consolidation moves): after release it returns to PENDING so a
        pipelined rebind can land it on its planned node.
        """
        pod = self.pods.get(pod_name)
        if pod is not None:
            pod.status = apis.PodStatus.RELEASING
            _gate.pod_touched(self.journal, pod_name)
            if restart:
                self.restarting.add(pod_name)

    def tick(self, seconds: float = 1.0) -> None:
        """Advance time: bound pods start running, releasing pods vanish
        (or restart as pending, if their controller recreates them)."""
        self.now += seconds
        _gate.time_advanced(self.journal)
        for name in list(self.pods):
            pod = self.pods[name]
            if pod.status == apis.PodStatus.RELEASING:
                # the pod's DRA claims deallocate with it (ref claim
                # deallocation on pod deletion) ...
                for claim in self.resource_claims.values():
                    if claim.owner_pod == name:
                        claim.node = None
                        claim.devices = []
                        claim.owner_pod = None
                # ... and its device reservations drop this sharer (the
                # reservation pod is deleted with the last one)
                self.reservations.release(name)
                if name in self.restarting:
                    self.restarting.discard(name)
                    pod.status = apis.PodStatus.PENDING
                    pod.node = None
                    pod.accel_devices = []
                    _gate.pod_touched(self.journal, name)
                else:
                    del self.pods[name]
                    _gate.pod_removed(self.journal, name)
            elif pod.status == apis.PodStatus.BOUND:
                pod.status = apis.PodStatus.RUNNING
                _gate.pod_touched(self.journal, name)

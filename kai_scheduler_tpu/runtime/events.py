"""Per-gang decision events — the "why is my job not running" surface.

The reference answers that question with pod events written by the
status updater (``UnschedulableOnNodePool`` conditions and
per-pod-group eviction/preemption events).  Here every considered gang
records its cycle outcome into a bounded per-cycle buffer:

* ``allocated``      — the gang's tasks bound (or pipelined) this cycle;
* ``fit-failure``    — no node satisfied the gang (reason text from
  ``Session.FIT_REASONS``);
* ``quota-gate``     — the placement attempt failed on capacity or
  queue gates (fit-reason code 3);
* ``preempted-for``  — the gang's running pods were evicted to free
  capacity for pending work (detail names the beneficiaries when the
  commit pipelined onto the freed capacity);
* ``repacked-for``   — the gang's running pods were migrated by the
  kai-repack defragmentation solver (``ops/repack.py``): evicted with a
  pipelined rebind onto a node outside the target rack, to free the
  rack for a stranded large gang (named in the detail);
* ``starved``        — the gang's pending age crossed the configured
  starvation alarm (``SchedulerConfig.starvation_alarm_cycles``);
  detail carries the FIT_REASONS text of its current blocker
  (kai-pulse, ``ops/analytics.py``).

The log retains the last N cycles and is served by
``GET /debug/events?gang=<name>`` on the SchedulerServer; its last-cycle
summary rides the ``/healthz`` cycle-stats document.

Concurrency: events for one cycle are built on the cycle thread and
enter the ring in one append under ``_lock``; ringed entries are
immutable tuples (discipline declared in ``analysis/guarded_by.json``,
checked by kai-race) — a concurrent scrape can never observe a
half-recorded cycle.
"""
from __future__ import annotations

import dataclasses
import threading

__all__ = [
    "GangDecision", "DecisionLog", "OUTCOME_ALLOCATED",
    "OUTCOME_FIT_FAILURE", "OUTCOME_QUOTA_GATE", "OUTCOME_PREEMPTED_FOR",
    "OUTCOME_REPACKED_FOR", "OUTCOME_STARVED",
]

OUTCOME_ALLOCATED = "allocated"
OUTCOME_FIT_FAILURE = "fit-failure"
OUTCOME_QUOTA_GATE = "quota-gate"
OUTCOME_PREEMPTED_FOR = "preempted-for"
OUTCOME_REPACKED_FOR = "repacked-for"
OUTCOME_STARVED = "starved"


@dataclasses.dataclass(frozen=True)
class GangDecision:
    """One gang's outcome in one cycle."""

    gang: str
    queue: str
    outcome: str
    detail: str = ""

    def to_doc(self, cycle: int) -> dict:
        return {"cycle": cycle, "gang": self.gang, "queue": self.queue,
                "outcome": self.outcome, "detail": self.detail}


class DecisionLog:
    """Bounded ring of per-cycle gang decision events."""

    def __init__(self, retain_cycles: int = 8,
                 max_events_per_cycle: int = 4096):
        self._lock = threading.Lock()
        #: (cycle id, immutable event tuple, dropped count, exact
        #: outcome counts), oldest first
        self._cycles: list[tuple[int, tuple, int, dict]] = []  # kai-race: guarded-by=_lock
        self._retain = max(1, int(retain_cycles))
        #: per-cycle event bound — a 50k-gang snapshot must not turn the
        #: debug surface into a second commit path
        self.max_events_per_cycle = max(1, int(max_events_per_cycle))

    def record_cycle(self, cycle_id: int, events: list,
                     dropped: int = 0, counts: dict | None = None) -> None:
        """Ring one cycle's events atomically.  ``dropped`` counts
        candidates the producer already truncated; anything beyond the
        per-cycle bound here adds to it.  ``counts`` carries the
        producer's EXACT per-outcome totals (cheap to compute
        vectorized) so the summary stays honest when the event list is
        truncated; omitted, the summary counts the retained events."""
        cap = self.max_events_per_cycle
        over = max(0, len(events) - cap)
        if counts is None:
            counts = {}
            for e in events:
                counts[e.outcome] = counts.get(e.outcome, 0) + 1
        entry = (int(cycle_id), tuple(events[:cap]),
                 int(dropped) + over, dict(counts))
        with self._lock:
            self._cycles.append(entry)
            del self._cycles[:-self._retain]

    def events(self, gang: str | None = None, limit: int = 500) -> list[dict]:
        """Decision docs, newest cycle first, optionally filtered to one
        gang — the ``GET /debug/events?gang=`` payload."""
        with self._lock:
            cycles = list(self._cycles)
        out: list[dict] = []
        for cid, evs, _dropped, _counts in reversed(cycles):
            for e in evs:
                if gang is None or e.gang == gang:
                    out.append(e.to_doc(cid))
                    if len(out) >= limit:
                        return out
        return out

    def summary(self) -> dict:
        """Last cycle's EXACT outcome counts (``outcomes``) plus how
        many events the ring retains (``events``) — the ``/healthz``
        slice."""
        with self._lock:
            if not self._cycles:
                return {}
            cid, evs, dropped, counts = self._cycles[-1]
        return {"cycle": cid, "outcomes": dict(counts),
                "events": len(evs), "dropped": dropped}

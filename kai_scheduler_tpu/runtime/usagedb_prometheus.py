"""Prometheus-backed usage source — the query-construction layer of
``pkg/scheduler/cache/usagedb/prometheus/prometheus.go``.

The reference builds PromQL strings per resource:

- a decay factor ``0.5^((<anchor> - time()) / <half-life seconds>)``
  (``getExponentialDecayQuery``, prometheus.go:290-300),
- sliding windows as
  ``sum_over_time(((<metric>) * (<decay>))[<window>:<resolution>])``
  (prometheus.go:217),
- tumbling windows as ``sum_over_time(<decayed metric>)`` ranged from
  the latest cron reset to now (prometheus.go:230-260), the reset time
  coming from a cron expression,

normalizes allocation integrals by the capacity integral over the same
window, and hands per-queue usage to the proportion plugin.  Staleness
handling lives in the lister: a dead Prometheus degrades to plain
weight-based fairness (usagedb.go:20-60).

This module mirrors that construction against any Prometheus-compatible
HTTP API.  The transport is a pluggable ``http_get(path, params) ->
dict`` so tests drive it with a mock backend; the default uses stdlib
urllib against ``address``.
"""
from __future__ import annotations

import dataclasses
import datetime as dt
import json
import urllib.parse
import urllib.request
from typing import Callable, Mapping

import numpy as np

from ..apis.types import NUM_RESOURCES, RESOURCE_ACCEL, RESOURCE_CPU
from .usagedb import UsageParams

#: ref prometheus.go queueNameLabel
QUEUE_LABEL = "queue_name"

#: resource slot -> default allocation / capacity metric names
#: (ref prometheus.go allocationMetricsMap / capacityMetricsMap)
DEFAULT_ALLOCATION_METRICS = {
    RESOURCE_ACCEL: "kai_queue_allocated_gpus",
    RESOURCE_CPU: "kai_queue_allocated_cpu_cores",
}
DEFAULT_CAPACITY_METRICS = {
    RESOURCE_ACCEL: "kai_cluster_capacity_gpus",
    RESOURCE_CPU: "kai_cluster_capacity_cpu_cores",
}


def decay_query(anchor_s: float, half_life_s: float | None) -> str:
    """``getExponentialDecayQuery``: weight samples by how recent they
    are, half-life ``half_life_s``; empty when decay is disabled."""
    if half_life_s is None:
        return ""
    return f"0.5^(({int(anchor_s)} - time()) / {half_life_s:f})"


def decayed_metric(metric: str, anchor_s: float,
                   half_life_s: float | None) -> str:
    d = decay_query(anchor_s, half_life_s)
    return f"(({metric}) * ({d}))" if d else metric


def sliding_window_query(metric: str, anchor_s: float,
                         params: UsageParams,
                         resolution_s: float = 60.0) -> str:
    """``sum_over_time((<decayed>)[<window>:<resolution>])`` — the
    sliding-window usage integral ending at the query instant."""
    window = int(params.half_life_s * 4) if params.half_life_s else \
        int(params.tumbling_window_s)
    dm = decayed_metric(metric, anchor_s, params.half_life_s)
    return f"sum_over_time(({dm})[{window}s:{int(resolution_s)}s])"


def tumbling_window_query(metric: str, anchor_s: float,
                          params: UsageParams) -> str:
    """``sum_over_time(<decayed>)`` — evaluated as a range query from
    the latest window reset (see :func:`latest_cron_reset`) to now."""
    dm = decayed_metric(metric, anchor_s, params.half_life_s)
    return f"sum_over_time({dm})"


def latest_cron_reset(expr: str, now_s: float) -> float:
    """Latest occurrence <= ``now_s`` of a 5-field cron expression
    (minute hour day-of-month month day-of-week; ``*`` or integers) —
    the tumbling window's reset anchor (ref cronWindowExpression).
    Epoch seconds in UTC."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"cron expression needs 5 fields: {expr!r}")

    def match(val: int, spec: str) -> bool:
        return spec == "*" or int(spec) == val

    t = dt.datetime.fromtimestamp(now_s, dt.timezone.utc).replace(
        second=0, microsecond=0)
    for _ in range(366 * 24 * 60 // max(1, 60)):  # scan back <= 1 year, hourly
        day_ok = (match(t.day, fields[2]) and match(t.month, fields[3])
                  and match(t.isoweekday() % 7, fields[4]))
        if day_ok and match(t.hour, fields[1]):
            # scan this hour's minutes downward
            m = t
            while m.hour == t.hour:
                if match(m.minute, fields[0]) and m.timestamp() <= now_s:
                    return m.timestamp()
                if m.minute == 0:
                    break
                m -= dt.timedelta(minutes=1)
        t = (t - dt.timedelta(hours=1)).replace(minute=59)
    raise ValueError(f"no occurrence of {expr!r} within a year")


def _default_http_get(address: str):
    def get(path: str, query: dict) -> dict:
        url = f"{address}{path}?{urllib.parse.urlencode(query)}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.load(resp)
    return get


@dataclasses.dataclass
class PrometheusUsageClient:
    """Constructs + issues the usage queries; returns per-queue usage
    vectors normalized by the capacity integral — the quantity the
    division kernel's ``k_value`` term consumes."""

    address: str = "http://localhost:9090"
    params: UsageParams = dataclasses.field(default_factory=UsageParams)
    allocation_metrics: dict = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_ALLOCATION_METRICS))
    capacity_metrics: dict = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_CAPACITY_METRICS))
    #: cron reset for tumbling windows, e.g. "0 0 * * *" (midnight UTC)
    cron_reset: str = "0 0 * * *"
    resolution_s: float = 60.0
    http_get: Callable[[str, dict], dict] | None = None

    def _get(self, path: str, query: dict) -> dict:
        get = self.http_get or _default_http_get(self.address)
        return get(path, query)

    def _query_vector(self, resource: int, metric: str,
                      now_s: float) -> dict[str, float]:
        """One usage integral per queue label, via instant query
        (sliding) or range query from the cron reset (tumbling)."""
        if self.params.window_type == "sliding":
            expr = sliding_window_query(metric, now_s, self.params,
                                        self.resolution_s)
            doc = self._get("/api/v1/query",
                            {"query": expr, "time": now_s})
            rows = doc["data"]["result"]
            return {r["metric"].get(QUEUE_LABEL, ""):
                    float(r["value"][1]) for r in rows}
        expr = tumbling_window_query(metric, now_s, self.params)
        start = latest_cron_reset(self.cron_reset, now_s)
        doc = self._get("/api/v1/query_range", {
            "query": expr, "start": start, "end": now_s,
            "step": self.resolution_s})
        out: dict[str, float] = {}
        for r in doc["data"]["result"]:
            # the integral is the LAST sample of sum_over_time ranged
            # from the reset (samples accumulate within the window)
            if r["values"]:
                out[r["metric"].get(QUEUE_LABEL, "")] = float(
                    r["values"][-1][1])
        return out

    def fetch_usage(self, now_s: float) -> dict[str, np.ndarray]:
        """{queue: usage [R]} — allocation integral / capacity integral
        per resource (ref queryResourceCapacity + GetResourceUsage)."""
        out: dict[str, np.ndarray] = {}
        for resource, metric in self.allocation_metrics.items():
            cap_metric = self.capacity_metrics.get(resource)
            cap = 1.0
            if cap_metric:
                cap_rows = self._query_vector(resource, cap_metric, now_s)
                cap = sum(cap_rows.values()) or 1.0
            for queue, val in self._query_vector(
                    resource, metric, now_s).items():
                vec = out.setdefault(
                    queue, np.zeros((NUM_RESOURCES,), np.float32))
                vec[resource] = val / cap
        return out


class PrometheusUsageLister:
    """Drop-in for ``UsageLister`` backed by the query layer: same
    ``maybe_fetch``/``queue_usage`` surface the Scheduler consumes,
    same staleness rejection (a dead Prometheus degrades to plain
    weight-based fairness)."""

    def __init__(self, client: PrometheusUsageClient):
        self.client = client
        self.params = client.params
        self._last: dict[str, np.ndarray] | None = None
        #: attempt time throttles retries (advances on FAILURE too — a
        #: dead Prometheus must not add a blocking query per cycle);
        #: data time drives staleness
        self._last_attempt: float | None = None
        self._last_data: float | None = None

    def maybe_fetch(self, now: float) -> bool:
        if (self._last_attempt is not None
                and now - self._last_attempt < self.params.fetch_interval_s):
            return False
        self._last_attempt = now
        try:
            self._last = self.client.fetch_usage(now)
            self._last_data = now
            return True
        except Exception:  # noqa: BLE001 — degrade, never stall a cycle
            return False

    def queue_usage(self, now: float) -> dict[str, np.ndarray] | None:
        if self._last_data is None:
            return None
        if now - self._last_data > self.params.staleness():
            return None  # stale pipeline: reject frozen history
        return self._last

"""Continuous profiling — the Pyroscope analogue.

The reference streams Go runtime profiles to a Pyroscope server for the
life of the process (``cmd/scheduler/profiling/pyroscope.go:13-30``,
flags ``cmd/scheduler/app/options/options.go:110-113``).  The Python
equivalent here is a wall-clock stack sampler: a daemon thread samples
every live thread's stack ``sample_hz`` times per second, folds them
into Brendan-Gregg collapsed-stack lines ("a;b;c count"), rolls the
aggregate over fixed windows, and either

- POSTs each closed window to a configured server (the
  ``pyroscope-address`` flag; Pyroscope's HTTP ``/ingest`` API accepts
  exactly this folded-text format), and/or
- retains a ring of recent windows served by the PluginServer at
  ``GET /debug/pprof/continuous`` — so a cluster without a Pyroscope
  deployment still gets scrapeable continuous profiles.

Push failures are swallowed after counting (a profiling sink must never
affect scheduling).
"""
from __future__ import annotations

import sys
import threading
import time
import urllib.request

__all__ = ["ContinuousProfiler"]


class ContinuousProfiler:
    """Folded-stack wall sampler with windowed push/retain."""

    def __init__(self, *, sample_hz: float = 100.0, window_s: float = 10.0,
                 server_address: str = "", app_name: str = "kai-scheduler",
                 retain_windows: int = 6):
        self.sample_hz = max(1.0, float(sample_hz))
        self.window_s = max(0.1, float(window_s))
        self.server_address = server_address.rstrip("/")
        self.app_name = app_name
        self.retain_windows = retain_windows
        self._lock = threading.Lock()
        self._current: dict[str, int] = {}  # kai-race: guarded-by=_lock
        self._window_start = time.time()  # kai-race: guarded-by=_lock
        #: closed windows, newest last: (start_ts, end_ts, folded dict)
        self.windows: list[tuple[float, float, dict[str, int]]] = []  # kai-race: guarded-by=_lock
        self.pushed = 0  # kai-race: guarded-by=_lock
        self.push_errors = 0  # kai-race: guarded-by=_lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ---------------------------------------------------------

    def _fold(self, frame) -> str:
        parts: list[str] = []
        while frame is not None:
            code = frame.f_code
            parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]})")
            frame = frame.f_back
        return ";".join(reversed(parts))

    def _sample_once(self) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        with self._lock:
            for tid, frame in frames.items():
                if tid == me:
                    continue  # the sampler observing itself is noise
                key = self._fold(frame)
                self._current[key] = self._current.get(key, 0) + 1

    def _roll_window(self, now: float) -> None:
        with self._lock:
            window = (self._window_start, now, self._current)
            self._current = {}
            self._window_start = now
            self.windows.append(window)
            del self.windows[:-self.retain_windows]
        if self.server_address and window[2]:
            self._push(window)

    def _push(self, window) -> None:
        start, end, folded = window
        body = self.render_folded(folded).encode()
        url = (f"{self.server_address}/ingest?name={self.app_name}"
               f"&from={int(start)}&until={int(end)}&format=folded")
        try:
            req = urllib.request.Request(url, data=body, method="POST")
            urllib.request.urlopen(req, timeout=2.0).read()
            # counters under the lock: stop()'s final roll can push from
            # the caller thread while the sampler's own push is in flight
            with self._lock:
                self.pushed += 1
            self._count_push(ok=True)
        except Exception:  # noqa: BLE001 — profiling must never bite
            with self._lock:
                self.push_errors += 1
            self._count_push(ok=False)

    @staticmethod
    def _count_push(ok: bool) -> None:
        """Mirror the push counters into the metrics registry
        (``kai_profiler_pushed_windows_total`` /
        ``kai_profiler_push_errors_total``) so ``/metrics`` sees them —
        the bare instance attributes stay for direct inspection."""
        try:
            # package-relative cycle-breaker: framework.server lazily
            # imports this module, and importing the framework package
            # here at module scope would drag jax into every profiler
            # import
            from ..framework import metrics
            if ok:
                metrics.profiler_pushed_windows.inc()
            else:
                metrics.profiler_push_errors.inc()
        except Exception:  # noqa: BLE001 — a metrics mirror must never
            pass  # kill the sampler thread (attribute counters stand)

    def _run(self) -> None:
        period = 1.0 / self.sample_hz
        with self._lock:
            next_roll = self._window_start + self.window_s
        while not self._stop.wait(period):
            self._sample_once()
            now = time.time()
            if now >= next_roll:
                self._roll_window(now)
                next_roll = now + self.window_s

    # -- lifecycle / rendering -------------------------------------------

    def start(self) -> "ContinuousProfiler":
        if self._thread is not None and not self._thread.is_alive():
            # a previous stop() timed out on join and the straggler has
            # since exited — safe to forget it and restart
            self._thread = None
        if self._thread is not None:
            if self._stop.is_set():
                # stop() joined with a timeout and the old sampler is
                # STILL running; starting another would leak a second
                # daemon sampler writing into the same windows
                raise RuntimeError(
                    "previous sampler thread has not stopped "
                    "(stop() join timed out) — cannot start a second one")
            return self  # already running
        # stop() leaves the event set; without clearing it a re-started
        # sampler thread would exit immediately and silently stop
        # profiling
        self._stop.clear()
        with self._lock:
            self._window_start = time.time()
        self._thread = threading.Thread(
            target=self._run, name="continuous-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # keep the reference: a later start() must refuse to run
                # a second sampler beside the straggler
                self._roll_window(time.time())
                return
            self._thread = None
        self._roll_window(time.time())

    @staticmethod
    def render_folded(folded: dict[str, int]) -> str:
        return "\n".join(f"{k} {v}" for k, v in sorted(folded.items()))

    def render(self) -> str:
        """All retained windows plus the in-flight one, newest last,
        separated by window headers — the ``/debug/pprof/continuous``
        body."""
        with self._lock:
            parts = []
            for start, end, folded in self.windows:
                parts.append(f"# window {start:.0f}-{end:.0f}")
                parts.append(self.render_folded(folded))
            parts.append(f"# window {self._window_start:.0f}-now")
            parts.append(self.render_folded(self._current))
        return "\n".join(p for p in parts if p)

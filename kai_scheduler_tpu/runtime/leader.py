"""Leader election — lease-based HA gate for the cycle driver.

Reference: ``cmd/scheduler/app/server.go:60-63`` — the scheduler runs
under ``leaderelection`` with a Lease object (``resourcelock``); only
the elected instance executes ``Scheduler.Run``.  Constants mirror the
reference defaults (15s lease, 10s renew deadline, 2s retry).

The ``Lease`` here is the coordination object: in-process it is shared
directly between Scheduler instances (the envtest analogue); a
deployment backs the same three fields (holder / acquire time / renew
time) with its coordination store.
"""
from __future__ import annotations

import dataclasses
import threading

#: reference defaults (client-go leaderelection)
LEASE_DURATION_S = 15.0
RETRY_PERIOD_S = 2.0


@dataclasses.dataclass
class Lease:
    """coordination.k8s.io/Lease analogue."""

    holder: str | None = None
    acquire_time: float = 0.0
    renew_time: float = 0.0
    duration_s: float = LEASE_DURATION_S
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def try_acquire_or_renew(self, identity: str, now: float) -> bool:
        """One election round (``tryAcquireOrRenew``): renew if held,
        take over if expired, otherwise lose."""
        with self._lock:
            if self.holder == identity:
                self.renew_time = now
                return True
            if self.holder is None or now - self.renew_time > self.duration_s:
                self.holder = identity
                self.acquire_time = now
                self.renew_time = now
                return True
            return False

    def release(self, identity: str) -> None:
        """Voluntary step-down (``releaseOnCancel``)."""
        with self._lock:
            if self.holder == identity:
                self.holder = None
                self.renew_time = 0.0


class LeaderElector:
    """Per-instance view of a shared :class:`Lease`."""

    def __init__(self, lease: Lease, identity: str):
        self.lease = lease
        self.identity = identity

    def is_leader(self, now: float) -> bool:
        return self.lease.try_acquire_or_renew(self.identity, now)

    def resign(self) -> None:
        self.lease.release(self.identity)

"""Resource-reservation registry — the reservation-pod lifecycle.

Reference: for every SHARED GPU the binder ensures a reservation pod in
``kai-resource-reservation`` (``binder/binding/resourcereservation/``);
the pod discovers its device through NVML and patches the device UUID
onto itself (``cmd/resourcereservation/app/app.go:30-60``); fractional
sharers join the group, and the reservation is deleted when the last
sharer leaves.

TPU-native substitution: device identity is scheduler-owned (device
indices are first-class in the snapshot and BindRequests), so no agent
process is needed to DISCOVER the device — but the reservation object
itself still matters: it pins a (node, device) share group, carries the
stable runtime identifier sharers mount, and tracks the sharer set so
the device is released exactly when the last fractional pod leaves.
This registry is that object store; the binder's gpusharing plugin
drives acquire/release, and ``Cluster.tick`` releases on pod deletion.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Reservation:
    """One shared accelerator — ref the per-GPU-group reservation pod."""

    node: str
    device: int
    #: stable runtime identifier sharers mount (NVML UUID analogue)
    uuid: str
    #: fractional pods sharing the device
    owners: set = dataclasses.field(default_factory=set)


class ReservationRegistry:
    """Share-group bookkeeping keyed by (node, device)."""

    def __init__(self):
        self._by_group: dict[tuple[str, int], Reservation] = {}

    def acquire(self, node: str, device: int, pod_name: str) -> Reservation:
        """Join (creating if needed) the reservation for a device —
        the binder's ``reserveGPUs`` + wait-for-UUID step collapsed:
        identity is synthesized deterministically instead of being
        discovered by an agent process."""
        key = (node, device)
        res = self._by_group.get(key)
        if res is None:
            res = Reservation(node=node, device=device,
                              uuid=f"accel://{node}/{device}")
            self._by_group[key] = res
        res.owners.add(pod_name)
        return res

    def release(self, pod_name: str, node: str | None = None,
                device: int | None = None) -> None:
        """Drop a sharer; the reservation dies with its last owner (ref
        the binder deleting the reservation pod when the group empties).
        ``node`` alone sweeps every group of the pod on that node;
        neither sweeps all of the pod's groups — the pod-deletion path.
        """
        for key, res in list(self._by_group.items()):
            if node is not None and key[0] != node:
                continue
            if device is not None and key[1] != device:
                continue
            res.owners.discard(pod_name)
            if not res.owners:
                del self._by_group[key]

    def get(self, node: str, device: int) -> Reservation | None:
        return self._by_group.get((node, device))

    def for_pod(self, pod_name: str) -> list[Reservation]:
        return [r for r in self._by_group.values()
                if pod_name in r.owners]

    def __len__(self) -> int:
        return len(self._by_group)

"""Time-based fairshare usage source — ref ``pkg/scheduler/cache/usagedb``.

The reference polls a Prometheus-backed ``UsageLister`` every
``fetchInterval`` (default 1m) for each queue's allocation metrics
aggregated over a decay window, normalizes by the cluster-capacity
integral over the same window, and hands the result to the proportion
plugin, where the over-quota share weight becomes
``max(0, w + k*(w - usage))`` (``resource_division.go:238-246``).  Stale
data (older than ``stalenessPeriod``, default 5× fetch interval) is
rejected so a dead metrics pipeline degrades to plain weight-based
fairness instead of frozen history (``usagedb.go:20-60``).

Here the same shape is a host-side accumulator: a pluggable client
reports instantaneous per-queue allocation; the lister integrates it
into either

- a **sliding window with exponential decay** (``halfLifePeriod``, ref
  ``prometheus.go`` getExponentialDecayQuery), or
- a **tumbling window** that resets on a fixed period boundary (ref
  cron-reset tumbling windows),

and exposes usage normalized by the capacity integral — exactly the
``usage/clusterCapacity`` quantity the division kernel's ``k_value``
term expects (``ops/drf.py``).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Mapping

import numpy as np

from ..apis.types import NUM_RESOURCES

#: client signature: now -> {queue name: allocation vector [R]}
UsageClient = Callable[[float], Mapping[str, np.ndarray]]


@dataclasses.dataclass
class UsageParams:
    """ref ``cache/usagedb/api`` UsageParams + defaults."""

    window_type: str = "sliding"          # "sliding" | "tumbling"
    half_life_s: float | None = 3600.0    # sliding decay half-life
    tumbling_window_s: float = 24 * 3600.0
    tumbling_window_start: float = 0.0
    fetch_interval_s: float = 60.0
    staleness_period_s: float | None = None   # default 5x fetch interval

    def staleness(self) -> float:
        if self.staleness_period_s is None:
            return 5.0 * self.fetch_interval_s
        return max(self.staleness_period_s, self.fetch_interval_s)


class UsageLister:
    """Poll-driven usage accumulator with staleness semantics."""

    def __init__(self, client: UsageClient, params: UsageParams | None = None,
                 capacity_fn: Callable[[float], np.ndarray] | None = None):
        self.client = client
        self.params = params or UsageParams()
        #: instantaneous cluster capacity [R] (integrated alongside usage)
        self.capacity_fn = capacity_fn
        self._usage: dict[str, np.ndarray] = {}
        self._capacity_integral = np.zeros((NUM_RESOURCES,), np.float64)
        self._last_fetch: float | None = None
        self._last_data_time: float | None = None

    # -- the poll loop body (driver calls this; ref usagedb.go Start) ------

    def maybe_fetch(self, now: float) -> bool:
        """Fetch + integrate if ``fetch_interval`` elapsed.  Returns True
        when a fetch happened."""
        if (self._last_fetch is not None
                and now - self._last_fetch < self.params.fetch_interval_s):
            return False
        self.fetch(now)
        return True

    def fetch(self, now: float) -> None:
        """One poll: decay/reset the window, then integrate the client's
        current allocation report over the elapsed interval."""
        p = self.params
        dt = (0.0 if self._last_fetch is None
              else max(0.0, now - self._last_fetch))
        if p.window_type == "tumbling":
            period = max(p.tumbling_window_s, 1e-9)
            prev_win = (math.floor(((self._last_fetch or now)
                                    - p.tumbling_window_start) / period))
            cur_win = math.floor((now - p.tumbling_window_start) / period)
            if cur_win != prev_win:  # crossed a boundary: reset
                self._usage.clear()
                self._capacity_integral[:] = 0.0
        elif p.half_life_s:
            decay = 0.5 ** (dt / p.half_life_s)
            for vec in self._usage.values():
                vec *= decay
            self._capacity_integral *= decay

        try:
            report = self.client(now)
        except Exception:
            # fetch failure: keep the last data; staleness will reject it
            self._last_fetch = now
            return
        if dt > 0:
            for name, alloc in report.items():
                vec = self._usage.setdefault(
                    name, np.zeros((NUM_RESOURCES,), np.float64))
                vec += np.asarray(alloc, np.float64) * dt
            if self.capacity_fn is not None:
                self._capacity_integral += (
                    np.asarray(self.capacity_fn(now), np.float64) * dt)
        self._last_fetch = now
        self._last_data_time = now

    # -- consumer side (session open; ref GetResourceUsage) ----------------

    def queue_usage(self, now: float) -> dict[str, np.ndarray] | None:
        """Normalized usage per queue ([R], fraction of the capacity
        integral), or None when the data is stale/absent — callers then
        run plain weight-based fairness (k term inert)."""
        if self._last_data_time is None:
            return None
        if now - self._last_data_time > self.params.staleness():
            return None
        cap = np.maximum(self._capacity_integral, 1e-9)
        return {name: (vec / cap).astype(np.float32)
                for name, vec in self._usage.items()}


def cluster_allocation_client(cluster) -> UsageClient:
    """A client reporting live per-queue allocation straight from the
    in-memory hub — the simulation analogue of the queuecontroller's
    ``kai_queue_allocated_*`` metrics feed (ref
    ``pkg/queuecontroller/metrics/metrics.go:33-39``)."""
    from ..apis import types as apis

    def client(now: float) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        for pod in cluster.pods.values():
            if pod.status not in (apis.PodStatus.BOUND,
                                  apis.PodStatus.RUNNING):
                continue
            group = cluster.pod_groups.get(pod.group)
            if group is None:
                continue
            vec = out.setdefault(
                group.queue, np.zeros((NUM_RESOURCES,), np.float64))
            vec += np.asarray(pod.resources.as_tuple(), np.float64)
        return out

    return client


def cluster_capacity_fn(cluster):
    """Instantaneous cluster allocatable [R] from the hub."""
    def capacity(now: float) -> np.ndarray:
        total = np.zeros((NUM_RESOURCES,), np.float64)
        for node in cluster.nodes.values():
            if not node.unschedulable:
                total += np.asarray(node.allocatable.as_tuple(), np.float64)
        return total
    return capacity

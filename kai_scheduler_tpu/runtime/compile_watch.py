"""kai-wire's compile half — jit cache-miss attribution.

Recompiles are the other way the host↔device link silently eats a
cycle: a drifting abstract signature (a padded dim that crossed a
bucket, an unstable static config) turns "one dispatch per cycle" into
seconds of XLA compile, and nothing in the repo could say *which entry*
recompiled or *why*.  The jaxpr probe (``analysis/trace_probe.py``)
asserts two equivalent builds share one compile at canonical shapes —
a CI property; this module is the production counterpart: a
:class:`CompileWatcher` wrapping the package's jit entry points (the
same entries the analysis call graph enumerates) that attributes every
cache miss to its ``(entry, abstract-shape-signature)`` pair, times it,
and raises a **recompile-storm alarm** when one entry misses repeatedly
inside a sliding window (the padded-capacity-oscillation failure mode:
a cluster whose entity counts straddle a bucket boundary recompiles
every other cycle).

Mechanics: the watcher models jax's cache key — the pytree structure of
``(args, kwargs)`` with array leaves abstracted to ``(shape, dtype)``
and non-array leaves (static configs) to their ``repr`` — and treats
the first call per unseen signature as the compile.  The kai-resident
fused entry (``resident_cycle``) is the one the steady-state cycle
lives on: its delta segments bucket to powers of two precisely so this
watcher sees ONE signature per snapshot shape bucket — a resident
recompile storm means the bucketing broke, and the alarm below is the
tripwire.  The model is
checked against jax itself where possible: wrappers forward the
underlying ``_cache_size`` probe, which the trace probe's
compile-once assertion continues to consume.

The wrapper is HOST-side and adds ~tens of microseconds per call
(one ``tree_flatten`` + tuple build) — never traced, zero new
primitives in any jit region (the jaxpr probe baseline is unchanged).

Surfaces: ``kai_compile_*`` registry metrics, the ``compile`` section
of ``GET /debug/wire``, and per-event docs in a bounded ring.
Concurrency: all watcher state is accessed under ``_lock`` (declared
in ``analysis/guarded_by.json``); events ring as immutable dicts.
"""
from __future__ import annotations

import functools
import threading
import time
import zlib

import jax

__all__ = ["CompileWatcher", "WATCHER", "watch"]


def _signature(args, kwargs) -> tuple:
    """The abstract signature jax's jit cache keys on, modeled: tree
    structure + per-leaf ``(shape, dtype)`` for arrays, ``repr`` for
    static leaves (configs, ints, strings)."""
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, dict(sorted(kwargs.items()))))
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(("a", tuple(shape), str(dtype)))
        else:
            parts.append(("s", repr(leaf)))
    return (str(treedef), tuple(parts))


def _render_signature(sig: tuple) -> str:
    """Compact human-readable form: digest + the dominant array shapes
    (full signatures are hundreds of tokens; the doc needs a label)."""
    digest = f"{zlib.crc32(repr(sig).encode()):08x}"
    counts: dict[str, int] = {}
    for part in sig[1]:
        if part[0] == "a":
            key = f"{part[2]}[{','.join(str(d) for d in part[1])}]"
            counts[key] = counts.get(key, 0) + 1
    top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    shapes = ", ".join(f"{k}×{n}" if n > 1 else k for k, n in top)
    return f"sig-{digest}" + (f" ({shapes}, …)" if shapes else "")


class CompileWatcher:
    """Attributes jit cache misses to ``(entry, signature)`` pairs."""

    def __init__(self, retain_events: int = 256,
                 storm_threshold: int = 3,
                 storm_window_s: float = 300.0):
        self._lock = threading.Lock()
        #: entry -> set of seen signatures
        self._seen: dict[str, set] = {}
        #: entry -> {"misses": n, "seconds": s, "calls": n}
        self._stats: dict[str, dict] = {}
        #: bounded ring of immutable miss-event docs, oldest first
        self._events: list[dict] = []
        #: entry -> recent miss monotonic stamps (storm detection)
        self._miss_times: dict[str, list] = {}
        self._alarms = 0
        #: bounds — immutable after construction
        self._retain = max(1, int(retain_events))
        self.storm_threshold = max(2, int(storm_threshold))
        self.storm_window_s = float(storm_window_s)

    # -- wrapping ----------------------------------------------------------

    def wrap(self, entry: str, fn):
        """Wrap a jitted callable; every call classifies its abstract
        signature, and a first-seen signature is recorded as the
        entry's compile (timed around the dispatch, which on a miss is
        dominated by trace + XLA compile).  ``_cache_size`` and
        ``__wrapped__`` forward to the underlying jit object / raw
        function so the trace probe's compile-once assertion keeps
        working through the wrapper."""
        with self._lock:
            self._seen.setdefault(entry, set())
            self._stats.setdefault(
                entry, {"misses": 0, "seconds": 0.0, "calls": 0})

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            sig = _signature(args, kwargs)
            if not self._observe_call(entry, sig):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            self._observe_miss(entry, sig, time.perf_counter() - t0)
            return out

        # the raw python function, one hop past the jit object (jax's
        # own functools.wraps chain) — what make_jaxpr consumers want
        wrapped.__wrapped__ = getattr(fn, "__wrapped__", fn)
        cache_probe = getattr(fn, "_cache_size", None)
        if cache_probe is not None:
            wrapped._cache_size = cache_probe
        wrapped.__kai_entry__ = entry
        wrapped.__kai_jit__ = fn
        return wrapped

    def _observe_call(self, entry: str, sig: tuple) -> bool:
        """Register the call; True when the signature is new (a
        presumed cache miss — the caller times the dispatch)."""
        with self._lock:
            self._stats[entry]["calls"] += 1
            seen = self._seen[entry]
            if sig in seen:
                return False
            seen.add(sig)
            return True

    def _observe_miss(self, entry: str, sig: tuple,
                      seconds: float) -> None:
        now = time.monotonic()
        with self._lock:
            stamps = self._miss_times.setdefault(entry, [])
            stamps.append(now)
            cutoff = now - self.storm_window_s
            while stamps and stamps[0] < cutoff:
                stamps.pop(0)
            storm = len(stamps) >= self.storm_threshold
            if storm:
                self._alarms += 1
            st = self._stats[entry]
            st["misses"] += 1
            st["seconds"] += seconds
            self._events.append({
                "entry": entry,
                "signature": _render_signature(sig),
                "seconds": round(seconds, 6),
                "storm": storm,
                "wall": time.time(),
            })
            del self._events[:-self._retain]
        self._export_metrics(entry, seconds, storm)

    def _export_metrics(self, entry, seconds, storm) -> None:
        try:
            # package-relative cycle-breaker (see runtime/profiling.py):
            # ops/framework modules wrap their entries at import time,
            # so the registry import must stay lazy
            from ..framework import metrics
        except Exception:  # noqa: BLE001 — a metrics mirror must never
            return         # fail a dispatch (the watcher ring stands)
        metrics.compile_cache_misses.inc(entry)
        metrics.compile_seconds.inc(entry, by=float(seconds))
        if storm:
            metrics.compile_storm_alarms.inc(entry)

    # -- reading -----------------------------------------------------------

    def entries(self) -> list[str]:
        with self._lock:
            return sorted(self._seen)

    def events(self, n: int | None = None) -> list[dict]:
        """Recent miss events, oldest first (immutable docs)."""
        with self._lock:
            evs = self._events if n is None else self._events[-max(1, n):]
            return [dict(e) for e in evs]

    def report(self) -> dict:
        """The ``compile`` section of ``GET /debug/wire``."""
        with self._lock:
            entries = {
                name: {"signatures": len(self._seen[name]),
                       "misses": st["misses"], "calls": st["calls"],
                       "seconds": round(st["seconds"], 6)}
                for name, st in sorted(self._stats.items())}
            events = [dict(e) for e in self._events]
            alarms = self._alarms
        return {"entries": entries, "events": events, "alarms": alarms,
                "storm_threshold": self.storm_threshold,
                "storm_window_s": self.storm_window_s}


#: the process-global watcher the package's jit entry points wrap with
WATCHER = CompileWatcher()


def watch(entry: str, fn):
    """Hook one jit entry point into the global watcher — the one-line
    idiom the entry-point modules use at module scope::

        allocate_jit = compile_watch.watch("allocate", allocate_jit)
    """
    return WATCHER.wrap(entry, fn)

"""kai-wire — the host↔device transfer ledger.

BENCH_r05's honest per-cycle p99 (~162 ms) is dominated by a measured
~109 ms host↔device link floor, and ROADMAP item 1's acceptance bar is
"a multi-cycle soak that never re-uploads an unchanged leaf" — a claim
the phase tracer (``runtime/tracing.py``) cannot adjudicate: it times
the ``upload`` phase but cannot say *which leaves, how many bytes, or
why*.  This module is the evidence layer: a :class:`TransferLedger`
that is the package's single **mandatory choke point** for every
``jax.device_put`` (kai-lint rule ``KAI071`` forbids the raw call
anywhere else), recording per-cycle, per-leaf upload events — leaf
name, nbytes, dtype/shape, content fingerprint, and a *reason*:

* ``full-build``     — ``build_snapshot``'s one-shot snapshot transfer;
* ``journal-patch``  — the incremental snapshotter's changed-leaves
  ship (``state/incremental.py``), batched into ONE dispatch;
* ``delta-apply``    — the kai-resident packed journal delta
  (``ops/resident.py``): the only steady-state upload once the
  snapshot lives on device; its buffers are **transient** (consumed by
  the donated scatter-apply dispatch), so they are counted on the wire
  but kept out of the device-residency gauge and the redundancy
  compare (delta *indices* legitimately repeat cycle-to-cycle — the
  redundancy invariant is about resident snapshot leaves);
* ``fallback``       — the incremental engine rebuilt in full (cold
  start, structural change, feature pods, dirty-threshold, ...);
* ``verify``         — the patched==fresh verifier's reference rebuild;
* ``mesh-shard``     — ``parallel/mesh.shard_state`` mesh placement.

Three derived surfaces ride the ledger:

* a **redundancy detector**: every upload is fingerprinted (full-buffer
  ``zlib.crc32`` + nbytes/dtype/shape) against the last upload of the
  same ``(site, leaf)`` key, and re-uploaded-*identical* bytes are
  counted per reason — the exact invariant ROADMAP-1's delta-only
  device-resident rewrite must drive to zero on the patch path;
* a **device-residency gauge**: the ledger-known resident set (last
  upload per leaf key) as live buffer count / bytes plus a per-cycle
  peak watermark — the baseline ROADMAP-1's buffer donation will be
  measured against;
* per-cycle summaries in a bounded ring (``GET /debug/wire``, the
  ``/healthz`` wire slice, ``CycleResult.wire``, Chrome-trace counter
  lanes) and cumulative ``kai_wire_*`` registry metrics.

Accounting honesty: the ledger sees *dispatches*, not the allocator —
"resident" means "the latest buffer uploaded through the ledger for
this leaf key", which matches reality as long as snapshots rebind their
leaves (they do: the snapshotter swaps whole pytrees).  Leaves that are
not host ``numpy`` arrays (e.g. already-on-device arrays headed to a
mesh layout) are counted by size but not fingerprinted — hashing them
would itself force a device→host transfer; ``unfingerprinted_bytes``
reports the blind spot instead of pretending.

Concurrency model (disciplines declared in ``analysis/guarded_by.json``,
checked by kai-race): event recording happens on whichever thread
dispatches the transfer (cycle thread, HTTP cycle handlers), cycle
roll-over on the cycle thread, and readers (``/debug/wire`` handler
threads) take consistent copies — every access to ledger state holds
``_lock``, ring entries are immutable once rolled, and the
``jax.device_put`` dispatch itself runs *outside* the lock so a slow
transfer never stalls a concurrent scrape.
"""
from __future__ import annotations

import contextlib
import threading
import time
import zlib

import jax
import numpy as np

__all__ = [
    "TransferLedger", "LEDGER", "REASON_FULL_BUILD",
    "REASON_JOURNAL_PATCH", "REASON_DELTA_APPLY", "REASON_FALLBACK",
    "REASON_VERIFY", "REASON_MESH_SHARD",
]

REASON_FULL_BUILD = "full-build"
REASON_JOURNAL_PATCH = "journal-patch"
REASON_DELTA_APPLY = "delta-apply"
REASON_FALLBACK = "fallback"
REASON_VERIFY = "verify"
REASON_MESH_SHARD = "mesh-shard"

#: leaves larger than this are size-counted but not fingerprinted —
#: crc32 runs ~0.5 GB/s, and the ledger must never turn a huge upload
#: into a hashing stall.  Far above every leaf of the 10k×50k headline
#: snapshot, so in practice everything is fingerprinted exactly.
_FINGERPRINT_LIMIT_BYTES = 64 * 1024 * 1024

_TOTAL_FIELDS = ("leaves", "bytes", "redundant_leaves",
                 "redundant_bytes", "dispatches",
                 "unfingerprinted_bytes")


def _fingerprint(leaf, limit: int) -> tuple | None:
    """Content fingerprint of a host array: full-buffer crc32 qualified
    by nbytes/dtype/shape (a crc collision alone cannot fake identity
    across different geometry).  None for non-numpy leaves and
    over-limit buffers — those are never counted redundant."""
    if not isinstance(leaf, np.ndarray) or leaf.nbytes > limit:
        return None
    arr = np.ascontiguousarray(leaf)
    if arr.nbytes == 0:
        crc = 0
    else:
        try:
            crc = zlib.crc32(memoryview(arr).cast("B"))
        except (TypeError, ValueError):
            # 0-d and zero-stride views refuse the flat cast
            crc = zlib.crc32(arr.tobytes())
    return (crc, int(arr.nbytes), str(arr.dtype), tuple(arr.shape))


def _leaf_doc(name: str, leaf, reason: str, site: str,
              redundant: bool) -> dict:
    shape = getattr(leaf, "shape", None)
    return {
        "leaf": name,
        "nbytes": int(getattr(leaf, "nbytes", 0)),
        "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
        "shape": list(shape) if shape is not None else [],
        "reason": reason,
        "site": site,
        "redundant": bool(redundant),
    }


class TransferLedger:
    """Per-cycle, per-leaf host→device upload accounting.

    One process-global instance (:data:`LEDGER`) serves the whole
    package: the ledger is a property of the *wire*, not of any one
    scheduler, so every dispatch in the process is on the books
    (including ``profile_cycle``'s synthetic cycles — exactly like the
    metrics registry).  Uploads between cycle rolls accumulate in an
    open window; :meth:`roll_cycle` closes the window into an immutable
    ring entry and returns the cycle summary.
    """

    def __init__(self, retain_cycles: int = 32,
                 max_events_per_cycle: int = 512,
                 fingerprint_limit_bytes: int = _FINGERPRINT_LIMIT_BYTES):
        self._lock = threading.Lock()
        #: immutable per-cycle documents, oldest first
        self._ring: list[dict] = []
        #: open-window bounded event docs (the per-cycle detail)
        self._window_events: list[dict] = []
        self._window_dropped = 0
        #: open-window aggregates by reason — kept separately from the
        #: bounded event list so dropped events still count their bytes
        self._window_totals: dict[str, dict] = {}
        self._window_peak = 0
        #: (site, leaf) -> (fingerprint, nbytes): the ledger-known
        #: device-resident set (last upload per leaf key)
        self._resident: dict[tuple[str, str], tuple] = {}
        self._resident_bytes = 0
        #: resident keys (re)uploaded in the open window — at roll
        #: time, resident bytes NOT in this set were *reused* on device
        #: without touching the wire (the kai-resident payoff gauge)
        self._window_uploaded_keys: set[tuple[str, str]] = set()
        #: cumulative accounted D2H readbacks (:meth:`device_get`) —
        #: kept separate from the upload ``by_reason`` totals so upload
        #: invariants (bytes == delta size) never absorb download bytes
        self._downloads: dict[str, dict] = {}
        self._window_downloads: dict[str, dict] = {}
        #: cumulative per-reason aggregates since process start
        self._totals: dict[str, dict] = {}
        #: ring/event bounds + fingerprint limit — immutable after init
        self._retain = max(1, int(retain_cycles))
        self.max_events_per_cycle = max(1, int(max_events_per_cycle))
        self.fingerprint_limit_bytes = int(fingerprint_limit_bytes)
        #: per-thread reason override (see :meth:`override_reason`);
        #: read-only binding after init
        self._local = threading.local()

    # -- recording ---------------------------------------------------------

    @contextlib.contextmanager
    def override_reason(self, reason: str):
        """Re-label transfers dispatched inside the block — the
        incremental snapshotter wraps ``build_snapshot`` with this so a
        full rebuild it *fell back* to is distinguishable from a
        deliberate one (and the verifier's reference rebuild from
        both)."""
        prev = getattr(self._local, "reason", None)
        self._local.reason = reason
        try:
            yield
        finally:
            self._local.reason = prev

    def device_put(self, tree, sharding=None, *, reason: str,
                   site: str = "snapshot", replace_site: bool = False,
                   leaf_names: list[str] | None = None,
                   transient: bool = False):
        """THE package choke point for ``jax.device_put`` (KAI071).

        Dispatches the whole ``tree`` in ONE ``jax.device_put`` call
        (per-leaf transfers cost a round trip each through a tunneled
        TPU — see ``cluster_state.py``) and records one event per leaf.
        ``sharding`` passes through untouched.  ``replace_site=True``
        declares the upload supersedes the site's entire resident set
        (a full snapshot rebuild drops the previous snapshot's
        buffers); the default accumulates (a patch replaces only the
        leaves it ships).  ``leaf_names`` overrides the derived
        ``jax.tree_util.keystr`` names — the batched patch path ships a
        ``{keystr: leaf}`` dict and passes the original names so
        redundancy tracking keys identically across full builds and
        patches.  Names must follow the tree's FLATTEN order (jax
        flattens dict keys SORTED, not in insertion order).

        ``transient=True`` marks a consumable upload — a buffer a
        donated dispatch eats in the same cycle (the kai-resident
        packed delta).  Transient leaves count toward bytes on the
        wire but are excluded from the device-residency gauge (they do
        not outlive the dispatch, and counting them would double-book
        the donated snapshot buffers they scatter into) and from the
        redundancy compare (delta segments may legitimately repeat
        content across cycles without any leaf being re-uploaded).
        """
        override = getattr(self._local, "reason", None)
        if override is not None:
            reason = override
        leaves_p, _ = jax.tree_util.tree_flatten_with_path(tree)
        if not leaves_p:
            return tree
        t0 = time.perf_counter()
        out = (jax.device_put(tree) if sharding is None
               else jax.device_put(tree, sharding))
        dispatch_s = time.perf_counter() - t0
        if leaf_names is not None and len(leaf_names) != len(leaves_p):
            raise ValueError(
                f"leaf_names has {len(leaf_names)} entries for "
                f"{len(leaves_p)} leaves")
        limit = self.fingerprint_limit_bytes
        staged = []  # (name, leaf, nbytes, fingerprint)
        for i, (path, leaf) in enumerate(leaves_p):
            name = (leaf_names[i] if leaf_names is not None
                    else jax.tree_util.keystr(path) or f"[{i}]")
            # transient (donated-consumable) uploads skip the content
            # fingerprint: they never enter the resident set or the
            # redundancy compare, so hashing them is pure overhead
            staged.append((name, leaf, int(getattr(leaf, "nbytes", 0)),
                           None if transient
                           else _fingerprint(leaf, limit)))
        agg = dict.fromkeys(_TOTAL_FIELDS, 0)
        agg["dispatches"] = 1
        with self._lock:
            # replace_site: leaves of this site NOT re-uploaded by this
            # dispatch are superseded and leave the resident set — but
            # only AFTER the per-leaf compares, so a full rebuild that
            # re-ships identical bytes is still caught red-handed (the
            # redundancy ROADMAP-1's device-resident rewrite deletes)
            stale = ({k for k in self._resident if k[0] == site}
                     if replace_site else None)
            for name, leaf, nbytes, fp in staged:
                key = (site, name)
                if stale is not None:
                    stale.discard(key)
                redundant = False
                if not transient:
                    prev = self._resident.get(key)
                    redundant = (fp is not None and prev is not None
                                 and prev[0] == fp)
                    self._resident_bytes += nbytes - (
                        prev[1] if prev is not None else 0)
                    self._resident[key] = (fp, nbytes)
                    self._window_uploaded_keys.add(key)
                agg["leaves"] += 1
                agg["bytes"] += nbytes
                if redundant:
                    agg["redundant_leaves"] += 1
                    agg["redundant_bytes"] += nbytes
                if fp is None and not transient:
                    agg["unfingerprinted_bytes"] += nbytes
                if len(self._window_events) < self.max_events_per_cycle:
                    self._window_events.append(
                        _leaf_doc(name, leaf, reason, site, redundant))
                else:
                    self._window_dropped += 1
            for key in sorted(stale or ()):
                self._resident_bytes -= self._resident.pop(key)[1]
                self._window_uploaded_keys.discard(key)
            self._window_peak = max(self._window_peak,
                                    self._resident_bytes)
            for dst in (self._window_totals.setdefault(
                            reason, dict.fromkeys(_TOTAL_FIELDS, 0)),
                        self._totals.setdefault(
                            reason, dict.fromkeys(_TOTAL_FIELDS, 0))):
                for field in _TOTAL_FIELDS:
                    dst[field] += agg[field]
            resident_bytes = self._resident_bytes
            resident_buffers = len(self._resident)
        self._export_metrics(reason, agg, resident_bytes,
                             resident_buffers, dispatch_s)
        return out

    def _export_metrics(self, reason, agg, resident_bytes,
                        resident_buffers, dispatch_s) -> None:
        """Mirror one dispatch into the ``kai_wire_*`` registry metrics
        (outside ``_lock``; each metric takes its own)."""
        try:
            # package-relative cycle-breaker: framework pulls this
            # module through state/cluster_state at import time, so the
            # registry import must stay lazy (same idiom as
            # runtime/profiling.py)
            from ..framework import metrics
        except Exception:  # noqa: BLE001 — a metrics mirror must never
            return         # fail a transfer (the ledger itself stands)
        metrics.wire_uploaded_bytes.inc(reason, by=float(agg["bytes"]))
        metrics.wire_uploaded_leaves.inc(reason, by=float(agg["leaves"]))
        metrics.wire_dispatches.inc(reason, by=float(agg["dispatches"]))
        metrics.wire_redundant_bytes.inc(
            reason, by=float(agg["redundant_bytes"]))
        metrics.wire_dispatch_seconds.inc(reason, by=float(dispatch_s))
        metrics.wire_resident_bytes.set(value=float(resident_bytes))
        metrics.wire_resident_buffers.set(value=float(resident_buffers))

    def device_get(self, tree, *, reason: str, site: str = "snapshot"):
        """Accounted batched device→host readback — the D2H counterpart
        of :meth:`device_put` for the few legitimate bulk gathers
        outside the packed commit (the kai-resident verify gather, the
        rare repack-plan readback on resident cycles).  One
        ``jax.device_get`` call for the whole tree; bytes are booked in
        a separate ``downloads`` ledger so upload invariants (patched
        bytes == delta size) never absorb readback traffic."""
        leaves = jax.tree_util.tree_leaves(tree)
        out = jax.device_get(tree)
        nbytes = sum(int(getattr(leaf, "nbytes", 0)) for leaf in leaves)
        with self._lock:
            for dst in (self._window_downloads, self._downloads):
                t = dst.setdefault(reason, {"leaves": 0, "bytes": 0,
                                            "dispatches": 0})
                t["leaves"] += len(leaves)
                t["bytes"] += nbytes
                t["dispatches"] += 1
        try:
            from ..framework import metrics  # package-relative, lazy
        except Exception:  # noqa: BLE001 — mirror must never fail a read
            return out
        metrics.wire_downloaded_bytes.inc(reason, by=float(nbytes))
        return out

    def roll_cycle(self, cycle_id: int) -> dict:
        """Close the open window into an immutable ring entry and
        return the cycle summary (``CycleResult.wire``).  Called by the
        cycle driver at the end of every ``run_once``; uploads from
        harnesses that never roll (bench refreshes, CLIs) simply land
        in the next rolled window."""
        with self._lock:
            by_reason = {r: dict(t)
                         for r, t in sorted(self._window_totals.items())}
            events = tuple(self._window_events)
            dropped = self._window_dropped
            peak = max(self._window_peak, self._resident_bytes)
            # kai-resident payoff gauge: resident bytes that stayed on
            # device this cycle without touching the wire, vs bytes
            # actually uploaded.  A steady resident cycle reads
            # reused ≈ snapshot size, uploaded ≈ packed delta size.
            reused = sum(
                ent[1] for key, ent in self._resident.items()
                if key not in self._window_uploaded_keys)
            downloads = {r: dict(t) for r, t
                         in sorted(self._window_downloads.items())}
            self._window_events = []
            self._window_dropped = 0
            self._window_totals = {}
            self._window_downloads = {}
            self._window_uploaded_keys = set()
            self._window_peak = self._resident_bytes
            resident_bytes = self._resident_bytes
            resident_buffers = len(self._resident)
            summary = {
                "cycle": int(cycle_id),
                "by_reason": by_reason,
                "dropped": dropped,
                "resident_bytes": resident_bytes,
                "resident_buffers": resident_buffers,
                "peak_resident_bytes": peak,
                "resident_reused_bytes": reused,
                "downloads": downloads,
            }
            for field in _TOTAL_FIELDS:
                summary[field] = sum(t[field] for t in by_reason.values())
            summary["resident_uploaded_bytes"] = summary["bytes"]
            entry = dict(summary)
            entry["events"] = events
            self._ring.append(entry)
            del self._ring[:-self._retain]
        self._export_cycle_metrics(summary)
        return summary

    def _export_cycle_metrics(self, summary) -> None:
        try:
            from ..framework import metrics  # package-relative, lazy
        except Exception:  # noqa: BLE001
            return
        metrics.wire_cycle_uploaded_bytes.observe(
            value=float(summary["bytes"]))
        # kai-resident: reused-on-device vs uploaded bytes per cycle —
        # the gauge pair ROADMAP-1's acceptance bar reads (reused ≈
        # snapshot size, uploaded ≈ packed delta size in steady state)
        metrics.wire_resident_reused_bytes.set(
            value=float(summary["resident_reused_bytes"]))
        metrics.wire_resident_uploaded_bytes.set(
            value=float(summary["resident_uploaded_bytes"]))

    # -- reading -----------------------------------------------------------

    def totals(self) -> dict:
        """Cumulative per-reason aggregates since process start — the
        bench's wire-bytes-per-cycle columns are deltas of this."""
        with self._lock:
            return {"by_reason": {r: dict(t) for r, t
                                  in sorted(self._totals.items())},
                    "downloads_by_reason": {
                        r: dict(t)
                        for r, t in sorted(self._downloads.items())},
                    "resident_bytes": self._resident_bytes,
                    "resident_buffers": len(self._resident)}

    def residency(self) -> dict:
        with self._lock:
            return {"buffers": len(self._resident),
                    "bytes": self._resident_bytes,
                    "peak_bytes": max(self._window_peak,
                                      self._resident_bytes)}

    def last(self, n: int = 1) -> list[dict]:
        """The most recent ``n`` rolled cycle documents, oldest first
        (immutable — events are tuples of per-leaf docs)."""
        with self._lock:
            return list(self._ring[-max(1, n):])

    def wire_doc(self, cycles: int | None = None) -> dict:
        """The ``GET /debug/wire`` document: rolled cycle ring (bounded
        by ``?cycles=``), the open window's partial aggregates, the
        residency gauge, and cumulative totals.  Ring entries are
        immutable once rolled, so the document can never tear."""
        with self._lock:
            ring = list(self._ring if cycles is None
                        else self._ring[-max(1, cycles):])
            window = {
                "by_reason": {r: dict(t) for r, t
                              in sorted(self._window_totals.items())},
                "events": len(self._window_events),
                "dropped": self._window_dropped,
            }
            residency = {"buffers": len(self._resident),
                         "bytes": self._resident_bytes,
                         "peak_bytes": max(self._window_peak,
                                           self._resident_bytes)}
            totals = {r: dict(t) for r, t in sorted(self._totals.items())}
            downloads = {r: dict(t)
                         for r, t in sorted(self._downloads.items())}
        return {
            "cycles": [dict(c, events=list(c["events"])) for c in ring],
            "window": window,
            "residency": residency,
            "totals": {"by_reason": totals,
                       "downloads_by_reason": downloads},
        }


#: the process-global ledger every package ``device_put`` flows through
LEDGER = TransferLedger()

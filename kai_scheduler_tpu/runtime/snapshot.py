"""Whole-cluster snapshot to JSON + deterministic replay.

The reference's main production-debugging artifact: the snapshot plugin
serializes every raw object the scheduler sees to zipped JSON
(``plugins/snapshot/snapshot.go:40-60``), and ``cmd/snapshot-tool``
(``main.go:30-90``) loads it into fake clients and re-runs a full
scheduling cycle offline.  Here the cluster hub IS the object store, so
the snapshot is a JSON rendering of it plus the scheduler config; replay
builds a fresh ``Cluster`` and runs ``Scheduler.run_once``.  Replaying
the same snapshot twice yields byte-identical commit sets (the kernels
are deterministic functions of the snapshot).
"""
from __future__ import annotations

import dataclasses
import enum
import gzip
import json
from typing import Any

from ..apis import types as apis
from .cluster import Cluster

SNAPSHOT_VERSION = 1


def _to_jsonable(obj: Any) -> Any:
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_to_jsonable(v) for v in obj]
    return obj


def _resource_vec(d: dict) -> apis.ResourceVec:
    return apis.ResourceVec(**d)


def _queue_resource(d: dict) -> apis.QueueResource:
    return apis.QueueResource(**d)


def _queue(d: dict) -> apis.Queue:
    d = dict(d)
    for k in ("accel", "cpu", "memory"):
        d[k] = _queue_resource(d[k])
    return apis.Queue(**d)


def _taint(d: dict) -> apis.Taint:
    return apis.Taint(**d)


def _node(d: dict) -> apis.Node:
    d = dict(d)
    d["allocatable"] = _resource_vec(d["allocatable"])
    d["taints"] = [_taint(t) for t in d.get("taints", [])]
    return apis.Node(**d)


def _topology_constraint(d: dict | None) -> apis.TopologyConstraint | None:
    return None if d is None else apis.TopologyConstraint(**d)


def _sub_group(d: dict) -> apis.SubGroup:
    d = dict(d)
    d["topology_constraint"] = _topology_constraint(
        d.get("topology_constraint"))
    return apis.SubGroup(**d)


def _pod_group(d: dict) -> apis.PodGroup:
    d = dict(d)
    d["preemptibility"] = apis.Preemptibility(d["preemptibility"])
    d["phase"] = apis.PodGroupPhase(d["phase"])
    d["topology_constraint"] = _topology_constraint(
        d.get("topology_constraint"))
    d["sub_groups"] = [_sub_group(s) for s in d.get("sub_groups", [])]
    return apis.PodGroup(**d)


def _pod(d: dict) -> apis.Pod:
    d = dict(d)
    d["resources"] = _resource_vec(d["resources"])
    d["status"] = apis.PodStatus(d["status"])
    d["tolerations"] = [apis.Toleration(**t)
                        for t in d.get("tolerations", [])]
    d["node_affinity"] = [
        apis.AffinityExpr(key=e["key"], operator=e["operator"],
                          values=tuple(e.get("values", ())))
        for e in d.get("node_affinity", [])]
    d["pod_affinity"] = [
        apis.PodAffinityTerm(
            match_labels=tuple(tuple(kv) for kv in t.get("match_labels", ())),
            topology_key=t.get("topology_key", "kubernetes.io/hostname"),
            anti=t.get("anti", False), required=t.get("required", True))
        for t in d.get("pod_affinity", [])]
    return apis.Pod(**d)


def _bind_request(d: dict) -> apis.BindRequest:
    d = dict(d)
    d["received_resource_type"] = apis.ReceivedResourceType(
        d["received_resource_type"])
    return apis.BindRequest(**d)


def dump_cluster(cluster: Cluster) -> dict:
    """Cluster → JSON-ready dict (the RawKubernetesObjects analogue)."""
    return {
        "version": SNAPSHOT_VERSION,
        "now": cluster.now,
        "nodes": [_to_jsonable(n) for n in cluster.nodes.values()],
        "queues": [_to_jsonable(q) for q in cluster.queues.values()],
        "pod_groups": [_to_jsonable(g) for g in cluster.pod_groups.values()],
        "pods": [_to_jsonable(p) for p in cluster.pods.values()],
        "topology": ([_to_jsonable(t) for t in cluster.topology]
                     if isinstance(cluster.topology, list)
                     else _to_jsonable(cluster.topology)),
        "bind_requests": [_to_jsonable(b)
                          for b in cluster.bind_requests.values()],
        "resource_claims": [_to_jsonable(c)
                            for c in cluster.resource_claims.values()],
        "device_classes": [_to_jsonable(c)
                           for c in cluster.device_classes.values()],
        "volume_claims": [_to_jsonable(c)
                          for c in cluster.volume_claims.values()],
        "storage_classes": [_to_jsonable(c)
                            for c in cluster.storage_classes.values()],
        "restarting": sorted(cluster.restarting),
    }


def load_cluster(doc: dict) -> Cluster:
    """Inverse of :func:`dump_cluster`."""
    if doc.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot version {doc.get('version')}")
    raw_topo = doc.get("topology")
    if isinstance(raw_topo, list):
        topo = [apis.Topology(**t) for t in raw_topo]
    else:
        topo = apis.Topology(**raw_topo) if raw_topo else None
    cluster = Cluster.from_objects(
        [_node(d) for d in doc["nodes"]],
        [_queue(d) for d in doc["queues"]],
        [_pod_group(d) for d in doc["pod_groups"]],
        [_pod(d) for d in doc["pods"]],
        topo)
    cluster.now = doc.get("now", 0.0)
    for d in doc.get("bind_requests", []):
        br = _bind_request(d)
        cluster.bind_requests[br.pod_name] = br
    for d in doc.get("resource_claims", []):
        claim = apis.ResourceClaim(**d)
        cluster.resource_claims[claim.name] = claim
    for d in doc.get("device_classes", []):
        dc = apis.DeviceClass(**d)
        cluster.device_classes[dc.name] = dc
    for d in doc.get("volume_claims", []):
        pvc = apis.PersistentVolumeClaim(**d)
        cluster.volume_claims[pvc.name] = pvc
    for d in doc.get("storage_classes", []):
        sc = apis.StorageClass(**d)
        cluster.storage_classes[sc.name] = sc
    cluster.restarting = set(doc.get("restarting", []))
    rebuild_reservations(cluster)
    return cluster


def rebuild_reservations(cluster: Cluster) -> None:
    """Rebuild the shared-device reservation registry from bound
    fractional pods — reservations are derived state (the reference
    reconciles reservation pods from the cluster the same way), so
    every wire ingest (JSON snapshot or proto ClusterDoc) reconstructs
    them rather than serializing them."""
    for pod in cluster.pods.values():
        if (pod.node and pod.accel_devices
                and (pod.accel_portion > 0 or pod.accel_memory_gib > 0)
                and pod.status in (apis.PodStatus.BOUND,
                                   apis.PodStatus.RUNNING,
                                   apis.PodStatus.RELEASING)):
            cluster.reservations.acquire(pod.node, pod.accel_devices[0],
                                         pod.name)


def save(cluster: Cluster, path: str) -> None:
    """Write a (gzipped, like the reference's zip) snapshot file."""
    data = json.dumps(dump_cluster(cluster), sort_keys=True).encode()
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(data)


def load(path: str) -> Cluster:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return load_cluster(json.loads(f.read().decode()))

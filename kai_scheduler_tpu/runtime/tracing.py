"""kai-trace — the cycle flight recorder.

The reference treats observability as a first-class layer (per-action /
per-plugin latency metrics, pod events explaining unschedulability,
continuous profiles).  This module is the span half of that story for
the TPU rebuild: a thread-safe recorder of *phase-attributed spans*
over the scheduling cycle, kept in a bounded ring of recent cycle
traces and exportable as Chrome-trace ("Trace Event Format") JSON —
loadable in ``chrome://tracing`` / Perfetto — via ``GET /debug/trace``
on the :class:`~..framework.server.SchedulerServer`.

Why spans and not three wall timers: kernels dispatch *async*, so a
naive per-step timer smears device execution, transfer wait, and host
decode into whichever step first blocks (historically all of it landed
in ``commit_seconds``).  The cycle driver therefore records explicit
**device-sync markers** (``device_sync=True`` spans) around the first
blocking transfer, splitting the old commit wall into
``device_wait`` / ``host_decode`` / ``commit`` — the attribution
ROADMAP item 1 (breaking the ~109 ms host↔device link floor) needs
before any of that floor can be attacked.

Concurrency model: span recording is **thread-local** — each thread
owns the trace of the cycle it is running (an open trace is reachable
only through ``threading.local``, so no other thread can observe a
half-built span tree).  A trace enters the shared ring only once the
cycle closes, and ring entries are never mutated afterwards; ring
append/read is serialized under ``_lock`` (discipline declared in
``analysis/guarded_by.json``, checked by kai-race).  Exports therefore
can never serve a torn document.

Tracer calls are HOST-side by construction: kai-lint rule ``KAI061``
forbids them inside the jit-traced region (a span body executes at
trace time, not at kernel run time — it would record compilation, not
execution, and its timestamps would be garbage).
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

__all__ = ["Span", "CycleTrace", "CycleTracer"]

#: attr value types exported verbatim; anything else is stringified
_JSONABLE = (str, int, float, bool, type(None))


@dataclasses.dataclass
class Span:
    """One timed region of a cycle.

    ``start``/``end`` are ``time.perf_counter`` seconds (monotonic);
    ``children`` are strictly nested inside ``[start, end]`` by
    construction (context-manager discipline).
    """

    name: str
    start: float
    end: float = 0.0
    attrs: dict = dataclasses.field(default_factory=dict)
    children: list = dataclasses.field(default_factory=list)
    #: an explicit device-sync marker: this span brackets a blocking
    #: device→host (or host→device) boundary, so its duration is link +
    #: device time, not host work
    device_sync: bool = False

    @property
    def seconds(self) -> float:
        return max(0.0, self.end - self.start)


@dataclasses.dataclass
class CycleTrace:
    """One completed cycle's span tree — immutable once ringed."""

    cycle_id: int
    #: unix epoch at cycle start — anchors perf_counter offsets so
    #: multiple cycles export onto one consistent timeline
    wall_start: float
    #: the root "cycle" span; the phase spans are its children
    root: Span
    #: ``(name, {series: value})`` samples appended before the cycle
    #: closes — exported as Chrome "C" (counter) events at the cycle's
    #: start timestamp, so per-cycle scalars (kai-wire bytes-on-wire,
    #: device-resident bytes) render as step charts aligned with the
    #: phase lanes
    counters: list = dataclasses.field(default_factory=list)

    def phase_seconds(self) -> dict[str, float]:
        """Top-level (phase) span durations by name.

        Direct children named ``upload`` are promoted to their own
        phase and subtracted from their parent — matching the cycle
        driver's ``CycleResult.phase_seconds`` convention, where the
        snapshotter's transfer-dispatch section is carved out of the
        ``snapshot`` phase.  Without the promotion the trace's
        ``snapshot`` number would disagree with the metric/healthz/
        bench surfaces by exactly the upload duration.
        """
        out: dict[str, float] = {}
        for sp in self.root.children:
            secs = sp.seconds
            up = sum(c.seconds for c in sp.children
                     if c.name == "upload")
            if up:
                out["upload"] = out.get("upload", 0.0) + up
                secs = max(0.0, secs - up)
            out[sp.name] = out.get(sp.name, 0.0) + secs
        return out


def _clean_attrs(attrs: dict, extra: dict | None = None) -> dict:
    out = {}
    for k, v in attrs.items():
        out[str(k)] = v if isinstance(v, _JSONABLE) else str(v)
    if extra:
        out.update(extra)
    return out


def _emit_span(events: list, sp: Span, origin_us: float, root_start: float,
               tid: int) -> None:
    """Append one span (and, recursively, its children) as a Chrome
    "X" (complete) event.  ``origin_us`` maps this trace's
    ``perf_counter`` timeline onto the shared wall-anchored export
    timeline."""
    extra = {"device_sync": True} if sp.device_sync else None
    events.append({
        "name": sp.name, "ph": "X", "pid": 0, "tid": tid,
        "ts": round(origin_us + (sp.start - root_start) * 1e6, 3),
        "dur": round(sp.seconds * 1e6, 3),
        "args": _clean_attrs(sp.attrs, extra),
    })
    for child in sp.children:
        _emit_span(events, child, origin_us, root_start, tid)


class CycleTracer:
    """Thread-safe cycle span recorder with a bounded trace ring.

    Recording API (all host-side; never call from jit-traced code —
    KAI061)::

        with tracer.cycle() as trace:            # one scheduling cycle
            with tracer.span("snapshot") as sp:  # a phase
                ...
                sp.attrs["mode"] = "patched"
            with tracer.span("device_wait", device_sync=True):
                host = gather()                  # the blocking transfer
        trace.phase_seconds()                    # {"snapshot": ..., ...}

    ``span`` outside an open cycle records nothing (it yields a
    detached dummy span), so instrumented helpers — e.g. the
    incremental snapshotter's upload section — stay callable from
    benches and CLIs that never open a cycle.
    """

    def __init__(self, retain_cycles: int = 16):
        self._lock = threading.Lock()
        self._ring: list[CycleTrace] = []  # kai-race: guarded-by=_lock
        self._cycle_seq = 0  # kai-race: guarded-by=_lock
        #: ring bound — immutable after construction
        self._retain = max(1, int(retain_cycles))
        #: per-thread open-span stack (an open trace is visible only to
        #: the thread recording it; read-only binding after init)
        self._local = threading.local()

    # -- recording --------------------------------------------------------

    @contextlib.contextmanager
    def cycle(self, **attrs):
        """Record one cycle; the trace enters the ring when the block
        exits (never before, so readers cannot observe a live tree)."""
        with self._lock:
            cid = self._cycle_seq
            self._cycle_seq += 1
        root = Span(name="cycle", start=time.perf_counter(),
                    attrs=_clean_attrs(attrs))
        trace = CycleTrace(cycle_id=cid, wall_start=time.time(), root=root)
        prev = getattr(self._local, "stack", None)
        self._local.stack = [root]
        try:
            yield trace
        finally:
            root.end = time.perf_counter()
            self._local.stack = prev
            with self._lock:
                self._ring.append(trace)
                del self._ring[:-self._retain]

    @contextlib.contextmanager
    def span(self, name: str, *, device_sync: bool = False, **attrs):
        stack = getattr(self._local, "stack", None)
        if not stack:
            # no open cycle on this thread: detached spans record
            # nothing (the dummy keeps `sp.attrs[...] = ...` callers
            # working unconditionally)
            yield Span(name=name, start=0.0, attrs=_clean_attrs(attrs),
                       device_sync=device_sync)
            return
        sp = Span(name=name, start=time.perf_counter(),
                  attrs=_clean_attrs(attrs), device_sync=device_sync)
        stack[-1].children.append(sp)
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = time.perf_counter()
            stack.pop()

    def add_span(self, name: str, start: float, end: float,
                 *, device_sync: bool = False, **attrs) -> None:
        """Attach an already-timed span (``perf_counter`` seconds) as a
        child of the currently open span — for sections timed inside
        helpers that cannot hold a context manager open (e.g. the
        snapshotter's upload loop).  No-op without an open cycle."""
        stack = getattr(self._local, "stack", None)
        if not stack:
            return
        stack[-1].children.append(Span(
            name=name, start=start, end=end, attrs=_clean_attrs(attrs),
            device_sync=device_sync))

    # -- reading ----------------------------------------------------------

    def last(self, n: int = 1) -> list[CycleTrace]:
        """The most recent ``n`` completed cycle traces, oldest first."""
        with self._lock:
            return list(self._ring[-max(1, n):])

    def export_chrome(self, cycles: int | None = None) -> dict:
        """The retained ring (or the last ``cycles``) as a Chrome-trace
        JSON document: ``{"traceEvents": [...]}`` with "X" complete
        events, one ``tid`` lane per cycle so concurrent recorders can
        never interleave into a partially-overlapping (non-nested)
        lane."""
        with self._lock:
            traces = list(self._ring if cycles is None
                          else self._ring[-max(1, cycles):])
        events: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": 0, "tid": 0,
            "args": {"name": "kai-scheduler"},
        }]
        if traces:
            epoch = min(t.wall_start for t in traces)
            for t in traces:
                tid = t.cycle_id
                events.append({
                    "ph": "M", "name": "thread_name", "pid": 0,
                    "tid": tid, "args": {"name": f"cycle-{t.cycle_id}"},
                })
                origin_us = (t.wall_start - epoch) * 1e6
                _emit_span(events, t.root, origin_us, t.root.start, tid)
                for cname, values in t.counters:
                    events.append({
                        "ph": "C", "name": str(cname), "pid": 0,
                        "tid": tid, "ts": round(origin_us, 3),
                        "args": _clean_attrs(dict(values)),
                    })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

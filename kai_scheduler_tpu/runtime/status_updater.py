"""Async status updater — batched writes off the cycle path.

Reference: ``pkg/scheduler/cache/status_updater`` — PodGroup/pod
condition and event writes go through a bounded worker pool
(``status_updater/concurrency.go``, ``NumOfStatusRecordingWorkers``
default 5) so a slow API server cannot stall the scheduling cycle; the
cycle only ENQUEUES updates.

Here the writer is any callable (the in-process ``Cluster`` mutation, or
a real API client in a deployment); the updater owns the queue and the
workers.  Updates for the same key coalesce (``inFlightPodGroups``
semantics: a newer status for a pod group supersedes a queued one).
"""
from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable

#: ref NumOfStatusRecordingWorkers (cache/cache.go), default 5
DEFAULT_WORKERS = 5


@dataclasses.dataclass
class StatusUpdate:
    """One queued write: ``key`` coalesces (latest wins), ``apply`` runs
    on a worker."""

    key: str
    apply: Callable[[], Any]


class AsyncStatusUpdater:
    """Worker-pool status writer (``defaultStatusUpdater`` analogue)."""

    def __init__(self, workers: int = DEFAULT_WORKERS):
        self._queue: "queue.Queue[str | None]" = queue.Queue()
        self._latest: dict[str, StatusUpdate] = {}
        self._lock = threading.Lock()
        #: serializes ``apply()`` across the worker pool, so two workers
        #: never interleave writes to one object.  The CYCLE thread does
        #: NOT take this lock (a slow store must never stall the cycle):
        #: snapshot-vs-apply tearing is instead prevented by the write
        #: ORDERING inside the apply closures — every GIL-atomic prefix
        #: a racing snapshot can observe is a conservative state (see
        #: ``Scheduler._record_fit_status``).
        self.apply_lock = threading.Lock()
        self._inflight = 0
        self._applied = 0
        self._errors = 0
        self._stopped = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(max(1, workers))]
        for t in self._threads:
            t.start()

    # -- cycle side (non-blocking) ---------------------------------------

    def enqueue(self, key: str, apply: Callable[[], Any]) -> None:
        """Queue a write; a queued-but-unapplied write for the same key
        is superseded (the reference keeps one in-flight record per pod
        group)."""
        with self._lock:
            fresh = key not in self._latest
            self._latest[key] = StatusUpdate(key, apply)
        if fresh:
            self._queue.put(key)

    @property
    def applied(self) -> int:
        with self._lock:
            return self._applied

    @property
    def errors(self) -> int:
        with self._lock:
            return self._errors

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._latest)

    # -- worker side ------------------------------------------------------

    def _worker(self) -> None:
        while True:
            key = self._queue.get()
            if key is None:
                return
            with self._lock:
                update = self._latest.pop(key, None)
                if update is not None:
                    self._inflight += 1
            if update is None:
                continue
            try:
                with self.apply_lock:
                    update.apply()
                with self._lock:  # workers race each other on the counters
                    self._applied += 1
            except Exception:  # noqa: BLE001 — a failed write never
                with self._lock:  # stalls the pool (reference logs+drops)
                    self._errors += 1
            finally:
                with self._lock:
                    self._inflight -= 1

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for the queue AND in-flight applies to drain."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                drained = not self._latest and self._inflight == 0
            if drained and self._queue.empty():
                return True
            time.sleep(0.005)
        return False

    def stop(self) -> None:
        self._stopped = True
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=5)

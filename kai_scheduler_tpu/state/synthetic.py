"""Synthetic cluster generators — the test & benchmark harness.

Mirrors the role of the reference's fake-cluster builders
(``pkg/scheduler/test_utils/test_utils.go:40-70`` TestTopologyBasic with
``jobs_fake/``, ``nodes_fake/``; and the benchmark sizes in
``pkg/scheduler/actions/benchmark_test.go:30-121``), plus the five
benchmark configs from ``BASELINE.json``.
"""
from __future__ import annotations

import numpy as np

from ..apis import types as apis


def make_cluster(
    *,
    num_nodes: int = 16,
    node_accel: float = 8.0,
    node_cpu: float = 64.0,
    node_mem: float = 256.0,
    num_departments: int = 2,
    queues_per_department: int = 2,
    queue_accel_quota: float | None = None,
    num_gangs: int = 8,
    tasks_per_gang: int = 2,
    task_accel: float = 1.0,
    task_cpu: float = 1.0,
    task_mem: float = 4.0,
    running_fraction: float = 0.0,
    #: running gangs take the first half of the leaf queues, pending the
    #: second half — creates over-quota victims vs under-share
    #: reclaimers (the reclaim benchmark shape)
    partition_queues_by_running: bool = False,
    priority_spread: int = 1,
    #: added to every PENDING gang's priority — makes each pending gang
    #: outrank the running gangs of its own queue (the many-queue
    #: preempt shape)
    pending_priority_boost: int = 0,
    topology_levels: tuple[int, ...] = (),
    required_level: str | None = None,
    seed: int = 0,
) -> tuple[list[apis.Node], list[apis.Queue], list[apis.PodGroup], list[apis.Pod], apis.Topology | None]:
    """Build a synthetic cluster.

    ``topology_levels``: sizes of physical domains outermost-first, e.g.
    ``(4, 8)`` = 4 blocks x 8 racks each; hostname level appended
    automatically.  ``running_fraction`` of gangs start as running
    (round-robin over nodes) — victims for reclaim/preempt tests.
    """
    rng = np.random.default_rng(seed)

    topology = None
    level_keys: list[str] = []
    if topology_levels:
        level_keys = [f"topo/level{i}" for i in range(len(topology_levels))]
        topology = apis.Topology(
            name="default", levels=level_keys + ["kubernetes.io/hostname"])

    nodes = []
    for i in range(num_nodes):
        labels = {"kubernetes.io/hostname": f"node-{i}"}
        if topology_levels:
            # nest nodes into the domain tree by index arithmetic
            span = num_nodes
            idx = i
            for key, size in zip(level_keys, topology_levels):
                span = max(1, span // size)
                labels[key] = f"{key.split('/')[-1]}-{idx // span}"
                idx = idx % span
        nodes.append(apis.Node(
            name=f"node-{i}",
            allocatable=apis.ResourceVec(node_accel, node_cpu, node_mem),
            labels=labels,
        ))

    total_accel = num_nodes * node_accel
    num_queues = num_departments * queues_per_department
    if queue_accel_quota is None:
        queue_accel_quota = total_accel / max(1, num_queues)
    queues = []
    for d in range(num_departments):
        queues.append(apis.Queue(
            name=f"dept-{d}",
            accel=apis.QueueResource(quota=queue_accel_quota * queues_per_department),
            creation_timestamp=float(d),
        ))
    for d in range(num_departments):
        for j in range(queues_per_department):
            queues.append(apis.Queue(
                name=f"queue-{d}-{j}",
                parent=f"dept-{d}",
                accel=apis.QueueResource(quota=queue_accel_quota),
                creation_timestamp=float(d * queues_per_department + j),
            ))
    leaf_queues = [q.name for q in queues if q.parent is not None]

    pod_groups: list[apis.PodGroup] = []
    pods: list[apis.Pod] = []
    num_running = int(num_gangs * running_fraction)
    node_cursor = 0
    for g in range(num_gangs):
        running = g < num_running
        if partition_queues_by_running and len(leaf_queues) >= 2:
            half = len(leaf_queues) // 2
            pool = leaf_queues[:half] if running else leaf_queues[half:]
            queue = pool[g % len(pool)]
        else:
            queue = leaf_queues[g % len(leaf_queues)]
        pg = apis.PodGroup(
            name=f"gang-{g}",
            queue=queue,
            min_member=tasks_per_gang,
            priority=int(rng.integers(0, priority_spread))
            + (0 if running else pending_priority_boost),
            creation_timestamp=float(g),
            last_start_timestamp=0.0 if running else None,
            topology_constraint=(
                apis.TopologyConstraint(topology="default",
                                        required_level=required_level)
                if required_level else None),
        )
        pod_groups.append(pg)
        for t in range(tasks_per_gang):
            pod = apis.Pod(
                name=f"gang-{g}-pod-{t}",
                group=pg.name,
                resources=apis.ResourceVec(task_accel, task_cpu, task_mem),
                creation_timestamp=float(g),
            )
            if running:
                pod.status = apis.PodStatus.RUNNING
                pod.node = nodes[node_cursor % num_nodes].name
                node_cursor += 1
            pods.append(pod)
    return nodes, queues, pod_groups, pods, topology

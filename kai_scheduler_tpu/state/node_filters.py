"""Node-filter classes: the wide predicate surface, host-evaluated.

The reference filters each (pod, node) pair through the upstream
kube-scheduler plugins — TaintToleration, NodeAffinity, InterPodAffinity
(``k8s_internal/predicates/predicates.go:70-140``) — an irregular,
string-matching computation that has no good dense-tensor form.  The
TPU-native design exploits the same redundancy the reference's
scheduling-signature skip list does (``actions/common/
minimal_job_comparison.go``): pods overwhelmingly share identical filter
specs (one pod template per gang), so the *distinct* specs form a small
vocabulary.  At snapshot build each distinct spec is evaluated against
every node ONCE on the host, yielding

- ``filter_masks``  bool [X, N] — hard feasibility per (spec, node)
- ``soft_scores``   f32  [X, N] — the soft bands (PreferNoSchedule taint
  penalty + preferred pod-affinity), pre-weighted into the K8sPlugins
  score band (``plugins/scores/scores.go`` K8sPlugins = 1e5)

and every task carries its spec's class id.  The device kernels then pay
ONE gather per task instead of re-running string matches per node —
irregular logic runs once per distinct spec, regular lookup runs on the
accelerator.

Class 0 is always the empty spec (no tolerations, no affinity): its mask
still excludes nodes with untolerated hard taints, which is what keeps
plain pods off control-plane/maintenance nodes.

IN-CYCLE AFFINITY SEMANTICS: required (anti-)affinity vs RUNNING pods
is evaluated here at snapshot build — BOTH directions: the incoming
pod's own terms against running pods, and running pods' required anti
terms against the incoming pod's labels (upstream InterPodAffinity's
existing-pod check), the latter via the ``reverse_labels`` component
of the spec key.  Required anti-affinity BETWEEN gangs placed in the
SAME cycle — mutual ("one db per node/rack"), asymmetric (only one
side carries the term; forward and reverse), and NodePorts conflicts
between two pending pods — is enforced in-cycle through the
exclusion-term rows the snapshot emits (``GangState.anti_marks`` /
``anti_avoids``) and the cycle's claimed-domain table
(``AllocationResult.anti_used``), which ALL placement actions honour:
the allocate wavefront and the victim actions' placements alike (see
``AllocateConfig.anti_groups``).  The slot dimension is sized from the
snapshot (every distinct term row gets a slot — see ``ANTI_SLOTS``),
so no exclusion term is ever dropped.  Required POSITIVE affinity
toward a gang placed in the same cycle is enforced through ATTRACTION
rows in the same table (``GangState.attract_needs``): the depender's
static fold is lifted and it may only place into domains a running
match or an in-cycle anchor claimed (``AllocateConfig.attract_groups``),
so anchor + depender arriving in one cycle co-land.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..apis import types as apis

#: score-band ceiling and weight (ref plugins/scores/scores.go)
_MAX_BAND = 9.0
_W_K8S = 100_000.0

_HARD_EFFECTS = ("NoSchedule", "NoExecute")


def pod_filter_spec(pod: apis.Pod, dra: tuple = (),
                    volume: tuple = (),
                    reverse_labels: tuple = ()) -> tuple:
    """Canonical hashable key of a pod's node-filter spec.

    ``dra`` carries the pod's resolved DeviceClass constraints —
    ``(min_memory_gib, ((label, value), ...))`` — and ``volume`` its
    resolved VolumeBinding label constraints (bound-PVC node affinity ∪
    unbound classes' allowedTopologies), so DRA and storage node
    selection (ref ``plugins/dynamicresources`` and the VolumeBinding
    predicate) ride the same vocabulary.  ``host_ports`` feed the
    NodePorts predicate.  ``reverse_labels`` is the pod's label subset
    that any RUNNING pod's required anti-affinity selector could match
    (upstream InterPodAffinity also enforces EXISTING pods' anti terms
    against the incoming pod — the "reverse" direction); restricting to
    the keys those selectors mention keeps the vocabulary small.
    """
    aff = tuple(sorted(
        (e.key, e.operator, tuple(e.values)) for e in pod.node_affinity))
    tol = tuple(sorted(
        (t.key or "", t.operator, t.value, t.effect or "")
        for t in pod.tolerations))
    pa = tuple(sorted(
        (term.match_labels, term.topology_key, term.anti, term.required)
        for term in pod.pod_affinity))
    return (aff, tol, pa, dra, volume, tuple(sorted(pod.host_ports)),
            reverse_labels)


EMPTY_SPEC = ((), (), (), (), (), (), ())


@dataclasses.dataclass
class _RunningPodView:
    """What pod-affinity / NodePorts terms need to know about existing
    pods."""

    labels: dict[str, str]
    node: int  # snapshot node index, -1 unknown
    host_ports: tuple = ()
    #: the pod's REQUIRED ANTI terms as (match_labels, topology_key) —
    #: enforced in reverse against incoming pods (upstream
    #: InterPodAffinity's existing-pod anti-affinity check)
    anti_terms: tuple = ()


def reverse_anti_keys(running_pods) -> frozenset:
    """Label KEYS mentioned by any running pod's required anti-affinity
    selector — the subset of an incoming pod's labels that can decide
    the reverse InterPodAffinity check (everything else is irrelevant,
    which keeps the filter-class vocabulary from growing per pod)."""
    keys: set[str] = set()
    for pod in running_pods:
        for term in pod.pod_affinity:
            if term.required and term.anti:
                keys.update(k for k, _ in term.match_labels)
    return frozenset(keys)


def _domain_ids(node_topo: np.ndarray, topo_levels: list[str],
                topology_key: str, num_nodes: int) -> np.ndarray:
    """i32 [N]: the domain each node belongs to at ``topology_key``'s
    level; unknown keys mean per-node (hostname) granularity."""
    if topology_key in topo_levels:
        return node_topo[:num_nodes, topo_levels.index(topology_key)]
    return np.arange(num_nodes, dtype=np.int32)


def evaluate_filter_classes(
    specs: list[tuple],
    pods_by_spec: dict[tuple, apis.Pod],
    live_nodes: list[apis.Node],
    node_topo: np.ndarray,          # i32 [N_padded, L]
    topo_levels: list[str],
    running: list[_RunningPodView],
    num_nodes_padded: int,
    incycle_pos_terms: frozenset = frozenset(),
) -> tuple[np.ndarray, np.ndarray]:
    """Evaluate every distinct spec against every node.

    Returns (filter_masks bool [X, N_padded], soft_scores f32
    [X, N_padded]); padded node slots are masked False.
    """
    X = len(specs)
    N = len(live_nodes)
    masks = np.zeros((X, num_nodes_padded), bool)
    soft = np.zeros((X, num_nodes_padded), np.float32)
    # host-port occupancy per node (NodePorts input), built once
    used_ports: dict[int, set] = {}
    for rp in running:
        if rp.node >= 0 and rp.host_ports:
            used_ports.setdefault(rp.node, set()).update(rp.host_ports)
    # reverse-anti exclusion masks, hoisted: per distinct running-side
    # required anti term, the nodes whose domain hosts a carrier — an
    # incoming pod matching the selector is excluded from them (one [N]
    # mask per term instead of a domain rebuild per spec × running pod)
    rev_excl: dict[tuple, np.ndarray] = {}
    for rv in running:
        if rv.node < 0:
            continue
        for ml, tkey in rv.anti_terms:
            doms = _domain_ids(node_topo, topo_levels, tkey, N)
            d = doms[rv.node]
            if d < 0:
                continue
            cur = rev_excl.setdefault((ml, tkey), np.zeros((N,), bool))
            cur |= doms == d

    for xi, spec in enumerate(specs):
        pod = pods_by_spec[spec]
        mask = np.ones((N,), bool)
        prefer_penalty = np.zeros((N,), np.float32)
        # --- taints vs tolerations (upstream TaintToleration) ------------
        for ni, node in enumerate(live_nodes):
            for taint in node.taints:
                tolerated = any(t.tolerates(taint) for t in pod.tolerations)
                if tolerated:
                    continue
                if taint.effect in _HARD_EFFECTS:
                    mask[ni] = False
                elif taint.effect == "PreferNoSchedule":
                    prefer_penalty[ni] += 1.0
        # --- node affinity expressions (upstream NodeAffinity) -----------
        if pod.node_affinity:
            for ni, node in enumerate(live_nodes):
                if mask[ni] and not all(
                        e.matches(node.labels) for e in pod.node_affinity):
                    mask[ni] = False
        # --- DRA DeviceClass constraints (plugins/dynamicresources) ------
        if len(spec) > 3 and spec[3]:
            min_mem, sel_items = spec[3]
            for ni, node in enumerate(live_nodes):
                if not mask[ni]:
                    continue
                if min_mem > 0 and node.accel_memory_gib < min_mem:
                    mask[ni] = False
                elif any(node.labels.get(k) != v for k, v in sel_items):
                    mask[ni] = False
        # --- VolumeBinding: bound-PVC affinity / class topology ----------
        # the hostname key falls back to the node NAME, so volumes the
        # binder pinned per-node stay reachable on unlabeled nodes
        if len(spec) > 4 and spec[4]:
            for ni, node in enumerate(live_nodes):
                if mask[ni] and any(
                        node.labels.get(k, node.name
                                        if k == "kubernetes.io/hostname"
                                        else None) != v
                        for k, v in spec[4]):
                    mask[ni] = False
        # --- NodePorts: requested host ports must be free on the node ---
        if len(spec) > 5 and spec[5]:
            want = set(spec[5])
            for ni in range(N):
                if mask[ni] and want & used_ports.get(ni, set()):
                    mask[ni] = False
        # --- REVERSE required anti-affinity: a running pod's own anti
        # term excludes incoming pods matching its selector from its
        # domain (upstream InterPodAffinity's existingAntiAffinity check)
        if len(spec) > 6 and spec[6]:
            own_labels = dict(spec[6])
            for (ml, _tkey), excl in rev_excl.items():
                if all(own_labels.get(k) == v for k, v in ml):
                    mask &= ~excl
        # --- inter-pod (anti-)affinity (upstream InterPodAffinity) -------
        pref_aff = np.zeros((N,), np.float32)
        for term_key in spec[2]:
            match_labels, topology_key, anti, required = term_key
            term = apis.PodAffinityTerm(
                match_labels=match_labels, topology_key=topology_key,
                anti=anti, required=required)
            doms = _domain_ids(node_topo, topo_levels, topology_key, N)
            dmax = int(doms.max(initial=-1)) + 1
            counts = np.zeros((max(dmax, 1),), np.int64)
            for rp in running:
                if rp.node >= 0 and rp.node < N and term.selects(rp.labels):
                    d = doms[rp.node]
                    if d >= 0:
                        counts[d] += 1
            node_counts = np.where(doms >= 0, counts[np.maximum(doms, 0)], 0)
            if required:
                if anti:
                    mask &= node_counts == 0
                elif (match_labels, topology_key) not in incycle_pos_terms:
                    mask &= node_counts > 0
                # else: a PENDING anchor exists — enforced through the
                # cycle's claimed-domain table (GangState.attract_needs;
                # running matches pre-marked in attract_static)
            else:
                pref_aff += (-node_counts if anti
                             else node_counts).astype(np.float32)
        # --- soft bands, normalized into [0, MAX_BAND] --------------------
        band = np.zeros((N,), np.float32)
        pmax = prefer_penalty.max(initial=0.0)
        if pmax > 0:  # fewer untolerated PreferNoSchedule taints = better
            band += _MAX_BAND * (pmax - prefer_penalty) / pmax
        lo, hi = pref_aff.min(initial=0.0), pref_aff.max(initial=0.0)
        if hi > lo:  # more preferred-affinity matches = better
            band += _MAX_BAND * (pref_aff - lo) / (hi - lo)
        masks[xi, :N] = mask
        soft[xi, :N] = np.clip(band, 0.0, _MAX_BAND) * _W_K8S
    return masks, soft


def anti_self_term(pod: apis.Pod, topo_levels: list[str],
                   num_levels: int) -> tuple[int, tuple]:
    """(level, term key) of the WINNING self-selecting required anti
    term: the gang-internal spread constraint (two pods of the gang may
    not share a domain at this level; ``num_levels`` = per-node, -1 =
    none), and the key that identifies the CROSS-GANG anti group — two
    gangs carrying the SAME winning (selector, level) term and matching
    it mutually must not share a domain within a cycle (ref
    InterPodAffinity over virtually-allocated session state).

    One group slot per gang: when a pod carries SEVERAL self-selecting
    terms, only the coarsest one defines the group, so a peer sharing
    only a finer term is not in-cycle-excluded against it (that pair
    converges next cycle through the filter masks, like asymmetric
    terms).  Coarsest-first is the conservative pick — it is the widest
    exclusion the gang itself demands."""
    best, key = -1, ()
    for term in pod.pod_affinity:
        if not (term.required and term.anti and term.selects(pod.labels)):
            continue
        if term.topology_key in topo_levels:
            lvl = topo_levels.index(term.topology_key)
        else:
            lvl = num_levels  # per-node
        cand = (term.match_labels, lvl)
        # deterministic: coarsest level wins, smallest key on ties
        if best < 0 or lvl < best or (lvl == best and cand < key):
            best, key = lvl, cand
    return best, key

"""Incremental snapshot engine: journaled dirty-set refresh.

The reference keeps cluster state *incrementally* current via API-server
watches (SURVEY §2.6): each ``runOnce`` starts from an already-warm
cache and only the objects that changed since the last cycle cost any
work.  The seed port re-ran the full vectorized ``build_snapshot`` host
pass (~0.2 s warm at 10k nodes × 50k pods) plus one monolithic
``device_put`` every cycle — historically several times the entire
on-device solve, until this module (PR 1) made the host pass
O(change) and kai-resident (PR 11, ``ops/resident.py``) removed the
per-cycle re-upload entirely: the snapshot stays resident on device
and patched cycles ship only a packed journal delta.  At production
scale, cycle-to-cycle churn is a tiny fraction of the cluster; state
refresh cost is proportional to *change*, not cluster size (the
Tesserae approach, arXiv:2508.04953).

Three pieces:

- :class:`MutationJournal` — the cluster hub's change feed.  Every
  mutation (``submit``/``bind_pod``/``evict_pod``/``tick``, binder
  commits, wire-delta upserts/deletes) records dirty node/queue/gang/pod
  keys under a generation counter.  Multiple consumers each get their
  own :class:`JournalCursor`.

- :class:`IncrementalSnapshotter` — retains the previous cycle's host
  arrays + ``SnapshotIndex`` and re-derives only dirty rows through the
  per-section builders factored out of ``build_snapshot``
  (``build_queue_tables``/``derive_rollups`` are shared verbatim; the
  pending-task and running-pod sections are re-assembled from cached
  per-entity encodes with vectorized numpy).  Only changed leaves ship
  to the device; unchanged leaves reuse the previous cycle's device
  buffers.

- Automatic **fallback to the full rebuild** whenever a patch cannot be
  proven bit-identical to a fresh ``build_snapshot``:

  * structural change — node/queue/pod-group set or order changed,
    topology swapped, padded-dim overflow (entity counts outgrew the
    pinned :class:`~.cluster_state.SnapshotCapacity`);
  * vocabulary growth — selector keys, extended (MIG) keys, or filter
    classes beyond the empty spec would renumber dense id spaces;
  * feature pods — fractional/memory-share requests, DRA claims,
    volumes, host ports, pod affinity, tolerations, node affinity,
    nominated nodes, declared subgroups (the irregular intake paths
    stay on the proven full builder);
  * dirty fraction above ``dirty_threshold`` — patching stops paying
    once most of the cluster changed;
  * ledger drift — an object mutated without a journal mark (the
    object model is uninstrumented; a cheap identity/field sweep
    detects direct writes and falls back rather than serving a stale
    snapshot).

``verify=True`` (the scheduler's ``verify_incremental`` flag) rebuilds
from scratch after every patch and asserts the patched ``ClusterState``
is element-wise identical — including ``SnapshotIndex`` name maps.  On
the kai-resident path it additionally gates a device gather-and-compare
(:meth:`IncrementalSnapshotter.verify_device_residency`) so the
donated, in-place-updated device state is provably the mirror's twin —
without ever reading the device state back on non-verify runs.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import weakref

import jax
import numpy as np

from ..apis import types as apis
from ..ops import resident as _resident
from ..runtime import wire_ledger as _wire
from . import cluster_state as _cs
from .cluster_state import (
    SnapshotCapacity,
    _LEADER_ROLES,
    _round_up,
    build_queue_tables,
    dense_row_ids,
    derive_rollups,
)

R = apis.NUM_RESOURCES

_PENDING = int(apis.PodStatus.PENDING)
_BOUND = int(apis.PodStatus.BOUND)
_RUNNING = int(apis.PodStatus.RUNNING)
_RELEASING = int(apis.PodStatus.RELEASING)


class IncrementalVerifyError(AssertionError):
    """A patched snapshot diverged from a fresh full rebuild."""


class _Fallback(Exception):
    """Internal: abandon the patch attempt, run the full rebuild."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


# ---------------------------------------------------------------------------
# Mutation journal
# ---------------------------------------------------------------------------


_CURSOR_FIELDS = ("pods_dirty", "pods_added", "pods_removed",
                  "gangs_dirty", "gangs_added", "nodes_dirty",
                  "structural", "time_dirty")


class JournalBatch:
    """One drained window of changes — private to the consumer that
    drained it (no lock needed to read it)."""

    __slots__ = _CURSOR_FIELDS

    def __init__(self):
        self.pods_dirty: set[str] = set()
        self.pods_added: list[str] = []
        self.pods_removed: set[str] = set()
        self.gangs_dirty: set[str] = set()
        self.gangs_added: list[str] = []
        self.nodes_dirty: set[str] = set()
        self.structural: list[str] = []
        self.time_dirty = False


class JournalCursor:
    """One consumer's pending change sets (drained by ``consume``).

    The cursor shares its journal's lock: marks (any thread — binder,
    status-updater workers, HTTP handler deltas) and ``consume`` (the
    snapshotter's refresh) are mutually exclusive, so a drain can never
    observe a half-recorded mutation or drop a mark that raced the
    field swap.
    """

    __slots__ = _CURSOR_FIELDS + ("_lock", "__weakref__")

    def __init__(self, lock: threading.Lock | None = None):
        self._lock = lock if lock is not None else threading.Lock()
        self._reset()

    def _reset(self) -> None:
        self.pods_dirty: set[str] = set()
        self.pods_added: list[str] = []
        self.pods_removed: set[str] = set()
        self.gangs_dirty: set[str] = set()
        self.gangs_added: list[str] = []
        self.nodes_dirty: set[str] = set()
        self.structural: list[str] = []
        self.time_dirty = False

    def consume(self) -> "JournalBatch":
        """Move the accumulated sets into a private batch and reset —
        atomically with respect to concurrent marks."""
        out = JournalBatch()
        with self._lock:
            for slot in _CURSOR_FIELDS:
                setattr(out, slot, getattr(self, slot))
            self._reset()
        return out


class MutationJournal:
    """The cluster hub's change feed (fan-out to registered cursors).

    Marks are cheap set/list inserts; with no cursor registered only the
    generation counter moves.  Consumers (one ``IncrementalSnapshotter``
    each) register a :class:`JournalCursor` and drain it per refresh.

    Thread-safe: marks arrive from the binder, the async status-updater
    workers, and ThreadingHTTPServer delta handlers while the scheduler
    thread drains cursors — every mark and every ``consume`` runs under
    one journal lock (a torn or lost mark would let the snapshotter
    serve a silently stale patch; see ``tests/test_incremental.py``
    journal-hammer regression).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.generation = 0  # kai-race: guarded-by=_lock
        self._cursors: list = []  # weakrefs to JournalCursor

    def __deepcopy__(self, memo):
        # a deep-copied cluster document (profile_cycle's private copy)
        # starts its own change feed: locks are not copyable, and the
        # copy's mutations must not dirty the original's consumers
        return MutationJournal()

    def register(self) -> JournalCursor:
        cur = JournalCursor(self._lock)
        with self._lock:
            self._cursors.append(weakref.ref(cur))
        return cur

    def _each(self):
        if not self._cursors:
            return
        dead = False
        for ref in self._cursors:
            cur = ref()
            if cur is None:
                dead = True
            else:
                yield cur
        if dead:
            self._cursors = [r for r in self._cursors if r() is not None]

    # -- marks ------------------------------------------------------------

    def _apply_mark(self, kind: str, name: str) -> None:
        """One mark's cursor fan-out — caller holds ``self._lock``.
        The single implementation behind both the per-mark methods and
        the kai-intake bulk :meth:`merge`, so a coalesced lane batch can
        never drift from the sequential mark semantics."""
        self.generation += 1
        for c in self._each():
            if kind == "pod":
                c.pods_dirty.add(name)
            elif kind == "pod_added":
                if name not in c.pods_removed and name not in c.pods_dirty:
                    c.pods_added.append(name)
                else:
                    # removed-then-readded (or dirtied) inside one window:
                    # position in the dict may have moved — too subtle to
                    # patch, let the sweep/full rebuild sort it out
                    c.structural.append("pod-readded")
            elif kind == "pod_removed":
                c.pods_removed.add(name)
            elif kind == "gang":
                c.gangs_dirty.add(name)
            elif kind == "gang_added":
                c.gangs_added.append(name)
            elif kind == "node":
                c.nodes_dirty.add(name)
            elif kind == "structural":
                c.structural.append(name)
            elif kind == "time":
                c.time_dirty = True
            else:
                raise ValueError(f"unknown journal mark kind {kind!r}")

    def mark_pod(self, name: str) -> None:
        with self._lock:
            self._apply_mark("pod", name)

    def mark_pod_added(self, name: str) -> None:
        with self._lock:
            self._apply_mark("pod_added", name)

    def mark_pod_removed(self, name: str) -> None:
        with self._lock:
            self._apply_mark("pod_removed", name)

    def mark_gang(self, name: str) -> None:
        with self._lock:
            self._apply_mark("gang", name)

    def mark_gang_added(self, name: str) -> None:
        with self._lock:
            self._apply_mark("gang_added", name)

    def mark_node(self, name: str) -> None:
        with self._lock:
            self._apply_mark("node", name)

    def mark_structural(self, reason: str) -> None:
        with self._lock:
            self._apply_mark("structural", reason)

    def mark_time(self) -> None:
        with self._lock:
            self._apply_mark("time", "")

    def merge(self, marks) -> None:
        """Replay an ordered batch of ``(kind, name)`` mark operations
        under ONE lock acquisition — the kai-intake ``coalesce()``
        step's bulk merge of per-lane staged marks into the hub journal
        (``intake/router.py``).

        Event-for-event identical to calling the individual ``mark_*``
        methods in the same order: same per-cursor set/list mutations
        (including the pod-readded structural escalation, which is
        order-sensitive) and the same generation count.  Only the lock
        traffic is batched, so a 1M-event storm pays one acquisition
        per coalesce instead of one per mark."""
        if not marks:
            return
        with self._lock:
            for kind, name in marks:
                self._apply_mark(kind, name)


# ---------------------------------------------------------------------------
# The incremental snapshotter
# ---------------------------------------------------------------------------


def _slack(n: int) -> int:
    """Capacity headroom so modest growth between full rebuilds never
    changes a compiled shape (shapes recompile kernels)."""
    return n + max(2, n // 8)


def _is_plain_pod(pod: apis.Pod) -> bool:
    """Pods the patch path can encode row-wise.  Everything else rides
    the irregular intake paths of the full builder (filter classes,
    vocab growth, device-share bookkeeping) and forces a fallback."""
    return not (
        pod.node_selector or pod.tolerations or pod.node_affinity
        or pod.pod_affinity or pod.extended or pod.resource_claims
        or pod.volume_claims or pod.host_ports
        or pod.nominated_node is not None or pod.subgroup
        or pod.accel_portion > 0 or pod.accel_memory_gib > 0
        or pod.dra_accel_count > 0)


@dataclasses.dataclass
class SnapshotterStats:
    full_builds: int = 0
    patched: int = 0
    fallbacks: dict = dataclasses.field(default_factory=dict)
    leaves_shipped: int = 0
    bytes_shipped: int = 0
    #: the LAST refresh's journal-delta stats — mode (patched/full),
    #: fallback reason, dirty rows, changed leaves/bytes uploaded and
    #: upload seconds; feeds the kai-trace snapshot span's attributes
    #: and the bench phase attribution (runtime/tracing.py)
    last: dict = dataclasses.field(default_factory=dict)

    def fallback(self, reason: str) -> None:
        key = reason.split(":")[0]
        self.fallbacks[key] = self.fallbacks.get(key, 0) + 1


@dataclasses.dataclass
class ResidentRefresh:
    """One kai-resident refresh outcome (``refresh_resident``)."""

    #: "resident" — a packed delta is staged for the fused apply;
    #: "full" — a structural/cold fallback rebuilt and re-uploaded
    mode: str
    index: object
    #: freshly built device state (mode "full" only)
    state: object | None
    #: device-side packed journal delta (mode "resident" only)
    delta: dict | None
    #: the numpy mirror — host-side snapshot reads for the Session
    #: (None while a persistent environment condition keeps the
    #: per-entity ledger cold, e.g. DRA/volume feature stores)
    host: object | None


class IncrementalSnapshotter:
    """Journal-driven snapshot refresher for one ``Cluster``.

    ``refresh(cluster, now=..., queue_usage=...)`` returns the same
    ``(ClusterState, SnapshotIndex)`` pair ``build_snapshot`` would,
    either by patching the cached previous snapshot (dirty rows only,
    changed leaves only to device) or by falling back to the full
    builder.  Single consumer per journal cursor; one snapshotter per
    cluster document.
    """

    def __init__(self, *, verify: bool = False,
                 dirty_threshold: float = 0.35, tracer=None):
        self.verify = verify
        self.dirty_threshold = dirty_threshold
        self.stats = SnapshotterStats()
        #: optional runtime.tracing.CycleTracer — when the scheduler
        #: drives the refresh inside an open cycle trace, the patch /
        #: full-build sections and the device upload record themselves
        #: as child spans of the cycle's "snapshot" phase.  Tracer calls
        #: no-op without an open cycle (bench/CLI refreshes stay free).
        self._tracer = tracer
        self._cluster_ref = None
        self._cursor: JournalCursor | None = None
        self._host = None        # numpy ClusterState (previous cycle)
        self._dev = None         # device ClusterState (previous cycle)
        self._index = None
        self._capacity = SnapshotCapacity()
        #: kai-resident desync guard: True between a staged delta
        #: (refresh_resident) and its adoption (adopt_device_state)
        self._delta_outstanding = False
        #: kai-resident bucket hysteresis: per-group segment lengths
        #: only grow (see ops/resident.pack_delta) so the fused entry's
        #: abstract signature converges instead of recompiling whenever
        #: churn wobbles across a pow2 boundary
        self._delta_buckets: dict[str, int] = {}

    def _add_span(self, name: str, start: float, **attrs) -> None:
        if self._tracer is not None:
            self._tracer.add_span(name, start, time.perf_counter(),
                                  **attrs)

    # -- public -----------------------------------------------------------

    def _bind_cluster(self, cluster) -> None:
        if (self._cluster_ref is None
                or self._cluster_ref() is not cluster):
            self._cluster_ref = weakref.ref(cluster)
            journal = getattr(cluster, "journal", None)
            self._cursor = (journal.register()
                            if journal is not None else None)
            self._host = None

    def refresh(self, cluster, *, now: float | None = None,
                queue_usage=None):
        self._bind_cluster(cluster)
        j = (self._cursor.consume() if self._cursor is not None
             else None)
        reason = self._patch_blockers(cluster, j)
        if reason is None:
            t_patch = time.perf_counter()
            try:
                host_new, index = self._patch(cluster, j, now,
                                              queue_usage)
            except _Fallback as exc:
                reason = exc.reason
                self._add_span("snapshot.patch_abandoned", t_patch,
                               fallback_reason=reason)
            else:
                state = self._ship(host_new)
                self._index = index
                self.stats.patched += 1
                ship = self._last_ship
                self.stats.last = {
                    "mode": "patched", "fallback_reason": "",
                    "dirty_pods": self._last_dirty[0],
                    "dirty_gangs": self._last_dirty[1],
                    "leaves_shipped": ship[0], "bytes_shipped": ship[1],
                    "ship_seconds": ship[2], "ship_dispatches": ship[3],
                }
                self._add_span("snapshot.patch", t_patch,
                               **self.stats.last)
                if self.verify:
                    self._verify(cluster, now, queue_usage)
                return state, index
        self.stats.fallback(reason)
        t_full = time.perf_counter()
        out = self._full(cluster, now, queue_usage)
        # the full builder's device transfer happens inside
        # build_snapshot, so upload is not separable here — the whole
        # rebuild is one section
        self.stats.last = {
            "mode": "full", "fallback_reason": reason,
            "dirty_pods": 0, "dirty_gangs": 0,
            "leaves_shipped": 0, "bytes_shipped": 0,
            "ship_seconds": 0.0, "ship_dispatches": 0,
        }
        self._add_span("snapshot.full_build", t_full,
                       fallback_reason=reason)
        return out

    # -- kai-resident ------------------------------------------------------

    def refresh_resident(self, cluster, *, now: float | None = None,
                         queue_usage=None) -> "ResidentRefresh":
        """The kai-resident refresh: patch the host mirror, then stage
        a **packed journal delta** (``ops/resident.py``) for the fused
        scatter-apply dispatch instead of shipping changed leaves.

        On success (``mode == "resident"``) the device state has NOT
        been touched yet: the scheduler runs the fused entry over
        :attr:`device_state` (donating it) and hands the post-delta
        state back via :meth:`adopt_device_state` — until then a desync
        guard forces the next refresh to a full rebuild, so an aborted
        cycle can never leave the mirror ahead of the device.  Every
        fallback (cold start, structural change, feature pods, ...)
        returns ``mode == "full"`` with a freshly built + uploaded
        device state, exactly like :meth:`refresh`.
        """
        self._bind_cluster(cluster)
        j = (self._cursor.consume() if self._cursor is not None
             else None)
        reason = None
        if self._delta_outstanding:
            # the previous staged delta was never applied (the cycle
            # aborted between refresh and adopt): the mirror is ahead
            # of the device — rebuild rather than diff against it
            self._delta_outstanding = False
            self._host = None
            reason = "resident-desync"
        if reason is None:
            reason = self._patch_blockers(cluster, j)
        if reason is None:
            t_patch = time.perf_counter()
            try:
                host_new, index = self._patch(cluster, j, now,
                                              queue_usage)
                delta, merged, dstats = _resident.pack_delta(
                    self._host, host_new,
                    min_buckets=self._delta_buckets)
            except _Fallback as exc:
                reason = exc.reason
                self._add_span("snapshot.patch_abandoned", t_patch,
                               fallback_reason=reason)
            except _resident.DeltaShapeError as exc:
                reason = f"delta-shape:{exc}"
                self._add_span("snapshot.patch_abandoned", t_patch,
                               fallback_reason="delta-shape")
            else:
                t_ship = time.perf_counter()
                # ONE transient device_put: the delta is consumed by
                # the donated scatter-apply dispatch and never joins
                # the ledger's resident set (wire_ledger.py)
                delta_dev = _wire.LEDGER.device_put(
                    delta, reason=_wire.REASON_DELTA_APPLY,
                    site="delta", transient=True)
                ship_s = time.perf_counter() - t_ship
                self._host = merged
                self._index = index
                self._delta_outstanding = True
                self._delta_buckets.update(dstats["buckets"])
                self.stats.patched += 1
                self.stats.leaves_shipped += dstats["leaves"]
                self.stats.bytes_shipped += dstats["bytes"]
                self.stats.last = {
                    "mode": "resident", "fallback_reason": "",
                    "dirty_pods": self._last_dirty[0],
                    "dirty_gangs": self._last_dirty[1],
                    "leaves_shipped": dstats["leaves"],
                    "bytes_shipped": dstats["bytes"],
                    "delta_elements": dstats["elements"],
                    "ship_seconds": ship_s, "ship_dispatches": 1,
                }
                self._add_span("snapshot.patch", t_patch,
                               **self.stats.last)
                self._add_span("upload", t_ship,
                               leaves=dstats["leaves"],
                               bytes=dstats["bytes"], dispatches=1)
                if self.verify:
                    self._verify(cluster, now, queue_usage)
                return ResidentRefresh(
                    mode="resident", index=index, state=None,
                    delta=delta_dev, host=self._host)
        self.stats.fallback(reason)
        t_full = time.perf_counter()
        state, index = self._full(cluster, now, queue_usage)
        self.stats.last = {
            "mode": "full", "fallback_reason": reason,
            "dirty_pods": 0, "dirty_gangs": 0,
            "leaves_shipped": 0, "bytes_shipped": 0,
            "ship_seconds": 0.0, "ship_dispatches": 0,
        }
        self._add_span("snapshot.full_build", t_full,
                       fallback_reason=reason)
        return ResidentRefresh(mode="full", index=index, state=state,
                               delta=None, host=self._host)

    @property
    def device_state(self):
        """The device-resident ``ClusterState`` (the fused entry's
        donation target).  Reading it is safe; the VALUE passed into a
        donated dispatch must never be touched afterwards (KAI081)."""
        return self._dev

    def adopt_device_state(self, state) -> None:
        """Install the fused entry's post-delta output as the resident
        state for the next cycle (clears the desync guard armed by
        :meth:`refresh_resident`)."""
        self._dev = state
        self._delta_outstanding = False

    def verify_device_residency(self) -> None:
        """Gather the device-resident state and assert it is leaf-wise
        identical to the host mirror — the kai-resident half of
        ``verify_incremental``.  Only ever called on verify runs, so
        the donation discipline of production cycles is untouched."""
        if self._host is None or self._dev is None:
            return
        host_paths = jax.tree_util.tree_flatten_with_path(self._host)[0]
        dev_host = _wire.LEDGER.device_get(
            self._dev, reason=_wire.REASON_VERIFY)
        dev_leaves = jax.tree_util.tree_leaves(dev_host)
        for (path, mine), dev in zip(host_paths, dev_leaves):
            name = jax.tree_util.keystr(path)
            dev = np.asarray(dev)
            if dev.shape != mine.shape or dev.dtype != mine.dtype:
                raise IncrementalVerifyError(
                    f"resident leaf {name}: shape/dtype "
                    f"{dev.shape}/{dev.dtype} != "
                    f"{mine.shape}/{mine.dtype}")
            if not np.array_equal(dev, mine,
                                  equal_nan=mine.dtype.kind == "f"):
                bad = np.nonzero(dev != mine)
                raise IncrementalVerifyError(
                    f"resident leaf {name}: {len(bad[0])} elements "
                    f"diverged from the host mirror")

    # -- fallback decisions ----------------------------------------------

    def _patch_blockers(self, cluster, j) -> str | None:
        # environment conditions first: they also tell _full whether a
        # ledger rebuild is worth paying for
        if self._cursor is None:
            return "no-journal"
        if (cluster.resource_claims or cluster.device_classes
                or cluster.volume_claims or cluster.storage_classes):
            return "feature-stores"
        if self._host is None:
            return "cold"
        if j.structural:
            return f"structural:{j.structural[0]}"
        if j.nodes_dirty:
            return "node-dirty"
        if cluster.topology is not self._topology:
            return "topology-changed"
        if not self._clean:
            return "vocab-residue"
        if self._nonplain > 0:
            return "nonplain-pods"
        if self._nonplain_gangs > 0:
            return "nonplain-gangs"
        if self._present_twice > 0:
            return "inflight-move"
        live = int(self.p_live.sum())
        if len(self.p_objs) > 2 * max(live, 64):
            return "ledger-compaction"
        return None

    # ------------------------------------------------------------------
    # Full rebuild: run build_snapshot, then rebuild every ledger/cache
    # ------------------------------------------------------------------

    def _full(self, cluster, now, queue_usage):
        self.stats.full_builds += 1
        # go cold first: if the build raises (bad config propagates to
        # the caller), the next refresh must not patch over a cache that
        # no longer matches the already-consumed journal
        self._host = None
        lists = cluster.snapshot_lists()
        nodes, queues, groups, pods, topology = lists
        live_nodes = [n for n in nodes if not n.unschedulable]
        pend_per_group: dict[str, int] = {g.name: 0 for g in groups}
        n_running = 0
        for p in pods:
            if p.status == apis.PodStatus.PENDING:
                if p.group in pend_per_group:
                    pend_per_group[p.group] += 1
            elif p.status in (apis.PodStatus.BOUND, apis.PodStatus.RUNNING,
                              apis.PodStatus.RELEASING):
                n_running += 1
        max_pending = max(pend_per_group.values(), default=0)
        cap = SnapshotCapacity(
            nodes=_slack(len(live_nodes)), queues=_slack(len(queues)),
            gangs=_slack(len(groups)), tasks=_slack(max_pending),
            running=_slack(n_running), types=0)
        # through the module attribute so test harnesses that wrap
        # build_snapshot (padding unification) stay in effect.  The
        # wire ledger re-labels the build's transfer "fallback": the
        # incremental engine rebuilt in full (cold start included) —
        # distinguishable on /debug/wire from a deliberate full build
        with _wire.LEDGER.override_reason(_wire.REASON_FALLBACK):
            state, index, host = _cs.build_snapshot(
                *lists, now=now, queue_usage=queue_usage,
                resource_claims=cluster.resource_claims,
                device_classes=cluster.device_classes,
                volume_claims=cluster.volume_claims,
                storage_classes=cluster.storage_classes,
                capacity=cap, _return_host=True)
        # the per-entity ledger only pays off if a later cycle can
        # actually patch — skip it (stay cold) while a persistent
        # environment condition forces full rebuilds regardless, e.g. a
        # DRA/volume deployment whose feature stores never empty
        if (self._cursor is None or cluster.resource_claims
                or cluster.device_classes or cluster.volume_claims
                or cluster.storage_classes):
            return state, index
        # pin realized padded dims as the next capacity (floors already
        # include the slack via `cap`; Y absorbs its own round-up slack)
        old_capacity = self._capacity
        self._capacity = SnapshotCapacity(
            nodes=host.nodes.valid.shape[0],
            queues=host.queues.valid.shape[0],
            gangs=host.gangs.valid.shape[0],
            tasks=host.gangs.task_valid.shape[1],
            running=host.running.valid.shape[0],
            types=host.gangs.type_req.shape[0])
        if self._capacity != old_capacity:
            # kai-resident bucket hysteresis is scoped to ONE snapshot
            # shape: a rebuild that re-padded the axes recompiles the
            # fused entry regardless, and carrying a larger previous
            # cluster's floors forward would pin every future delta to
            # its historical maximum (inflated wire bytes + scatter
            # work forever).  Same-shape rebuilds keep the floors — the
            # settled signature stays warm across the fallback.
            self._delta_buckets.clear()
        self._host, self._dev, self._index = host, state, index
        self._rebuild_ledgers(cluster, lists, host, index)
        return state, index

    def _rebuild_ledgers(self, cluster, lists, host, index) -> None:
        nodes, queues, groups, pods, topology = lists
        self._topology = cluster.topology
        # --- node-section caches (valid until any node is dirty) ---------
        self._node_names = index.node_names
        self._node_index = {n: i for i, n in enumerate(index.node_names)}
        live_nodes = [n for n in nodes if not n.unschedulable]
        self._node_objs = live_nodes
        self._node_cache = [
            (n, n.allocatable, n.labels, n.taints, n.extended,
             n.accel_memory_gib) for n in live_nodes]
        # the patch path only reproduces builds whose dense id spaces
        # are trivial — any residual vocabulary (from since-departed
        # feature pods) keeps forcing full rebuilds until one comes out
        # clean
        self._clean = (
            not index.selector_keys and not index.label_vocab
            and not index.extended_keys
            and np.asarray(host.nodes.filter_masks).shape[0] == 1)
        self._accel_counts = np.fromiter(
            (int(round(n.allocatable.accel)) for n in live_nodes),
            np.int64, len(live_nodes))
        N = host.nodes.valid.shape[0]
        D = host.nodes.device_free.shape[1]
        tmpl = np.zeros((N, D), np.float32)
        for i, c in enumerate(self._accel_counts):
            tmpl[i, :c] = 1.0
        self._dev_template = tmpl
        self._queue_names = list(index.queue_names)
        # topology level resolution caches (gang encodes)
        if topology is None:
            topos: list[apis.Topology] = []
        elif isinstance(topology, apis.Topology):
            topos = [topology]
        else:
            topos = list(topology)
        self._topo_levels = [lvl for t in topos for lvl in t.levels]
        self._topo_slices = {}
        off = 0
        for t in topos:
            self._topo_slices[t.name] = (off, list(t.levels))
            off += len(t.levels)
        # --- gang ledger --------------------------------------------------
        NG = len(groups)
        # rows start as None so _encode_gang's nonplain delta-tracking
        # sees a fresh row (not the gang it is about to encode)
        self.g_objs: list = [None] * NG
        self.g_names: list[str] = [g.name for g in groups]
        self._gang_index = {g.name: i for i, g in enumerate(groups)}
        self.g_queue = np.zeros((NG,), np.int32)
        self.g_minm = np.zeros((NG,), np.int32)
        self.g_prio = np.zeros((NG,), np.int32)
        self.g_preempt = np.zeros((NG,), bool)
        self.g_unsched = np.zeros((NG,), bool)
        self.g_start = np.full((NG,), -1.0, np.float64)
        self.g_stale = np.full((NG,), np.nan, np.float64)
        self.g_reqlvl = np.full((NG,), -1, np.int32)
        self.g_preflvl = np.full((NG,), -1, np.int32)
        self.g_tc: list = [None] * NG
        self._q_index = {n: i for i, n in enumerate(self._queue_names)}
        self._nonplain_gangs = 0
        for i, g in enumerate(groups):
            self._encode_gang(i, g)
        # --- pod ledger ---------------------------------------------------
        U = len(pods)
        self.p_objs: list = [None] * U
        self.p_names = np.empty((U,), object)
        #: per-row (obj, raw status, raw node) — ONE list index per pod
        #: in the sweep's hot loop
        self.p_sweep: list = [None] * U
        self.p_live = np.zeros((U,), bool)
        self.p_req = np.zeros((U, R), np.float32)
        self.p_prio = np.zeros((U,), np.int64)
        self.p_crea = np.zeros((U,), np.float64)
        self.p_group = np.full((U,), -1, np.int32)
        self.p_leader = np.zeros((U,), bool)
        self.p_plain = np.zeros((U,), bool)
        self.p_devmask = np.zeros((U,), np.int32)
        self.p_held = np.zeros((U,), np.float32)
        self.p_hasdev = np.zeros((U,), bool)
        self.p_eff_status = np.full((U,), -1, np.int8)
        self.p_eff_node = np.full((U,), -1, np.int32)
        self.p_iid = np.full((U,), -1, np.int32)
        self.p_ti = np.full((U,), -1, np.int32)
        self._intern: dict[tuple, int] = {}
        self._intern_req = np.zeros((0, R), np.float32)
        self._nonplain = 0
        self._present_twice = 0
        # NOTE: ledger rows follow the RAW pod-dict order — the lists
        # argument interleaves presentation copies, so encode from the
        # cluster store itself (presentation is re-derived per row)
        self._pod_row = {}
        for row, (name, pod) in enumerate(cluster.pods.items()):
            self._pod_row[name] = row
            self._encode_pod(row, pod, cluster)
        self._order = np.arange(U, dtype=np.int64)
        self._order_list = list(range(U))
        #: BindRequest presentation cache — a Pending BR re-presents its
        #: pod as bound (snapshot_lists), so BR creation/phase/target
        #: drift must dirty the pod even when the pod object is untouched
        self._br_cache = {
            name: (br, br.phase, br.selected_node)
            for name, br in cluster.bind_requests.items()}
        # cached per-pod task slots come from the freshly built tables
        self._task_names_obj = np.array(index.task_names, dtype=object) \
            if index.task_names else np.full(
                (host.gangs.valid.shape[0],
                 host.gangs.task_valid.shape[1]), None, object)
        self._seed_task_slots(host)
        # constant gang-side tables reused by identity between refreshes
        g = host.gangs
        self._const = dict(
            task_selector=np.asarray(g.task_selector),
            task_portion=np.asarray(g.task_portion),
            task_accel_mem=np.asarray(g.task_accel_mem),
            task_filter_class=np.asarray(g.task_filter_class),
            task_nominated=np.asarray(g.task_nominated),
            anti_self_level=np.asarray(g.anti_self_level),
            anti_marks=np.asarray(g.anti_marks),
            anti_avoids=np.asarray(g.anti_avoids),
            attract_needs=np.asarray(g.attract_needs),
            anti_term_level=np.asarray(g.anti_term_level),
            attract_static=np.asarray(g.attract_static),
            task_subgroup=np.asarray(g.task_subgroup),
            task_extended=np.asarray(g.task_extended),
            task_dra=np.asarray(g.task_dra),
            ext_accel=np.asarray(g.ext_accel),
            type_selector=np.asarray(g.type_selector),
            type_portion=np.asarray(g.type_portion),
            type_mem=np.asarray(g.type_mem),
            type_class=np.asarray(g.type_class),
            type_extended=np.asarray(g.type_extended),
        )

    def _seed_task_slots(self, host) -> None:
        """Recover per-pod (gang, slot) assignments from the built task
        tables so undirty gangs never need re-sorting."""
        self.p_ti[:] = -1
        names = self._task_names_obj
        G, T = names.shape
        name_row = self._pod_row
        gi, ti = np.nonzero(np.asarray(host.gangs.task_valid))
        for g0, t0 in zip(gi.tolist(), ti.tolist()):
            nm = names[g0, t0]
            if nm is not None:
                row = name_row.get(nm)
                if row is not None:
                    self.p_ti[row] = t0

    # -- per-entity encodes ------------------------------------------------

    def _encode_gang(self, i, g: apis.PodGroup) -> None:
        prev = self.g_objs[i]
        was_nonplain = bool(prev is not None and prev.sub_groups)
        self._nonplain_gangs += int(bool(g.sub_groups)) - int(was_nonplain)
        self.g_objs[i] = g
        self.g_names[i] = g.name
        self.g_queue[i] = self._q_index.get(g.queue, 0)
        self.g_minm[i] = g.min_member
        self.g_prio[i] = g.priority
        self.g_preempt[i] = (
            g.preemptibility == apis.Preemptibility.PREEMPTIBLE)
        self.g_unsched[i] = bool(g.unschedulable)
        self.g_start[i] = (-1.0 if g.last_start_timestamp is None
                           else g.last_start_timestamp)
        self.g_stale[i] = (np.nan if g.stale_since is None
                           else g.stale_since)
        tc = g.topology_constraint
        self.g_tc[i] = tc
        self.g_reqlvl[i] = self._resolve_level(tc, "required_level")
        self.g_preflvl[i] = self._resolve_level(tc, "preferred_level")

    def _resolve_level(self, tc, attr) -> int:
        if tc is None or not self._topo_levels:
            return -1
        start, lvls = self._topo_slices.get(
            tc.topology, (0, self._topo_levels))
        name = getattr(tc, attr)
        return start + lvls.index(name) if name in lvls else -1

    def _encode_pod(self, row, pod: apis.Pod, cluster) -> None:
        was_plain = bool(self.p_plain[row]) if self.p_live[row] else True
        was_twice = bool(self.p_live[row]
                         and self.p_eff_status[row] == -2)
        self.p_objs[row] = pod
        self.p_names[row] = pod.name
        self.p_live[row] = True
        self.p_sweep[row] = (pod, pod.status, pod.node)
        self.p_req[row] = pod.resources.as_tuple()
        self.p_prio[row] = pod.priority
        self.p_crea[row] = pod.creation_timestamp
        self.p_group[row] = self._gang_index.get(pod.group, -1)
        labels = pod.labels
        self.p_leader[row] = (
            (labels.get("training.kubeflow.org/job-role")
             or labels.get("ray.io/node-type")) not in _LEADER_ROLES
            if labels else True)
        plain = _is_plain_pod(pod) and all(
            0 <= d < 32 for d in pod.accel_devices)
        self.p_plain[row] = plain
        self._nonplain += (not plain) - (not was_plain)
        k = int(round(pod.resources.accel))
        devs = list(pod.accel_devices)[:k] if plain else []
        mask = 0
        for d in devs:
            mask |= 1 << int(d)
        self.p_devmask[row] = mask
        self.p_held[row] = float(len(devs))
        self.p_hasdev[row] = bool(pod.accel_devices)
        # presented (effective) status — the snapshot_lists semantics
        st, nd = int(pod.status), pod.node
        twice = False
        br = cluster.bind_requests.get(pod.name)
        if br is not None and br.phase == "Pending":
            if st == _PENDING:
                st, nd = _BOUND, br.selected_node
            elif st == _RELEASING:
                twice = True  # presented twice: old node + rebind target
        self._present_twice += int(twice) - int(was_twice)
        self.p_eff_status[row] = -2 if twice else st
        self.p_eff_node[row] = (self._node_index.get(nd, -1)
                                if nd is not None else -1)
        key = tuple(float(x) for x in pod.resources.as_tuple())
        iid = self._intern.get(key)
        if iid is None:
            iid = len(self._intern)
            self._intern[key] = iid
            self._intern_req = np.concatenate(
                [self._intern_req,
                 np.asarray([key], np.float32)], axis=0)
        self.p_iid[row] = iid

    def _release_pod(self, row) -> None:
        if not self.p_live[row]:
            return
        self.p_live[row] = False
        self._nonplain -= int(not self.p_plain[row])
        self._present_twice -= int(self.p_eff_status[row] == -2)
        self.p_objs[row] = None
        self.p_sweep[row] = None

    # ------------------------------------------------------------------
    # Patch path
    # ------------------------------------------------------------------

    def _grow_pods(self, extra: int) -> None:
        """Grow the ARRAY capacity (lists append exactly; arrays carry
        slack so appends stay amortized O(1))."""
        U = len(self.p_live)
        n = max(extra, U // 2, 64)
        self.p_names = np.concatenate(
            [self.p_names, np.empty((n,), object)])
        for name in ("p_live", "p_leader", "p_plain", "p_hasdev"):
            setattr(self, name, np.concatenate(
                [getattr(self, name), np.zeros((n,), bool)]))
        self.p_req = np.concatenate(
            [self.p_req, np.zeros((n, R), np.float32)])
        self.p_prio = np.concatenate(
            [self.p_prio, np.zeros((n,), np.int64)])
        self.p_crea = np.concatenate(
            [self.p_crea, np.zeros((n,), np.float64)])
        self.p_group = np.concatenate(
            [self.p_group, np.full((n,), -1, np.int32)])
        self.p_devmask = np.concatenate(
            [self.p_devmask, np.zeros((n,), np.int32)])
        self.p_held = np.concatenate(
            [self.p_held, np.zeros((n,), np.float32)])
        self.p_eff_status = np.concatenate(
            [self.p_eff_status, np.full((n,), -1, np.int8)])
        self.p_eff_node = np.concatenate(
            [self.p_eff_node, np.full((n,), -1, np.int32)])
        self.p_iid = np.concatenate(
            [self.p_iid, np.full((n,), -1, np.int32)])
        self.p_ti = np.concatenate(
            [self.p_ti, np.full((n,), -1, np.int32)])

    def _grow_gangs(self, extra: int) -> None:
        """Array-capacity growth; the g_* lists append exactly."""
        n = max(extra, 8)
        self.g_queue = np.concatenate(
            [self.g_queue, np.zeros((n,), np.int32)])
        self.g_minm = np.concatenate(
            [self.g_minm, np.zeros((n,), np.int32)])
        self.g_prio = np.concatenate(
            [self.g_prio, np.zeros((n,), np.int32)])
        self.g_preempt = np.concatenate(
            [self.g_preempt, np.zeros((n,), bool)])
        self.g_unsched = np.concatenate(
            [self.g_unsched, np.zeros((n,), bool)])
        self.g_start = np.concatenate(
            [self.g_start, np.full((n,), -1.0, np.float64)])
        self.g_stale = np.concatenate(
            [self.g_stale, np.full((n,), np.nan, np.float64)])
        self.g_reqlvl = np.concatenate(
            [self.g_reqlvl, np.full((n,), -1, np.int32)])
        self.g_preflvl = np.concatenate(
            [self.g_preflvl, np.full((n,), -1, np.int32)])

    def _apply_journal(self, cluster, j) -> tuple[set, set]:
        """Membership + dirty-field updates → (dirty pod rows, dirty
        gang rows).  Raises _Fallback on anything unpatchable."""
        dirty_gangs: set[int] = set()
        dirty_rows: set[int] = set()
        membership = bool(j.pods_added or j.pods_removed)
        # gang appends first so new pods resolve their group row
        if j.gangs_added:
            for name in j.gangs_added:
                g = cluster.pod_groups.get(name)
                if g is None or name in self._gang_index:
                    raise _Fallback("gang-add-drift")
                i = len(self._gang_index)
                if i >= len(self.g_queue):
                    self._grow_gangs(max(8, i // 4))
                self.g_objs.append(None)
                self.g_names.append("")
                self.g_tc.append(None)
                self._gang_index[name] = i
                self._encode_gang(i, g)
                dirty_gangs.add(i)
            # a pod encoded before its group existed now resolves
            unresolved = np.nonzero(self.p_live
                                    & (self.p_group < 0))[0]
            for row in unresolved.tolist():
                gi = self._gang_index.get(self.p_objs[row].group, -1)
                if gi >= 0:
                    self.p_group[row] = gi
                    dirty_rows.add(row)
                    dirty_gangs.add(gi)
        for name in j.gangs_dirty:
            i = self._gang_index.get(name)
            if i is None:
                continue  # deleted since; structural would have fired
            g = cluster.pod_groups.get(name)
            if g is None:
                raise _Fallback("gang-removed-unjournaled")
            self._encode_gang(i, g)
            dirty_gangs.add(i)
        for name in j.pods_removed:
            row = self._pod_row.get(name)
            if row is None or not self.p_live[row]:
                continue
            gi = int(self.p_group[row])
            if gi >= 0:
                dirty_gangs.add(gi)
            self._release_pod(row)
            del self._pod_row[name]
            membership = True
        added_rows: list[int] = []
        for name in j.pods_added:
            pod = cluster.pods.get(name)
            if pod is None:
                continue  # added then removed within the window
            if name in self._pod_row:
                raise _Fallback("pod-add-drift")
            row = len(self.p_objs)
            if row >= len(self.p_live):
                self._grow_pods(64)
            self.p_objs.append(None)
            self.p_sweep.append(None)
            self._pod_row[name] = row
            self._encode_pod(row, pod, cluster)
            dirty_rows.add(row)
            added_rows.append(row)
            gi = int(self.p_group[row])
            if gi >= 0:
                dirty_gangs.add(gi)
        for name in j.pods_dirty:
            row = self._pod_row.get(name)
            if row is None:
                continue
            pod = cluster.pods.get(name)
            if pod is None:
                raise _Fallback("pod-removed-unjournaled")
            gi_old = int(self.p_group[row])
            self._encode_pod(row, pod, cluster)
            dirty_rows.add(row)
            for gi in (gi_old, int(self.p_group[row])):
                if gi >= 0:
                    dirty_gangs.add(gi)
        if membership or added_rows:
            keep = self.p_live[self._order]
            order = self._order[keep]
            if added_rows:
                order = np.concatenate(
                    [order, np.asarray(added_rows, np.int64)])
            self._order = order
            self._order_list = order.tolist()
        return dirty_rows, dirty_gangs

    def _sweep(self, cluster, dirty_rows: set, dirty_gangs: set) -> None:
        """Detect un-journaled drift: object replacement, status/node
        writes, gang status writes, node mutations.  Cheap identity and
        field compares; anything the ledger cannot attribute raises
        _Fallback (full rebuild) rather than serving stale state."""
        if len(cluster.pods) != len(self._order_list):
            raise _Fallback("pod-membership-drift")
        # BindRequest drift (created/replaced/phase-flipped/cleared —
        # bench and test harnesses touch the store directly): re-encode
        # the affected pods' presentation
        brs = cluster.bind_requests
        br_cache = self._br_cache
        br_dirty: list[str] = []
        if brs or br_cache:
            for name, br in brs.items():
                c = br_cache.get(name)
                if (c is None or c[0] is not br or c[1] != br.phase
                        or c[2] != br.selected_node):
                    br_dirty.append(name)
            if len(br_cache) != len(brs) or br_dirty:
                # sorted: the set difference iterates in hash order,
                # which would make the dirty-row encode order (and any
                # tie-broken downstream buffer) run-dependent (KAI041)
                for name in sorted(br_cache.keys() - brs.keys()):
                    br_dirty.append(name)
                self._br_cache = {
                    name: (br, br.phase, br.selected_node)
                    for name, br in brs.items()}
        for name in br_dirty:
            row = self._pod_row.get(name)
            if row is None or not self.p_live[row]:
                continue
            if row not in dirty_rows:
                self._encode_pod(row, self.p_objs[row], cluster)
                dirty_rows.add(row)
                gi = int(self.p_group[row])
                if gi >= 0:
                    dirty_gangs.add(gi)
        cache = self.p_sweep
        changed: list[int] = []
        for row, pod in zip(self._order_list, cluster.pods.values()):
            c = cache[row]
            if c[1] is not pod.status or c[0] is not pod \
                    or c[2] != pod.node:
                changed.append(row)
        for row in changed:
            pod = self.p_objs[row]
            if pod is not cache[row][0] or pod is not cluster.pods.get(
                    pod.name if pod is not None else ""):
                raise _Fallback("pod-object-drift")
            if row not in dirty_rows:
                self._encode_pod(row, pod, cluster)
                dirty_rows.add(row)
                gi = int(self.p_group[row])
                if gi >= 0:
                    dirty_gangs.add(gi)
        if len(cluster.pod_groups) != len(self.g_objs):
            raise _Fallback("gang-membership-drift")
        for i, g in enumerate(cluster.pod_groups.values()):
            if self.g_objs[i] is not g:
                raise _Fallback("gang-object-drift")
            start = (-1.0 if g.last_start_timestamp is None
                     else g.last_start_timestamp)
            stale_c = self.g_stale[i]
            stale_eq = ((g.stale_since is None and np.isnan(stale_c))
                        or (g.stale_since is not None
                            and stale_c == g.stale_since))
            if (bool(g.unschedulable) != bool(self.g_unsched[i])
                    or self.g_start[i] != start or not stale_eq
                    or self.g_tc[i] is not g.topology_constraint):
                if g.sub_groups:
                    raise _Fallback("gang-grew-subgroups")
                self._encode_gang(i, g)
                dirty_gangs.add(i)
        # nodes: any drift at all → full rebuild (vocabularies, masks,
        # device tables and capacity all hang off the node section)
        node_vals = [n for n in cluster.nodes.values()
                     if not n.unschedulable]
        if len(node_vals) != len(self._node_objs):
            raise _Fallback("node-membership-drift")
        for cached, n in zip(self._node_cache, node_vals):
            if (cached[0] is not n or cached[1] is not n.allocatable
                    or cached[2] is not n.labels
                    or cached[3] is not n.taints
                    or cached[4] is not n.extended
                    or cached[5] != n.accel_memory_gib):
                raise _Fallback("node-drift")
        if cluster.topology is not self._topology:
            raise _Fallback("topology-drift")

    def _patch(self, cluster, j, now, queue_usage):
        dirty_rows, dirty_gangs = self._apply_journal(cluster, j)
        self._sweep(cluster, dirty_rows, dirty_gangs)
        self._last_dirty = (len(dirty_rows), len(dirty_gangs))
        if self._nonplain > 0:
            raise _Fallback("nonplain-pods")
        if self._nonplain_gangs > 0:
            raise _Fallback("nonplain-gangs")
        if self._present_twice > 0:
            raise _Fallback("inflight-move")
        live = int(self.p_live.sum())
        dirty_frac = max(
            len(dirty_rows) / max(live, 1),
            len(dirty_gangs) / max(len(self.g_objs), 1))
        if dirty_frac > self.dirty_threshold:
            raise _Fallback("dirty-threshold")
        cap = self._capacity
        if len(self.g_objs) > cap.gangs:
            raise _Fallback("overflow-gangs")
        if len(self._queue_names) != len(cluster.queues):
            raise _Fallback("queue-set-changed")
        host_old = self._host
        if now is None:
            order = self._order
            now = float(self.p_crea[order].max()) if len(order) else 0.0
        return self._assemble(
            cluster, dirty_gangs, now, queue_usage, host_old)

    # -- assembly ----------------------------------------------------------

    def _assemble(self, cluster, dirty_gangs, now, queue_usage, old):
        cap = self._capacity
        G, T = cap.gangs, cap.tasks
        N, Q, M = cap.nodes, cap.queues, cap.running
        NG = len(self.g_objs)
        order = self._order
        eff = self.p_eff_status[order]
        grp_all = self.p_group[order]
        # --- queues (always re-encoded; tiny) ----------------------------
        queues = list(cluster.queues.values())
        qt = build_queue_tables(queues, Q)
        if qt["queue_names"] != self._queue_names:
            raise _Fallback("queue-order-changed")
        # --- pending intake ----------------------------------------------
        pend = order[(eff == _PENDING) & (grp_all >= 0)]
        intake = pend[np.argsort(self.p_group[pend], kind="stable")]
        counts = (np.bincount(self.p_group[intake], minlength=NG)
                  if NG else np.zeros((0,), np.int64))
        if counts.size and int(counts.max()) > T:
            raise _Fallback("overflow-tasks")
        # fresh first-encounter type ids from the stable intern ids
        iid_seq = self.p_iid[intake]
        if len(iid_seq):
            uniq, first, inv = np.unique(
                iid_seq, return_index=True, return_inverse=True)
            order_first = np.argsort(first, kind="stable")
            rank = np.empty(len(uniq), np.int64)
            rank[order_first] = np.arange(len(uniq))
            tid_seq = rank[inv]
            reps = uniq[order_first]
            Yn = len(uniq)
        else:
            tid_seq = np.zeros((0,), np.int64)
            reps = np.zeros((0,), np.int64)
            Yn = 0
        Y = _round_up(max(Yn, 1, cap.types), 4)
        if Y != cap.types and Yn > cap.types:
            raise _Fallback("overflow-types")
        # --- dirty-gang task rows -----------------------------------------
        og = old.gangs
        task_valid = np.asarray(og.task_valid)
        task_req = np.asarray(og.task_req)
        task_type_old = np.asarray(og.task_type)
        tnames = self._task_names_obj
        if dirty_gangs:
            dg = np.asarray(sorted(dirty_gangs), np.int64)
            task_valid = task_valid.copy()
            task_req = task_req.copy()
            tnames = tnames.copy()
            task_valid[dg] = False
            task_req[dg] = 0.0
            tnames[dg] = None
            dflag = np.zeros((NG,), bool)
            dflag[dg[dg < NG]] = True
            dsel = dflag[self.p_group[intake]]
            rows_d = intake[dsel]
            if len(rows_d):
                names_d = self.p_names[rows_d].astype(str)
                order_d = np.lexsort((
                    names_d, self.p_crea[rows_d], -self.p_prio[rows_d],
                    self.p_leader[rows_d], self.p_group[rows_d]))
                rows_s = rows_d[order_d]
                g_of = self.p_group[rows_s]
                first_g = np.ones(len(rows_s), bool)
                first_g[1:] = g_of[1:] != g_of[:-1]
                seg_start = np.nonzero(first_g)[0]
                seg = np.cumsum(first_g) - 1
                ti = (np.arange(len(rows_s)) - seg_start[seg]).astype(
                    np.int32)
                self.p_ti[rows_s] = ti
                task_valid[g_of, ti] = True
                task_req[g_of, ti] = self._intern_req[self.p_iid[rows_s]]
                tnames[g_of, ti] = self.p_names[rows_s]
            self._task_names_obj = tnames
        # task_type renumbers globally (dense first-encounter ids)
        task_type = np.zeros((G, T), np.int32)
        if len(intake):
            task_type[self.p_group[intake], self.p_ti[intake]] = tid_seq
        task_type = self._swap_if_equal(task_type, task_type_old)
        # --- type table ---------------------------------------------------
        type_req = np.zeros((Y, R), np.float32)
        if Yn:
            type_req[:Yn] = self._intern_req[reps]
        type_req = self._swap_if_equal(type_req, np.asarray(og.type_req))
        # --- gang scalar tables (vectorized over the ledger) -------------
        gk_valid = np.zeros((G,), bool)
        gk_valid[:NG] = counts > 0
        queue_col = np.zeros((G,), np.int32)
        queue_col[:NG] = self.g_queue[:NG]
        min_member = np.zeros((G,), np.int32)
        min_member[:NG] = self.g_minm[:NG]
        priority = np.zeros((G,), np.int32)
        priority[:NG] = self.g_prio[:NG]
        preemptible = np.zeros((G,), bool)
        preemptible[:NG] = self.g_preempt[:NG]
        creation = np.zeros((G,), np.int32)
        creation[:NG] = np.arange(NG, dtype=np.int32)
        backoff = np.zeros((G,), np.int32)
        backoff[:NG] = self.g_unsched[:NG].astype(np.int32)
        req_lvl = np.full((G,), -1, np.int32)
        req_lvl[:NG] = self.g_reqlvl[:NG]
        pref_lvl = np.full((G,), -1, np.int32)
        pref_lvl[:NG] = self.g_preflvl[:NG]
        S = np.asarray(og.subgroup_valid).shape[1]
        sub_valid = np.zeros((G, S), bool)
        sub_valid[:NG, 0] = True
        sub_minm = np.zeros((G, S), np.int32)
        sub_minm[:NG, 0] = min_member[:NG]
        sub_rlvl = np.full((G, S), -1, np.int32)
        sub_rlvl[:NG] = np.where(req_lvl[:NG, None] >= 0,
                                 req_lvl[:NG, None], -1)
        stale_s = np.full((G,), -1.0, np.float32)
        has_stale = ~np.isnan(self.g_stale[:NG])
        stale_s[:NG] = np.where(
            has_stale,
            np.maximum(0.0, now - np.where(has_stale, self.g_stale[:NG],
                                           0.0)),
            -1.0).astype(np.float32)
        # --- running section ---------------------------------------------
        run_sel = (eff >= _BOUND) & (eff <= _RELEASING)
        run_rows = order[run_sel]
        Mu = len(run_rows)
        if Mu > M:
            raise _Fallback("overflow-running")
        r_node = self.p_eff_node[run_rows]
        r_grp = self.p_group[run_rows]
        r_rel = self.p_eff_status[run_rows] == _RELEASING
        r_req = self.p_req[run_rows].copy()
        rk = dict(
            req=np.zeros((M, R), np.float32),
            node=np.full((M,), -1, np.int32),
            queue=np.zeros((M,), np.int32),
            gang=np.full((M,), -1, np.int32),
            priority=np.zeros((M,), np.int32),
            preemptible=np.zeros((M,), bool),
            valid=np.zeros((M,), bool),
            releasing=np.zeros((M,), bool),
            runtime_s=np.zeros((M,), np.float32),
            device=np.full((M,), -1, np.int32),
            devices_mask=np.zeros((M,), np.int32),
            accel_held=np.zeros((M,), np.float32),
            accel_mem=np.zeros((M,), np.float32),
            filter_class=np.zeros((M,), np.int32),
            extended=np.zeros((M, np.asarray(old.running.extended
                                             ).shape[1]), np.float32),
        )
        running_count = np.zeros((G,), np.int32)
        sub_running = np.zeros((G, S), np.int32)
        if Mu:
            rk["req"][:Mu] = r_req
            rk["node"][:Mu] = r_node
            rk["gang"][:Mu] = r_grp
            rk["valid"][:Mu] = True
            rk["releasing"][:Mu] = r_rel
            has_grp = r_grp >= 0
            gsafe = np.maximum(r_grp, 0)
            if NG:
                rk["queue"][:Mu] = np.where(
                    has_grp, self.g_queue[:NG][gsafe], 0)
                rk["priority"][:Mu] = np.where(
                    has_grp, self.g_prio[:NG][gsafe], 0)
                rk["preemptible"][:Mu] = (has_grp
                                          & self.g_preempt[:NG][gsafe])
                started = self.g_start[:NG][gsafe]
                rk["runtime_s"][:Mu] = np.where(
                    has_grp & (started >= 0),
                    np.maximum(0.0, now - started), -1.0)
            active = has_grp & ~r_rel
            np.add.at(running_count, gsafe[active], 1)
            np.add.at(sub_running,
                      (gsafe[active],
                       np.zeros(int(active.sum()), np.int64)), 1)
        self._occupancy(rk, run_rows, r_node, r_rel, N)
        min_needed = np.maximum(min_member - running_count, 0)
        sub_min_needed = np.maximum(sub_minm - sub_running, 0)
        # --- scheduling signatures (same code as the builder) ------------
        task_sub = self._const["task_subgroup"]
        big = np.int64(Y) * (S + 1) + 1
        comp = np.where(task_valid,
                        task_type.astype(np.int64) * (S + 1) + task_sub,
                        big)
        comp = np.sort(comp, axis=1)
        sub_mn = np.where(sub_valid, sub_min_needed, -2)
        sub_rl = np.where(sub_valid, sub_rlvl, -2)
        sig_mat = np.concatenate([
            comp, sub_mn, sub_rl,
            queue_col[:, None].astype(np.int64),
            min_needed[:, None], req_lvl[:, None],
            pref_lvl[:, None], self._const["anti_self_level"][:, None],
            preemptible[:, None].astype(np.int64),
            (~gk_valid[:, None]).astype(np.int64),
        ], axis=1, dtype=np.int64)
        sig = dense_row_ids(sig_mat).astype(np.int32)
        # --- rollups (shared section builder) ----------------------------
        gk_roll = dict(task_req=task_req, task_valid=task_valid,
                       queue=queue_col, valid=gk_valid,
                       task_extended=self._const["task_extended"])
        roll = derive_rollups(
            node_alloc=np.asarray(old.nodes.allocatable),
            claim_used=np.zeros((N, R), np.float32),
            rk=rk, gk=gk_roll,
            g_of_ext=self._const["ext_accel"],
            r_mig=np.zeros((M,), np.float32),
            queue_usage=queue_usage, q_index=qt["q_index"],
            q_parent=qt["q_parent"], q_depth=qt["q_depth"],
            num_queues=len(queues))
        # --- hints (same expressions as the builder) ---------------------
        has_fracs = bool(self._const["task_portion"].any()
                         or self._const["task_accel_mem"].any()
                         or (rk["device"] >= 0).any())
        tvm = task_valid[:, :, None]
        uniform = (
            not has_fracs
            and bool((self._const["task_nominated"] < 0).all())
            and bool((self._const["anti_self_level"] == -1).all())
            and bool((np.where(tvm, task_req, task_req[:, :1])
                      == task_req[:, :1]).all())
            and bool((np.where(
                tvm, self._const["task_selector"],
                self._const["task_selector"][:, :1])
                == self._const["task_selector"][:, :1]).all())
            and bool((np.where(
                task_valid, self._const["task_filter_class"],
                self._const["task_filter_class"][:, :1])
                == self._const["task_filter_class"][:, :1]).all()))
        node_valid = np.asarray(old.nodes.valid)
        dense = (
            len(self._node_names) >= 0
            and bool(np.asarray(old.nodes.filter_masks)[0][
                node_valid].all())
            and bool((self._const["anti_self_level"] < 0).all())
            and bool((sub_rlvl < 0).all()))
        # --- assemble host ClusterState ----------------------------------
        sw = self._swap_if_equal
        gangs = old.gangs.replace(
            queue=sw(queue_col, np.asarray(og.queue)),
            min_member=sw(min_member, np.asarray(og.min_member)),
            priority=sw(priority, np.asarray(og.priority)),
            preemptible=sw(preemptible, np.asarray(og.preemptible)),
            valid=sw(gk_valid, np.asarray(og.valid)),
            creation_order=sw(creation, np.asarray(og.creation_order)),
            backoff=sw(backoff, np.asarray(og.backoff)),
            task_req=sw(task_req, np.asarray(og.task_req)),
            task_valid=sw(task_valid, np.asarray(og.task_valid)),
            required_level=sw(req_lvl, np.asarray(og.required_level)),
            preferred_level=sw(pref_lvl,
                               np.asarray(og.preferred_level)),
            running_count=sw(running_count,
                             np.asarray(og.running_count)),
            min_needed=sw(min_needed, np.asarray(og.min_needed)),
            stale_s=sw(stale_s, np.asarray(og.stale_s)),
            task_type=sw(task_type, task_type_old),
            sig=sw(sig, np.asarray(og.sig)),
            type_req=type_req,
            subgroup_valid=sw(sub_valid, np.asarray(og.subgroup_valid)),
            subgroup_min_member=sw(sub_minm,
                                   np.asarray(og.subgroup_min_member)),
            subgroup_min_needed=sw(sub_min_needed,
                                   np.asarray(og.subgroup_min_needed)),
            subgroup_required_level=sw(
                sub_rlvl, np.asarray(og.subgroup_required_level)),
        )
        orn = old.running
        running = old.running.replace(**{
            k: sw(v, np.asarray(getattr(orn, k)))
            for k, v in rk.items()})
        oq = old.queues
        queues_st = old.queues.replace(
            parent=sw(qt["q_parent"], np.asarray(oq.parent)),
            depth=sw(qt["q_depth"], np.asarray(oq.depth)),
            priority=sw(qt["q_priority"], np.asarray(oq.priority)),
            quota=sw(qt["q_quota"], np.asarray(oq.quota)),
            over_quota_weight=sw(qt["q_oqw"],
                                 np.asarray(oq.over_quota_weight)),
            limit=sw(qt["q_limit"], np.asarray(oq.limit)),
            allocated=sw(roll["q_alloc"], np.asarray(oq.allocated)),
            allocated_nonpreemptible=sw(
                roll["q_alloc_np"],
                np.asarray(oq.allocated_nonpreemptible)),
            request=sw(roll["q_request"], np.asarray(oq.request)),
            usage=sw(roll["q_usage"], np.asarray(oq.usage)),
            valid=sw(qt["q_valid"], np.asarray(oq.valid)),
            creation_order=sw(qt["q_creation"],
                              np.asarray(oq.creation_order)),
            preempt_min_runtime=sw(qt["q_preempt_mrt"],
                                   np.asarray(oq.preempt_min_runtime)),
            reclaim_min_runtime=sw(qt["q_reclaim_mrt"],
                                   np.asarray(oq.reclaim_min_runtime)),
            preempt_min_runtime_eff=sw(
                np.asarray(qt["q_preempt_eff"], np.float32),
                np.asarray(oq.preempt_min_runtime_eff)),
            reclaim_min_runtime_eff=sw(
                np.asarray(qt["q_reclaim_eff"], np.float32),
                np.asarray(oq.reclaim_min_runtime_eff)),
        )
        nodes_st = old.nodes.replace(
            free=sw(roll["node_free"], np.asarray(old.nodes.free)),
            releasing=sw(roll["node_rel"],
                         np.asarray(old.nodes.releasing)),
            device_free=sw(self._occ_dev_free,
                           np.asarray(old.nodes.device_free)),
            device_releasing=sw(self._occ_dev_rel,
                                np.asarray(old.nodes.device_releasing)),
        )
        host_new = _cs.ClusterState(
            nodes=nodes_st, queues=queues_st, gangs=gangs,
            running=running)
        # --- index --------------------------------------------------------
        running_names = [""] * M
        if Mu:
            running_names[:Mu] = self.p_names[run_rows].tolist()
        index = _cs.SnapshotIndex(
            node_names=self._node_names,
            queue_names=qt["queue_names"],
            gang_names=list(self.g_names),
            task_names=self._task_names_obj.tolist(),
            running_pod_names=running_names,
            selector_keys=[],
            label_vocab={},
            topology_levels=self._topo_levels,
            needs_device_table=has_fracs,
            uniform_gangs=uniform,
            has_required_topology=bool((req_lvl >= 0).any()),
            has_preferred_topology=bool((pref_lvl >= 0).any()),
            has_subgroup_topology=bool((sub_rlvl >= 0).any()),
            has_extended_resources=False,
            extended_keys=[],
            has_reclaim_minruntime=bool((qt["q_reclaim_mrt"] > 0).any()),
            has_anti_groups=len(self._const["anti_term_level"]) > 0,
            num_anti_groups=len(self._const["anti_term_level"]),
            has_attract_groups=bool(
                (self._const["attract_needs"] >= 0).any()),
            max_queue_depth=int(qt["q_depth"].max(initial=0)),
            num_leaf_queues=int(
                (qt["q_valid"] & ~np.isin(
                    np.arange(Q),
                    qt["q_parent"][qt["q_parent"] >= 0])).sum()),
            num_pending_gangs=int(
                np.asarray(gangs.task_valid).any(axis=1).sum()),
            claims_by_pod={},
            host_tables={
                "task_portion": self._const["task_portion"],
                "task_accel_mem": self._const["task_accel_mem"],
                "task_req0": np.ascontiguousarray(task_req[:, :, 0]),
                "task_dra": self._const["task_dra"],
                "running_gang": rk["gang"],
                "queue_usage": roll["q_usage"],
                # the device-side gangs.valid mask (gangs with pending
                # tasks), host copy — kai-pulse starvation counters
                # advance against exactly what the kernel sees
                "gang_valid": np.asarray(gangs.valid),
            },
            dense_feasibility=dense,
        )
        # pre-seed the columnar name views (cached_property slots)
        index.task_names_arr = self._task_names_obj
        index.node_names_arr = np.array(self._node_names, dtype=object)
        index.gang_names_arr = np.array(index.gang_names, dtype=object)
        index.running_pod_names_arr = np.array(running_names,
                                               dtype=object)
        return host_new, index

    @staticmethod
    def _swap_if_equal(new: np.ndarray, old: np.ndarray) -> np.ndarray:
        """Reuse the previous cycle's array object when the recomputed
        content is identical — downstream, `is` short-circuits both the
        ship compare and the device transfer."""
        if (new is old) or (new.shape == old.shape
                            and new.dtype == old.dtype
                            and np.array_equal(new, old)):
            return old
        return new

    # -- device occupancy (gated subset of the builder's section) ---------

    def _occupancy(self, rk, run_rows, r_node, r_rel, N) -> None:
        D = self._dev_template.shape[1]
        dev_free = self._dev_template.copy()
        dev_rel = np.zeros((N, D), np.float32)
        whole_k = np.rint(self.p_req[run_rows, 0]).astype(np.int64)
        has_dev = self.p_hasdev[run_rows]
        on = r_node >= 0
        touches = on & (whole_k > 0)
        special = touches & has_dev
        node_special = np.zeros((N,), bool)
        node_special[r_node[special]] = True
        vec = touches & ~special & ~node_special[np.maximum(r_node, 0)]
        vj = np.nonzero(vec)[0]
        if len(vj):
            accel_counts_a = self._accel_counts
            vn = r_node[vj]
            ordv = np.argsort(vn, kind="stable")
            vj, vn = vj[ordv], vn[ordv]
            vk = whole_k[vj]
            cum = np.cumsum(vk) - vk
            first = np.ones(len(vj), bool)
            first[1:] = vn[1:] != vn[:-1]
            grp = np.cumsum(first) - 1
            off = cum - cum[np.nonzero(first)[0]][grp]
            k_eff = np.clip(accel_counts_a[vn] - off, 0, vk)
            end = off + k_eff
            rk["devices_mask"][vj] = (
                (np.int64(1) << end) - (np.int64(1) << off)
            ).astype(np.int32)
            rk["accel_held"][vj] = k_eff.astype(np.float32)
            tot = int(k_eff.sum())
            if tot:
                rep = np.repeat(np.arange(len(vj)), k_eff)
                dpos = (np.arange(tot)
                        - np.repeat(np.cumsum(k_eff) - k_eff, k_eff)
                        + np.repeat(off, k_eff))
                nrep = vn[rep]
                dev_free[nrep, dpos] = 0.0
                relm = r_rel[vj][rep]
                dev_rel[nrep[relm], dpos[relm]] += 1.0
        rest = np.nonzero(touches & ~vec)[0]
        if len(rest):
            # exact vectorized path for recorded-device whole pods: a
            # debit is the template value and order is irrelevant UNLESS
            # the node hosts a first-fit pod (no recorded devices) or a
            # double-booked device cell — only those nodes' pods replay
            # the builder's sequential loop
            seq_nodes = np.zeros((N,), bool)
            seq_nodes[r_node[rest[~has_dev[rest]]]] = True
            vecr = rest[~seq_nodes[r_node[rest]]]
            masks = self.p_devmask[run_rows[vecr]]

            def held_cells(sub, sub_masks):
                """(node*D + dev) flat indices of every held device."""
                pj, dj = np.nonzero(
                    (sub_masks[:, None] >> np.arange(D)) & 1)
                return r_node[sub][pj] * D + dj

            cells = held_cells(vecr, masks)
            cnt = np.bincount(cells, minlength=N * D)
            booked_nodes = np.nonzero(
                (cnt.reshape(N, D) > 1).any(axis=1))[0]
            if len(booked_nodes):
                seq_nodes[booked_nodes] = True
                keep = ~seq_nodes[r_node[vecr]]
                vecr, masks = vecr[keep], masks[keep]
                cells = held_cells(vecr, masks)
                cnt = np.bincount(cells, minlength=N * D)
            if len(vecr):
                tmpl = self._dev_template
                dev_free -= tmpl * (cnt.reshape(N, D) > 0)
                rk["devices_mask"][vecr] = masks
                rk["accel_held"][vecr] = self.p_held[run_rows[vecr]]
                relj = vecr[r_rel[vecr]]
                if len(relj):
                    rel_cells = held_cells(relj,
                                           self.p_devmask[run_rows[relj]])
                    dev_rel += (tmpl.reshape(-1) * np.bincount(
                        rel_cells, minlength=N * D)).reshape(N, D)
            seq = rest[seq_nodes[r_node[rest]]]
            if len(seq):
                self._occupancy_sequential(
                    rk, run_rows, r_node, r_rel, seq, whole_k,
                    dev_free, dev_rel)
        self._occ_dev_free = dev_free
        self._occ_dev_rel = dev_rel

    def _occupancy_sequential(self, rk, run_rows, r_node, r_rel, rest,
                              whole_k, dev_free, dev_rel) -> None:
        """Builder-identical per-pod loop for order-dependent cases
        (first-fit pods on device-recorded nodes, double-booked cells)."""
        for jj in rest.tolist():
            pod = self.p_objs[run_rows[jj]]
            ni = int(r_node[jj])
            k = int(whole_k[jj])
            if pod.accel_devices:
                devs = list(pod.accel_devices)[:k]
            else:
                devs = list(np.nonzero(
                    dev_free[ni] >= 1.0 - 1e-6)[0][:k])
            mask = 0
            for d0 in devs:
                taken = min(1.0, dev_free[ni, d0])
                dev_free[ni, d0] -= taken
                if r_rel[jj]:
                    dev_rel[ni, d0] += taken
                mask |= 1 << int(d0)
            rk["devices_mask"][jj] = mask
            rk["accel_held"][jj] = float(len(devs))

    # -- shipping ----------------------------------------------------------

    def _ship(self, host_new):
        """Transfer only changed leaves; unchanged leaves keep their
        previous device buffers (and their previous host objects, so the
        next cycle's compares short-circuit on identity).  The transfer
        section is timed (and span-recorded) as the cycle's "upload"
        phase.

        All changed leaves ship in ONE batched ``device_put`` (a
        ``{keystr: array}`` dict, mirroring ``build_snapshot``'s
        one-shot pattern) through the kai-wire TransferLedger — the
        previous per-leaf loop cost one dispatch round trip per changed
        leaf through a tunneled TPU.  The ledger records both the
        would-have-been dispatch count (``leaves``) and the actual one
        (``dispatches`` == 1), keyed by the same leaf names the full
        build uses so redundancy tracking spans both paths.
        """
        t_ship = time.perf_counter()
        new_paths, treedef = jax.tree_util.tree_flatten_with_path(
            host_new)
        old_leaves = jax.tree_util.tree_leaves(self._host)
        dev_leaves = jax.tree_util.tree_leaves(self._dev)
        out_dev, out_host = list(dev_leaves), list(old_leaves)
        changed: dict[str, object] = {}
        slot: dict[str, int] = {}
        bytes_ = 0
        for i, ((path, new), old) in enumerate(zip(new_paths,
                                                   old_leaves)):
            # equal_nan on float leaves: a NaN-carrying leaf (e.g.
            # unset stale timestamps) must not read as "changed"
            # forever — the ledger would (rightly) flag the identical
            # re-upload as redundant bytes every cycle
            if new is old or (
                    getattr(new, "shape", None) == old.shape
                    and new.dtype == old.dtype
                    and np.array_equal(new, old,
                                       equal_nan=new.dtype.kind == "f")):
                continue
            name = jax.tree_util.keystr(path) or f"[{i}]"
            changed[name] = new
            slot[name] = i
            out_host[i] = new
            bytes_ += int(new.nbytes)
        leaves = len(changed)
        dispatches = 0
        if changed:
            dispatches = 1
            # leaf_names must follow FLATTEN order, and jax flattens
            # dict keys sorted — insertion (traversal) order would pair
            # names with the wrong leaves whenever a patch spans
            # sections (ClusterState fields don't sort alphabetically)
            shipped = _wire.LEDGER.device_put(
                changed, reason=_wire.REASON_JOURNAL_PATCH,
                leaf_names=sorted(changed))
            for name, dev in shipped.items():
                out_dev[slot[name]] = dev
        self._host = jax.tree_util.tree_unflatten(treedef, out_host)
        self._dev = jax.tree_util.tree_unflatten(treedef, out_dev)
        ship_s = time.perf_counter() - t_ship
        self.stats.leaves_shipped += leaves
        self.stats.bytes_shipped += bytes_
        self._last_ship = (leaves, bytes_, ship_s, dispatches)
        # NOT a device_sync span: jax.device_put is async, so this times
        # the transfer DISPATCH (flatten + compares + enqueue); the
        # transfer itself overlaps the solve and completion is absorbed
        # by the cycle's device_wait sync — exactly the async-attribution
        # rule the tracer exists to make explicit
        self._add_span("upload", t_ship, leaves=leaves, bytes=bytes_,
                       dispatches=dispatches)
        return self._dev

    # -- verification ------------------------------------------------------

    def _verify(self, cluster, now, queue_usage) -> None:
        """Assert the patched snapshot equals a fresh full rebuild,
        element-wise, including the index name maps."""
        # reason "verify" on the wire ledger: the reference rebuild's
        # transfer is deliberate re-upload, not patch-path redundancy
        with _wire.LEDGER.override_reason(_wire.REASON_VERIFY):
            _, fresh_index, fresh_host = _cs.build_snapshot(
                *cluster.snapshot_lists(), now=now,
                queue_usage=queue_usage,
                resource_claims=cluster.resource_claims,
                device_classes=cluster.device_classes,
                volume_claims=cluster.volume_claims,
                storage_classes=cluster.storage_classes,
                capacity=self._capacity, _return_host=True)
        paths_new = jax.tree_util.tree_flatten_with_path(self._host)[0]
        paths_ref = jax.tree_util.tree_flatten_with_path(fresh_host)[0]
        for (path, mine), (_, ref) in zip(paths_new, paths_ref):
            name = jax.tree_util.keystr(path)
            if mine.shape != ref.shape or mine.dtype != ref.dtype:
                raise IncrementalVerifyError(
                    f"leaf {name}: shape/dtype {mine.shape}/{mine.dtype}"
                    f" != {ref.shape}/{ref.dtype}")
            if not np.array_equal(np.asarray(mine), np.asarray(ref)):
                bad = np.nonzero(np.asarray(mine) != np.asarray(ref))
                raise IncrementalVerifyError(
                    f"leaf {name}: {len(bad[0])} mismatching elements "
                    f"(first at {[int(b[0]) for b in bad if len(b)]})")
        mine_i, ref_i = self._index, fresh_index
        for field in ("node_names", "queue_names", "gang_names",
                      "task_names", "running_pod_names", "selector_keys",
                      "label_vocab", "topology_levels",
                      "needs_device_table", "uniform_gangs",
                      "has_required_topology", "has_preferred_topology",
                      "has_subgroup_topology", "has_extended_resources",
                      "extended_keys", "has_reclaim_minruntime",
                      "has_anti_groups", "has_attract_groups",
                      "max_queue_depth", "num_leaf_queues",
                      "num_pending_gangs",
                      "num_anti_groups", "claims_by_pod",
                      "dense_feasibility"):
            if getattr(mine_i, field) != getattr(ref_i, field):
                raise IncrementalVerifyError(
                    f"index.{field}: {getattr(mine_i, field)!r} != "
                    f"{getattr(ref_i, field)!r}")

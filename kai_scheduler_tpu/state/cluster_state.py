"""The tensorized cluster snapshot.

The reference scheduler materializes an object-graph snapshot each cycle
(``pkg/scheduler/cache/cluster_info/cluster_info.go:119`` building
``api.ClusterInfo`` out of NodeInfo / PodInfo / PodGroupInfo / QueueInfo,
SURVEY.md section 2.6).  The TPU-native design replaces that object graph
with a **struct-of-arrays pytree** so every per-cycle decision — fairness
division, predicate masks, scoring, gang allocation, victim search — is a
tensor op over static shapes:

- node axis  ``N``  (padded)            — ref NodeInfo
- queue axis ``Q``  (padded, 2+ levels) — ref QueueInfo
- gang axis  ``G``  (padded PodGroups)  — ref PodGroupInfo
- task axis  ``T``  (pending tasks per gang, padded) — ref tasksToAllocate
- running-pod axis ``M`` (bound/running pods, victims) — ref PodInfo
- resource axis ``R = 3`` (accel devices, cpu cores, mem GiB)
- selector-key axis ``K`` (label vocabulary for nodeSelector matching)
- topology-level axis ``L`` (domain id per physical level)

All arrays are fixed-shape so one XLA compilation serves every cycle;
capacity growth only triggers recompiles at padded-size boundaries.
"""
from __future__ import annotations

import dataclasses
import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..apis import types as apis
from ..runtime import wire_ledger as _wire
from . import node_filters

UNLIMITED = apis.UNLIMITED
R = apis.NUM_RESOURCES


class NodeState(struct.PyTreeNode):
    """Per-node accounting — ref ``api/node_info/node_info.go:68-96``.

    ``free`` mirrors NodeInfo.Idle; ``releasing`` the resources of
    terminating pods (allocatable-but-not-yet); ``allocatable`` the total.
    ``device_free`` is the per-accelerator share table (ref
    ``GpuSharingNodeInfo`` + GPU groups): 1.0 = device fully free, partial
    values = fractional sharing in flight; slots past a node's device
    count stay 0.  The accel component of ``free`` equals
    ``device_free.sum(-1)`` by construction.
    """

    allocatable: jax.Array   # f32 [N, R]
    free: jax.Array          # f32 [N, R]
    releasing: jax.Array     # f32 [N, R]
    valid: jax.Array         # bool [N]
    labels: jax.Array        # i32 [N, K]   value-id per selector key, -1 = unset
    topology: jax.Array      # i32 [N, L]   domain id per level, innermost = hostname
    device_free: jax.Array       # f32 [N, D]  idle share per device
    device_releasing: jax.Array  # f32 [N, D]  share being released per device
    #: per-device memory GiB (ref MemoryOfEveryGpuOnNode) for memory-based
    #: share requests
    device_memory_gib: jax.Array  # f32 [N]
    #: hard feasibility per (filter-class, node) — taints/tolerations,
    #: affinity expressions, required pod-(anti-)affinity, evaluated
    #: host-side per distinct pod spec (see ``state/node_filters.py``)
    filter_masks: jax.Array      # bool [X, N]
    #: soft bands per (filter-class, node), pre-weighted (K8sPlugins band)
    soft_scores: jax.Array       # f32 [X, N]
    #: extended scalar resources (MIG profiles etc.) — vocab-encoded
    #: axis E; E=1 all-zero when the snapshot has none
    extended_free: jax.Array       # f32 [N, E]
    extended_releasing: jax.Array  # f32 [N, E]

    @property
    def n(self) -> int:
        return self.valid.shape[0]

    @property
    def d(self) -> int:
        return self.device_free.shape[1]


class QueueState(struct.PyTreeNode):
    """Queue hierarchy + resource shares.

    Ref ``api/queue_info/queue_info.go:32-43`` and the proportion plugin's
    ``resource_share.ResourceShare`` (Deserved / FairShare / MaxAllowed /
    OverQuotaWeight / Allocated / Request / Usage).
    """

    parent: jax.Array        # i32 [Q]  index of parent queue, -1 = top level
    depth: jax.Array         # i32 [Q]  0 = top level
    priority: jax.Array      # i32 [Q]
    quota: jax.Array         # f32 [Q, R]  deserved; UNLIMITED sentinel allowed
    over_quota_weight: jax.Array  # f32 [Q, R]
    limit: jax.Array         # f32 [Q, R]  maxAllowed; UNLIMITED sentinel
    allocated: jax.Array     # f32 [Q, R]  currently allocated to running pods
    allocated_nonpreemptible: jax.Array  # f32 [Q, R]
    request: jax.Array       # f32 [Q, R]  allocated + pending requests
    usage: jax.Array         # f32 [Q, R]  normalized historical usage (usagedb)
    fair_share: jax.Array    # f32 [Q, R]  output of the DRF division kernel
    valid: jax.Array         # bool [Q]
    creation_order: jax.Array  # i32 [Q]  tie-break (older first)
    #: minruntime protection (ref queue_types.go PreemptMinRuntime /
    #: ReclaimMinRuntime, plugins/minruntime) — seconds a job in this queue
    #: must have run before it may be victimized.  Raw per-queue values:
    preempt_min_runtime: jax.Array  # f32 [Q]
    reclaim_min_runtime: jax.Array  # f32 [Q]
    #: hierarchy-resolved values (ref plugins/minruntime/resolver.go):
    #: preempt inherits up the victim's chain; reclaim resolves per
    #: (victim leaf, reclaimer leaf) via the LCA method — the value is
    #: inherited from the victim-side child of the LCA upward.
    preempt_min_runtime_eff: jax.Array  # f32 [Q]
    reclaim_min_runtime_eff: jax.Array  # f32 [Q, Q]  [victim, reclaimer]

    @property
    def q(self) -> int:
        return self.valid.shape[0]


class GangState(struct.PyTreeNode):
    """Pending pod groups with padded task tables.

    Ref ``api/podgroup_info/job_info.go:65-99`` (PodGroupInfo) and
    ``api/podgroup_info/allocation_info.go:27`` (GetTasksToAllocate).
    Tasks are pre-sorted host-side by the task-order plugin semantics
    (priority desc, creation asc) so the allocation kernel can use
    stop-at-first-failure prefix semantics.
    """

    queue: jax.Array         # i32 [G]  queue index
    min_member: jax.Array    # i32 [G]
    priority: jax.Array      # i32 [G]
    preemptible: jax.Array   # bool [G]
    valid: jax.Array         # bool [G]
    creation_order: jax.Array  # i32 [G]  tie-break (older first)
    backoff: jax.Array       # i32 [G]  cycles to skip (SchedulingBackoff)
    task_req: jax.Array      # f32 [G, T, R]
    task_valid: jax.Array    # bool [G, T]
    task_selector: jax.Array  # i32 [G, T, K]  required node-label value-id, -1 = any
    task_portion: jax.Array  # f32 [G, T]  fractional accel request (0 = whole)
    #: memory-based share request GiB (0 = not memory-based); the per-node
    #: portion is ``task_accel_mem / device_memory_gib[node]``
    task_accel_mem: jax.Array  # f32 [G, T]
    required_level: jax.Array   # i32 [G]  topology level index, -1 = none
    preferred_level: jax.Array  # i32 [G]  topology level index, -1 = none
    #: count of this gang's bound/running (non-releasing) pods — feeds
    #: stalegangeviction and elastic ordering
    running_count: jax.Array    # i32 [G]
    #: tasks still needed to reach minMember this cycle:
    #: ``max(0, min_member - running_count)`` — the reference's
    #: GetNumAliveTasks/minAvailable offset (elastic scale-up gangs and
    #: gangs with a bound-but-pipelined remainder need fewer than
    #: min_member new placements to be whole).
    min_needed: jax.Array       # i32 [G]
    #: seconds the gang has been below minMember after starting; -1 = not
    #: stale (ref PodGroupInfo staleness + stalegangeviction action)
    stale_s: jax.Array          # f32 [G]
    #: node-filter class per task (gather row into NodeState.filter_masks)
    task_filter_class: jax.Array  # i32 [G, T]
    #: task-type id per task — distinct (request, selector, portion,
    #: memory, filter-class) tuples; powers the cheap whole-gang
    #: feasibility prefilter (ref ``actions/common/feasible_nodes.go:11``)
    task_type: jax.Array          # i32 [G, T]
    #: scheduling-constraints signature per gang — equivalent gangs (same
    #: queue, task-type multiset, quorum, topology constraints) share an
    #: id, so one fit failure skips the rest for the cycle (ref
    #: ``actions/common/minimal_job_comparison.go``,
    #: ``podgroup_info`` schedulingConstraintsSignature)
    sig: jax.Array                # i32 [G]
    #: extended scalar requests per task (MIG profiles; ref migResources)
    task_extended: jax.Array      # f32 [G, T, E]
    #: accel g-number equivalent per extended key (MIG g-slices, ref
    #: resource_info.go GetTotalGPURequest) — lets the placement kernels
    #: fold MIG requests into the in-cycle queue accel ledger; zeros
    #: for non-MIG keys and when the snapshot has no extended resources
    ext_accel: jax.Array          # f32 [E]
    #: accel devices requested via DRA claims per task (ref draGpuCounts;
    #: already folded into task_req accel for accounting)
    task_dra: jax.Array           # i32 [G, T]
    #: the task-type table (Y distinct types, padded)
    type_req: jax.Array           # f32 [Y, R]
    type_selector: jax.Array      # i32 [Y, K]
    type_portion: jax.Array       # f32 [Y]
    type_mem: jax.Array           # f32 [Y]
    type_class: jax.Array         # i32 [Y]
    type_extended: jax.Array      # f32 [Y, E]
    # --- hierarchical subgroups (ref podgroup_types.go SubGroups +
    # subgroup_info PodSet tree; allocation semantics in
    # actions/common/allocate.go:71-140 allocateSubGroupSet).  Slot 0 is
    # the implicit default subgroup; gangs without declared subgroups put
    # every task there with the gang's own minMember.
    #: subgroup slot per task
    task_subgroup: jax.Array        # i32 [G, T]
    subgroup_valid: jax.Array       # bool [G, S]
    subgroup_min_member: jax.Array  # i32 [G, S]
    #: minMember minus the subgroup's bound/running pods — new placements
    #: needed for the subgroup's quorum this cycle
    subgroup_min_needed: jax.Array  # i32 [G, S]
    #: per-subgroup required topology level (-1 = none): every task of
    #: the subgroup must land in ONE domain at this level, independently
    #: chosen per subgroup
    subgroup_required_level: jax.Array  # i32 [G, S]

    @property
    def s(self) -> int:
        return self.subgroup_valid.shape[1]
    #: nominated node index per task, -1 = none (nominatednode plugin)
    task_nominated: jax.Array     # i32 [G, T]
    #: gang-internal anti-affinity: tasks of this gang may not share a
    #: topology domain at this level (L = per-node, -1 = none)
    anti_self_level: jax.Array    # i32 [G]
    #: IN-CYCLE exclusion terms (the tensorization of InterPodAffinity /
    #: NodePorts over virtually-allocated session state): a term is a
    #: row of the cycle's claimed-domain table (AllocationResult
    #: ``anti_used``).  When a gang with ``anti_marks`` slots places, it
    #: claims its nodes' domains (at each term's level) in those rows; a
    #: gang may never place into a domain claimed in any of its
    #: ``anti_avoids`` rows.  Three term kinds share the machinery:
    #: SYMMETRIC rows (mutual required anti-affinity — members mark and
    #: avoid), FORWARD/REVERSE row pairs (asymmetric required anti:
    #: label-matchers mark fwd / carriers avoid fwd, carriers mark rev /
    #: matchers avoid rev), and PORT rows (pending pods sharing a host
    #: port — carriers mark and avoid at per-node granularity).
    #: -1 = unused slot; term ids index ``anti_term_level``.
    anti_marks: jax.Array         # i32 [G, KT]
    anti_avoids: jax.Array        # i32 [G, KT]
    #: topology level per term row (num_topo_levels = per-node)
    anti_term_level: jax.Array    # i32 [TA]
    #: IN-CYCLE attraction (required POSITIVE affinity toward a gang
    #: that places earlier this cycle — upstream InterPodAffinity over
    #: virtually-allocated session state): need rows in the SAME
    #: claimed-domain table.  A gang with need slots may only place on
    #: nodes whose domain (at the row's level) is claimed in EVERY need
    #: row — statically by a running match (``attract_static``) or
    #: in-cycle by an anchor gang's placement (anchors carry the row in
    #: ``anti_marks``; the marking machinery is shared).  -1 = unused.
    attract_needs: jax.Array      # i32 [G, KP]
    #: statically-satisfied nodes per table row (running matches at
    #: snapshot build), OR-ed with the in-cycle claims — bool [TA, N]
    attract_static: jax.Array     # bool [TA, N]

    @property
    def g(self) -> int:
        return self.valid.shape[0]

    @property
    def t(self) -> int:
        return self.task_valid.shape[1]


class RunningState(struct.PyTreeNode):
    """Bound/running pods — the victim candidates for reclaim / preempt /
    consolidation.  Ref PodInfo with status in {Bound, Running, Releasing}.
    """

    req: jax.Array           # f32 [M, R]
    node: jax.Array          # i32 [M]  node index, -1 invalid
    queue: jax.Array         # i32 [M]
    gang: jax.Array          # i32 [M]  owning pod-group id (host-side table)
    priority: jax.Array      # i32 [M]
    preemptible: jax.Array   # bool [M]
    valid: jax.Array         # bool [M]
    #: pod is terminating — occupies resources but is not a victim candidate
    releasing: jax.Array     # bool [M]
    #: seconds since the owning gang started (for minruntime filters)
    runtime_s: jax.Array     # f32 [M]
    #: shared device index for fractional pods (-1 = whole-device pod)
    device: jax.Array        # i32 [M]
    #: bitmask of occupied devices for whole-device pods (bit d set =>
    #: device d held); 0 for fractional pods
    devices_mask: jax.Array  # i32 [M]
    #: accel share actually held (portion for fractional, device count for
    #: whole) — the amount returned to ``device_free`` on eviction
    accel_held: jax.Array    # f32 [M]
    #: memory-based request GiB (0 = not memory-based) — consolidation
    #: re-placement must recompute the portion for the *target* node
    accel_mem: jax.Array     # f32 [M]
    #: node-filter class (consolidation moves must respect the pod's
    #: taints/affinity constraints on the target node)
    filter_class: jax.Array  # i32 [M]
    #: extended (MIG) scalars actually held — credited back to the
    #: scenario pools when the pod is victimised
    extended: jax.Array      # f32 [M, E]

    @property
    def m(self) -> int:
        return self.valid.shape[0]


class ClusterState(struct.PyTreeNode):
    """The full per-cycle snapshot handed to the solver kernels."""

    nodes: NodeState
    queues: QueueState
    gangs: GangState
    running: RunningState

    @property
    def total_capacity(self) -> jax.Array:
        """Cluster-wide allocatable per resource, f32 [R]."""
        return jnp.sum(
            jnp.where(self.nodes.valid[:, None], self.nodes.allocatable, 0.0),
            axis=0,
        )


# ---------------------------------------------------------------------------
# Padding helpers
# ---------------------------------------------------------------------------

#: MINIMUM in-cycle exclusion term slots per gang (marks/avoids each);
#: the snapshot builder widens the slot dimension (bucketed to powers of
#: two) whenever a gang carries more distinct terms, so no term is ever
#: dropped — only the compiled shape changes
ANTI_SLOTS = 4


def _round_up(n: int, multiple: int = 8) -> int:
    """Pad sizes to multiples so capacity growth rarely recompiles."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def _pow2_ceil(n: int) -> int:
    """Smallest power of two >= n — the shared slot/row bucketing, so
    count drift across cycles rarely changes a compiled shape."""
    return 1 << max(0, n - 1).bit_length()


def dense_row_ids(mat: "np.ndarray") -> "np.ndarray":
    """Dense ids over distinct rows, identical to
    ``np.unique(mat, axis=0, return_inverse=True)[1]`` (ids index the
    lexicographically sorted distinct rows) but ~50x faster at the
    scheduling-signature shape: ``unique(axis=0)`` compares rows as
    void scalars, one memcmp per comparison, while a column lexsort +
    neighbor compare stays fully vectorized."""
    if not len(mat):
        return np.zeros((0,), np.int64)
    order = np.lexsort(mat.T[::-1])
    s = mat[order]
    neq = np.any(s[1:] != s[:-1], axis=1)
    ranks = np.concatenate([[0], np.cumsum(neq)])
    inv = np.empty(len(mat), np.int64)
    inv[order] = ranks
    return inv


#: leader-role label values — ref plugins/kubeflow (job-role master/
#: launcher) and plugins/ray (node-type head)
_LEADER_ROLES = ("master", "launcher", "head")


@dataclasses.dataclass(frozen=True)
class SnapshotCapacity:
    """Padded-size floors for the snapshot axes.

    The incremental snapshotter (``state/incremental.py``) pins these so
    consecutive cycles keep identical compiled shapes while entity
    counts drift — capacity only grows (with slack) at full rebuilds,
    mirroring how the reference's cache rarely reallocates.  Zero floors
    keep the plain count-derived padding.
    """

    nodes: int = 0
    queues: int = 0
    gangs: int = 0
    tasks: int = 0
    running: int = 0
    types: int = 0


# ---------------------------------------------------------------------------
# Per-section builders — factored out of build_snapshot so the
# incremental snapshotter (state/incremental.py) re-derives sections
# from cached encodes through the SAME code paths the full build runs.
# ---------------------------------------------------------------------------


def build_queue_tables(queues: list[apis.Queue], Q: int) -> dict:
    """Per-queue static tables + minruntime hierarchy resolution.

    Ref ``api/queue_info`` and ``plugins/minruntime`` (resolver.go) —
    see the inline comments.  Returns every ``q_*`` array keyed by name
    plus ``q_index``/``queue_names``.
    """
    queue_names = [q.name for q in queues]
    q_index = {name: i for i, name in enumerate(queue_names)}
    q_parent = np.full((Q,), -1, np.int32)
    q_depth = np.zeros((Q,), np.int32)
    q_priority = np.zeros((Q,), np.int32)
    q_quota = np.zeros((Q, R), np.float32)
    q_oqw = np.zeros((Q, R), np.float32)
    q_limit = np.full((Q, R), UNLIMITED, np.float32)
    q_valid = np.zeros((Q,), bool)
    q_creation = np.zeros((Q,), np.int32)
    q_preempt_mrt = np.zeros((Q,), np.float32)
    q_reclaim_mrt = np.zeros((Q,), np.float32)
    for i, q in enumerate(queues):
        q_valid[i] = True
        q_priority[i] = q.priority
        q_creation[i] = i
        q_preempt_mrt[i] = q.preempt_min_runtime
        q_reclaim_mrt[i] = q.reclaim_min_runtime
        if q.parent is not None:
            q_parent[i] = q_index[q.parent]
        for r in range(R):
            qr = q.resource(r)
            q_quota[i, r] = qr.quota
            q_oqw[i, r] = qr.over_quota_weight
            q_limit[i, r] = qr.limit
    # depth by chasing parents (hierarchy is shallow; bounded loop)
    for i in range(len(queues)):
        d, p = 0, int(q_parent[i])
        while p >= 0:
            d, p = d + 1, int(q_parent[p])
        q_depth[i] = d

    # --- minruntime hierarchy resolution (ref plugins/minruntime) ---------
    def _inherit(vals: np.ndarray) -> np.ndarray:
        """First set (>0) value walking self → root; 0 when none."""
        eff = vals.copy()
        cur = q_parent.copy()
        for _ in range(int(q_depth.max(initial=0)) + 1):
            unset = (eff <= 0) & (cur >= 0)
            if not unset.any():
                break
            eff[unset] = vals[cur[unset]]
            cur = np.where(cur >= 0, q_parent[np.maximum(cur, 0)], -1)
        return np.maximum(eff, 0.0)

    q_preempt_eff = _inherit(q_preempt_mrt)
    if not (q_reclaim_mrt > 0).any():
        # common case: no queue configures reclaim minruntime — skip the
        # O(Q^2 x depth) pairwise LCA resolution entirely
        q_reclaim_eff = np.zeros((Q, Q), np.float32)
    else:
        # ancestor-at-depth table for the LCA walk (top-level first)
        maxd = int(q_depth.max(initial=0)) + 1
        anc_at = np.full((Q, maxd), -1, np.int64)
        for i in range(len(queues)):
            chain_q, p = [i], int(q_parent[i])
            while p >= 0:
                chain_q.append(p)
                p = int(q_parent[p])
            for d, qx in enumerate(reversed(chain_q)):
                anc_at[i, d] = qx
        # match depth per (victim, reclaimer) pair; start queue = the
        # victim-side child of the LCA (clamped to the victim's leaf;
        # different top-level queues degenerate to the victim's top-level
        # queue — the "shadow parent" rule in resolver.go)
        eq = (anc_at[:, None, :] == anc_at[None, :, :]) & (
            anc_at[:, None, :] >= 0)                          # [Q, Q, D]
        match_d = (eq * (np.arange(maxd) + 1)).max(axis=-1) - 1
        start_d = np.minimum(match_d + 1,
                             q_depth[:, None].astype(np.int64))
        start_q = np.take_along_axis(
            np.broadcast_to(anc_at[:, None, :], (Q, Q, maxd)),
            start_d[:, :, None], axis=2)[:, :, 0]             # [Q, Q]
        q_reclaim_inh = _inherit(q_reclaim_mrt)
        q_reclaim_eff = q_reclaim_inh[np.maximum(start_q, 0)]
        q_reclaim_eff[start_q < 0] = 0.0
    return dict(
        queue_names=queue_names, q_index=q_index, q_parent=q_parent,
        q_depth=q_depth, q_priority=q_priority, q_quota=q_quota,
        q_oqw=q_oqw, q_limit=q_limit, q_valid=q_valid,
        q_creation=q_creation, q_preempt_mrt=q_preempt_mrt,
        q_reclaim_mrt=q_reclaim_mrt, q_preempt_eff=q_preempt_eff,
        q_reclaim_eff=q_reclaim_eff)


def derive_rollups(*, node_alloc, claim_used, rk, gk, g_of_ext, r_mig,
                   queue_usage, q_index, q_parent, q_depth,
                   num_queues) -> dict:
    """Derived node free/releasing + queue allocated/request/usage
    rollups — the host mirror of the queuecontroller status (vectorized
    scatter-adds over the running/pending tables).  Shared verbatim by
    the full build and the incremental patch path so both derive
    bit-identical ledgers from the same section tables.
    """
    N = node_alloc.shape[0]
    Q = q_parent.shape[0]
    node_used = np.zeros((N, R), np.float32)
    node_rel = np.zeros((N, R), np.float32)
    on_node = rk["valid"] & (rk["node"] >= 0)
    rel_m = on_node & rk["releasing"]
    used_m = on_node & ~rk["releasing"]
    # unknown nodes count for queues, not for node capacity
    np.add.at(node_rel, rk["node"][rel_m], rk["req"][rel_m])
    np.add.at(node_used, rk["node"][used_m], rk["req"][used_m])
    node_free = np.maximum(
        node_alloc - node_used - node_rel - claim_used, 0.0)

    q_alloc = np.zeros((Q, R), np.float32)
    q_alloc_np = np.zeros((Q, R), np.float32)
    q_request = np.zeros((Q, R), np.float32)
    vmask = rk["valid"]
    np.add.at(q_alloc, rk["queue"][vmask], rk["req"][vmask])
    np_mask = vmask & ~rk["preemptible"]
    np.add.at(q_alloc_np, rk["queue"][np_mask], rk["req"][np_mask])
    # The MIG g-equivalents enter the rollups — REQUESTED amounts, not
    # the capacity-clamped held table (rk["extended"]): like the
    # core-resource path, a running MIG pod on an unknown/overcommitted
    # node still counts toward its queue's ledger.
    if g_of_ext.any():
        np.add.at(q_alloc[:, 0], rk["queue"][vmask], r_mig[vmask])
        np.add.at(q_alloc_np[:, 0], rk["queue"][np_mask],
                  r_mig[np_mask])
    q_request += q_alloc
    pending_req = (gk["task_req"]
                   * gk["task_valid"][:, :, None]).sum(axis=1)  # [G, R]
    np.add.at(q_request, gk["queue"][gk["valid"]],
              pending_req[gk["valid"]])
    if g_of_ext.any():
        g_mig = ((gk["task_extended"]
                  * gk["task_valid"][:, :, None]).sum(axis=1)
                 @ g_of_ext)                                    # [G]
        np.add.at(q_request[:, 0], gk["queue"][gk["valid"]],
                  g_mig[gk["valid"]])
    # historical usage (usagedb feed), normalized usage/clusterCapacity —
    # the k_value term of the DRF waterfill (ref usagedb.go:20-60)
    q_usage = np.zeros((Q, R), np.float32)
    if queue_usage:
        for qname, vec in queue_usage.items():
            qi2 = q_index.get(qname)
            if qi2 is not None:
                q_usage[qi2] = np.asarray(vec, np.float32)
    # propagate to parents (requests/allocations roll up the hierarchy)
    for arr in (q_alloc, q_alloc_np, q_request, q_usage):
        for i in sorted(range(num_queues), key=lambda i: -q_depth[i]):
            p = q_parent[i]
            if p >= 0:
                arr[p] += arr[i]
    return dict(node_rel=node_rel, node_free=node_free, q_alloc=q_alloc,
                q_alloc_np=q_alloc_np, q_request=q_request,
                q_usage=q_usage)


# ---------------------------------------------------------------------------
# Snapshot builder (host): api objects -> ClusterState
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SnapshotIndex:
    """Host-side name<->index maps produced alongside a ClusterState so the
    commit path can translate placement tensors back into BindRequests.
    """

    node_names: list[str]
    queue_names: list[str]
    gang_names: list[str]
    #: task pod names per gang slot, [G][T]
    task_names: list[list[str | None]]
    running_pod_names: list[str]
    selector_keys: list[str]
    label_vocab: dict[tuple[str, str], int]
    topology_levels: list[str]
    #: snapshot-derived kernel-config hints (see AllocateConfig): whether
    #: any fractional/memory-based accel request exists (device table
    #: needed), whether every gang's pending tasks are identical replicas
    #: (whole-gang fast path valid), and whether any gang carries a
    #: required topology level (domain loop needed)
    needs_device_table: bool = True
    uniform_gangs: bool = False
    has_required_topology: bool = True
    has_subgroup_topology: bool = True
    has_preferred_topology: bool = True
    has_extended_resources: bool = False
    extended_keys: list[str] = dataclasses.field(default_factory=list)
    #: any queue configures reclaimMinRuntime — its per-(victim,
    #: reclaimer) LCA tables are lane-dependent, so the chunked victim
    #: path must stay off (see VictimConfig.chunk_reclaim)
    has_reclaim_minruntime: bool = False
    #: the snapshot emitted in-cycle exclusion term rows (mutual or
    #: asymmetric required anti-affinity between pending gangs, or a
    #: host port shared by >=2 pending gangs): the placement wavefronts
    #: track their claimed domains in-cycle (AllocateConfig.anti_groups)
    has_anti_groups: bool = False
    #: attraction need rows exist (same-cycle required positive affinity)
    has_attract_groups: bool = False
    #: deepest queue depth (0 = flat) — Session widens its division
    #: recursion to cover the whole hierarchy
    max_queue_depth: int = 1
    #: valid childless queues — preempt chunk width auto-tunes with
    #: this (preemptors spread across many queues fill wider chunks)
    num_leaf_queues: int = 0
    #: gangs with at least one pending task — the live preemptor
    #: spread; the Session clamps the victim wavefront's lane width to
    #: it so junk lanes stop paying freed-pool cost (-1 = unknown)
    num_pending_gangs: int = -1
    #: emitted term-row count (the anti_used table's row dimension is
    #: sized from the state arrays; this is informational)
    num_anti_groups: int = 0
    #: host (numpy) copies of the snapshot-side tables the commit path
    #: reads — kept so cycle results never transfer them back from the
    #: device (see framework.session._pack_commit)
    host_tables: dict = dataclasses.field(default_factory=dict)
    #: pod name → its ResourceClaim names (only pods that declare any) —
    #: the commit path records them on BindRequests
    claims_by_pod: dict = dataclasses.field(default_factory=dict)
    #: feasibility spans the whole node axis: no selectors, filter
    #: classes, anti-affinity, or topology constraints in the snapshot
    dense_feasibility: bool = False

    def node_index(self, name: str) -> int:
        return self.node_names.index(name)

    # object-array views of the name tables, built once per snapshot so
    # the commit path gathers names columnar instead of per-row indexing
    @functools.cached_property
    def task_names_arr(self) -> "np.ndarray":
        return np.array(self.task_names, dtype=object)

    @functools.cached_property
    def node_names_arr(self) -> "np.ndarray":
        return np.array(self.node_names, dtype=object)

    @functools.cached_property
    def gang_names_arr(self) -> "np.ndarray":
        return np.array(self.gang_names, dtype=object)

    @functools.cached_property
    def running_pod_names_arr(self) -> "np.ndarray":
        return np.array(self.running_pod_names, dtype=object)


def build_snapshot(
    nodes: list[apis.Node],
    queues: list[apis.Queue],
    pod_groups: list[apis.PodGroup],
    pods: list[apis.Pod],
    topology: apis.Topology | None = None,
    *,
    max_tasks_per_gang: int | None = None,
    pad: int = 8,
    dtype=jnp.float32,
    now: float | None = None,
    queue_usage: dict[str, "np.ndarray"] | None = None,
    resource_claims: dict[str, apis.ResourceClaim] | None = None,
    device_classes: dict[str, apis.DeviceClass] | None = None,
    volume_claims: dict[str, apis.PersistentVolumeClaim] | None = None,
    storage_classes: dict[str, apis.StorageClass] | None = None,
    capacity: SnapshotCapacity | None = None,
    _return_host: bool = False,
) -> tuple[ClusterState, SnapshotIndex]:
    """Flatten API objects into a ClusterState (+ index for the commit path).

    This is the TPU-native analogue of the reference's snapshot step
    (``cache/cluster_info/cluster_info.go:229`` snapshotNodes,
    ``:346`` snapshotPodGroups).
    """
    cap = capacity or SnapshotCapacity()
    # --- vocabularies -----------------------------------------------------
    selector_keys: list[str] = []
    for pod in pods:
        for k in pod.node_selector:
            if k not in selector_keys:
                selector_keys.append(k)
    label_vocab: dict[tuple[str, str], int] = {}

    def value_id(key: str, value: str) -> int:
        return label_vocab.setdefault((key, value), len(label_vocab))

    # multiple Topology CRDs (ref topology_plugin.go building one domain
    # tree PER Topology object): each tree's levels occupy a distinct
    # slice of the level axis; domain ids stay globally dense, and a
    # gang's TopologyConstraint resolves level names inside ITS named
    # tree
    if topology is None:
        topos: list[apis.Topology] = []
    elif isinstance(topology, apis.Topology):
        topos = [topology]
    else:
        topos = list(topology)
    topo_levels = [lvl for t in topos for lvl in t.levels]
    topo_slices: dict[str, tuple[int, list[str]]] = {}
    _off = 0
    for t in topos:
        topo_slices[t.name] = (_off, list(t.levels))
        _off += len(t.levels)

    def resolve_level(tc: "apis.TopologyConstraint | None",
                      attr: str) -> int:
        if tc is None or not topo_levels:
            return -1
        start, lvls = topo_slices.get(tc.topology, (0, topo_levels))
        name = getattr(tc, attr)
        return start + lvls.index(name) if name in lvls else -1

    L = max(1, len(topo_levels))
    K = max(1, len(selector_keys))

    # extended scalar-resource vocabulary (MIG profiles etc.)
    ext_keys = sorted(
        {k for nd in nodes for k in nd.extended}
        | {k for p in pods for k in p.extended})
    E = max(1, len(ext_keys))
    ext_index = {k: i for i, k in enumerate(ext_keys)}
    # MIG profiles count their g-number toward queue GPU accounting
    # (ref resource_info.go GetTotalGPURequest: totalGpusQuota +=
    # gpuPortion * count).  The per-key g-equivalent vector feeds the
    # snapshot rollups below AND ships with the state (GangState.
    # ext_accel) so the placement kernels apply the same equivalents to
    # their in-cycle queue deltas — MIG-heavy queues hit quota and
    # over-share gates in the cycle that places them.
    g_of_ext = np.zeros((E,), np.float32)
    for _ek, _col in ext_index.items():
        _m = re.search(r"mig-(\d+)g\.", _ek)
        if _m:
            g_of_ext[_col] = float(_m.group(1))

    # --- nodes ------------------------------------------------------------
    live_nodes = [n for n in nodes if not n.unschedulable]
    N = _round_up(max(len(live_nodes), cap.nodes), pad)
    node_alloc = np.zeros((N, R), np.float32)
    node_labels = np.full((N, K), -1, np.int32)
    node_topo = np.full((N, L), -1, np.int32)
    node_valid = np.zeros((N,), bool)
    node_names = [n.name for n in live_nodes]
    domain_vocab: dict[tuple[int, str], int] = {}
    # accel device table (GPU-group equivalent)
    accel_counts = [int(round(n.allocatable.accel)) for n in live_nodes]
    D = max(1, max(accel_counts, default=1))
    if D > 31:
        # whole-device occupancy is tracked as an int32 bitmask
        # (RunningState.devices_mask); >31 devices per node would overflow
        raise ValueError(
            f"nodes with {D} accel devices exceed the 31-devices-per-node "
            "limit of the device bitmask")
    dev_free = np.zeros((N, D), np.float32)
    dev_rel = np.zeros((N, D), np.float32)
    node_dev_mem = np.zeros((N,), np.float32)
    ext_free = np.zeros((N, E), np.float32)
    ext_rel = np.zeros((N, E), np.float32)
    accel_mems = [n.accel_memory_gib for n, c in zip(live_nodes, accel_counts)
                  if c > 0]
    #: cluster-min device memory quantifies memory-based requests for
    #: queue accounting (ref ClusterInfo.MinNodeGPUMemory)
    min_dev_mem = min(accel_mems) if accel_mems else 16.0
    for i, n in enumerate(live_nodes):
        node_alloc[i] = n.allocatable.as_tuple()
        node_valid[i] = True
        dev_free[i, :accel_counts[i]] = 1.0
        node_dev_mem[i] = n.accel_memory_gib
        for ek, ev in n.extended.items():
            ext_free[i, ext_index[ek]] = ev
        for ki, key in enumerate(selector_keys):
            if key in n.labels:
                node_labels[i, ki] = value_id(key, n.labels[key])
        # Topology domains: id per level = dense index of the label-path
        # prefix at that level, so equal ids <=> same physical domain
        # (ref plugins/topology/topology_structs.go DomainID = joined
        # path); the path prefix resets per Topology tree
        off = 0
        for t in topos:
            path: list[str] = []
            for lj, level_key in enumerate(t.levels):
                val = n.labels.get(level_key)
                if val is None:
                    break
                path.append(val)
                node_topo[i, off + lj] = domain_vocab.setdefault(
                    (off + lj, "/".join(path)), len(domain_vocab))
            off += len(t.levels)

    # --- queues (parents before children) --------------------------------
    Q = _round_up(max(len(queues), cap.queues), pad)
    qt = build_queue_tables(queues, Q)
    queue_names, q_index = qt["queue_names"], qt["q_index"]
    q_parent, q_depth = qt["q_parent"], qt["q_depth"]
    q_priority, q_quota, q_oqw = qt["q_priority"], qt["q_quota"], qt["q_oqw"]
    q_limit, q_valid, q_creation = qt["q_limit"], qt["q_valid"], qt["q_creation"]
    q_preempt_mrt, q_reclaim_mrt = qt["q_preempt_mrt"], qt["q_reclaim_mrt"]
    q_preempt_eff, q_reclaim_eff = qt["q_preempt_eff"], qt["q_reclaim_eff"]

    # --- pod groups + tasks ----------------------------------------------
    group_names = [g.name for g in pod_groups]
    g_index = {name: i for i, name in enumerate(group_names)}
    pending_by_group: dict[str, list[apis.Pod]] = {g.name: [] for g in pod_groups}
    running_pods: list[apis.Pod] = []
    for pod in pods:
        if pod.status == apis.PodStatus.PENDING:
            if pod.group in pending_by_group:
                pending_by_group[pod.group].append(pod)
        elif pod.status in (apis.PodStatus.BOUND, apis.PodStatus.RUNNING,
                            apis.PodStatus.RELEASING):
            running_pods.append(pod)

    max_pending = max([len(v) for v in pending_by_group.values()] + [1])
    T = max_tasks_per_gang or max_pending
    if T < max_pending:
        raise ValueError(
            f"max_tasks_per_gang={T} < largest gang ({max_pending} pending "
            "tasks); truncating would starve gangs whose min_member exceeds "
            "the cap")
    T = _round_up(max(T, cap.tasks), 4)
    G = _round_up(max(len(pod_groups), cap.gangs), pad)
    gk = dict(
        queue=np.zeros((G,), np.int32),
        min_member=np.zeros((G,), np.int32),
        priority=np.zeros((G,), np.int32),
        preemptible=np.zeros((G,), bool),
        valid=np.zeros((G,), bool),
        creation_order=np.zeros((G,), np.int32),
        backoff=np.zeros((G,), np.int32),
        task_req=np.zeros((G, T, R), np.float32),
        task_valid=np.zeros((G, T), bool),
        task_selector=np.full((G, T, K), -1, np.int32),
        task_portion=np.zeros((G, T), np.float32),
        task_accel_mem=np.zeros((G, T), np.float32),
        required_level=np.full((G,), -1, np.int32),
        preferred_level=np.full((G,), -1, np.int32),
        running_count=np.zeros((G,), np.int32),
        min_needed=np.zeros((G,), np.int32),
        stale_s=np.full((G,), -1.0, np.float32),
        task_filter_class=np.zeros((G, T), np.int32),
        task_nominated=np.full((G, T), -1, np.int32),
        anti_self_level=np.full((G,), -1, np.int32),
        anti_marks=np.full((G, ANTI_SLOTS), -1, np.int32),
        anti_avoids=np.full((G, ANTI_SLOTS), -1, np.int32),
        attract_needs=np.full((G, 2), -1, np.int32),
        task_type=np.zeros((G, T), np.int32),
        sig=np.zeros((G,), np.int32),
        task_extended=np.zeros((G, T, E), np.float32),
        ext_accel=g_of_ext,
        task_dra=np.zeros((G, T), np.int32),
    )
    # --- subgroup tables (slot 0 = implicit default subgroup, so the
    # slot count is max declared subgroups + 1) ----------------------------
    S = _round_up(max([len(g.sub_groups) for g in pod_groups] + [0]) + 1, 4)
    gk["task_subgroup"] = np.zeros((G, T), np.int32)
    gk["subgroup_valid"] = np.zeros((G, S), bool)
    gk["subgroup_min_member"] = np.zeros((G, S), np.int32)
    gk["subgroup_min_needed"] = np.zeros((G, S), np.int32)
    gk["subgroup_required_level"] = np.full((G, S), -1, np.int32)
    sub_slot: list[dict[str, int]] = [{} for _ in range(G)]
    sub_running = np.zeros((G, S), np.int32)
    # --- node-filter classes: dedupe pod specs ---------------------------
    filter_specs: list[tuple] = [node_filters.EMPTY_SPEC]
    spec_index: dict[tuple, int] = {node_filters.EMPTY_SPEC: 0}
    spec_pods: dict[tuple, apis.Pod] = {
        node_filters.EMPTY_SPEC: apis.Pod("", "")}

    #: consumers admitted this snapshot per claim name — dra_of runs
    #: once per pending pod in intake order, so the counter mirrors the
    #: reference's virtual ReservedFor growth within a cycle
    claim_admitted: dict[str, int] = {}

    def dra_of(pod: apis.Pod,
               queue_name: str | None = None) -> tuple[int, tuple]:
        """(device count, resolved DeviceClass constraint key) — real
        ResourceClaim objects drive the count and the node constraints
        (ref dynamicresources.go claim→deviceclass selection); bare
        ``dra_accel_count`` keeps the legacy unconstrained behavior.
        Non-accel device classes keep their node constraints but skip
        the accel accounting ("non gpu claims doesn't count for gpu
        limit").

        With ``queue_name`` (pending pods only) the upstream draPlugin
        preFilter gates apply (``dynamicresources.go:139-160``): a pod
        whose claim already has ``RESERVED_FOR_MAX`` consumers (existing
        + earlier pending referents this cycle — the virtual ReservedFor
        growth) never schedules, and a SHARED (non-template) GPU claim
        must carry the pod's queue under the ``kai.scheduler/queue``
        label.  Violations resolve to an unsatisfiable node constraint,
        so the gang stays pending with a feasibility fit error — the
        tensor analogue of the reference's preFilter error."""
        if not pod.resource_claims or not resource_claims:
            return pod.dra_accel_count, ()
        cnt, min_mem, bad = 0, 0.0, False
        sels: list[tuple[str, str]] = []
        #: this pod's provisional admissions — committed to the cycle
        #: counter only if the pod passes EVERY gate, so one rejected
        #: claim cannot inflate the virtual consumer count other claims
        #: see for later pods (the reference never grows ReservedFor for
        #: a pod its preFilter rejected)
        admit: dict[str, int] = {}
        for cname in pod.resource_claims:
            claim = resource_claims.get(cname)
            if claim is None:
                continue
            dc = (device_classes or {}).get(claim.device_class)
            is_accel = dc is None or dc.accel
            if queue_name is not None:
                taken = (claim.reserved_for
                         + claim_admitted.get(cname, 0)
                         + admit.get(cname, 0))
                bad_label = (is_accel and not claim.from_template
                             and claim.labels.get(apis.QUEUE_LABEL)
                             != queue_name)
                if taken >= apis.RESERVED_FOR_MAX or bad_label:
                    bad = True
                else:
                    admit[cname] = admit.get(cname, 0) + 1
            if dc is not None:
                min_mem = max(min_mem, dc.min_memory_gib)
                sels.extend(sorted(dc.node_selector.items()))
            if is_accel:
                cnt += claim.count
        if bad:
            return cnt, (float("inf"), ())
        for cname, inc in admit.items():
            claim_admitted[cname] = claim_admitted.get(cname, 0) + inc
        key = (min_mem, tuple(sels)) if (min_mem or sels) else ()
        return cnt, key

    def vol_of(pod: apis.Pod) -> tuple:
        """Resolved VolumeBinding label constraints: a BOUND claim pins
        to its volume's topology; an unbound WaitForFirstConsumer claim
        restricts to its class's allowedTopologies (the volume binds at
        PreBind) — ref the VolumeBinding predicate in
        ``k8s_internal/predicates/predicates.go:70-140``."""
        if not pod.volume_claims or not volume_claims:
            return ()
        items: list[tuple[str, str]] = []
        for vname in pod.volume_claims:
            pvc = volume_claims.get(vname)
            if pvc is None:
                continue
            if pvc.bound:
                items.extend(sorted(pvc.node_affinity.items()))
            else:
                sc = (storage_classes or {}).get(pvc.storage_class)
                if sc is not None:
                    items.extend(sorted(sc.allowed_topology.items()))
        return tuple(items)

    #: label keys any running pod's required anti selector mentions —
    #: incoming pods carrying them need the reverse-anti evaluation
    rev_keys = node_filters.reverse_anti_keys(running_pods)

    def filter_class_of(pod: apis.Pod, dra_key: tuple = ()) -> int:
        rev_labels = tuple(sorted(
            (k, v) for k, v in pod.labels.items() if k in rev_keys))
        # fast path: the overwhelming majority of pods carry no filter
        # spec at all — class 0 without building the canonical key
        if not (pod.tolerations or pod.node_affinity or pod.pod_affinity
                or dra_key or pod.volume_claims or pod.host_ports
                or rev_labels):
            return 0
        key = node_filters.pod_filter_spec(pod, dra_key, vol_of(pod),
                                           rev_labels)
        if key not in spec_index:
            spec_index[key] = len(filter_specs)
            filter_specs.append(key)
            spec_pods[key] = pod
        return spec_index[key]

    node_idx0 = {name: i for i, name in enumerate(node_names)}
    task_names: list[list[str | None]] = [[None] * T for _ in range(G)]
    for i, g in enumerate(pod_groups):
        gk["queue"][i] = q_index.get(g.queue, 0)
        gk["min_member"][i] = g.min_member
        gk["priority"][i] = g.priority
        gk["preemptible"][i] = g.preemptibility == apis.Preemptibility.PREEMPTIBLE
        gk["valid"][i] = bool(pending_by_group[g.name])
        gk["creation_order"][i] = i
        # the UnschedulableOnNodePool condition keeps the gang out of the
        # cycle until cleared (ref cluster_info skipping marked groups)
        gk["backoff"][i] = 1 if g.unschedulable else 0
        # declared subgroups take slots 1.. ; slot 0 is the default
        # subgroup (all tasks of a plain gang, quorum = gang minMember)
        for si, sg in enumerate(g.sub_groups[:S - 1], start=1):
            sub_slot[i][sg.name] = si
            gk["subgroup_valid"][i, si] = True
            gk["subgroup_min_member"][i, si] = sg.min_member
            gk["subgroup_required_level"][i, si] = resolve_level(
                sg.topology_constraint, "required_level")
        gk["subgroup_valid"][i, 0] = True
        gk["subgroup_min_member"][i, 0] = \
            0 if g.sub_groups else g.min_member
        gk["required_level"][i] = resolve_level(
            g.topology_constraint, "required_level")
        gk["preferred_level"][i] = resolve_level(
            g.topology_constraint, "preferred_level")
        # a gang-level required topology level is enforced through the
        # subgroup machinery: subgroups without their own constraint
        # (incl. the default slot 0) inherit it, so every task locks into
        # ONE domain at that level with the capacity-aware first pick
        if gk["required_level"][i] >= 0:
            for si in range(S):
                if gk["subgroup_required_level"][i, si] < 0:
                    gk["subgroup_required_level"][i, si] = \
                        gk["required_level"][i]

    # --- task intake: one global lexsort + a type-table gather -----------
    # Task-order semantics (ref plugins/kubeflow + plugins/ray leader pods
    # first on the job-role / node-type labels, then priority desc,
    # creation asc, name — the taskorder plugin) run as ONE vectorized
    # lexsort over all pending pods instead of a per-gang Python sort, and
    # every per-task field is an O(distinct-spec) encode + O(tasks) gather
    # — the host snapshot must stay a small fraction of the device cycle
    # at 50k pods.
    all_pend: list[apis.Pod] = []
    for g in pod_groups:
        all_pend.extend(pending_by_group[g.name])
    counts = np.fromiter(
        (len(pending_by_group[g.name]) for g in pod_groups), np.int64,
        len(pod_groups)) if pod_groups else np.zeros((0,), np.int64)
    nf = len(all_pend)
    anti_term_level = np.zeros((0,), np.int32)
    attract_static = np.zeros((0, node_topo.shape[0]), bool)
    incycle_pos_terms: set = set()
    task_type_index: dict[tuple, int] = {}
    if nf:
        gidx = np.repeat(np.arange(len(pod_groups)), counts)
        leader = np.fromiter(
            ((p.labels.get("training.kubeflow.org/job-role")
              or p.labels.get("ray.io/node-type")) not in _LEADER_ROLES
             for p in all_pend), bool, nf)
        prio_a = np.fromiter((p.priority for p in all_pend), np.int64, nf)
        crea_a = np.fromiter((p.creation_timestamp for p in all_pend),
                             np.float64, nf)
        names_a = np.array([p.name for p in all_pend])
        # gidx is already non-decreasing (groups appended in order), so
        # the stable lexsort only permutes within each gang
        order = np.lexsort((names_a, crea_a, -prio_a, leader, gidx))
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        gi_a = gidx
        ti_a = np.arange(nf) - starts[gidx]
        if (ti_a >= T).any():
            raise AssertionError("task slots exceed padded T")  # unreachable

        # distinct task specs: one dict probe per pod, everything heavier
        # once per distinct type
        def _tkey(p: apis.Pod, qname: str) -> tuple:
            dra_cnt, dra_key = dra_of(p, queue_name=qname)
            return (
                p.resources.as_tuple(),
                tuple(sorted(p.node_selector.items()))
                if p.node_selector else (),
                p.accel_portion, p.accel_memory_gib, dra_cnt,
                filter_class_of(p, dra_key),
                tuple(sorted(p.extended.items())) if p.extended else ())

        tid = np.fromiter(
            (task_type_index.setdefault(
                _tkey(p, pod_groups[gidx[j]].queue),
                len(task_type_index))
             for j, p in enumerate(all_pend)), np.int64, nf)
        Yn = len(task_type_index)
        t_req = np.zeros((Yn, R), np.float32)
        t_sel = np.full((Yn, K), -1, np.int32)
        t_por = np.zeros((Yn,), np.float32)
        t_mem = np.zeros((Yn,), np.float32)
        t_cls = np.zeros((Yn,), np.int32)
        t_ext = np.zeros((Yn, E), np.float32)
        t_dra = np.zeros((Yn,), np.int32)
        for (req_t, sel_items, por, memg, dra, cls,
             ext_items), y in task_type_index.items():
            t_req[y] = req_t
            # fractional / memory-based requests carry their share in the
            # accel slot so queue & node totals stay consistent
            # (memory-based quantified against the cluster-min device
            # memory, ref GetTasksToAllocateInitResource MinNodeGPUMemory);
            # DRA-claimed devices count like whole devices (ref
            # draGpuCounts added to total requested GPUs)
            if por > 0:
                t_req[y, 0] = por
            elif memg > 0:
                t_req[y, 0] = memg / min_dev_mem
            t_req[y, 0] += dra
            t_por[y], t_mem[y], t_cls[y], t_dra[y] = por, memg, cls, dra
            for k2, v2 in sel_items:
                t_sel[y, selector_keys.index(k2)] = value_id(k2, v2)
            for k2, v2 in ext_items:
                t_ext[y, ext_index[k2]] = v2

        tid_s = tid[order]
        gk["task_valid"][gi_a, ti_a] = True
        gk["task_req"][gi_a, ti_a] = t_req[tid_s]
        gk["task_selector"][gi_a, ti_a] = t_sel[tid_s]
        gk["task_portion"][gi_a, ti_a] = t_por[tid_s]
        gk["task_accel_mem"][gi_a, ti_a] = t_mem[tid_s]
        gk["task_filter_class"][gi_a, ti_a] = t_cls[tid_s]
        gk["task_extended"][gi_a, ti_a] = t_ext[tid_s]
        gk["task_dra"][gi_a, ti_a] = t_dra[tid_s]
        gk["task_type"][gi_a, ti_a] = tid_s
        names_obj = names_a.astype(object)[order]
        tnames_arr = np.full((G, T), None, object)
        tnames_arr[gi_a, ti_a] = names_obj
        task_names = tnames_arr.tolist()

        # sparse per-pod attributes: touch only the pods that carry them
        nom = np.fromiter(
            ((-1 if p.nominated_node is None
              else node_idx0.get(p.nominated_node, -1))
             for p in all_pend), np.int32, nf)
        gk["task_nominated"][gi_a, ti_a] = nom[order]
        has_subs_g = np.fromiter((bool(s) for s in sub_slot), bool, G)
        if has_subs_g.any():
            subcol = np.zeros((nf,), np.int32)
            for j in np.nonzero(has_subs_g[gidx])[0].tolist():
                subcol[j] = sub_slot[gidx[j]].get(
                    all_pend[j].subgroup or "", 0)
            gk["task_subgroup"][gi_a, ti_a] = subcol[order]
        paff = np.fromiter((bool(p.pod_affinity) for p in all_pend), bool,
                           nf)
        # gang-internal spread level (self-selecting required anti term)
        for j in np.nonzero(paff)[0].tolist():
            asl, _ = node_filters.anti_self_term(all_pend[j],
                                                 topo_levels, L)
            if asl >= 0:
                i = gidx[j]
                cur = gk["anti_self_level"][i]
                gk["anti_self_level"][i] = (asl if cur < 0
                                            else min(cur, asl))
        # in-cycle exclusion terms (see GangState.anti_marks): collect
        # each gang's required anti terms + label dicts, then emit
        # symmetric rows / forward+reverse row pairs / port rows
        terms_by_gang: dict[int, set] = {}
        pos_by_gang: dict[int, set] = {}
        for j in np.nonzero(paff)[0].tolist():
            i = gidx[j]
            for term in all_pend[j].pod_affinity:
                if not term.required:
                    continue
                lvl = (topo_levels.index(term.topology_key)
                       if term.topology_key in topo_levels else L)
                if term.anti:
                    terms_by_gang.setdefault(i, set()).add(
                        (term.match_labels, lvl))
                else:
                    pos_by_gang.setdefault(i, set()).add(
                        (term.match_labels, term.topology_key, lvl))
        ports_by_gang: dict[int, set] = {}
        port_counts: dict[int, dict] = {}
        for j, p in enumerate(all_pend):
            if p.host_ports:
                i = gidx[j]
                ports_by_gang.setdefault(i, set()).update(p.host_ports)
                cnts = port_counts.setdefault(i, {})
                # sorted: set order is hash-seed dependent, and these
                # counts feed the gang-kernel tables — two builds of the
                # same cluster must stay bit-identical (kai-lint KAI041)
                for prt in sorted(set(p.host_ports)):
                    cnts[prt] = cnts.get(prt, 0) + 1
        for i, cnts in port_counts.items():
            # replicas SHARING a port can never share a node; a gang
            # whose pods all use distinct ports co-locates freely.
            # Granularity note: anti-self is gang-wide, so a gang mixing
            # ported and portless pods over-spreads the portless ones —
            # conservative (never an invalid co-placement), and exact
            # for the dominant uniform-replica shape.
            if any(c >= 2 for c in cnts.values()):
                cur = gk["anti_self_level"][i]
                gk["anti_self_level"][i] = L if cur < 0 else min(cur, L)
        all_terms = sorted({t for s in terms_by_gang.values() for t in s})
        pos_terms = sorted({t for s in pos_by_gang.values() for t in s})
        labels_by_gang: dict[int, list] = {}
        # per-gang FULL pending label list (anchor strictness check:
        # every pod of an anchor gang must match the term selector)
        pend_labels_all: dict[int, list] = {}
        if pos_terms:
            for j, p in enumerate(all_pend):
                pend_labels_all.setdefault(gidx[j], []).append(
                    p.labels or {})
        if all_terms or pos_terms:
            term_keys = ({k for ml, _ in all_terms for k, _ in ml}
                         | {k for ml, _, _ in pos_terms for k, _ in ml})
            for j, p in enumerate(all_pend):
                if p.labels and term_keys & p.labels.keys():
                    labels_by_gang.setdefault(gidx[j], [])
                    if p.labels not in labels_by_gang[gidx[j]]:
                        labels_by_gang[gidx[j]].append(p.labels)
        rows: list[int] = []      # level per emitted row
        marks_of: dict[int, list] = {}
        avoids_of: dict[int, list] = {}

        def _slot(d, i, row):
            lst = d.setdefault(i, [])
            if row not in lst:
                lst.append(row)

        for ml, lvl in all_terms:
            carriers = {i for i, ts in terms_by_gang.items()
                        if (ml, lvl) in ts}
            matchers = {i for i, lds in labels_by_gang.items()
                        if any(all(ld.get(k) == v for k, v in ml)
                               for ld in lds)}
            if not matchers:
                continue  # nobody to exclude — row would never be marked
            if matchers == carriers:
                row = len(rows)
                rows.append(lvl)
                for i in carriers:
                    _slot(marks_of, i, row)
                    _slot(avoids_of, i, row)
            else:
                fwd = len(rows)
                rows.append(lvl)
                rev = len(rows)
                rows.append(lvl)
                for i in matchers:
                    _slot(marks_of, i, fwd)
                    _slot(avoids_of, i, rev)
                for i in carriers:
                    _slot(avoids_of, i, fwd)
                    _slot(marks_of, i, rev)
        all_ports = sorted({p for s in ports_by_gang.values() for p in s})
        for port in all_ports:
            carriers = {i for i, ps in ports_by_gang.items() if port in ps}
            if len(carriers) < 2:
                continue  # single carrier: anti_self covers it
            # Granularity note: marks claim ALL of a carrier gang's
            # placement nodes, so a gang mixing ported and portless
            # pods over-excludes the other carriers from its portless
            # nodes for ONE cycle (next cycle the filter masks see the
            # exact running ports) — conservative, never an invalid
            # co-placement; exact for uniform-replica gangs.
            row = len(rows)
            rows.append(L)  # per-node
            for i in carriers:
                _slot(marks_of, i, row)
                _slot(avoids_of, i, row)
        # attraction rows — required POSITIVE affinity with a PENDING
        # anchor (upstream InterPodAffinity over virtually-allocated
        # session state, ``k8s_internal/predicates/predicates.go:70-140``).
        # A term the carrier gang ITSELF matches folds into the
        # required-topology machinery (co-locate the gang in one domain
        # at the term's level — the upstream greedy where every pod
        # joins the first pod's virtual domain); carriers that do NOT
        # match get a need row they must find claimed at placement time:
        # statically by a running match (``attract_static``) or in-cycle
        # by an anchor gang's placement (anchors carry the row in
        # ``anti_marks``).  Terms handled in-cycle are excluded from the
        # static filter fold (``incycle_pos_terms``).
        needs_of: dict[int, list] = {}
        attract_rows: list[tuple[int, tuple, int]] = []

        def _running_match(ml) -> bool:
            return any(
                rp.status != apis.PodStatus.RELEASING
                and node_idx0.get(rp.node, -1) >= 0
                and all(rp.labels.get(k) == v for k, v in ml)
                for rp in running_pods)

        for ml, tkey, lvl in pos_terms:
            carriers = {i for i, ts in pos_by_gang.items()
                        if (ml, tkey, lvl) in ts}
            matchers = {i for i, lds in labels_by_gang.items()
                        if any(all(ld.get(k) == v for k, v in ml)
                               for ld in lds)}
            if not matchers:
                continue  # no pending anchor — the static fold decides
            # levels are outermost-first, so the STRICTER of two
            # required-colocation levels is the FINER one (max index —
            # one host implies one rack); contrast anti_self_level,
            # where coarser (min) is stricter for spreading
            self_skipped = False
            rm = _running_match(ml)
            for i in carriers & matchers:
                # self-anchored: the gang's own pods satisfy the term by
                # co-locating in one domain at the term's level.  With
                # running matches present the gang must still JOIN a
                # matched domain (static fold, or the need row below
                # when a depender row disables the fold); without, the
                # fold is skipped (the k8s self-match bootstrap rule).
                # Hostname-level self-affinity stays with the static
                # masks (next-cycle convergence).
                if lvl < L:
                    cur = gk["required_level"][i]
                    gk["required_level"][i] = (lvl if cur < 0
                                               else max(cur, lvl))
                    for si in range(S):
                        csg = gk["subgroup_required_level"][i, si]
                        gk["subgroup_required_level"][i, si] = (
                            lvl if csg < 0 else max(csg, lvl))
                    if not rm:
                        incycle_pos_terms.add((ml, tkey))
                        self_skipped = True
            dependers = carriers - matchers
            # anchors must mark ONLY domains that will hold a matching
            # pod, but marking is gang-granular (anti_mark_placements
            # claims EVERY placed task's domain) — so only gangs whose
            # pending pods ALL match the selector may anchor; a
            # mixed-label matcher stays out (its dependers converge
            # next cycle via the running-match masks, never a violation)
            anchors = {i for i in matchers
                       if all(all(ld.get(k) == v for k, v in ml)
                              for ld in pend_labels_all.get(i, []))}
            # a need row is emitted whenever dependers exist and the
            # term is handled in-cycle — including the anchor-less case
            # where a SELF-fold already skipped the shared static fold
            # (the row then confines dependers to running-match domains,
            # restoring exactly what the skipped fold enforced)
            if not dependers or not (anchors or self_skipped):
                continue
            row = len(rows)
            rows.append(lvl)
            for i in anchors:
                _slot(marks_of, i, row)
            # the row disables the shared static fold for EVERY pod
            # carrying the term, so carrier∩matcher gangs whose fold was
            # load-bearing get the need row as well: hostname-level
            # selfs (no node-granular fold exists) and folded selfs
            # with running matches (the fold also forced them INTO a
            # matched domain — the row's attract_static restores that
            # exactly, and in-cycle anchors extend it).  Only folded
            # selfs with NO running match go row-free: the k8s
            # self-match bootstrap lets them open a fresh domain.
            needy_selfs = {i for i in carriers & matchers
                           if lvl >= L or rm}
            for i in dependers | needy_selfs:
                lst = needs_of.setdefault(i, [])
                if row not in lst:
                    lst.append(row)
            incycle_pos_terms.add((ml, tkey))
            attract_rows.append((row, ml, lvl))
        needp = max((len(lst) for lst in needs_of.values()), default=0)
        if needp > gk["attract_needs"].shape[1]:
            Gp = gk["attract_needs"].shape[0]
            gk["attract_needs"] = np.full((Gp, _pow2_ceil(needp)), -1,
                                          np.int32)
        for i, lst in needs_of.items():
            gk["attract_needs"][i, :len(lst)] = lst
        # size the slot dimension from the snapshot: every distinct term
        # row a gang carries gets a slot (dropping one would unenforce a
        # required anti term for a cycle, and binds are permanent).  The
        # dim is bucketed to powers of two >= ANTI_SLOTS so term-count
        # drift across cycles rarely changes the compiled shape.
        need = max((len(lst) for d in (marks_of, avoids_of)
                    for lst in d.values()), default=0)
        if need > ANTI_SLOTS:
            slots = _pow2_ceil(need)
            Gp = gk["anti_marks"].shape[0]
            gk["anti_marks"] = np.full((Gp, slots), -1, np.int32)
            gk["anti_avoids"] = np.full((Gp, slots), -1, np.int32)
        for i, lst in marks_of.items():
            gk["anti_marks"][i, :len(lst)] = lst
        for i, lst in avoids_of.items():
            gk["anti_avoids"][i, :len(lst)] = lst
        # pad the row count to a power of two: anti_term_level's shape
        # sizes the anti_used table, and AllocateConfig-keyed kernels
        # recompile on every distinct shape — without padding a pending
        # set whose term count drifts 3 -> 4 -> 3 across cycles would
        # recompile every cycle.  Padded rows are never referenced (no
        # gang's marks/avoids point at them).
        if rows:
            rows = rows + [0] * (_pow2_ceil(len(rows)) - len(rows))
        anti_term_level = np.asarray(rows, np.int32)
        # statically-satisfied nodes per attract row: the domains (at
        # the row's level) that already hold a RUNNING match — OR-ed
        # with the in-cycle claims at placement time
        attract_static = np.zeros((len(rows), node_topo.shape[0]), bool)
        for row, ml, lvl in attract_rows:
            for rp in running_pods:
                if rp.status == apis.PodStatus.RELEASING:
                    continue
                ni = node_idx0.get(rp.node, -1)
                if ni < 0 or not all(
                        rp.labels.get(k) == v for k, v in ml):
                    continue
                if lvl < L:
                    d = node_topo[ni, lvl]
                    if d >= 0:
                        attract_static[row] |= node_topo[:, lvl] == d
                    else:
                        attract_static[row, ni] = True
                else:
                    attract_static[row, ni] = True

    # --- running pods -----------------------------------------------------
    # Pods whose node is missing from the snapshot (cordoned/deleted) keep
    # valid=True with node=-1: they still count toward queue allocation so
    # DRF fairness stays honest, but victim kernels skip node<0 rows.
    M = _round_up(max(len(running_pods), cap.running), pad)
    node_idx = {name: i for i, name in enumerate(node_names)}
    rk = dict(
        req=np.zeros((M, R), np.float32),
        node=np.full((M,), -1, np.int32),
        queue=np.zeros((M,), np.int32),
        gang=np.full((M,), -1, np.int32),
        priority=np.zeros((M,), np.int32),
        preemptible=np.zeros((M,), bool),
        valid=np.zeros((M,), bool),
        releasing=np.zeros((M,), bool),
        runtime_s=np.zeros((M,), np.float32),
        device=np.full((M,), -1, np.int32),
        devices_mask=np.zeros((M,), np.int32),
        accel_held=np.zeros((M,), np.float32),
        accel_mem=np.zeros((M,), np.float32),
        filter_class=np.zeros((M,), np.int32),
        extended=np.zeros((M, E), np.float32),
    )
    running_names: list[str] = [""] * M
    if now is None:
        now = max([p.creation_timestamp for p in pods], default=0.0)
    Mu = len(running_pods)
    if Mu:
        # --- bulk per-pod fields (vectorized; the device-occupancy and
        # memory-share paths below stay per-pod but are guarded) ----------
        r_req = np.array([p.resources.as_tuple() for p in running_pods],
                         np.float32)
        r_node = np.fromiter(
            (node_idx.get(p.node, -1) for p in running_pods), np.int32, Mu)
        r_por = np.fromiter((p.accel_portion for p in running_pods),
                            np.float32, Mu)
        r_mem = np.fromiter((p.accel_memory_gib for p in running_pods),
                            np.float32, Mu)
        r_grp = np.fromiter(
            (g_index.get(p.group, -1) for p in running_pods), np.int32, Mu)
        r_rel = np.fromiter(
            (p.status == apis.PodStatus.RELEASING for p in running_pods),
            bool, Mu)
        # a running pod's node is known: debit its *actual* per-node
        # share so free accel stays equal to device_free.sum(-1)
        # (pending pods use the canonical cluster-min quantification)
        dm = np.where(r_node >= 0,
                      node_dev_mem[np.maximum(r_node, 0)], min_dev_mem)
        r_req[:, 0] = np.where(
            r_por > 0, r_por,
            np.where(r_mem > 0, r_mem / np.maximum(dm, 1e-6), r_req[:, 0]))
        rk["req"][:Mu] = r_req
        rk["node"][:Mu] = r_node
        rk["accel_mem"][:Mu] = r_mem
        rk["gang"][:Mu] = r_grp
        rk["valid"][:Mu] = True
        rk["releasing"][:Mu] = r_rel
        rk["filter_class"][:Mu] = np.fromiter(
            (filter_class_of(p, dra_of(p)[1]) for p in running_pods),
            np.int32, Mu)
        # group-derived fields via per-group tables + one gather
        ng = len(pod_groups)
        pg_queue = np.fromiter(
            (q_index.get(g2.queue, 0) for g2 in pod_groups), np.int32,
            ng) if ng else np.zeros((0,), np.int32)
        pg_prio = np.fromiter((g2.priority for g2 in pod_groups), np.int32,
                              ng) if ng else np.zeros((0,), np.int32)
        pg_pre = np.fromiter(
            (g2.preemptibility == apis.Preemptibility.PREEMPTIBLE
             for g2 in pod_groups), bool, ng) if ng else np.zeros((0,), bool)
        # float64: unix-epoch timestamps lose ~128s of precision in
        # float32, which corrupts minruntime protection windows
        pg_start = np.array(
            [(-1.0 if g2.last_start_timestamp is None
              else g2.last_start_timestamp) for g2 in pod_groups],
            np.float64) if ng else np.zeros((0,), np.float64)
        has_grp = r_grp >= 0
        gsafe = np.maximum(r_grp, 0)
        if ng:
            rk["queue"][:Mu] = np.where(has_grp, pg_queue[gsafe], 0)
            rk["priority"][:Mu] = np.where(has_grp, pg_prio[gsafe], 0)
            rk["preemptible"][:Mu] = has_grp & pg_pre[gsafe]
            # -1 sentinel when the gang never started: the reference's
            # minruntime protection returns NOT protected for a nil
            # LastStartTimestamp (minruntime.go isPreemptMinRuntimeProtected)
            started = pg_start[gsafe]
            rk["runtime_s"][:Mu] = np.where(
                has_grp & (started >= 0),
                np.maximum(0.0, now - started), -1.0)
        np.add.at(gk["running_count"], gsafe[has_grp & ~r_rel], 1)
        # subgroup attribution: pods of plain gangs (no declared
        # subgroups) count toward the default slot 0 in bulk; only gangs
        # with declared subgroups need the per-pod name lookup
        has_subs = np.fromiter((bool(s) for s in sub_slot), bool, G)
        active = has_grp & ~r_rel
        plain = active & ~has_subs[gsafe]
        np.add.at(sub_running, (gsafe[plain], np.zeros(int(plain.sum()),
                                                      np.int64)), 1)
        for j in np.nonzero(active & has_subs[gsafe])[0]:
            sub_running[r_grp[j], sub_slot[r_grp[j]].get(
                running_pods[j].subgroup or "", 0)] += 1
    if Mu:
        running_names[:Mu] = [p.name for p in running_pods]
        # --- device occupancy (GPU-group bookkeeping) --------------------
        # Fast path: whole-device pods with no recorded device list on
        # nodes carrying no fractional/pinned pods get first-fit devices —
        # which is exactly a contiguous per-node assignment in pod order,
        # computed as one grouped prefix sum.  Fractional pods, pods with
        # recorded devices, and every pod sharing a node with one take the
        # per-pod path (order within a node matches the old sequential
        # first-fit exactly: node sets are disjoint between the paths).
        whole_k = np.rint(r_req[:, 0] * (r_por <= 0) * (r_mem <= 0)
                          ).astype(np.int64)
        has_dev = np.fromiter((bool(p.accel_devices) for p in running_pods),
                              bool, Mu)
        has_ext = np.fromiter((bool(p.extended) for p in running_pods),
                              bool, Mu)
        on = r_node >= 0
        frac = (r_por > 0) | (r_mem > 0)
        touches = on & (frac | (whole_k > 0))
        special = touches & (frac | has_dev)
        node_special = np.zeros((N,), bool)
        node_special[r_node[special]] = True
        vec = touches & ~special & ~node_special[np.maximum(r_node, 0)]
        # extended scalars: only pods that carry them
        for j in np.nonzero(has_ext & on)[0].tolist():
            pod = running_pods[j]
            ni = int(r_node[j])
            for ek, ev in pod.extended.items():
                ei = ext_index[ek]
                taken = min(ev, float(ext_free[ni, ei]))
                ext_free[ni, ei] -= taken
                rk["extended"][j, ei] = taken
                if pod.status == apis.PodStatus.RELEASING:
                    ext_rel[ni, ei] += taken
        vj = np.nonzero(vec)[0]
        if len(vj):
            accel_counts_a = np.asarray(accel_counts, np.int64)
            vn = r_node[vj]
            ordv = np.argsort(vn, kind="stable")
            vj, vn = vj[ordv], vn[ordv]
            vk = whole_k[vj]
            cum = np.cumsum(vk) - vk
            first = np.ones(len(vj), bool)
            first[1:] = vn[1:] != vn[:-1]
            grp = np.cumsum(first) - 1
            off = cum - cum[np.nonzero(first)[0]][grp]
            k_eff = np.clip(accel_counts_a[vn] - off, 0, vk)
            end = off + k_eff
            rk["devices_mask"][vj] = (
                (np.int64(1) << end) - (np.int64(1) << off)).astype(np.int32)
            rk["accel_held"][vj] = k_eff.astype(np.float32)
            tot = int(k_eff.sum())
            if tot:
                rep = np.repeat(np.arange(len(vj)), k_eff)
                dpos = (np.arange(tot)
                        - np.repeat(np.cumsum(k_eff) - k_eff, k_eff)
                        + np.repeat(off, k_eff))
                nrep = vn[rep]
                dev_free[nrep, dpos] = 0.0
                relm = r_rel[vj][rep]
                dev_rel[nrep[relm], dpos[relm]] += 1.0
        for j in np.nonzero(touches & ~vec)[0].tolist():
            pod = running_pods[j]
            ni = int(r_node[j])
            if frac[j]:
                p = (pod.accel_portion if pod.accel_portion > 0
                     else pod.accel_memory_gib / max(node_dev_mem[ni], 1e-6))
                if pod.accel_devices:
                    d0 = pod.accel_devices[0]
                else:  # deterministic first-fit, matching the binder
                    fits = np.nonzero(dev_free[ni] >= p - 1e-6)[0]
                    d0 = int(fits[0]) if len(fits) else 0
                taken = min(p, dev_free[ni, d0])
                dev_free[ni, d0] -= taken
                if pod.status == apis.PodStatus.RELEASING:
                    dev_rel[ni, d0] += taken
                rk["device"][j] = d0
                rk["accel_held"][j] = p
            else:
                k = int(whole_k[j])
                if pod.accel_devices:
                    devs = list(pod.accel_devices)[:k]
                else:
                    devs = list(np.nonzero(
                        dev_free[ni] >= 1.0 - 1e-6)[0][:k])
                mask = 0
                for d0 in devs:
                    taken = min(1.0, dev_free[ni, d0])
                    dev_free[ni, d0] -= taken
                    if pod.status == apis.PodStatus.RELEASING:
                        dev_rel[ni, d0] += taken
                    mask |= 1 << int(d0)
                rk["devices_mask"][j] = mask
                rk["accel_held"][j] = float(len(devs))
    # --- allocated DRA claims hold concrete devices (ref
    # populateDRAGPUs): debit the device table and node accel pool —
    # running claim-holders' own req rows do NOT include the claimed
    # devices, so this is the single accounting point -----------------
    claim_used = np.zeros((N, R), np.float32)
    for claim in (resource_claims or {}).values():
        ni = node_idx.get(claim.node) if claim.node else None
        if ni is None:
            continue
        for d0 in claim.devices:
            if d0 < D:
                taken = min(1.0, float(dev_free[ni, d0]))
                dev_free[ni, d0] -= taken
                claim_used[ni, 0] += taken
    for i, grp_obj in enumerate(pod_groups):
        if grp_obj.stale_since is not None:
            gk["stale_s"][i] = max(0.0, now - grp_obj.stale_since)
    gk["min_needed"] = np.maximum(gk["min_member"] - gk["running_count"], 0)
    gk["subgroup_min_needed"] = np.maximum(
        gk["subgroup_min_member"] - sub_running, 0)

    # --- task-type table + scheduling signatures --------------------------
    Y = _round_up(max(len(task_type_index), 1, cap.types), 4)
    gk["type_req"] = np.zeros((Y, R), np.float32)
    gk["type_selector"] = np.full((Y, K), -1, np.int32)
    gk["type_portion"] = np.zeros((Y,), np.float32)
    gk["type_mem"] = np.zeros((Y,), np.float32)
    gk["type_class"] = np.zeros((Y,), np.int32)
    gk["type_extended"] = np.zeros((Y, E), np.float32)
    if nf:
        gk["type_req"][:Yn] = t_req
        gk["type_selector"][:Yn] = t_sel
        gk["type_portion"][:Yn] = t_por
        gk["type_mem"][:Yn] = t_mem
        gk["type_class"][:Yn] = t_cls
        gk["type_extended"][:Yn] = t_ext
    # scheduling-constraints signature (ref minimal_job_comparison.go):
    # equivalent gangs = identical rows of [sorted (type,subgroup) multiset
    # | per-subgroup (min_needed, required_level) | queue/quorum/topology
    # scalars] — one np.unique instead of a per-gang Python tuple build
    big = np.int64(Y) * (S + 1) + 1
    comp = np.where(gk["task_valid"],
                    gk["task_type"].astype(np.int64) * (S + 1)
                    + gk["task_subgroup"], big)
    comp.sort(axis=1)
    sub_mn = np.where(gk["subgroup_valid"], gk["subgroup_min_needed"], -2)
    sub_rl = np.where(gk["subgroup_valid"], gk["subgroup_required_level"],
                      -2)
    sig_mat = np.concatenate([
        comp, sub_mn, sub_rl,
        gk["queue"][:, None].astype(np.int64),
        gk["min_needed"][:, None], gk["required_level"][:, None],
        gk["preferred_level"][:, None], gk["anti_self_level"][:, None],
        gk["preemptible"][:, None].astype(np.int64),
        (~gk["valid"][:, None]).astype(np.int64),
    ], axis=1, dtype=np.int64)
    gk["sig"] = dense_row_ids(sig_mat).astype(np.int32)

    # --- derived node free/releasing + queue rollups (shared section) ----
    # The MIG g-equivalents enter the SNAPSHOT rollups — allocated,
    # request, and through them the fairness division — AND (via
    # GangState.ext_accel) the in-cycle placement queue deltas, so
    # over-share detection and the quota/reclaim gates fire for
    # pure-MIG queues in the same cycle (ref GetTotalGPURequest).
    r_mig = np.zeros((M,), np.float32)
    if g_of_ext.any():
        for _j, _pod in enumerate(running_pods):
            if _pod.extended:
                r_mig[_j] = sum(
                    g_of_ext[ext_index[k]] * v
                    for k, v in _pod.extended.items()
                    if k in ext_index)
    roll = derive_rollups(
        node_alloc=node_alloc, claim_used=claim_used, rk=rk, gk=gk,
        g_of_ext=g_of_ext, r_mig=r_mig, queue_usage=queue_usage,
        q_index=q_index, q_parent=q_parent, q_depth=q_depth,
        num_queues=len(queues))
    node_rel, node_free = roll["node_rel"], roll["node_free"]
    q_alloc, q_alloc_np = roll["q_alloc"], roll["q_alloc_np"]
    q_request, q_usage = roll["q_request"], roll["q_usage"]

    # --- evaluate filter classes against nodes (host, once per spec) ------
    running_views = [
        node_filters._RunningPodView(
            labels=pod.labels,
            node=int(rk["node"][j]),
            host_ports=tuple(pod.host_ports),
            anti_terms=tuple(
                (t.match_labels, t.topology_key)
                for t in pod.pod_affinity if t.required and t.anti))
        for j, pod in enumerate(running_pods)
        if pod.status != apis.PodStatus.RELEASING]
    filter_masks, soft_scores = node_filters.evaluate_filter_classes(
        filter_specs, spec_pods, live_nodes, node_topo, topo_levels,
        running_views, N, incycle_pos_terms=frozenset(incycle_pos_terms))

    # --- kernel-config hints derived from the snapshot shape --------------
    has_fracs = bool(gk["task_portion"].any() or gk["task_accel_mem"].any()
                     or (rk["device"] >= 0).any())
    tvm = gk["task_valid"][:, :, None]
    uniform = (
        not has_fracs
        and not ext_keys  # extended resources take the per-task path
        # declared subgroups need the per-task path; a gang-level
        # required topology level (slot 0) is native to the whole-gang
        # kernel's single-domain fill
        and not any(g.sub_groups for g in pod_groups)
        and bool((gk["task_nominated"] < 0).all())
        # per-node anti-self is supported by the whole-gang kernel (one
        # replica per node); coarser levels need the per-task path
        and bool(((gk["anti_self_level"] == -1)
                  | (gk["anti_self_level"] == L)).all())
        # padded task rows are zero — compare valid rows against task 0
        and bool((np.where(tvm, gk["task_req"],
                           gk["task_req"][:, :1]) ==
                  gk["task_req"][:, :1]).all())
        and bool((np.where(tvm, gk["task_selector"],
                           gk["task_selector"][:, :1]) ==
                  gk["task_selector"][:, :1]).all())
        and bool((np.where(gk["task_valid"], gk["task_filter_class"],
                           gk["task_filter_class"][:, :1]) ==
                  gk["task_filter_class"][:, :1]).all()))

    # assemble host-side (numpy) and ship with ONE device_put: per-array
    # transfers cost a round trip each through a tunneled TPU
    def _f(a):
        return np.asarray(a, dtype) if a.dtype.kind == "f" else a

    state = ClusterState(
        nodes=NodeState(
            allocatable=_f(node_alloc),
            free=_f(node_free),
            releasing=_f(node_rel),
            valid=node_valid,
            labels=node_labels,
            topology=node_topo,
            device_free=_f(dev_free),
            device_releasing=_f(dev_rel),
            device_memory_gib=_f(node_dev_mem),
            filter_masks=np.asarray(filter_masks),
            soft_scores=_f(np.asarray(soft_scores, dtype)),
            extended_free=_f(ext_free),
            extended_releasing=_f(ext_rel),
        ),
        queues=QueueState(
            parent=q_parent,
            depth=q_depth,
            priority=q_priority,
            quota=_f(q_quota),
            over_quota_weight=_f(q_oqw),
            limit=_f(q_limit),
            allocated=_f(q_alloc),
            allocated_nonpreemptible=_f(q_alloc_np),
            request=_f(q_request),
            usage=_f(q_usage),
            fair_share=np.zeros((Q, R), dtype),
            valid=q_valid,
            creation_order=q_creation,
            preempt_min_runtime=_f(q_preempt_mrt),
            reclaim_min_runtime=_f(q_reclaim_mrt),
            preempt_min_runtime_eff=_f(np.asarray(q_preempt_eff, dtype)),
            reclaim_min_runtime_eff=_f(np.asarray(q_reclaim_eff, dtype)),
        ),
        gangs=GangState(**gk, anti_term_level=anti_term_level,
                        attract_static=attract_static),
        running=RunningState(**rk),
    )
    host_state = state
    # through the kai-wire TransferLedger (the package's device_put
    # choke point, KAI071): the full snapshot supersedes the previous
    # one's buffers, so the upload replaces the ledger's resident set
    state = _wire.LEDGER.device_put(
        state, reason=_wire.REASON_FULL_BUILD, replace_site=True)
    index = SnapshotIndex(
        node_names=node_names,
        queue_names=queue_names,
        gang_names=group_names,
        task_names=task_names,
        running_pod_names=running_names,
        selector_keys=selector_keys,
        label_vocab=label_vocab,
        topology_levels=topo_levels,
        needs_device_table=has_fracs,
        uniform_gangs=uniform,
        has_required_topology=bool((gk["required_level"] >= 0).any()),
        has_preferred_topology=bool((gk["preferred_level"] >= 0).any()),
        has_subgroup_topology=bool(
            (gk["subgroup_required_level"] >= 0).any()),
        has_extended_resources=bool(ext_keys),
        extended_keys=ext_keys,
        has_reclaim_minruntime=bool((q_reclaim_mrt > 0).any()),
        has_anti_groups=len(anti_term_level) > 0,
        num_anti_groups=len(anti_term_level),
        has_attract_groups=bool((gk["attract_needs"] >= 0).any()),
        max_queue_depth=int(q_depth.max(initial=0)),
        num_leaf_queues=int(
            (q_valid & ~np.isin(np.arange(Q),
                                q_parent[q_parent >= 0])).sum()),
        num_pending_gangs=int(gk["task_valid"].any(axis=1).sum()),
        claims_by_pod={p.name: list(p.resource_claims)
                       for p in all_pend if p.resource_claims},
        host_tables={
            "task_portion": gk["task_portion"],
            "task_accel_mem": gk["task_accel_mem"],
            "task_req0": np.ascontiguousarray(gk["task_req"][:, :, 0]),
            "task_dra": gk["task_dra"],
            "running_gang": rk["gang"],
            "queue_usage": q_usage,
            # gangs with pending tasks this snapshot — the SAME mask
            # the analytics kernel reads as ``gangs.valid``, so the
            # kai-pulse starvation counters advance in lockstep with
            # the device-side top-K table
            "gang_valid": gk["valid"],
        },
        dense_feasibility=(
            not selector_keys and len(filter_specs) == 1
            # class-0 must actually span the node axis: untolerated
            # NoSchedule/NoExecute taints shrink even the empty-spec mask
            and bool(np.asarray(filter_masks)[0][node_valid].all())
            and bool((gk["anti_self_level"] < 0).all())
            and bool((gk["subgroup_required_level"] < 0).all())),
    )
    if _return_host:
        # the incremental snapshotter caches the pre-device_put numpy
        # leaves so later cycles can patch rows and ship only changes
        return state, index, host_state
    return state, index

from .cluster_state import (  # noqa: F401
    ClusterState,
    GangState,
    NodeState,
    QueueState,
    RunningState,
    SnapshotCapacity,
    SnapshotIndex,
    build_snapshot,
)
from .incremental import (  # noqa: F401
    IncrementalSnapshotter,
    IncrementalVerifyError,
    MutationJournal,
)
from .synthetic import make_cluster  # noqa: F401

from .cluster_state import (  # noqa: F401
    ClusterState,
    GangState,
    NodeState,
    QueueState,
    RunningState,
    SnapshotIndex,
    build_snapshot,
)
from .synthetic import make_cluster  # noqa: F401

"""kai_scheduler_tpu — a TPU-native batch/gang scheduling framework.

A ground-up rebuild of the capabilities of KAI-Scheduler (reference:
``/root/reference``, a fork of NVIDIA/KAI-Scheduler) designed for TPU
hardware: the per-cycle O(jobs x nodes) scheduling math — DRF fair-share
division, predicate masks, binpack/spread scoring, gang all-or-nothing
allocation, and reclaim victim search — runs as vmapped / ``lax.scan``
XLA kernels over a tensorized cluster snapshot, shardable across a
``jax.sharding.Mesh``.  A host-side framework preserves the reference's
architecture: actions, plugins, Session, and Statement
(checkpoint/rollback/commit) transaction semantics.

Layout (mirrors the reference's layer map, SURVEY.md section 1):

- ``apis``       CRD-equivalent dataclasses (Queue, PodGroup, BindRequest,
                 Topology, SchedulingShard, Config) — ref ``pkg/apis``.
- ``state``      the tensorized snapshot (``ClusterState`` struct-of-arrays)
                 plus synthetic cluster generators — ref ``pkg/scheduler/api``
                 info structs + ``pkg/scheduler/test_utils``.
- ``ops``        the solver kernels (the "native" compute layer, here XLA):
                 DRF division, predicates, scoring, gang allocate, victim
                 search, topology — replaces the reference's Go hot loops.
- ``parallel``   mesh/sharding helpers (shard the node axis over ICI).
- ``framework``  Session / Statement / registries / cycle driver — ref
                 ``pkg/scheduler/framework``.
- ``actions``    allocate, reclaim, preempt, consolidation,
                 stalegangeviction — ref ``pkg/scheduler/actions``.
- ``plugins``    score/mask/order plugins — ref ``pkg/scheduler/plugins``.
- ``models``     workload-kind groupers (the podgrouper catalog) — ref
                 ``pkg/podgrouper``.
- ``binder``     bind execution with backoff/rollback — ref ``pkg/binder``.
- ``utils``      logging, metrics, priority queues.
"""

__version__ = "0.1.0"

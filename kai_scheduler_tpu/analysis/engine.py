"""kai-lint rule engine — registry, suppressions, baseline, drivers.

A rule is a function ``(RuleCtx) -> Iterator[Finding]`` registered
under a stable ``KAI0xx`` code with a one-line title and a pair of
self-test fixtures (a snippet that must trigger and one that must not —
``tests/test_analysis.py`` runs every rule against its own fixtures so
a refactor can't silently lobotomize a check).

Suppressions are inline comments, pylint-style::

    x = foo()  # kai-lint: disable=KAI001
    # kai-lint: disable=KAI007,KAI009   (own line: applies to the next)

Every suppression must keep matching a live finding: one that stops
matching is reported as ``KAI000 stale-suppression`` so disables rot
loudly instead of silently (the meta-test pins this).

The optional baseline (``--baseline``) holds ``{file, code, count}``
rows; findings are only *new* beyond the baselined count per (file,
code).  The shipped package baselines nothing — the tree lints clean —
but the mechanism lets a consumer adopt the linter before finishing
their own sweep.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from typing import Callable, Iterable, Iterator

from .callgraph import ModuleInfo, PackageGraph

_SUPPRESS_RE = re.compile(r"#\s*kai-lint:\s*disable=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit, stable across runs (sortable for diffing)."""

    file: str
    line: int
    col: int
    code: str
    message: str
    function: str = ""

    def render(self) -> str:
        where = f" [{self.function}]" if self.function else ""
        return (f"{self.file}:{self.line}:{self.col}: "
                f"{self.code} {self.message}{where}")


@dataclasses.dataclass
class Rule:
    code: str
    title: str
    check: Callable[["RuleCtx"], Iterator[Finding]]
    #: (must-trigger, must-not-trigger) source snippets for self-test
    fixture_bad: str = ""
    fixture_good: str = ""


RULES: dict[str, Rule] = {}


def rule(code: str, title: str, *, bad: str = "", good: str = ""):
    """Register a rule under its KAI code (see ``rules.py``)."""
    def deco(fn):
        RULES[code] = Rule(code=code, title=title, check=fn,
                           fixture_bad=bad, fixture_good=good)
        return fn
    return deco


#: program-level (jaxpr) rule codes — the checks live in
#: ``costmodel.py`` (KAI2xx, layer 4) and ``comms.py`` (KAI3xx, layer
#: 5), both needing jax, but the catalog must stay jax-free for
#: ``--list-rules`` and ``scripts/lint.py``; their fixtures are jax
#: functions exercised by ``tests/test_costmodel.py`` /
#: ``tests/test_comms.py``, not AST snippets, so they are NOT engine
#: ``Rule`` entries
PROGRAM_RULES = {
    "KAI201": "intermediate aval exceeds blowup_factor × the entry's "
              "largest input (broadcast blowup, jaxpr-level)",
    "KAI202": "donated input leaf not aliased to any output in the "
              "compiled executable (ineffective donation, "
              "jaxpr-level)",
    "KAI301": "intermediate materializes the full node axis "
              "REPLICATED on every device above the size threshold "
              "(accidental node-axis replication, jaxpr-level)",
    "KAI302": "declared mesh.state_shardings leaf disagrees with the "
              "kai-comms inferred seed spec (sharding drift, "
              "mesh-level, both directions)",
    "KAI303": "collective inside scan/while charged trip-count × "
              "exceeds the loop comm budget (collective-under-loop, "
              "jaxpr-level)",
}


def rule_catalog() -> dict[str, str]:
    """code -> title, for --list-rules and the docs (AST rules plus
    the program-level KAI2xx family)."""
    from . import concurrency as _conc  # noqa: F401  (registers on import)
    from . import rules as _rules  # noqa: F401  (registers on import)
    out = {c: RULES[c].title for c in sorted(RULES)}
    out.update(PROGRAM_RULES)
    return dict(sorted(out.items()))


@dataclasses.dataclass
class RuleCtx:
    """Everything a rule sees for one module."""

    mod: ModuleInfo
    #: qualnames of this module's functions inside the jit region
    jit_quals: set[str]
    #: module relpaths allowed to hold host-side f64 (see rules.KAI030)
    f64_allowlist: frozenset[str]

    def jit_nodes(self) -> Iterator[tuple[str, ast.AST]]:
        for q in sorted(self.jit_quals):
            node = self.mod.functions.get(q)
            if node is not None:
                yield q, node

    def finding(self, code: str, node: ast.AST, message: str,
                function: str = "") -> Finding:
        return Finding(file=self.mod.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       code=code, message=message, function=function)


#: modules whose f64 is the documented host-side precision boundary —
#: usage integrals (usagedb) and unix-epoch timestamps (snapshot
#: builders), all reduced to f32 deltas before any device transfer.
#: The f32-device side of the boundary is utils/numerics.py (cumsum_ds
#: double-single compensation instead of f64).  See COVERAGE.md.
F64_HOST_ALLOWLIST = frozenset({
    "kai_scheduler_tpu/runtime/usagedb.py",
    "kai_scheduler_tpu/state/cluster_state.py",
    "kai_scheduler_tpu/state/incremental.py",
    # kai-intake admission sweep: bound checks need full double
    # precision (float32's 64-unit ulp at the 1e9 cap would round
    # out-of-range values ONTO the bound); host-only, nothing crosses
    # to the device
    "kai_scheduler_tpu/intake/apply.py",
})


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    #: stale-suppression findings (KAI000), already included in findings
    stale_suppressions: list[Finding]
    #: raw finding count before suppressions/baseline (telemetry)
    raw_count: int
    baselined: int = 0
    #: the kai-race layer's report (thread roots, disciplines) when the
    #: KAI1xx family ran — see ``concurrency.py``
    race: "object" = None


def _suppressions(source: str) -> dict[int, set[str]]:
    """line -> suppressed codes.  An own-line comment binds to the next
    line; a trailing comment binds to its own line.  Only real COMMENT
    tokens count — example disables inside docstrings are inert."""
    out: dict[int, set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        row, col = tok.start
        own_line = tok.line[:col].strip() == ""
        out.setdefault(row + 1 if own_line else row, set()).update(codes)
    return out


def _apply_suppressions(mod: ModuleInfo, findings: list[Finding],
                        selected: set[str] | None = None,
                        ) -> tuple[list[Finding], list[Finding]]:
    """Drop suppressed findings; report unused suppressions (KAI000).

    A suppression only counts as stale when its rule actually RAN this
    pass (``selected``) — ``--select KAI041`` must not condemn a live
    KAI052 disable it never gave a chance to match."""
    supp = _suppressions(mod.source)
    used: set[tuple[int, str]] = set()
    kept = []
    for f in findings:
        codes = supp.get(f.line, ())
        if f.code in codes:
            used.add((f.line, f.code))
        else:
            kept.append(f)
    stale = [
        Finding(file=mod.relpath, line=line, col=0, code="KAI000",
                message=(f"stale suppression: no live {code} finding on "
                         f"this line — remove the disable comment"))
        for line in sorted(supp)
        for code in sorted(supp[line])
        if (line, code) not in used
        and (selected is None or code in selected)
    ]
    return kept, stale


def load_baseline(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("lint", [])


def _apply_baseline(findings: list[Finding],
                    baseline: list[dict]) -> tuple[list[Finding], int]:
    budget = {(b["file"], b["code"]): int(b.get("count", 0))
              for b in baseline}
    kept, eaten = [], 0
    for f in sorted(findings):
        key = (f.file, f.code)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            eaten += 1
        else:
            kept.append(f)
    return kept, eaten


def _lint_module(mod: ModuleInfo, jit_quals: set[str],
                 select: Iterable[str] | None,
                 f64_allowlist: frozenset[str]) -> list[Finding]:
    from . import concurrency as _conc  # noqa: F401  (registers on import)
    from . import rules as _rules  # noqa: F401  (registers on import)
    ctx = RuleCtx(mod=mod, jit_quals=jit_quals,
                  f64_allowlist=f64_allowlist)
    out: list[Finding] = []
    for code in sorted(RULES):
        if select is not None and code not in select:
            continue
        out.extend(RULES[code].check(ctx))
    return out


def _race_by_module(graph: PackageGraph,
                    select: set[str] | None,
                    guarded_map: dict | None):
    """Run the graph-level kai-race pass (``concurrency.py``) and group
    its findings per module so suppressions apply alongside the
    per-module rules.  Returns ``(findings by modname, RaceReport)``;
    the pass is skipped entirely when ``--select`` names no KAI1xx
    code."""
    from . import concurrency
    codes = set(concurrency.race_codes())
    if select is not None and not (codes & select):
        return {}, None
    report = concurrency.analyze_package(
        graph, concurrency.load_guarded_map()
        if guarded_map is None else guarded_map)
    relpath_to_mod = {m.relpath: name
                      for name, m in graph.modules.items()}
    by_mod: dict[str, list[Finding]] = {}
    for f in report.findings:
        if select is not None and f.code not in select:
            continue
        modname = relpath_to_mod.get(f.file)
        if modname is not None:
            by_mod.setdefault(modname, []).append(f)
    return by_mod, report


def lint_package(root: str, *, package: str = "kai_scheduler_tpu",
                 select: Iterable[str] | None = None,
                 baseline: list[dict] | None = None,
                 f64_allowlist: frozenset[str] = F64_HOST_ALLOWLIST,
                 guarded_map: dict | None = None,
                 ) -> LintResult:
    """Lint every module of ``package`` under repo ``root`` — the
    per-module KAI0xx rules plus the graph-level KAI1xx race pass."""
    graph = PackageGraph(root, package=package)
    select = set(select) if select is not None else None
    race_hits, race_report = _race_by_module(graph, select, guarded_map)
    findings: list[Finding] = []
    stale: list[Finding] = []
    raw = 0
    for modname in sorted(graph.modules):
        mod = graph.modules[modname]
        hits = _lint_module(mod, graph.jit_functions(modname), select,
                            f64_allowlist)
        hits.extend(race_hits.get(modname, ()))
        raw += len(hits)
        kept, dead = _apply_suppressions(mod, hits, select)
        findings.extend(kept)
        stale.extend(dead)
    findings.extend(stale)
    eaten = 0
    if baseline:
        findings, eaten = _apply_baseline(findings, baseline)
    return LintResult(findings=sorted(findings),
                      stale_suppressions=sorted(stale),
                      raw_count=raw, baselined=eaten,
                      race=race_report)


def lint_source(source: str, *, filename: str = "<fixture>.py",
                select: Iterable[str] | None = None,
                f64_allowlist: frozenset[str] = frozenset(),
                ) -> list[Finding]:
    """Lint one in-memory module (rule fixtures / editor integration).

    The snippet is its own universe: jit entry points declared inside it
    (``@jax.jit`` etc.) grow its jit region exactly as in a package run,
    and thread spawns inside it seed the kai-race pass the same way.
    """
    graph = PackageGraph.__new__(PackageGraph)
    graph.root = "."
    graph.package = "<fixture>"
    mod = ModuleInfo(relpath=filename, modname="fixture",
                     tree=ast.parse(source, filename=filename),
                     source=source)
    graph.modules = {"fixture": mod}
    graph.jit_region = set()
    graph._grow()
    select = set(select) if select is not None else None
    hits = _lint_module(mod, graph.jit_functions("fixture"), select,
                        f64_allowlist)
    race_hits, _report = _race_by_module(graph, select, guarded_map={})
    hits.extend(race_hits.get("fixture", ()))
    kept, stale = _apply_suppressions(mod, hits, select)
    return sorted(kept + stale)

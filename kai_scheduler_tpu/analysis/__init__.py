"""kai-lint — static trace-safety, determinism, and recompile-hazard
analysis for the TPU hot path.

The scheduling cycle's whole value proposition is that it stays on
device as a fixed-shape compiled program (SURVEY §7): one dispatch per
cycle, one compile per (shape-bucket, config).  Nothing in Python
*enforces* that property — a stray ``.item()``, a branch on a tracer,
an f64 leak past the ``utils/numerics.py`` f32 discipline, or an
unordered-``set`` iteration feeding a snapshot buffer silently
reintroduces host syncs, recompiles, or nondeterministic signatures.
This package machine-checks those invariants in two layers:

* **Layer 1 — AST lint** (``engine``/``rules``/``callgraph``): a rule
  registry (``KAI0xx`` codes) over a jit-region call graph grown from
  the ``jax.jit`` entry points in ``framework/scheduler.py``,
  ``framework/session.py`` and ``ops/*``.  Pure AST — importing it
  never touches jax, so ``scripts/lint.py`` stays pre-commit fast.
* **Layer 2 — jaxpr probe** (``trace_probe``): traces every registered
  op at canonical padded shapes, walks the jaxpr for forbidden
  primitives (callbacks, f64), asserts compile-cache hits on re-trace
  within a shape bucket, and diffs per-op eqn/const-size stats against
  the checked-in ``baseline.json`` so constant bloat fails loudly.
* **Layer 3 — kai-race** (``concurrency``): thread-root call graphs +
  guarded-by lock-discipline analysis for the HOST runtime (the
  status-updater pool, the ThreadingHTTPServer handlers, the profiler
  sampler, the mutation journal).  ``KAI1xx`` codes, inline
  ``# kai-race: guarded-by=`` annotations, and the checked-in
  ``guarded_by.json`` audit map.  Pure AST, part of the lint layer.
* **Layer 4 — kai-cost** (``costmodel``): a static dataflow audit
  over the same per-entry jaxpr walk the probe uses — def/last-use
  liveness for peak-live-bytes (sub-jaxprs worst-case-resident), a
  per-primitive FLOPs/traffic cost table, the ``KAI201`` broadcast-
  blowup and ``KAI202`` donation-effectiveness checks, per-entry
  budgets in ``cost_baseline.json``, and a scaling mode that fits the
  peak-memory growth exponent over the node axis (the mesh-sharding
  go/no-go signal).
* **Layer 5 — kai-comms** (``comms``): a static SPMD sharding &
  collective-cost audit over the same shared walk — PartitionSpec
  propagation seeded from ``parallel/mesh.state_shardings``, a ring
  byte model per collective-inducing eqn (trip-count-charged under
  loops), the ``KAI301`` node-axis-replication / ``KAI302``
  declared-vs-inferred drift / ``KAI303`` collective-under-loop
  checks, per-entry budgets in ``comm_baseline.json``, an HLO
  lowering cross-validation on the virtual 8-device mesh, and a
  scaling mode that fits modeled comm bytes against device count
  (sublinear = the ROADMAP-2 "go" signal).

CLI: ``python -m kai_scheduler_tpu.analysis`` (see ``__main__``).
Suppression syntax: ``# kai-lint: disable=KAI001`` (own line → next
line; trailing → that line).  Stale suppressions are themselves
findings (``KAI000``), so every disable comment must keep matching a
live finding.
"""
from .engine import (Finding, LintResult, lint_package, lint_source,
                     load_baseline, rule_catalog)

__all__ = [
    "Finding", "LintResult", "lint_package", "lint_source",
    "load_baseline", "rule_catalog",
]

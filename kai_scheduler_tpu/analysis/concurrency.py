"""kai-race — thread-root call graphs + guarded-by lock discipline.

The on-device solve is machine-checked by the trace-safety families
(``rules.py``); this pass covers the other half of the correctness
story, the HOST runtime: the package runs concurrent daemon threads
(status-updater workers, the ThreadingHTTPServer handler pool, the
continuous-profiler sampler) against shared state — including the
``MutationJournal`` the incremental snapshotter depends on, where one
lost mark silently serves a stale snapshot.

Three stages, all pure AST (no jax import — ``scripts/lint.py`` stays
sub-second):

1. **Thread roots** — ``threading.Thread(target=...)`` /
   ``threading.Timer(..., fn)`` spawns and ``ThreadingHTTPServer``
   handler classes (every ``do_*`` method runs on a per-request
   thread).  Spawns inside loops/comprehensions and HTTP handlers are
   *multi-instance*: their accesses conflict with themselves.

2. **Per-root call graphs** — grown with the same best-effort
   resolution style as ``callgraph.py`` plus what host code needs:
   ``self.method()``, closure aliases of ``self`` (the ``outer = self``
   handler idiom), parameter/assignment/return-annotation type
   inference for package classes (``cluster.journal.mark_pod`` resolves
   through ``Cluster.journal -> MutationJournal``).

3. **Lock-context abstract interpretation** — each function body is
   walked with the set of held locks (``with self._lock:`` regions and
   linear ``acquire()``/``release()`` spans), propagated through
   resolved calls.  Every attribute access on a *surface class* (one
   that owns a thread root, or is listed in ``guarded_by.json``) is
   recorded as ``(class, attr, root, held locks, read|write)``.

Findings (the ``KAI1xx`` family, reported through the engine's
suppression/baseline machinery):

* ``KAI100`` stale ``# kai-race:`` annotation (mirrors KAI000)
* ``KAI101`` unguarded write to shared state
* ``KAI102`` mixed guarded/unguarded access or discipline violation
* ``KAI103`` inconsistent lock acquisition order across paths
* ``KAI104`` mutable class attribute shared across instances
* ``KAI105`` blocking call while holding a lock

Intent is declared inline — ``self.cluster = cluster  # kai-race:
guarded-by=_state_lock`` — or in the checked-in package map
(``analysis/guarded_by.json``).  Disciplines: ``guarded-by=<lockattr>``
(every access outside ``__init__`` must hold that lock),
``guarded-by=atomic-swap`` (the attribute is only ever rebound to fresh
immutable values, never mutated in place), ``guarded-by=single-writer``
(writes from at most one thread context).  An annotation that stops
matching live shared state is itself a finding (``KAI100``), so
documentation rots loudly.

Resolution is best-effort by design, exactly like the jit call graph: a
missed edge narrows the checked surface (a rule stays silent), never
breaks the build.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Iterator

from .callgraph import ModuleInfo, PackageGraph, _dotted
from .engine import Finding, RuleCtx, rule

_ANNOT_RE = re.compile(r"#\s*kai-race:\s*guarded-by=([A-Za-z0-9_\-]+)")

#: methods whose call on an object mutates it in place
_MUTATORS = frozenset({
    "append", "add", "pop", "popitem", "clear", "update", "extend",
    "insert", "remove", "discard", "setdefault", "sort", "reverse",
    "appendleft", "popleft",
})

#: threading/queue constructors that ARE synchronization objects —
#: attributes holding them are the mechanism, not the shared state
_LOCK_TYPES = frozenset({"threading.Lock", "threading.RLock"})
_SYNC_TYPES = _LOCK_TYPES | frozenset({
    "threading.Event", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "queue.Queue",
    "queue.SimpleQueue", "queue.LifoQueue", "queue.PriorityQueue",
})

#: calls that block (I/O, sleeps, device syncs) — holding a lock across
#: one stalls every contender (KAI105)
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "urllib.request.urlopen", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call", "subprocess.Popen",
})
_BLOCKING_METHODS = frozenset({"block_until_ready"})

#: ``__init__``-like methods: attribute writes there happen before the
#: object is published to other threads
_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

_RACE_CODES = ("KAI100", "KAI101", "KAI102", "KAI103", "KAI104",
               "KAI105")


def race_codes() -> tuple[str, ...]:
    return _RACE_CODES


# ---------------------------------------------------------------------------
# package indexing: classes, lock attributes, type inference
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ClassInfo:
    """One class of the package (including nested classes)."""

    modname: str
    qual: str                      # e.g. "SchedulerServer.__init__.Handler"
    node: ast.ClassDef
    #: method name -> function qualname in the module
    methods: dict[str, str] = dataclasses.field(default_factory=dict)
    lock_attrs: set[str] = dataclasses.field(default_factory=set)
    sync_attrs: set[str] = dataclasses.field(default_factory=set)
    #: attr -> (modname, classqual) for self.X = PackageClass(...) style
    attr_types: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    #: line -> attr for every ``self.X = ...`` assignment (annotations)
    attr_assign_lines: dict[int, str] = dataclasses.field(
        default_factory=dict)
    all_attrs: set[str] = dataclasses.field(default_factory=set)

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


@dataclasses.dataclass
class AccessRecord:
    """One attribute access observed during abstract interpretation."""

    cls: str                 # class qual (module-local)
    modname: str
    attr: str
    root: str                # thread-root id, or "main"
    held: frozenset          # lock ids held at the access
    write: bool
    rebind: bool             # plain ``x.attr = ...`` (vs in-place)
    file: str
    line: int
    function: str
    multi: bool              # root spawns multiple threads


@dataclasses.dataclass
class ThreadRoot:
    """One statically discovered thread entry point."""

    root_id: str             # "<relpath>::<qual>" (or ::external:<expr>)
    modname: str | None      # None for unresolved targets
    qual: str | None
    multi: bool              # pool/loop/per-request spawn
    kind: str                # "thread" | "timer" | "http-handler"
    file: str
    line: int


@dataclasses.dataclass
class RaceReport:
    findings: list[Finding]
    roots: list[ThreadRoot]
    #: (class qual, attr) -> discipline string for every declared attr
    disciplines: dict[tuple[str, str], str]
    #: number of live (non-stale) inline annotations
    live_annotations: int = 0
    #: every surface-class attribute access the interpretation
    #: recorded — meta-tests assert coverage (a resolution regression
    #: must fail loudly, not silently shrink the checked surface)
    interp_accesses: list[AccessRecord] = dataclasses.field(
        default_factory=list)


def _expr_type(mod: ModuleInfo, node: ast.AST) -> str | None:
    """Fully-qualified dotted name of a call/attribute chain, with the
    module's import aliases resolved (``threading.Thread`` stays,
    ``Thread`` imported from threading becomes ``threading.Thread``)."""
    d = _dotted(node)
    if d is None:
        return None
    base = d.split(".")[0]
    target = mod.alias_root(base)
    if target is None:
        return d
    return ".".join([target] + d.split(".")[1:])


class _Index:
    """Whole-package class/type index the interpreter resolves against."""

    def __init__(self, graph: PackageGraph):
        self.graph = graph
        #: (modname, classqual) -> ClassInfo
        self.classes: dict[tuple[str, str], ClassInfo] = {}
        #: modname -> {local class name -> classqual} (top-level only)
        self._top: dict[str, dict[str, str]] = {}
        #: (modname, global name) -> (modname, classqual) instance type
        self.globals: dict[tuple[str, str], tuple[str, str]] = {}
        #: function qualname -> owning (modname, classqual)
        self.owner: dict[tuple[str, str], tuple[str, str]] = {}
        for modname, mod in graph.modules.items():
            self._scan_classes(modname, mod)
        for modname, mod in graph.modules.items():
            self._scan_types(modname, mod)

    # -- discovery --------------------------------------------------------

    def _scan_classes(self, modname: str, mod: ModuleInfo) -> None:
        top = self._top.setdefault(modname, {})

        def walk(body, prefix):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    qual = prefix + node.name
                    info = ClassInfo(modname=modname, qual=qual, node=node)
                    self.classes[(modname, qual)] = info
                    if not prefix:
                        top[node.name] = qual
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fq = f"{qual}.{sub.name}"
                            info.methods[sub.name] = fq
                            self.owner[(modname, fq)] = (modname, qual)
                    walk(node.body, qual + ".")
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    walk(node.body, prefix + node.name + ".")

        walk(mod.tree.body, "")

    def resolve_class(self, modname: str,
                      name: str) -> tuple[str, str] | None:
        """Resolve a local name to a package class (same module, or one
        from-import hop, or one ``__init__`` re-export)."""
        mod = self.graph.modules.get(modname)
        if mod is None:
            return None
        qual = self._top.get(modname, {}).get(name)
        if qual is not None:
            return modname, qual
        if name in mod.sym_imports:
            src_mod, orig = mod.sym_imports[name]
            for cand in (src_mod, src_mod + ".__init__"):
                got = self._top.get(cand, {}).get(orig)
                if got is not None:
                    return cand, got
                sub = self.graph.modules.get(cand)
                if sub is not None and orig in sub.sym_imports:
                    m2, o2 = sub.sym_imports[orig]
                    got = self._top.get(m2, {}).get(o2)
                    if got is not None:
                        return m2, got
        return None

    def _class_of_call(self, modname: str,
                       expr: ast.AST) -> tuple[str, str] | None:
        """Instance type of an expression, if it (or a subexpression)
        constructs a package class or calls a function whose return
        annotation names one."""
        mod = self.graph.modules[modname]
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Name):
                cls = self.resolve_class(modname, sub.func.id)
                if cls is not None:
                    return cls
                fn = self.graph._resolve_call(mod, sub.func)
                if fn is not None:
                    ret = self._return_type(*fn)
                    if ret is not None:
                        return ret
            elif isinstance(sub.func, ast.Attribute):
                full = _expr_type(mod, sub.func)
                if full and "." in full:
                    head, meth = full.rsplit(".", 1)
                    cls = self._resolve_dotted_class(modname, head)
                    if cls is not None:
                        info = self.classes.get(cls)
                        if info and meth in info.methods:
                            ret = self._return_type(cls[0],
                                                    info.methods[meth])
                            if ret is not None:
                                return ret
                        if info and meth == info.name:
                            return cls
                # typed same-module global receiver:
                # ``registry.histogram(...)`` -> Registry.histogram's
                # return annotation
                if isinstance(sub.func.value, ast.Name):
                    g = self.globals.get((modname, sub.func.value.id))
                    if g is not None:
                        info = self.classes.get(g)
                        if info and sub.func.attr in info.methods:
                            ret = self._return_type(
                                g[0], info.methods[sub.func.attr])
                            if ret is not None:
                                return ret
        return None

    def _resolve_dotted_class(self, modname: str,
                              dotted: str) -> tuple[str, str] | None:
        """``pkg.mod.Class`` -> class, for alias-resolved chains."""
        if "." not in dotted:
            return self.resolve_class(modname, dotted)
        mod_part, cls_part = dotted.rsplit(".", 1)
        got = self._top.get(mod_part, {}).get(cls_part)
        if got is not None:
            return mod_part, got
        got = self._top.get(mod_part + ".__init__", {}).get(cls_part)
        if got is not None:
            return mod_part + ".__init__", got
        return None

    def _annotation_type(self, modname: str,
                         ann: ast.AST | None) -> tuple[str, str] | None:
        """Package class named by a parameter/return annotation (also
        inside ``X | None`` unions and ``"X"`` string forms)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        found = []
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Name):
                cls = self.resolve_class(modname, sub.id)
                if cls is not None:
                    found.append(cls)
            elif isinstance(sub, ast.Attribute):
                full = _expr_type(self.graph.modules[modname], sub)
                if full:
                    cls = self._resolve_dotted_class(modname, full)
                    if cls is not None:
                        found.append(cls)
        return found[0] if len(found) == 1 else None

    def _return_type(self, modname: str,
                     qual: str) -> tuple[str, str] | None:
        fn = self.graph.modules[modname].functions.get(qual)
        if fn is None:
            return None
        return self._annotation_type(modname, getattr(fn, "returns", None))

    def _scan_types(self, modname: str, mod: ModuleInfo) -> None:
        # module-level typed globals: registry = Registry() etc.
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cls = self._class_of_call(modname, node.value)
                if cls is not None:
                    self.globals[(modname, node.targets[0].id)] = cls
        # per-class: lock/sync attrs, attr types, assignment lines
        for (cmod, cqual), info in self.classes.items():
            if cmod != modname:
                continue
            self._scan_class_body(mod, info)

    def _scan_class_body(self, mod: ModuleInfo, info: ClassInfo) -> None:
        # dataclass-style annotated fields at class level
        for node in info.node.body:
            if isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                info.all_attrs.add(node.target.id)
                ann = self._ann_dotted(mod, node.annotation)
                if ann in _LOCK_TYPES:
                    info.lock_attrs.add(node.target.id)
                elif ann in _SYNC_TYPES:
                    info.sync_attrs.add(node.target.id)
        # instance attributes assigned in methods
        for mname, fq in info.methods.items():
            fn = mod.functions.get(fq)
            if fn is None:
                continue
            for node in ast.walk(fn):
                targets: list[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    info.all_attrs.add(t.attr)
                    info.attr_assign_lines.setdefault(node.lineno, t.attr)
                    vt = self._ctor_type(mod, value) \
                        if value is not None else None
                    if vt in _LOCK_TYPES:
                        info.lock_attrs.add(t.attr)
                    elif vt in _SYNC_TYPES:
                        info.sync_attrs.add(t.attr)
                    elif value is not None \
                            and t.attr not in info.attr_types:
                        cls = self._class_of_call(info.modname, value)
                        if cls is not None:
                            info.attr_types[t.attr] = cls

    @staticmethod
    def _ctor_type(mod: ModuleInfo, value: ast.AST) -> str | None:
        """Dotted type a value expression constructs, searching through
        wrappers like ``lock if lock is not None else threading.Lock()``
        or ``dataclasses.field(default_factory=threading.Lock)``."""
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                full = _expr_type(mod, sub.func)
                if full in _SYNC_TYPES:
                    return full
            elif isinstance(sub, (ast.Attribute, ast.Name)):
                full = _expr_type(mod, sub)
                if full in _SYNC_TYPES:
                    return full
        return None

    def _ann_dotted(self, mod: ModuleInfo, ann: ast.AST) -> str | None:
        # unwrap ``x: threading.Lock = field(...)`` style annotations
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        return _expr_type(mod, ann)


# ---------------------------------------------------------------------------
# thread-root discovery
# ---------------------------------------------------------------------------


def _iter_spawns(mod: ModuleInfo) -> Iterator[tuple[ast.Call, str, bool]]:
    """(spawn call, kind, multi) for every thread/timer spawn, where
    ``multi`` means the spawn site sits inside a loop/comprehension."""

    def walk(node, in_loop):
        loopy = in_loop or isinstance(
            node, (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                   ast.SetComp, ast.GeneratorExp, ast.DictComp))
        if isinstance(node, ast.Call):
            full = _expr_type(mod, node.func)
            if full == "threading.Thread":
                yield node, "thread", loopy
            elif full == "threading.Timer":
                yield node, "timer", loopy
        for child in ast.iter_child_nodes(node):
            yield from walk(child, loopy)

    yield from walk(mod.tree, False)


def _spawn_target(call: ast.Call, kind: str) -> ast.AST | None:
    if kind == "thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if kind == "timer" and len(call.args) >= 2:
        return call.args[1]
    return None


def _resolve_target(index: _Index, mod: ModuleInfo, fn_qual: str | None,
                    target: ast.AST) -> tuple[str, str] | None:
    """Resolve a spawn target expression to (modname, function qual)."""
    if isinstance(target, ast.Name):
        resolved = index.graph._resolve_call(mod, target)
        return resolved
    if isinstance(target, ast.Attribute) and isinstance(target.value,
                                                        ast.Name):
        base = target.value.id
        owner = None
        if base == "self" and fn_qual is not None:
            owner = index.owner.get((mod.modname, fn_qual))
        if owner is not None:
            info = index.classes.get(owner)
            if info is not None and target.attr in info.methods:
                return owner[0], info.methods[target.attr]
    return None


def _spawn_sites(index: _Index) -> list[tuple[str, ast.Call, str, bool,
                                              str | None]]:
    """(modname, spawn call, kind, multi, containing function qual) for
    every thread/timer spawn in the package — computed once and shared
    by root discovery and surface selection (the containing-function
    map costs a full AST walk per module)."""
    out = []
    for modname in sorted(index.graph.modules):
        mod = index.graph.modules[modname]
        containing: dict[int, str] = {}
        for qual, fn in mod.functions.items():
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    containing.setdefault(id(sub), qual)
        for call, kind, multi in _iter_spawns(mod):
            out.append((modname, call, kind, multi,
                        containing.get(id(call))))
    return out


def discover_roots(index: _Index,
                   spawns: list | None = None) -> list[ThreadRoot]:
    roots: list[ThreadRoot] = []
    seen: set[str] = set()

    def add(root: ThreadRoot) -> None:
        if root.root_id not in seen:
            seen.add(root.root_id)
            roots.append(root)

    if spawns is None:
        spawns = _spawn_sites(index)
    for modname, call, kind, multi, fn_qual in spawns:
        mod = index.graph.modules[modname]
        target = _spawn_target(call, kind)
        if target is None:
            continue
        resolved = _resolve_target(index, mod, fn_qual, target)
        if resolved is not None:
            rmod, rqual = resolved
            rel = index.graph.modules[rmod].relpath
            add(ThreadRoot(
                root_id=f"{rel}::{rqual}", modname=rmod, qual=rqual,
                multi=multi, kind=kind, file=mod.relpath,
                line=call.lineno))
        else:
            expr = ast.unparse(target)
            add(ThreadRoot(
                root_id=f"{mod.relpath}::external:{expr}",
                modname=None, qual=None, multi=multi, kind=kind,
                file=mod.relpath, line=call.lineno))
    for modname in sorted(index.graph.modules):
        mod = index.graph.modules[modname]
        # ThreadingHTTPServer(addr, Handler): every do_* method of the
        # handler class runs on a per-request thread
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 2):
                continue
            full = _expr_type(mod, node.func)
            if full not in ("http.server.ThreadingHTTPServer",
                            "socketserver.ThreadingTCPServer"):
                continue
            handler = node.args[1]
            if not isinstance(handler, ast.Name):
                continue
            # the handler class may be nested in the enclosing function
            cand = [
                (m, q) for (m, q), info in index.classes.items()
                if m == modname and info.name == handler.id]
            for cmod, cqual in sorted(cand):
                info = index.classes[(cmod, cqual)]
                for mname, fq in sorted(info.methods.items()):
                    if mname.startswith("do_"):
                        add(ThreadRoot(
                            root_id=f"{mod.relpath}::{fq}",
                            modname=cmod, qual=fq, multi=True,
                            kind="http-handler", file=mod.relpath,
                            line=info.node.lineno))
    return roots


# ---------------------------------------------------------------------------
# lock-context abstract interpretation
# ---------------------------------------------------------------------------


class _Interp:
    """Walks function bodies under a held-lock context, recording
    surface-class attribute accesses, lock orderings, and blocking
    calls."""

    def __init__(self, index: _Index, surface: set[tuple[str, str]]):
        self.index = index
        self.surface = surface
        self.accesses: list[AccessRecord] = []
        #: (outer lock, inner lock) -> first (file, line) observed
        self.order: dict[tuple, tuple[str, int]] = {}
        self.blocking: list[tuple[str, int, str, str]] = []
        self._seen: set[tuple] = set()
        self._root: str = "main"
        self._multi: bool = False

    # -- entry ------------------------------------------------------------

    def run_root(self, modname: str, qual: str, root: str,
                 multi: bool) -> None:
        self._root, self._multi = root, multi
        self._visit_function(modname, qual, frozenset())

    def _visit_function(self, modname: str, qual: str,
                        held: frozenset) -> None:
        key = (modname, qual, held, self._root)
        if key in self._seen or len(self._seen) > 4000:
            return
        self._seen.add(key)
        mod = self.index.graph.modules.get(modname)
        fn = mod.functions.get(qual) if mod is not None else None
        if fn is None:
            return
        aliases = self._self_aliases(mod, qual)
        locals_ = self._local_types(mod, fn, qual, aliases)
        self._walk_block(mod, qual, fn.body, held, aliases, locals_)

    # -- scope helpers ----------------------------------------------------

    def _self_aliases(self, mod: ModuleInfo,
                      qual: str) -> dict[str, tuple[str, str]]:
        """Names bound to an instance of a known class inside ``qual``:
        ``self`` (the owning class) plus ``outer = self`` closure
        aliases inherited from enclosing defs (the nested
        ThreadingHTTPServer handler idiom)."""
        out: dict[str, tuple[str, str]] = {}
        parts = qual.split(".")
        # enclosing def chain, outermost first, so inner bindings win
        for i in range(1, len(parts)):
            prefix = ".".join(parts[:i])
            owner = self.index.owner.get((mod.modname, prefix))
            fn = mod.functions.get(prefix)
            if owner is None or fn is None:
                continue
            for node in fn.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    out[node.targets[0].id] = owner
        me = self.index.owner.get((mod.modname, qual))
        if me is not None:
            out["self"] = me
        return out

    def _local_types(self, mod: ModuleInfo, fn: ast.AST, qual: str,
                     aliases: dict) -> dict[str, tuple[str, str]]:
        out: dict[str, tuple[str, str]] = {}
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg == "self":
                continue
            t = self.index._annotation_type(mod.modname, a.annotation)
            if t is not None:
                out[a.arg] = t
        # two passes so ``j = c.journal`` chains through ``c = ...``
        for _ in range(2):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                name = node.targets[0].id
                if name in out:
                    continue
                t = self.index._class_of_call(mod.modname, node.value)
                if t is None and isinstance(node.value,
                                            (ast.Name, ast.Attribute)):
                    t = self._instance_of(mod, node.value, aliases, out)
                if t is not None:
                    out[name] = t
        return out

    def _instance_of(self, mod, expr, aliases, locals_):
        """(modname, classqual) an expression statically refers to."""
        if isinstance(expr, ast.Name):
            if expr.id in aliases:
                return aliases[expr.id]
            if expr.id in locals_:
                return locals_[expr.id]
            g = self.index.globals.get((mod.modname, expr.id))
            if g is not None:
                return g
            if expr.id in mod.sym_imports:
                src_mod, orig = mod.sym_imports[expr.id]
                return self.index.globals.get((src_mod, orig))
            return None
        if isinstance(expr, ast.Attribute):
            base = self._instance_of(mod, expr.value, aliases, locals_)
            if base is not None:
                info = self.index.classes.get(base)
                if info is not None:
                    return info.attr_types.get(expr.attr)
            # module attribute: metrics.registry
            if isinstance(expr.value, ast.Name):
                target_mod = mod.alias_root(expr.value.id)
                if target_mod is not None:
                    return self.index.globals.get(
                        (target_mod, expr.attr)) or \
                        self.index.globals.get(
                            (target_mod + ".__init__", expr.attr))
        return None

    def _lock_id(self, mod, expr, aliases, locals_):
        """Identify a lock expression: ``self._lock`` / ``outer._x`` /
        a module-level lock global -> a stable hashable id."""
        if isinstance(expr, ast.Attribute):
            base = self._instance_of(mod, expr.value, aliases, locals_)
            if base is not None:
                info = self.index.classes.get(base)
                if info is not None and expr.attr in info.lock_attrs:
                    return (base[1], expr.attr)
        if isinstance(expr, ast.Name):
            # module-level ``_lock = threading.Lock()``
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == expr.id \
                        and _expr_type(mod, node.value) in _LOCK_TYPES:
                    return (mod.modname, expr.id)
        return None

    # -- the walk ---------------------------------------------------------

    def _walk_block(self, mod, qual, stmts, held, aliases, locals_):
        held = set(held)
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                inner = set(held)
                for item in stmt.items:
                    self._scan_expr(mod, qual, item.context_expr,
                                    frozenset(held), aliases, locals_)
                    lid = self._lock_id(mod, item.context_expr, aliases,
                                        locals_)
                    if lid is not None:
                        self._note_order(held, lid, mod, stmt)
                        inner.add(lid)
                self._walk_block(mod, qual, stmt.body, frozenset(inner),
                                 aliases, locals_)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run later, under their own roots
            # acquire()/release() spans within this block
            acq = self._acquire_toggle(mod, stmt, aliases, locals_)
            if acq is not None:
                lid, acquire = acq
                if acquire:
                    self._note_order(held, lid, mod, stmt)
                    held.add(lid)
                else:
                    held.discard(lid)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(mod, qual, stmt.test, frozenset(held),
                                aliases, locals_)
                self._walk_block(mod, qual, stmt.body, frozenset(held),
                                 aliases, locals_)
                self._walk_block(mod, qual, stmt.orelse, frozenset(held),
                                 aliases, locals_)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(mod, qual, stmt.iter, frozenset(held),
                                aliases, locals_)
                self._walk_block(mod, qual, stmt.body, frozenset(held),
                                 aliases, locals_)
                self._walk_block(mod, qual, stmt.orelse, frozenset(held),
                                 aliases, locals_)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_block(mod, qual, blk, frozenset(held),
                                     aliases, locals_)
                for h in stmt.handlers:
                    self._walk_block(mod, qual, h.body, frozenset(held),
                                     aliases, locals_)
            else:
                self._scan_expr(mod, qual, stmt, frozenset(held),
                                aliases, locals_)

    def _acquire_toggle(self, mod, stmt, aliases, locals_):
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in ("acquire", "release")):
            return None
        lid = self._lock_id(mod, stmt.value.func.value, aliases, locals_)
        if lid is None:
            return None
        return lid, stmt.value.func.attr == "acquire"

    def _note_order(self, held, inner, mod, node) -> None:
        for outer_lock in held:
            if outer_lock != inner:
                self.order.setdefault(
                    (outer_lock, inner), (mod.relpath, node.lineno))

    # -- expression scanning ----------------------------------------------

    def _scan_expr(self, mod, qual, node, held, aliases, locals_):
        writes: dict[int, bool] = {}  # id(Attribute) -> rebind?

        def mark_write(attr_node, rebind):
            if isinstance(attr_node, ast.Attribute):
                writes[id(attr_node)] = rebind

        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.Delete,
                                ast.AnnAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [getattr(sub, "target", None)]
                           if not isinstance(sub, ast.Delete)
                           else sub.targets)
                for t in targets:
                    if t is None:
                        continue
                    if isinstance(t, ast.Attribute):
                        mark_write(t, isinstance(sub, ast.Assign)
                                   or isinstance(sub, ast.AnnAssign))
                    elif isinstance(t, (ast.Subscript, ast.Starred)):
                        mark_write(t.value, False)
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Attribute):
                                mark_write(e, True)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS:
                mark_write(sub.func.value, False)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_blocking(mod, qual, sub, held, aliases,
                                     locals_)
                self._propagate_call(mod, qual, sub, held, aliases,
                                     locals_)
            if not isinstance(sub, ast.Attribute):
                continue
            base = self._instance_of(mod, sub.value, aliases, locals_)
            if base is None or base not in self.surface:
                continue
            info = self.index.classes.get(base)
            if info is None or sub.attr in info.lock_attrs \
                    or sub.attr in info.sync_attrs:
                continue
            if sub.attr in info.methods:
                continue  # bound-method reference, not state
            # writes in the owning class's __init__ happen before the
            # object is shared
            fname = qual.rsplit(".", 1)[-1]
            if fname in _INIT_METHODS \
                    and self.index.owner.get((mod.modname, qual)) == base:
                continue
            self.accesses.append(AccessRecord(
                cls=base[1], modname=base[0], attr=sub.attr,
                root=self._root, held=held,
                write=id(sub) in writes,
                rebind=writes.get(id(sub), False),
                file=mod.relpath, line=sub.lineno, function=qual,
                multi=self._multi))

    def _check_blocking(self, mod, qual, call, held, aliases, locals_):
        if not held:
            return
        full = _expr_type(mod, call.func)
        name = None
        if full in _BLOCKING_DOTTED:
            name = full
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr in _BLOCKING_METHODS:
            name = f".{call.func.attr}()"
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("get", "put") \
                and isinstance(call.func.value, ast.Attribute):
            # a blocking queue op on a queue-typed attribute
            recv = call.func.value
            base = self._instance_of(mod, recv.value, aliases, locals_)
            info = self.index.classes.get(base) if base else None
            if info is not None and recv.attr in info.sync_attrs:
                nonblocking = any(
                    k.arg == "block" and isinstance(k.value, ast.Constant)
                    and k.value.value is False for k in call.keywords)
                if not nonblocking:
                    name = f"queue .{call.func.attr}()"
        if name is not None:
            locks = ", ".join(sorted(".".join(l) for l in held))
            self.blocking.append((
                mod.relpath, call.lineno, qual,
                f"blocking call {name} while holding [{locks}] stalls "
                f"every contender on the lock — move the slow operation "
                f"outside the critical section"))

    def _propagate_call(self, mod, qual, call, held, aliases, locals_):
        func = call.func
        resolved = None
        if isinstance(func, ast.Name):
            # NB: constructor calls are NOT traversed — writes during
            # construction happen before the object is published
            resolved = self.index.graph._resolve_call(mod, func)
        elif isinstance(func, ast.Attribute):
            base = self._instance_of(mod, func.value, aliases, locals_)
            if base is not None:
                info = self.index.classes.get(base)
                if info is not None and func.attr in info.methods:
                    resolved = (base[0], info.methods[func.attr])
            if resolved is None:
                resolved = self.index.graph._resolve_call(mod, func)
        if resolved is not None:
            self._visit_function(resolved[0], resolved[1], held)


# ---------------------------------------------------------------------------
# annotations + the package guarded-by map
# ---------------------------------------------------------------------------


def _iter_annotation_comments(source: str) -> Iterator[
        tuple[int, bool, str]]:
    """(line, own_line, value) for every real ``# kai-race:`` COMMENT
    token — example annotations inside docstrings/fixture strings are
    inert, exactly like the engine's suppression parser."""
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ANNOT_RE.search(tok.string)
        if not m:
            continue
        row, col = tok.start
        yield row, tok.line[:col].strip() == "", m.group(1)


def _parse_annotations(index: _Index) -> tuple[
        dict[tuple[str, str], str], list[tuple[str, int, str]]]:
    """Inline ``# kai-race: guarded-by=X`` comments.

    Returns (declared disciplines keyed by (class qual, attr), orphan
    annotations that bind to no ``self.X = ...`` line)."""
    declared: dict[tuple[str, str], str] = {}
    orphans: list[tuple[str, int, str]] = []
    for modname in sorted(index.graph.modules):
        mod = index.graph.modules[modname]
        attr_lines: dict[int, tuple[str, str]] = {}
        for (cmod, cqual), info in index.classes.items():
            if cmod != modname:
                continue
            for line, attr in info.attr_assign_lines.items():
                attr_lines[line] = (cqual, attr)
        for row, own, value in _iter_annotation_comments(mod.source):
            # own-line comments bind to the next line
            bind = attr_lines.get(row + 1 if own else row)
            if bind is None:
                orphans.append((mod.relpath, row, value))
                continue
            declared[bind] = _normalize_discipline(value)
    return declared, orphans


def _normalize_discipline(value: str) -> str:
    if value in ("atomic-swap", "single-writer"):
        return value
    return f"lock:{value}"


def default_map_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "guarded_by.json")


def load_guarded_map(path: str | None = None) -> dict:
    path = path or default_map_path()
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the analysis driver
# ---------------------------------------------------------------------------


def analyze_package(graph: PackageGraph,
                    guarded_map: dict | None = None) -> RaceReport:
    """Run the full kai-race pass over an AST graph.

    Returns every raw finding (suppressions/baseline are the engine's
    job) plus the discovered thread roots and declared disciplines.
    """
    index = _Index(graph)
    guarded_map = guarded_map or {}
    spawns = _spawn_sites(index)
    roots = discover_roots(index, spawns)
    surface = _surface_classes(index, roots, guarded_map, spawns)

    interp = _Interp(index, surface)
    for r in roots:
        if r.modname is not None:
            interp.run_root(r.modname, r.qual, r.root_id, r.multi)
    _seed_main_contexts(index, interp, surface, roots)

    declared_inline, orphans = _parse_annotations(index)
    declared = dict(declared_inline)
    for cname, spec in guarded_map.get("classes", {}).items():
        for attr, value in spec.get("attrs", {}).items():
            for _key, info in index.classes.items():
                if info.name == cname:
                    declared.setdefault((info.qual, attr),
                                        _normalize_discipline(value))

    findings: list[Finding] = []
    findings.extend(_judge(index, interp, declared, orphans))
    findings.extend(_stale_annotation_findings(index, interp,
                                               declared_inline))
    findings.extend(_lock_order_findings(interp))
    findings.extend(_mutable_class_attr_findings(index))
    findings.extend(
        Finding(file=f, line=line, col=0, code="KAI105", message=msg,
                function=qual)
        for f, line, qual, msg in interp.blocking)
    live = _count_live_annotations(index, interp, declared_inline)
    return RaceReport(findings=sorted(set(findings)), roots=roots,
                      disciplines=declared, live_annotations=live,
                      interp_accesses=list(interp.accesses))


def _surface_classes(index: _Index, roots: list[ThreadRoot],
                     guarded_map: dict,
                     spawns: list | None = None) -> set[tuple[str, str]]:
    """Classes whose instance state the pass tracks: root owners, their
    enclosing instances (nested handler classes), thread spawners, and
    everything the checked-in map lists."""
    surface: set[tuple[str, str]] = set()
    for r in roots:
        if r.modname is None:
            continue
        owner = index.owner.get((r.modname, r.qual))
        if owner is not None:
            surface.add(owner)
        parts = (r.qual or "").split(".")
        for i in range(1, len(parts)):
            enc = index.owner.get((r.modname, ".".join(parts[:i])))
            if enc is not None:
                surface.add(enc)
    if spawns is None:
        spawns = _spawn_sites(index)
    for modname, _call, _kind, _multi, fq in spawns:
        if fq is not None:
            owner = index.owner.get((modname, fq))
            if owner is not None:
                surface.add(owner)
    for cname, spec in guarded_map.get("classes", {}).items():
        for key, info in index.classes.items():
            if info.name == cname and (
                    not spec.get("module")
                    or index.graph.modules[key[0]].relpath
                    == spec["module"]):
                surface.add(key)
    return surface


def _seed_main_contexts(index: _Index, interp: _Interp,
                        surface: set[tuple[str, str]],
                        roots: list[ThreadRoot]) -> None:
    """Analyze every externally-callable method of a surface class in
    the "main" context.  Underscore helpers with an internal ``self.``
    caller are reached through propagation instead — they inherit the
    caller's lock context (``_reset`` called under ``consume``'s lock
    must not be condemned for having no ``with`` of its own)."""
    root_quals = {(r.modname, r.qual) for r in roots}
    for key in sorted(surface):
        info = index.classes[key]
        mod = index.graph.modules[key[0]]
        internal_callees: set[str] = set()
        for fq in info.methods.values():
            fn = mod.functions.get(fq)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self":
                    internal_callees.add(sub.func.attr)
        for mname in sorted(info.methods):
            if mname in _INIT_METHODS:
                continue
            if mname.startswith("_") and mname in internal_callees:
                continue
            if (key[0], info.methods[mname]) in root_quals:
                continue
            interp.run_root(key[0], info.methods[mname], "main", False)


def _group_accesses(interp: _Interp) -> dict[tuple[str, str],
                                             list[AccessRecord]]:
    grouped: dict[tuple[str, str], list[AccessRecord]] = {}
    for rec in interp.accesses:
        grouped.setdefault((rec.cls, rec.attr), []).append(rec)
    return grouped


def _is_shared(recs: list[AccessRecord]) -> bool:
    roots = {r.root for r in recs}
    multi = any(r.multi for r in recs)
    return len(roots) >= 2 or multi


def _judge(index: _Index, interp: _Interp, declared, orphans
           ) -> Iterator[Finding]:
    for relpath, line, value in orphans:
        yield Finding(
            file=relpath, line=line, col=0, code="KAI100",
            message=(f"kai-race annotation `guarded-by={value}` is not "
                     f"attached to a `self.<attr> = ...` assignment — "
                     f"move it onto (or directly above) the attribute "
                     f"initialization"))
    grouped = _group_accesses(interp)
    for (cls, attr) in sorted(grouped):
        recs = sorted(grouped[(cls, attr)],
                      key=lambda r: (r.file, r.line))
        discipline = declared.get((cls, attr))
        shared = _is_shared(recs)
        if discipline is not None:
            yield from _judge_declared(cls, attr, recs, discipline)
            continue
        writes = [r for r in recs if r.write]
        if not shared or not writes:
            continue  # single-context, or immutable-after-init
        common = frozenset.intersection(*(r.held for r in recs))
        if common:
            continue  # uniformly guarded by one lock
        guarded = [r for r in recs if r.held]
        unguarded = [r for r in recs if not r.held]
        if guarded and unguarded:
            r = unguarded[0]
            locks = ", ".join(sorted({
                ".".join(l) for rec in guarded for l in rec.held}))
            yield Finding(
                file=r.file, line=r.line, col=0, code="KAI102",
                message=(f"{cls}.{attr} is accessed both under a lock "
                         f"({locks}) and without one — hold the lock on "
                         f"every access, or declare the discipline with "
                         f"`# kai-race: guarded-by=...`"),
                function=r.function)
        elif not guarded:
            r = sorted(writes, key=lambda w: (w.file, w.line))[0]
            roots = sorted({rec.root for rec in recs})
            yield Finding(
                file=r.file, line=r.line, col=0, code="KAI101",
                message=(f"unguarded write to {cls}.{attr}, shared "
                         f"across thread contexts [{', '.join(roots)}] "
                         f"— guard with a lock or declare "
                         f"`# kai-race: guarded-by=...`"),
                function=r.function)
        else:
            # every access guarded, but by disagreeing locks
            r = recs[0]
            locks = sorted({".".join(l) for rec in recs
                            for l in rec.held})
            yield Finding(
                file=r.file, line=r.line, col=0, code="KAI102",
                message=(f"{cls}.{attr} is guarded by different locks "
                         f"on different paths ({', '.join(locks)}) — "
                         f"accesses do not exclude each other"),
                function=r.function)


def _judge_declared(cls, attr, recs, discipline) -> Iterator[Finding]:
    if discipline.startswith("lock:"):
        lock = discipline.split(":", 1)[1]
        for r in recs:
            # exact lock identity: the attribute's own class must own
            # the held lock — another class's same-NAMED lock (half the
            # package calls its lock `_lock`) excludes nothing
            if (cls, lock) not in r.held:
                yield Finding(
                    file=r.file, line=r.line, col=0, code="KAI102",
                    message=(f"{cls}.{attr} is declared "
                             f"guarded-by={lock} but this "
                             f"{'write' if r.write else 'read'} does "
                             f"not hold it"),
                    function=r.function)
    elif discipline == "atomic-swap":
        for r in recs:
            if r.write and not r.rebind:
                yield Finding(
                    file=r.file, line=r.line, col=0, code="KAI102",
                    message=(f"{cls}.{attr} is declared atomic-swap "
                             f"(rebind-only) but is mutated in place "
                             f"here — build a fresh value and rebind"),
                    function=r.function)
    elif discipline == "single-writer":
        writer_roots = sorted({r.root for r in recs if r.write})
        if len(writer_roots) > 1:
            r = [x for x in recs if x.write][0]
            yield Finding(
                file=r.file, line=r.line, col=0, code="KAI102",
                message=(f"{cls}.{attr} is declared single-writer but "
                         f"is written from multiple thread contexts "
                         f"{writer_roots}"),
                function=r.function)


def _count_live_annotations(index, interp, declared_inline) -> int:
    grouped = _group_accesses(interp)
    return sum(1 for key in declared_inline if key in grouped
               and _is_shared(grouped[key]))


def _stale_annotation_findings(index: _Index, interp: _Interp,
                               declared_inline: dict,
                               ) -> Iterator[Finding]:
    """KAI100 for inline annotations whose attribute no longer matches
    live shared state (map entries stay lenient — they document the
    audit and are pinned by the thread-root meta-test instead)."""
    grouped = _group_accesses(interp)
    for (cls, attr), value in sorted(declared_inline.items()):
        loc = _annotation_location(index, cls, attr)
        if loc is None:
            continue
        if value.startswith("lock:"):
            lock = value.split(":", 1)[1]
            owner = next((info for info in index.classes.values()
                          if info.qual == cls), None)
            if owner is not None and lock not in owner.lock_attrs:
                yield Finding(
                    file=loc[0], line=loc[1], col=0, code="KAI100",
                    message=(f"stale kai-race annotation: {cls} has no "
                             f"lock attribute `{lock}`"))
                continue
        recs = grouped.get((cls, attr))
        if not recs or not _is_shared(recs):
            yield Finding(
                file=loc[0], line=loc[1], col=0, code="KAI100",
                message=(f"stale kai-race annotation on {cls}.{attr}: "
                         f"no shared cross-thread access observed — "
                         f"remove the annotation or re-check thread-"
                         f"root discovery"))


def _annotation_location(index: _Index, cls: str,
                         attr: str) -> tuple[str, int] | None:
    for key, info in index.classes.items():
        if info.qual != cls:
            continue
        mod = index.graph.modules[key[0]]
        lines = mod.source.splitlines()
        for line, a in sorted(info.attr_assign_lines.items()):
            if a != attr:
                continue
            if line <= len(lines) and _ANNOT_RE.search(lines[line - 1]):
                return (mod.relpath, line)
            if line >= 2 and _ANNOT_RE.search(lines[line - 2]):
                return (mod.relpath, line - 1)
    return None


def _lock_order_findings(interp: _Interp) -> list[Finding]:
    out = []
    seen_pairs = set()
    for (a, b), loc in sorted(interp.order.items()):
        if (b, a) in interp.order and frozenset((a, b)) not in seen_pairs:
            seen_pairs.add(frozenset((a, b)))
            loc2 = interp.order[(b, a)]
            where = max(loc, loc2)  # the later acquisition site
            out.append(Finding(
                file=where[0], line=where[1], col=0, code="KAI103",
                message=(f"inconsistent lock order: "
                         f"{'.'.join(a)} -> {'.'.join(b)} on one path "
                         f"and {'.'.join(b)} -> {'.'.join(a)} on "
                         f"another — deadlock window; pick one order")))
    return out


def _mutable_class_attr_findings(index: _Index) -> Iterator[Finding]:
    for (modname, cqual), info in sorted(index.classes.items()):
        mod = index.graph.modules[modname]
        for node in info.node.body:
            value = node.value if isinstance(
                node, (ast.Assign, ast.AnnAssign)) else None
            if value is None:
                continue
            mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp)) \
                or (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in ("list", "dict", "set",
                                          "bytearray"))
            if mutable:
                yield Finding(
                    file=mod.relpath, line=node.lineno, col=0,
                    code="KAI104",
                    message=(f"mutable class attribute on {cqual} is "
                             f"shared across every instance (and every "
                             f"thread touching any instance) — assign "
                             f"it in __init__ or use "
                             f"dataclasses.field(default_factory=...)"),
                    function=cqual)


# ---------------------------------------------------------------------------
# rule registration — the KAI1xx catalog entries.
#
# The checks themselves are graph-level (the engine invokes
# ``analyze_package`` once per lint run, not per module), so the
# registered check functions are inert; registration carries the
# titles for --list-rules/--select and the per-rule self-test fixtures
# ``tests/test_analysis.py`` exercises through ``lint_source``.
# ---------------------------------------------------------------------------


def _graph_level(ctx: RuleCtx) -> Iterator[Finding]:
    return iter(())


rule("KAI100", "stale kai-race annotation (guarded-by comment with no "
     "live shared state)",
     bad="""
import threading


class Worker:
    def __init__(self):
        self.count = 0  # kai-race: guarded-by=_lock
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        pass
""",
     good="""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # kai-race: guarded-by=_lock
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            self.count += 1

    def snapshot(self):
        with self._lock:
            return self.count
""")(_graph_level)


rule("KAI101", "unguarded write to state shared across thread contexts",
     bad="""
import threading


class Worker:
    def __init__(self):
        self.count = 0
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self.count += 1

    def snapshot(self):
        return self.count
""",
     good="""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            self.count += 1

    def snapshot(self):
        with self._lock:
            return self.count
""")(_graph_level)


rule("KAI102", "mixed guarded/unguarded access (or a declared "
     "guarded-by discipline violated)",
     bad="""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            self.state["k"] = 1

    def peek(self):
        return self.state.get("k")
""",
     good="""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}  # kai-race: guarded-by=atomic-swap
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self.state = {"k": 1}

    def peek(self):
        return self.state.get("k")
""")(_graph_level)


rule("KAI103", "inconsistent lock acquisition order across paths "
     "(deadlock window)",
     bad="""
import threading


class Worker:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        threading.Thread(target=self.one, daemon=True).start()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
""",
     good="""
import threading


class Worker:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        threading.Thread(target=self.one, daemon=True).start()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass
""")(_graph_level)


rule("KAI104", "mutable class attribute shared across instances",
     bad="""
class Pool:
    workers = []

    def add(self, w):
        self.workers.append(w)
""",
     good="""
class Pool:
    def __init__(self):
        self.workers = []

    def add(self, w):
        self.workers.append(w)
""")(_graph_level)


rule("KAI105", "blocking call (I/O, sleep, device sync) while holding "
     "a lock",
     bad="""
import threading
import time


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            time.sleep(1.0)
""",
     good="""
import threading
import time


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        time.sleep(1.0)
        with self._lock:
            self.n += 1

    def snapshot(self):
        with self._lock:
            return self.n
""")(_graph_level)

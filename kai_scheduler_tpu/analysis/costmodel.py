"""Layer 4 — kai-cost: static dataflow auditor over the entry jaxprs.

The probe (layer 2, ``trace_probe.py``) counts eqns and const bytes —
enough to catch program bloat, but silent on the binding constraint of
the 100k-node mesh target (ROADMAP 2): **peak live device memory per
entry**.  Nothing before PR 14 could say *before a run* whether a
sharded config fits in HBM, whether an intermediate silently
materializes at N× its inputs (the PR-5 ``[B,N,*]`` lane-prefix cumsum
class), or whether a declared ``donate_argnums`` actually aliased in
the compiled executable (the PR-11 XLA:CPU corruption class).  This
module runs four static analyses off the **shared per-entry jaxpr
walk** (``trace_probe.EntryTrace`` — one trace feeds probe and cost):

* **liveness** — a def/last-use linear scan over each entry's eqn
  list.  Level inputs are caller-held for the whole dispatch; internal
  values are live from their defining eqn to their last use;
  sub-jaxprs of ``cond``/``scan``/``while``/``pjit`` are charged
  **worst-case-resident** (their internal peak stacks on the outer
  live set at the call eqn).  Yields peak-live-bytes plus the top-K
  largest intermediates with their producing primitive.
* **FLOPs / memory traffic** — a per-primitive cost table
  (``dot_general`` from its dimension numbers, scatter/gather, the
  reduce and cumulative families, ``sort``/``top_k``, elementwise).
  Primitives outside the table are charged bytes-only and reported in
  ``unknown_prims`` so the table's coverage can't silently rot.
  ``scan`` bodies multiply by trip count; ``while`` bodies are charged
  one trip and counted in ``unbounded_whiles``; ``cond`` charges the
  worst branch.
* **broadcast-blowup (KAI201)** — any intermediate aval exceeding
  ``blowup_factor ×`` the entry's largest input (padding-era default
  16×; entries with a checked-in ``max_blowup`` get that ratio plus
  tolerance headroom instead, exactly like the eqn budgets).
* **donation effectiveness (KAI202)** — for entries that ship with
  ``donate_argnums`` (the fused ``resident_cycle`` path), lower and
  compile the *donating* jit and verify through the executable's
  ``input_output_alias`` metadata that every donated input leaf
  actually aliased an output.  A donated-but-unaliased buffer is freed
  instead of reused — statically, this is the bug class PR 11 hit at
  runtime.  The audit always donates argnum 0, independent of the
  production CPU carve-out (``_resident_donate_argnums``): it checks
  the program **as shipped on accelerator backends**.

Findings ride the engine's machinery: :class:`engine.Finding` objects
under ``file="jaxpr:<entry>"`` filtered through the same count-based
baseline rows (``cost_baseline.json`` ``"baselined"``, shipped empty —
program-level findings have no source line, so inline suppressions
don't apply; a deliberate exception is a justified baseline row).
Numeric budgets (peak/FLOPs/traffic/blowup) diff against the
``"entries"`` section with the shared tolerance helper
(``analysis/budgets.py``).

A **scaling mode** re-traces key entries at 2-3 padded node widths and
fits the peak-memory growth exponent (log-log least squares) — an
entry whose peak grows super-linearly in N is the mesh-sharding
go/no-go signal for ROADMAP 2, flagged before anyone burns an HBM OOM
discovering it.

Run via ``python -m kai_scheduler_tpu.analysis --cost`` (text/JSON;
``--scaling`` adds the exponent fit; ``--update-baseline`` refreshes
``cost_baseline.json``).  Tier-1: ``tests/test_costmodel.py``.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import warnings
from collections import Counter
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import budgets
from . import trace_probe as tp
from .engine import PROGRAM_RULES, Finding, _apply_baseline

COST_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                                  "cost_baseline.json")

#: tolerance headroom over the checked-in per-entry budgets — same
#: shape as the probe's eqn/const budgets (analysis/budgets.py is the
#: one shared formula).  Cost stats are deterministic at the pinned
#: canonical shapes, so the headroom absorbs compiler/minor-refactor
#: jitter, not measurement noise.
PEAK_TOLERANCE = 0.25
FLOP_TOLERANCE = 0.25
TRAFFIC_TOLERANCE = 0.25
BLOWUP_TOLERANCE = 0.25
PEAK_SLACK_BYTES = 4096
FLOP_SLACK = 16384
TRAFFIC_SLACK_BYTES = 16384

#: peak-memory growth exponent above which a scaling-mode entry is
#: flagged super-linear (the go/no-go bar for mesh-sharding the node
#: axis: peak ∝ N^1.0 shards; N^2 does not)
SUPERLINEAR_EXPONENT = 1.15

#: the KAI2xx catalog — program-level rules implemented here, listed
#: jax-free in ``engine.PROGRAM_RULES`` (one source for --list-rules;
#: the KAI3xx slice belongs to layer 5, ``comms.py``)
COST_RULES = {k: v for k, v in PROGRAM_RULES.items()
              if k.startswith("KAI2")}


@dataclasses.dataclass(frozen=True)
class CostConfig:
    """Knobs for the auditor (defaults are the shipped gate)."""

    #: flag intermediates above this multiple of the largest entry
    #: input when the entry has no baselined ``max_blowup`` (fresh
    #: entries); baselined entries get ``max_blowup × (1+tolerance)``
    #: if that is larger
    blowup_factor: float = 16.0
    #: how many largest intermediates each report retains
    top_k: int = 8


DEFAULT_CONFIG = CostConfig()


@dataclasses.dataclass
class CostReport:
    """One entry's static cost profile (the ``--cost`` unit)."""

    name: str
    peak_live_bytes: int
    input_bytes: int
    largest_input_bytes: int
    flops: int
    traffic_bytes: int
    #: max intermediate bytes / largest input bytes
    max_blowup: float
    #: top-K largest intermediates: {bytes, primitive, aval}
    top_intermediates: list
    #: primitive -> eqn count charged bytes-only (outside the table)
    unknown_prims: dict
    #: while-loops charged a single trip (trip count is dynamic)
    unbounded_whiles: int
    #: donation-effectiveness doc for donating entries, else None
    donation: dict | None
    #: KAI201/KAI202 findings (engine.Finding), pre-baseline
    findings: list


# ---------------------------------------------------------------------------
# jaxpr helpers

def _is_var(v) -> bool:
    """A binding variable (not an inline Literal constant)."""
    return not hasattr(v, "val")


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape)) * np.dtype(dtype).itemsize
    except Exception:  # noqa: BLE001 — abstract tokens etc.
        return 0


def _aval_str(aval) -> str:
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", ())
    try:
        d = np.dtype(dtype).name if dtype is not None else "?"
    except TypeError:       # extended dtypes (PRNG keys etc.)
        d = str(dtype)
    return f"{d}[{','.join(str(s) for s in shape)}]"


#: one structural scan shared with the probe walk — the two layers
#: must agree on nesting by construction, not by parallel edits
_sub_jaxprs = tp.eqn_sub_jaxprs


# ---------------------------------------------------------------------------
# per-primitive FLOP table

#: one output-element = one op (the elementwise/unary/binary family)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow",
    "integer_pow", "exp", "exp2", "log", "log1p", "expm1", "tanh",
    "logistic", "sqrt", "rsqrt", "cbrt", "abs", "neg", "sign", "floor",
    "ceil", "round", "is_finite", "not", "and", "or", "xor",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "eq_to", "ne_to", "lt_to",
    "le_to", "gt_to", "ge_to", "select_n", "clamp",
    "convert_element_type", "erf", "erf_inv", "erfc", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "nextafter",
    "population_count", "clz", "square", "real", "imag", "conj",
    "add_any",
})

#: one input-element = one op (reductions and cumulatives)
_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_precision", "cumsum", "cumprod", "cummax", "cummin",
    "cumlogsumexp",
})

#: pure data movement — zero FLOPs, bytes-only traffic
_MOVEMENT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "squeeze", "rev", "iota", "copy", "stop_gradient", "device_put",
    "split", "expand_dims", "gather", "bitcast_convert_type",
})


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _eqn_flops(eqn) -> tuple[int, bool]:
    """(flops, known?) for one leaf eqn of the cost table."""
    name = eqn.primitive.name
    out_elems = sum(_prod(getattr(v.aval, "shape", ()))
                    for v in eqn.outvars if _is_var(v))
    in_elems = sum(_prod(getattr(v.aval, "shape", ()))
                   for v in eqn.invars
                   if getattr(v, "aval", None) is not None)
    if name == "dot_general":
        (lc, _rc), (lb, _rb) = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
        batch = _prod(lhs[d] for d in lb)
        contract = _prod(lhs[d] for d in lc)
        m = _prod(lhs[d] for d in range(len(lhs))
                  if d not in set(lc) | set(lb))
        n = _prod(rhs[d] for d in range(len(rhs))
                  if d not in set(eqn.params["dimension_numbers"][0][1])
                  | set(eqn.params["dimension_numbers"][1][1]))
        return 2 * batch * m * n * contract, True
    if name in _ELEMENTWISE:
        return out_elems, True
    if name in _REDUCE:
        return in_elems, True
    if name.startswith("scatter"):
        # operand, indices, updates: one op per update element
        upd = eqn.invars[-1]
        return _prod(getattr(upd.aval, "shape", ())), True
    if name == "sort":
        n = max(out_elems, 1)
        return int(n * max(1.0, math.log2(n))), True
    if name == "top_k":
        k = int(eqn.params.get("k", 1))
        n = max(in_elems, 1)
        return int(n * max(1.0, math.log2(k + 1))), True
    if name in _MOVEMENT:
        return 0, True
    return 0, False


# ---------------------------------------------------------------------------
# liveness + rollup (one recursive sweep per entry)

@dataclasses.dataclass
class _LevelCost:
    peak: int
    flops: int
    traffic: int
    inters: list          # (nbytes, primitive, aval str)
    unknown: Counter
    whiles: int
    #: the bounded candidate list dropped smaller intermediates — any
    #: count derived from it is a lower bound, not exact
    truncated: bool = False


def _level_cost(jaxpr_like, config: CostConfig) -> _LevelCost:
    """Cost of one jaxpr level's *internal* values.

    Level invars/constvars belong to the caller's frame (the entry
    wrapper charges top-level inputs as resident for the whole
    dispatch), so the liveness scan here tracks only values this level
    defines: live from their producing eqn to their last use, jaxpr
    outvars live to the end of the level.  An eqn carrying sub-jaxprs
    is charged worst-case-resident: the largest sub-level peak stacks
    on the outer running set at that eqn.
    """
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    eqns = inner.eqns
    n = len(eqns)
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[v] = i
    out_set = {v for v in inner.outvars if _is_var(v)}

    deaths: list[list] = [[] for _ in range(n)]
    sizes: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if not _is_var(v) or _is_drop(v):
                continue
            sizes[v] = _aval_bytes(v.aval)
            if v in out_set:
                continue        # alive to level end
            deaths[max(last_use.get(v, i), i)].append(v)

    running = 0
    out = _LevelCost(peak=0, flops=0, traffic=0, inters=[],
                     unknown=Counter(), whiles=0)
    for i, eqn in enumerate(eqns):
        name = eqn.primitive.name
        sub_peak = 0
        subs = _sub_jaxprs(eqn)
        if subs:
            mult = 1
            if name == "scan":
                mult = max(1, int(eqn.params.get("length", 1) or 1))
            elif name == "while":
                out.whiles += 1
            sub_costs = [_level_cost(s, config) for s in subs]
            sub_peak = max(c.peak for c in sub_costs)
            if name == "cond":
                out.flops += max(c.flops for c in sub_costs)
                out.traffic += max(c.traffic for c in sub_costs)
            else:
                out.flops += mult * sum(c.flops for c in sub_costs)
                out.traffic += mult * sum(c.traffic for c in sub_costs)
            for c in sub_costs:
                out.inters.extend(c.inters)
                out.unknown.update(c.unknown)
                out.whiles += c.whiles
                out.truncated |= c.truncated
        else:
            fl, known = _eqn_flops(eqn)
            out.flops += fl
            if not known:
                out.unknown[name] += 1
            out.traffic += sum(
                _aval_bytes(getattr(v, "aval", None))
                for v in list(eqn.invars) + list(eqn.outvars)
                if getattr(v, "aval", None) is not None)
        for v in eqn.outvars:
            if _is_var(v) and not _is_drop(v):
                running += sizes[v]
                if v not in out_set:
                    out.inters.append((sizes[v], name,
                                       _aval_str(v.aval)))
        out.peak = max(out.peak, running + sub_peak)
        for v in deaths[i]:
            running -= sizes[v]
    # keep the level's candidate list bounded before it bubbles up
    out.inters.sort(key=lambda t: (-t[0], t[1], t[2]))
    cap = max(config.top_k * 4, 32)
    if len(out.inters) > cap:
        out.truncated = True
        del out.inters[cap:]
    return out


def _report_from_closed(name: str, closed, *, config: CostConfig,
                        base_entry: dict | None) -> CostReport:
    """Build one entry's report from its ClosedJaxpr — the shared back
    half of production entries and the KAI201 fixtures."""
    inner = closed.jaxpr
    input_avals = ([v.aval for v in inner.invars]
                   + [v.aval for v in inner.constvars])
    input_bytes = sum(_aval_bytes(a) for a in input_avals)
    largest_input = max((_aval_bytes(a) for a in input_avals),
                        default=0)
    lc = _level_cost(closed, config)
    peak = input_bytes + lc.peak
    top = [{"bytes": b, "primitive": p, "aval": a}
           for b, p, a in lc.inters[:config.top_k]]
    max_inter = lc.inters[0][0] if lc.inters else 0
    blowup = max_inter / max(largest_input, 1)

    findings: list[Finding] = []
    allowed_ratio = config.blowup_factor
    if base_entry is not None and "max_blowup" in base_entry:
        allowed_ratio = max(
            allowed_ratio,
            float(base_entry["max_blowup"]) * (1 + BLOWUP_TOLERANCE))
    offenders = [t for t in lc.inters
                 if t[0] > allowed_ratio * max(largest_input, 1)]
    if offenders:
        worst = offenders[0]
        # the candidate list is bounded per level, so after truncation
        # the offender count is only a lower bound
        count = f"{len(offenders)}{'+' if lc.truncated else ''}"
        findings.append(Finding(
            file=f"jaxpr:{name}", line=0, col=0, code="KAI201",
            message=(
                f"{count} intermediate(s) exceed "
                f"{allowed_ratio:.1f}× the entry's largest input "
                f"({largest_input}B); worst: {worst[2]} ({worst[0]}B, "
                f"{worst[0] / max(largest_input, 1):.1f}×) from "
                f"`{worst[1]}` — a silently materialized broadcast "
                f"scales this entry's HBM footprint past its inputs "
                f"(the PR-5 [B,N,*] lane-prefix class); restructure, "
                f"or absorb an intentional ratio with --cost "
                f"--update-baseline"),
            function=name))
    return CostReport(
        name=name, peak_live_bytes=peak, input_bytes=input_bytes,
        largest_input_bytes=largest_input, flops=lc.flops,
        traffic_bytes=lc.traffic, max_blowup=round(blowup, 2),
        top_intermediates=top, unknown_prims=dict(
            sorted(lc.unknown.items())),
        unbounded_whiles=lc.whiles, donation=None, findings=findings)


# ---------------------------------------------------------------------------
# donation effectiveness (KAI202)

@dataclasses.dataclass(frozen=True)
class DonationSpec:
    """A production entry that ships with ``donate_argnums``."""

    entry: str
    fn: Callable
    donate_argnums: tuple
    static_argnames: tuple


def _donation_specs() -> dict[str, DonationSpec]:
    """Every production entry whose accelerator build donates buffers.

    The audit re-jits with the donation FORCED ON (the production
    ``_resident_donate_argnums`` carve-out turns it off on CPU — the
    exact blindness that let PR 11's corruption ship; this check exists
    to see through it)."""
    from ..framework.scheduler import (RESIDENT_STATIC_ARGNAMES,
                                       resident_cycle)
    return {
        "resident_cycle": DonationSpec(
            entry="resident_cycle", fn=resident_cycle,
            donate_argnums=(0,),
            static_argnames=RESIDENT_STATIC_ARGNAMES),
    }


def _compiled_aliased_params(compiled) -> int | None:
    """Distinct parameter numbers the compiled executable aliases to
    outputs, read from the HloModule header's ``input_output_alias``
    config — ``None`` when the executable exposes no introspection
    (report as unverifiable, never as a silent pass)."""
    text = None
    try:
        mods = compiled.runtime_executable().hlo_modules()
        text = mods[0].to_string()
    except Exception:  # noqa: BLE001 — jax/jaxlib API drift
        try:
            text = compiled.as_text()
        except Exception:  # noqa: BLE001
            return None
    header = text.split("\n", 1)[0]
    if "input_output_alias" not in header:
        return 0
    return len(set(re.findall(
        r"\((\d+), \{[^}]*\}, (?:may|must)-alias\)", header)))


def check_donation(spec: DonationSpec, args: tuple,
                   kwargs: dict) -> tuple[dict, list[Finding]]:
    """Lower + compile the donating jit and verify every donated input
    leaf aliased an output in the executable."""
    # audit-time jit, built per check on purpose: the production
    # wrapper may carve donation OUT (CPU backend), and this one must
    # donate unconditionally; it is lowered+compiled exactly once per
    # audit and never dispatched, so the KAI032 per-call cache-miss
    # hazard does not apply
    jit_fn = jax.jit(  # kai-lint: disable=KAI032
        spec.fn, donate_argnums=spec.donate_argnums,
        static_argnames=spec.static_argnames)
    donated_leaves = sum(
        len(jax.tree_util.tree_leaves(args[p]))
        for p in spec.donate_argnums if p < len(args))
    with warnings.catch_warnings():
        # "Some donated buffers were not usable" is exactly what we
        # convert into a KAI202 finding below — don't also print it
        warnings.simplefilter("ignore")
        lowered = jit_fn.lower(*args, **kwargs)
        marked = len(re.findall(r"tf\.aliasing_output",
                                lowered.as_text()))
        compiled = lowered.compile()
    aliased = _compiled_aliased_params(compiled)
    if (aliased == 0 and donated_leaves > 0
            and marked == donated_leaves):
        # lowering marked EVERY donated leaf (tf.aliasing_output) yet
        # the compiled header parsed to zero aliases — far more likely
        # input_output_alias moved off the header line (jaxlib format
        # drift) than XLA dropping every alias.  Classify UNVERIFIABLE
        # so the failure diagnoses the parser, not a phantom
        # production donation bug
        aliased = None
    doc = {
        "entry": spec.entry,
        "donate_argnums": list(spec.donate_argnums),
        "donated_leaves": donated_leaves,
        "lowered_aliased": marked,
        "compiled_aliased": aliased,
        "verified": aliased is not None,
    }
    findings: list[Finding] = []
    if aliased is not None and aliased < donated_leaves:
        findings.append(Finding(
            file=f"jaxpr:{spec.entry}", line=0, col=0, code="KAI202",
            message=(
                f"only {aliased}/{donated_leaves} donated input "
                f"leaves aliased an output in the compiled executable "
                f"({marked} marked at lowering) — an unaliased donated "
                f"buffer is freed, not reused in place, so the "
                f"'resident' state silently diverges from the mirror "
                f"(the PR-11 corruption class, caught statically).  "
                f"Every donated leaf must flow to a matching output "
                f"aval"),
            function=spec.entry))
    return doc, findings


# ---------------------------------------------------------------------------
# entry audit driver

def registered_cost_entries() -> list[str]:
    """Cost coverage == probe coverage: one shared registry."""
    return tp.registered_ops()


#: CompileWatcher entry -> the cost-report names that audit it.  The
#: watcher's production entry list is the coverage oracle: the
#: meta-test in tests/test_costmodel.py pins this map against
#: ``WATCHER.entries()`` in both directions, so a new watched jit
#: entry cannot dodge the auditor.
WATCHER_COVERAGE = {
    "allocate": {"allocate"},
    "run_victim_action": {"victims_reclaim", "victims_preempt",
                          "victims_consolidate",
                          "victims_preempt_sparse"},
    "set_fair_share": {"set_fair_share"},
    "pack_commit": {"pack_commit"},
    "stale_gang_eviction": {"stale_gang_eviction"},
    "fused_pipeline": {"fused_pipeline"},
    "analytics": {"analytics"},
    "repack": {"repack"},
    "resident_cycle": {"resident_cycle"},
}


def run_cost(names: list[str] | None = None, *,
             traces: list | None = None,
             baseline: dict | None = None,
             config: CostConfig = DEFAULT_CONFIG,
             donation: bool = True) -> list[CostReport]:
    """Audit the selected (default: all) registered entries.

    ``traces`` accepts pre-built :class:`trace_probe.EntryTrace`
    objects (the shared walk) so a combined probe+cost run traces each
    entry once.  ``baseline`` (the ``entries`` dict of
    ``cost_baseline.json``) feeds the per-entry blowup allowance.
    """
    baseline = baseline or {}
    if traces is None:
        traces = tp.trace_entries(names)
    elif names:
        sel = set(names)
        traces = [t for t in traces if t.name in sel]
    specs = _donation_specs() if donation else {}
    env = None
    reports = []
    for t in traces:
        rep = _report_from_closed(t.name, t.closed, config=config,
                                  base_entry=baseline.get(t.name))
        if t.name in specs:
            if env is None:
                env = tp._canonical_env(now=1000.0)
            probe_spec = {s.name: s for s in tp._registry()}[t.name]
            args, kwargs = probe_spec.make_args(env)
            doc, dfind = check_donation(specs[t.name], args, kwargs)
            rep.donation = doc
            rep.findings.extend(dfind)
        reports.append(rep)
    return reports


# ---------------------------------------------------------------------------
# baseline

def load_cost_baseline(path: str = COST_BASELINE_PATH) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def unverifiable_donations(reports: list[CostReport]) -> list[str]:
    """Donating entries whose compiled executable exposed no aliasing
    introspection — always a failure (the KAI202 check must never pass
    vacuously), and a blocker for ``--update-baseline`` too."""
    return [
        f"{r.name}: compiled executable exposes no "
        f"input_output_alias introspection — the KAI202 "
        f"donation check is UNVERIFIABLE on this jax; re-wire "
        f"_compiled_aliased_params, don't skip the check"
        for r in reports
        if r.donation is not None and not r.donation["verified"]]


def check_against_cost_baseline(reports: list[CostReport],
                                baseline: dict, *,
                                full_coverage: bool = True
                                ) -> list[str]:
    """Numeric budget regressions ([] = clean) — peak/FLOPs/traffic
    against the checked-in per-entry stats, via the shared tolerance
    helper.  Blowup regressions surface as KAI201 findings instead
    (:func:`cost_findings`), not here."""
    entries = baseline.get("entries", {})
    problems: list[str] = unverifiable_donations(reports)
    for r in reports:
        base = entries.get(r.name)
        if base is None:
            problems.append(
                f"{r.name}: no cost baseline entry — run "
                f"`python -m kai_scheduler_tpu.analysis --cost "
                f"--update-baseline`")
            continue
        for metric, value, key, tol, slack, unit, hint in (
                ("peak live bytes", r.peak_live_bytes,
                 "peak_live_bytes", PEAK_TOLERANCE, PEAK_SLACK_BYTES,
                 "B", "the entry's HBM watermark grew — check the "
                 "top_intermediates diff before absorbing"),
                ("FLOPs", r.flops, "flops", FLOP_TOLERANCE,
                 FLOP_SLACK, "", ""),
                ("memory traffic", r.traffic_bytes, "traffic_bytes",
                 TRAFFIC_TOLERANCE, TRAFFIC_SLACK_BYTES, "B", "")):
            p = budgets.budget_problem(r.name, metric, value,
                                       base[key], tolerance=tol,
                                       slack=slack, unit=unit,
                                       hint=hint)
            if p:
                problems.append(p)
    if full_coverage:
        for name in sorted(set(entries) - {r.name for r in reports}):
            problems.append(
                f"cost baseline lists unknown entry `{name}` — stale, "
                f"refresh with --cost --update-baseline")
    return problems


def cost_findings(reports: list[CostReport],
                  baseline: dict | None = None) -> list[Finding]:
    """All KAI2xx findings, filtered through the engine's count-based
    baseline rows (``cost_baseline.json`` ``"baselined"`` — the same
    machinery as the lint baseline; shipped empty)."""
    findings = sorted(f for r in reports for f in r.findings)
    rows = (baseline or {}).get("baselined", [])
    if rows:
        findings, _eaten = _apply_baseline(findings, rows)
    return findings


def update_cost_baseline(reports: list[CostReport],
                         path: str = COST_BASELINE_PATH) -> None:
    """MERGE the reports' stats (an ``--ops`` subset must not drop the
    other entries' budgets); stale entries pruned only on a
    full-registry update.  The ``baselined`` finding rows are
    preserved verbatim."""
    data = {"baselined": [], "entries": {}}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    entries = data.setdefault("entries", {})
    entries.update({
        r.name: {"peak_live_bytes": r.peak_live_bytes,
                 "flops": r.flops,
                 "traffic_bytes": r.traffic_bytes,
                 "max_blowup": r.max_blowup}
        for r in sorted(reports, key=lambda r: r.name)})
    live = set(registered_cost_entries())
    if {r.name for r in reports} >= live:
        for name in sorted(set(entries) - live):
            del entries[name]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# scaling mode — peak-memory growth exponent over the node axis

def fit_exponent(node_counts, peaks) -> float:
    """Least-squares slope of log(peak) vs log(N) — f32 is plenty for
    a growth exponent (the f64 allowlist stays closed)."""
    xs = np.log(np.asarray(node_counts, dtype=np.float32))
    ys = np.log(np.maximum(np.asarray(peaks, dtype=np.float32), 1.0))
    return float(np.polyfit(xs, ys, 1)[0])


def scaling_report(names: tuple = ("fused_pipeline", "resident_cycle"),
                   node_counts: tuple = (32, 64, 128), *,
                   config: CostConfig = DEFAULT_CONFIG) -> dict:
    """Re-trace key entries at 2-3 padded node widths and fit each
    entry's peak-memory growth exponent.  ``superlinear`` entries
    (exponent > :data:`SUPERLINEAR_EXPONENT`) are the mesh-sharding
    go/no-go signal: their per-shard peak would not drop linearly with
    shard count."""
    unknown = set(names) - set(registered_cost_entries())
    if unknown:
        # a renamed/typoed entry must not vanish into a clean report
        # that reads as "nothing super-linear"
        raise ValueError(
            f"scaling_report: unknown entries {sorted(unknown)} — "
            f"not in the probe/cost registry")
    out: dict = {"node_counts": list(node_counts),
                 "threshold": SUPERLINEAR_EXPONENT, "entries": {}}
    peaks: dict[str, list[int]] = {n: [] for n in names}
    for count in node_counts:
        env = tp._canonical_env(now=1000.0, num_nodes=count)
        for t in tp.trace_entries(list(names), env=env):
            rep = _report_from_closed(t.name, t.closed, config=config,
                                      base_entry=None)
            peaks[t.name].append(rep.peak_live_bytes)
    for name in names:
        if len(peaks[name]) != len(node_counts):
            # a partially-traced entry must not vanish into a clean
            # report, same contract as the unknown-name ValueError
            raise RuntimeError(
                f"scaling_report: entry `{name}` traced at "
                f"{len(peaks[name])}/{len(node_counts)} node widths")
        exp = fit_exponent(node_counts, peaks[name])
        out["entries"][name] = {
            "peak_live_bytes": peaks[name],
            "exponent": round(exp, 3),
            "superlinear": exp > SUPERLINEAR_EXPONENT,
        }
    return out


def peak_mb_for_state(state, names: tuple = ("fused_pipeline",)
                      ) -> dict[str, float]:
    """Peak-live-bytes (MB) of the named entries traced AT the given
    snapshot's shapes — the bench artifact's ``cost_model_peak_mb``
    column (model-side HBM watermark next to the measured columns).
    The state is abstracted to ``ShapeDtypeStruct`` leaves first, so
    this is a pure re-trace: no compile, no dispatch at this shape."""
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                       jnp.result_type(x)), state)
    out = {}
    for t in tp.trace_entries(list(names), env=(abstract, None)):
        rep = _report_from_closed(t.name, t.closed,
                                  config=DEFAULT_CONFIG,
                                  base_entry=None)
        out[t.name] = round(rep.peak_live_bytes / 1e6, 2)
    return out


# ---------------------------------------------------------------------------
# KAI2xx fixtures — jax functions, not AST snippets (the rules judge
# programs); tests/test_costmodel.py runs both directions of each,
# mirroring the engine's per-rule fixture self-tests

def _fixture_blowup_bad(x):
    """f32[8] in, an f32[8,8,8,8,8] (4096×) intermediate mid-trace."""
    big = jnp.broadcast_to(x, (8, 8, 8, 8, 8)) * jnp.float32(2.0)
    return jnp.sum(big)


def _fixture_blowup_good(x):
    return x * jnp.float32(2.0) + jnp.float32(1.0)


def _fixture_donation_bad(x):
    """Donated f32[8] reduced to a scalar — no output can alias it."""
    return jnp.sum(x)


def _fixture_donation_good(x):
    return x + jnp.float32(1.0)


def audit_fixture(code: str, kind: str = "bad") -> list[Finding]:
    """Run one KAI2xx fixture through the same audit path as
    production entries and return its findings."""
    x = jnp.zeros((8,), jnp.float32)
    if code == "KAI201":
        fn = (_fixture_blowup_bad if kind == "bad"
              else _fixture_blowup_good)
        closed = jax.make_jaxpr(fn)(x)
        rep = _report_from_closed(f"fixture_{code}_{kind}", closed,
                                  config=DEFAULT_CONFIG,
                                  base_entry=None)
        return rep.findings
    if code == "KAI202":
        fn = (_fixture_donation_bad if kind == "bad"
              else _fixture_donation_good)
        spec = DonationSpec(entry=f"fixture_{code}_{kind}", fn=fn,
                            donate_argnums=(0,), static_argnames=())
        _doc, findings = check_donation(spec, (x,), {})
        return findings
    raise ValueError(f"unknown cost rule {code}")

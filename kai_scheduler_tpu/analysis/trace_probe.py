"""Layer 2 — jaxpr probe over every registered op.

The AST lint (layer 1) sees source; this layer sees the *program*.  Each
registered op — the compiled kernels the cycle actually dispatches — is
traced at canonical padded shapes and checked for:

* **forbidden primitives**: host callbacks (``pure_callback`` /
  ``io_callback`` / ``debug_callback``) and infeed/outfeed would smuggle
  a host round trip into "one dispatch per cycle"; f64 avals outside
  the allowlist break the f32 device discipline (``utils/numerics.py``);
* **recompilation**: re-tracing the op against a *freshly rebuilt*
  equivalent snapshot (same shape bucket, different host objects and
  clock) must hit the jit cache — this is the end-to-end determinism
  property: any unordered iteration or unstable static config between
  two equivalent builds shows up here as a second compile;
* **constant/eqn bloat**: per-op jaxpr eqn counts and closed-over
  constant bytes are recorded against ``baseline.json`` — a change that
  bakes a fat table into the program (recompiled and re-uploaded per
  shape bucket) fails loudly instead of shipping silently.

Run via ``python -m kai_scheduler_tpu.analysis --probe`` or the tier-1
``tests/test_analysis.py``.  ``--update-baseline`` refreshes the stats.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import budgets
from ..framework.scheduler import (_fused_pipeline, _resident_cycle,
                                   resident_cycle, run_actions,
                                   stale_eviction_jit)
from ..framework.session import (SessionConfig, _pack_commit,
                                 _set_fair_share_jit)
from ..ops import analytics as pulse
from ..ops import drf
from ..ops import repack as repack_ops
from ..ops import resident as resident_ops
from ..ops.allocate import (AllocateConfig, allocate, allocate_jit,
                            init_result)
from ..ops.stale import stale_gang_eviction
from ..ops.victims import (VictimConfig, run_victim_action,
                           run_victim_action_jit)
from ..state.cluster_state import build_snapshot
from ..state.synthetic import make_cluster
from ..utils import numerics

#: module-scope jit wrapper for the numerics helper (the production
#: call sites inline it into larger kernels; the probe needs it
#: addressable on its own)
_CUMSUM_JIT = jax.jit(numerics.cumsum_ds)

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

#: the Session-auto-tuned shape that engages the sparse preempt
#: wavefront (``ops/victims._sparse_preempt_ok``) — the canonical
#: cluster is uniform/no-fraction, so this mirrors what production
#: would compile for it
_VCFG_SPARSE = VictimConfig(placement=AllocateConfig(
    dynamic_order=False, track_devices=False, uniform_tasks=True,
    subgroup_topology=False, extended=False))

#: primitive names that must never appear in a cycle kernel's jaxpr
FORBIDDEN_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "infeed", "outfeed",
})

#: eqn-count headroom over baseline before the probe fails (compiler /
#: minor-refactor jitter); constants get less slack — they are the
#: regression this guard exists for
EQN_TOLERANCE = 0.25
CONST_TOLERANCE = 0.10
CONST_SLACK_BYTES = 1024


@dataclasses.dataclass
class ProbeSpec:
    """One registered op: how to build its canonical invocation."""

    name: str
    #: pure function for ``jax.make_jaxpr`` (static kwargs prebound)
    trace_fn: Callable
    #: the production jitted wrapper, for the compile-cache assertion
    jit_fn: Callable
    #: (args, kwargs) builder from a canonical env — called once per
    #: env so the cache check sees two independent builds
    make_args: Callable


@dataclasses.dataclass
class OpReport:
    name: str
    eqns: int
    const_bytes: int
    forbidden: list[str]
    f64_avals: list[str]
    cache_hit: bool | None      # None = wrapper exposes no cache probe


def _canonical_env(now: float, *, num_nodes: int = 8):
    """A small canonical cluster at production-padded shapes: running
    pods (victim paths need prey), a pending backlog, a 2-level
    topology, and a 2-deep queue hierarchy.  ``num_nodes`` widens the
    node axis only (the kai-cost scaling mode re-traces key entries at
    2-3 padded node widths to fit the peak-memory growth exponent)."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, num_gangs=8, tasks_per_gang=2,
        running_fraction=0.5, partition_queues_by_running=True,
        topology_levels=(2, 2), priority_spread=3,
        pending_priority_boost=2)
    # pad=32 EXPLICITLY: the test conftest widens the default pad to 32
    # for shape unification — pinning it here keeps the CLI probe and
    # the tier-1 probe tracing the same shapes (one baseline serves
    # both, and they share compile-cache entries with the suite)
    state, index = build_snapshot(nodes, queues, groups, pods, topo,
                                  now=now, pad=32)
    return state, index


def _registry() -> list[ProbeSpec]:
    """Every op the cycle dispatches, with canonical arguments.

    Grown alongside the kernels: a new jitted entry point in
    ``framework/`` or ``ops/`` belongs here (the coverage meta-test in
    ``tests/test_analysis.py`` cross-checks against the lint call
    graph's entry points).
    """
    cfg = SessionConfig()
    nl = cfg.num_levels
    acfg, vcfg = AllocateConfig(), VictimConfig()
    actions = ("allocate", "consolidation", "reclaim", "preempt",
               "stalegangeviction")

    def fair_share(state):
        if isinstance(jax.tree_util.tree_leaves(state)[0],
                      jax.ShapeDtypeStruct):
            # abstract env (kai-cost model-only re-trace, e.g. the
            # bench's cost_model_peak_mb column at 10k×50k): compute
            # the fair-share AVAL without compiling or dispatching the
            # standalone jit at this shape
            return jax.eval_shape(
                functools.partial(drf.set_fair_share, num_levels=nl),
                state, k_value=jnp.float32(0.0))
        return _set_fair_share_jit(state, num_levels=nl,
                                   k_value=jnp.float32(0.0))

    def state_fs_args(env):
        state, _ = env
        return (state, fair_share(state)), {}

    def victim_args(env, mode):
        state, _ = env
        return (state, fair_share(state), init_result(state)), {}

    specs = [
        ProbeSpec(
            "set_fair_share",
            functools.partial(drf.set_fair_share, num_levels=nl),
            _set_fair_share_jit,
            lambda env: ((env[0],),
                         dict(num_levels=nl,
                              k_value=jnp.float32(0.0)))),
        ProbeSpec(
            "allocate",
            functools.partial(allocate, num_levels=nl, config=acfg),
            allocate_jit,
            lambda env: (state_fs_args(env)[0],
                         dict(num_levels=nl, config=acfg))),
        *[
            ProbeSpec(
                f"victims_{mode}",
                functools.partial(run_victim_action, num_levels=nl,
                                  mode=mode, config=vcfg),
                run_victim_action_jit,
                functools.partial(
                    lambda env, m: (victim_args(env, m)[0],
                                    dict(num_levels=nl, mode=m,
                                         config=vcfg)), m=mode))
            for mode in ("reclaim", "preempt", "consolidate")
        ],
        ProbeSpec(
            # the sparse/optimistic preempt wavefront (ops/victims.py):
            # same jit entry point, but the sparse protocol only traces
            # under the uniform/no-device/no-extended/no-subgroup shape
            # the Session auto-tunes to — probed explicitly so its
            # jaxpr stays under the callback/f64/eqn budgets too
            "victims_preempt_sparse",
            functools.partial(run_victim_action, num_levels=nl,
                              mode="preempt", config=_VCFG_SPARSE),
            run_victim_action_jit,
            lambda env: (victim_args(env, "preempt")[0],
                         dict(num_levels=nl, mode="preempt",
                              config=_VCFG_SPARSE))),
        ProbeSpec(
            "stale_gang_eviction",
            functools.partial(stale_gang_eviction,
                              grace_s=cfg.stale_grace_s, num_levels=nl),
            stale_eviction_jit,
            lambda env: ((env[0], init_result(env[0])),
                         dict(grace_s=cfg.stale_grace_s,
                              num_levels=nl))),
        ProbeSpec(
            "fused_pipeline",
            functools.partial(run_actions, actions=actions,
                              num_levels=nl, acfg=acfg, vcfg=vcfg,
                              grace_s=cfg.stale_grace_s),
            _fused_pipeline,
            lambda env: (state_fs_args(env)[0],
                         dict(actions=actions, num_levels=nl, acfg=acfg,
                              vcfg=vcfg, grace_s=cfg.stale_grace_s))),
        ProbeSpec(
            # kai-resident fused cycle entry (framework/scheduler.py):
            # delta scatter-apply + fair share + the whole action
            # pipeline + analytics + packed commit as ONE program over
            # donated state — probed with a structurally-valid empty
            # delta (zero-size segments) at the canonical shapes, with
            # analytics riding (the production steady-state cycle)
            "resident_cycle",
            functools.partial(
                resident_cycle, actions=actions, num_levels=nl,
                acfg=acfg, vcfg=vcfg, grace_s=cfg.stale_grace_s,
                track_devices=False,
                analytics_cfg=pulse.AnalyticsConfig()),
            _resident_cycle,
            lambda env: ((env[0], resident_ops.empty_delta(env[0]),
                          jnp.zeros((env[0].gangs.g,), jnp.float32),
                          jnp.float32(0.0)),
                         dict(actions=actions, num_levels=nl, acfg=acfg,
                              vcfg=vcfg, grace_s=cfg.stale_grace_s,
                              track_devices=False,
                              analytics_cfg=pulse.AnalyticsConfig()))),
        ProbeSpec(
            "pack_commit",
            functools.partial(getattr(_pack_commit, "__wrapped__",
                                      _pack_commit),
                              track_devices=False,
                              track_analytics=False),
            _pack_commit,
            lambda env: ((_probe_result(env), env[0]),
                         dict(track_devices=False,
                              track_analytics=False))),
        ProbeSpec(
            # kai-pulse cluster-health kernel (ops/analytics.py): runs
            # over the post-decision snapshot every K cycles and rides
            # the packed commit — probed with a zeroed pending-age
            # vector at the canonical shapes
            "analytics",
            functools.partial(pulse.cluster_analytics,
                              config=pulse.AnalyticsConfig()),
            pulse.cluster_analytics_jit,
            lambda env: ((env[0], _probe_result(env),
                          jnp.zeros((env[0].gangs.g,), jnp.float32)),
                         dict(config=pulse.AnalyticsConfig()))),
        ProbeSpec(
            # kai-repack defragmentation solver (ops/repack.py):
            # dispatched only on fired trigger cycles, but its jaxpr
            # must honor the same no-callback/f32/compile-once budgets
            # as the every-cycle kernels — probed with a zeroed
            # pending-age vector at the canonical shapes
            "repack",
            functools.partial(repack_ops.plan_repack,
                              config=repack_ops.RepackConfig()),
            repack_ops.plan_repack_jit,
            lambda env: ((env[0],
                          jnp.zeros((env[0].gangs.g,), jnp.float32),
                          env[0].nodes.free),
                         dict(config=repack_ops.RepackConfig()))),
        ProbeSpec(
            "cumsum_ds",
            numerics.cumsum_ds,
            _CUMSUM_JIT,
            lambda env: ((jnp.ones((64,), jnp.float32),), {})),
    ]
    return specs


def _probe_result(env):
    return init_result(env[0])


def registered_ops() -> list[str]:
    return [s.name for s in _registry()]


# ---------------------------------------------------------------------------
# jaxpr walking

def eqn_sub_jaxprs(eqn) -> list:
    """Sub-jaxprs nested in an eqn's params — THE structural scan for
    every consumer of a walked entry (this walk and the kai-cost
    liveness sweep in ``costmodel.py``), so the layers can never
    disagree on nesting."""
    subs = []
    for p in eqn.params.values():
        for x in (p if isinstance(p, (tuple, list)) else (p,)):
            if hasattr(x, "eqns") or hasattr(x, "jaxpr"):
                subs.append(x)
    return subs


def _walk_jaxpr(jaxpr, eqns, prims, avals, consts):
    """Recursively visit eqns/sub-jaxprs of a (Closed)Jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for c in getattr(jaxpr, "consts", ()) or ():
        consts.append(c)
    for v in list(inner.invars) + list(inner.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            avals.append(aval)
    for eqn in inner.eqns:
        eqns.append(eqn)
        prims.append(eqn.primitive.name)
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                avals.append(aval)
        for sub in eqn_sub_jaxprs(eqn):
            _walk_jaxpr(sub, eqns, prims, avals, consts)


@dataclasses.dataclass
class EntryTrace:
    """One entry's walked jaxpr — THE shared per-entry walk.

    Both consumers of a traced entry run off this one object: the
    probe's eqn/const/forbidden-primitive stats (``probe_op``) and the
    kai-cost auditor's liveness/FLOP/traffic analysis
    (``costmodel.py``).  Tracing the big fused entries costs seconds
    each, so a full-gate CLI run builds each trace once and feeds it to
    both layers.
    """

    name: str
    #: the ClosedJaxpr from ``jax.make_jaxpr`` (costmodel's liveness
    #: scan needs the nested eqn structure, not just the flat lists)
    closed: object
    #: flattened across every nesting level (``_walk_jaxpr``)
    eqns: list
    prims: list
    avals: list
    consts: list


def trace_entry(spec: ProbeSpec, env) -> EntryTrace:
    """Trace one registered op at the canonical env and walk its jaxpr
    once — the shared front half of ``probe_op`` and every kai-cost
    entry report."""
    args, kwargs = spec.make_args(env)
    trace_kwargs = {k: v for k, v in kwargs.items()
                    if k in ("k_value",)}
    closed = jax.make_jaxpr(spec.trace_fn)(*args, **trace_kwargs)
    eqns, prims, avals, consts = [], [], [], []
    _walk_jaxpr(closed, eqns, prims, avals, consts)
    return EntryTrace(name=spec.name, closed=closed, eqns=eqns,
                      prims=prims, avals=avals, consts=consts)


def trace_entries(names: list[str] | None = None, *,
                  env=None) -> list[EntryTrace]:
    """Walked traces for the selected (default: all) registered ops."""
    specs = _registry()
    if names:
        specs = [s for s in specs if s.name in set(names)]
    if env is None:
        env = _canonical_env(now=1000.0)
    return [trace_entry(s, env) for s in specs]


def _const_bytes(consts) -> int:
    total = 0
    for c in consts:
        try:
            total += np.asarray(c).nbytes
        except Exception:
            pass
    return total


def _cache_size(fn) -> int | None:
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


def probe_op(spec: ProbeSpec, trace: EntryTrace | None = None) -> OpReport:
    """Trace + execute one op: jaxpr walk, then the two-build
    compile-cache assertion.  Pass a pre-built ``trace`` (the shared
    per-entry walk) to skip the re-trace — the cache assertion still
    runs its own two fresh builds either way."""
    env_a = _canonical_env(now=1000.0)
    args, kwargs = spec.make_args(env_a)
    if trace is None:
        trace = trace_entry(spec, env_a)
    forbidden = sorted({p for p in trace.prims
                        for f in FORBIDDEN_PRIMITIVES if f in p})
    f64 = sorted({str(a) for a in trace.avals
                  if getattr(a, "dtype", None) is not None
                  and str(a.dtype) in ("float64", "complex128")})

    # compile-cache discipline: two independent builds of an equivalent
    # cluster (fresh objects, different clock) must share one compile
    jit_fn = spec.jit_fn
    before = _cache_size(jit_fn)
    jax.block_until_ready(jit_fn(*args, **kwargs))
    mid = _cache_size(jit_fn)
    env_b = _canonical_env(now=2000.0)
    args_b, kwargs_b = spec.make_args(env_b)
    jax.block_until_ready(jit_fn(*args_b, **kwargs_b))
    after = _cache_size(jit_fn)
    cache_hit = None
    if mid is not None and after is not None:
        cache_hit = after == mid and (before is None or mid - before <= 1)
    return OpReport(name=spec.name, eqns=len(trace.eqns),
                    const_bytes=_const_bytes(trace.consts),
                    forbidden=forbidden, f64_avals=f64,
                    cache_hit=cache_hit)


def run_probe(names: list[str] | None = None, *,
              traces: list[EntryTrace] | None = None) -> list[OpReport]:
    specs = _registry()
    if names:
        specs = [s for s in specs if s.name in set(names)]
    by_name = {t.name: t for t in traces} if traces else {}
    return [probe_op(s, by_name.get(s.name)) for s in specs]


# ---------------------------------------------------------------------------
# baseline

def load_stats_baseline(path: str = BASELINE_PATH) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f).get("probe", {})


def check_invariants(reports: list[OpReport]) -> list[str]:
    """The baseline-independent properties: no host callbacks, no f64,
    one compile per shape bucket.  These are NEVER absorbed by
    ``--update-baseline`` — there is no legitimate new value."""
    problems = []
    for r in reports:
        if r.forbidden:
            problems.append(
                f"{r.name}: forbidden host-callback primitives in "
                f"jaxpr: {r.forbidden}")
        if r.f64_avals:
            problems.append(
                f"{r.name}: f64 avals on device: {r.f64_avals[:4]}")
        if r.cache_hit is False:
            problems.append(
                f"{r.name}: re-trace against an equivalent rebuilt "
                f"snapshot MISSED the compile cache (nondeterministic "
                f"signature or unstable static config)")
    return problems


def check_against_baseline(reports: list[OpReport], baseline: dict,
                           *, full_coverage: bool = True) -> list[str]:
    """Human-readable regression messages ([] = clean).

    ``full_coverage=False`` (an ``--ops`` subset run) skips the
    stale-baseline-entry sweep — ops that were not probed are not
    missing, just unselected."""
    problems = check_invariants(reports)
    for r in reports:
        base = baseline.get(r.name)
        if base is None:
            problems.append(
                f"{r.name}: no baseline entry — run "
                f"`python -m kai_scheduler_tpu.analysis --probe "
                f"--update-baseline`")
            continue
        # the shared tolerance helper (analysis/budgets.py) — one
        # formula for every baseline-diffed layer (probe AND kai-cost)
        p = budgets.budget_problem(
            r.name, "jaxpr eqn count", r.eqns, base["eqns"],
            tolerance=EQN_TOLERANCE, slack=8, unit=" eqns")
        if p:
            problems.append(p)
        p = budgets.budget_problem(
            r.name, "closed-over constants", r.const_bytes,
            base["const_bytes"], tolerance=CONST_TOLERANCE,
            slack=CONST_SLACK_BYTES, unit="B",
            hint="a baked-in table re-uploads per shape bucket")
        if p:
            problems.append(p)
    if full_coverage:
        for name in sorted(set(baseline) - {r.name for r in reports}):
            problems.append(
                f"baseline lists unknown op `{name}` — stale entry, "
                f"refresh with --update-baseline")
    return problems


def update_baseline(reports: list[OpReport],
                    path: str = BASELINE_PATH) -> None:
    """MERGE the given reports' stats into the baseline — a targeted
    ``--ops X --update-baseline`` must not delete the other ops'
    budgets.  Entries for ops dropped from the registry are pruned
    only on a full-registry update."""
    data = {"lint": [], "probe": {}}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    probe = data.setdefault("probe", {})
    probe.update({
        r.name: {"eqns": r.eqns, "const_bytes": r.const_bytes}
        for r in sorted(reports, key=lambda r: r.name)})
    live = set(registered_ops())
    if {r.name for r in reports} >= live:
        for name in sorted(set(probe) - live):
            del probe[name]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")

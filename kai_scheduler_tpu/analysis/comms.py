"""Layer 5 — kai-comms: static SPMD sharding & collective-cost auditor.

kai-cost (layer 4) told us each entry's peak memory scales ~linearly
in the node axis — the "go" signal for ROADMAP item 2 (mesh-shard the
node axis to 100k nodes).  This layer answers the question that comes
next: **are the entry jaxprs actually shardable under the layout
``parallel/mesh.py`` declares**, and what does the sharding cost in
cross-device traffic?  A single accidental node-axis gather, or a
collective trapped inside the per-gang scan, would erase the win — and
before this pass the first place that showed up was real hardware.

The auditor is a sharding-propagation abstract interpreter over the
same ``trace_probe.EntryTrace`` per-entry jaxpr walk the probe and the
cost model share.  Entry inputs are seeded from a registry mirroring
``mesh.state_shardings`` (node-axis arrays sharded over
:data:`~kai_scheduler_tpu.parallel.mesh.NODE_AXIS`, everything else
replicated); each eqn then either *follows* its operands' sharding
(elementwise, transpose, slice-in-place, ``dot_general`` free dims) or
*induces a collective* (all-reduce for reductions over a sharded dim,
all-gather when a sharded dim must materialize, reduce-scatter /
reshard for layout moves), with modeled cross-device bytes per
collective (ring cost: ``b·(d-1)/d``, all-reduce ``2×``).
``dot_general`` / the reduce family / ``scatter`` are exact from their
dimension numbers; unknown primitives are conservatively gathered to
replicated and *reported* (``conservative_prims``) so table coverage
can't silently rot.

Program-level findings (KAI3xx, on the shared ``engine.Finding``
machinery, listed jax-free in ``engine.PROGRAM_RULES``):

* **KAI301 accidental node-axis replication** — an intermediate
  materializes the full node axis replicated on every device above a
  size threshold: the footprint that sharding exists to remove.
* **KAI302 declared-vs-inferred sharding drift** — the
  ``mesh.state_shardings`` pytree and this auditor's seed registry
  must agree leaf-exact, both directions; a new snapshot section can't
  silently default to replicated on one side only.
* **KAI303 collective-under-loop** — a collective inside
  ``scan``/``while`` is charged trip-count× (the comm analogue of
  kai-cost's worst-case-resident rule) and flagged above a byte
  threshold: hoist it, or absorb a justified baseline row.

Per-entry collective-site counts and comm-byte budgets diff against
``comm_baseline.json`` via the shared tolerance helper
(``analysis/budgets.py``); ``--update-baseline`` refreshes probe, cost
and comm baselines atomically or not at all.  A **lowering
cross-validation** stage jits the fused entries with the real
``in_shardings`` on an 8-virtual-device CPU mesh and asserts the
collective ops in the compiled HLO are within the model's predicted
set — UNVERIFIABLE introspection blocks baseline updates, mirroring
KAI202.  ``--comms --scaling`` fits modeled comm bytes vs device count
{2, 4, 8}: the sub-linear-comm go/no-go signal for the sharded solver.

Run via ``python -m kai_scheduler_tpu.analysis --comms``.  Tier-1:
``tests/test_comms.py``; the mesh meta-test lives in
``tests/test_mesh.py``.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import os
import re
import warnings
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

from . import budgets
from . import trace_probe as tp
from .costmodel import (_aval_bytes, _aval_str, _is_drop, _is_var,
                        fit_exponent)
from .engine import PROGRAM_RULES, Finding, _apply_baseline
from ..parallel import mesh as mesh_mod
from ..state.cluster_state import ClusterState

COMM_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                                  "comm_baseline.json")

#: tolerance headroom over the checked-in per-entry comm budgets —
#: the shared formula (analysis/budgets.py), same shape as probe/cost
COMM_TOLERANCE = 0.25
SITE_SLACK = 4
COMM_SLACK_BYTES = 4096

#: comm-bytes-vs-devices exponent at or above which an entry's
#: modeled comm grows linearly-or-worse with mesh width — the no-go
#: bar for ROADMAP 2 (ring collectives plateau at (d-1)/d ≈ const, so
#: a healthy entry fits well under 1.0)
SUBLINEAR_EXPONENT_BAR = 1.0

#: the KAI3xx catalog — program-level rules implemented here, listed
#: jax-free in ``engine.PROGRAM_RULES`` (one source for --list-rules)
COMM_RULES = {k: v for k, v in PROGRAM_RULES.items()
              if k.startswith("KAI3")}

#: the fused production entries the HLO cross-validation stage lowers
#: with real in_shardings on the virtual CPU mesh
LOWERING_ENTRIES = ("fused_pipeline", "resident_cycle")


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Knobs for the auditor (defaults are the shipped gate)."""

    #: mesh width the byte model charges (the virtual CPU mesh the
    #: lowering stage compiles against — one shared constant)
    num_devices: int = mesh_mod.VIRTUAL_DEVICE_COUNT
    #: KAI301 fires when a REPLICATED intermediate carrying the node
    #: axis exceeds this many bytes (canonical 32-wide shapes stay far
    #: under; bench/production widths do not)
    node_materialize_bytes: int = 1 << 20
    #: KAI303 fires when trip-count-charged loop collectives exceed
    #: this many modeled cross-device bytes per entry
    loop_comm_bytes: int = 8 << 20
    #: how many largest collectives each report retains
    top_k: int = 8


DEFAULT_CONFIG = CommConfig()


# ---------------------------------------------------------------------------
# PartitionSpec lattice

@dataclasses.dataclass(frozen=True)
class Spec:
    """An inferred PartitionSpec: one mesh-axis name (or None) per
    dim.  Unregistered dataclass on purpose — a pytree LEAF, so a
    ClusterState-shaped tree of Specs flattens 1:1 with the state."""

    dims: tuple

    @property
    def sharded(self) -> bool:
        return any(d is not None for d in self.dims)


def _ndim(x) -> int:
    s = getattr(x, "shape", None)
    if s is not None:
        return len(s)
    return int(np.ndim(x))


def _replicated(ndim: int) -> Spec:
    return Spec((None,) * int(ndim))


def _meet(a: Spec, b: Spec) -> Spec:
    """Lattice meet toward replicated: a dim keeps its axis name only
    when both sides agree (monotone — the fixpoint loops terminate)."""
    if len(a.dims) != len(b.dims):
        return _replicated(max(len(a.dims), len(b.dims)))
    return Spec(tuple(x if x == y else None
                      for x, y in zip(a.dims, b.dims)))


def _dedupe(dims: list) -> Spec:
    """A mesh axis can shard at most one dim — first occurrence wins
    (matches GSPMD's prefix resolution for our single-axis mesh)."""
    seen: set = set()
    out = []
    for d in dims:
        if d is not None and d in seen:
            out.append(None)
        else:
            if d is not None:
                seen.add(d)
            out.append(d)
    return Spec(tuple(out))


def collective_bytes(kind: str, nbytes: int, num_devices: int) -> int:
    """Modeled cross-device bytes for one collective over a ``nbytes``
    full (unsharded) array on a ``num_devices`` ring: gather/scatter
    families move ``b·(d-1)/d``; all-reduce is reduce-scatter +
    all-gather, ``2×`` that."""
    d = max(2, int(num_devices))
    base = int(nbytes) * (d - 1) // d
    if kind == "all_reduce":
        return 2 * base
    return base


# ---------------------------------------------------------------------------
# seed registry — the auditor's own, deliberately independent
# reimplementation of mesh.state_shardings (KAI302 cross-checks the
# two leaf-exact, both directions)

#: NodeState tables that carry the node axis SECOND ([X, N]); every
#: other node-section array is node-axis-first
NODE_AXIS_SECOND = frozenset({"filter_masks", "soft_scores"})

_STATE_SECTIONS = ("nodes", "queues", "gangs", "running")


def seed_state_specs(state: ClusterState):
    """A ClusterState-shaped pytree of :class:`Spec` seeds: node-axis
    arrays sharded over :data:`mesh.NODE_AXIS`, everything else
    replicated.  A snapshot section this registry does not know is a
    hard error — a new section must be classified here (and in
    ``mesh.state_shardings``) before it can ride the mesh."""
    sections = {f.name for f in dataclasses.fields(type(state))}
    unknown = sections - set(_STATE_SECTIONS)
    if unknown:
        raise ValueError(
            f"seed_state_specs: unclassified ClusterState section(s) "
            f"{sorted(unknown)} — add them to the kai-comms seed "
            f"registry AND mesh.state_shardings (KAI302 pins the two "
            f"against each other)")

    def repl(x):
        return _replicated(_ndim(x))

    node_specs = {}
    for f in dataclasses.fields(type(state.nodes)):
        if not f.metadata.get("pytree_node", True):
            continue
        nd = _ndim(getattr(state.nodes, f.name))
        if f.name in NODE_AXIS_SECOND:
            dims = (None, mesh_mod.NODE_AXIS) + (None,) * (nd - 2)
        else:
            dims = (mesh_mod.NODE_AXIS,) + (None,) * (nd - 1)
        node_specs[f.name] = Spec(dims)
    return state.replace(
        nodes=state.nodes.replace(**node_specs),
        queues=jax.tree.map(repl, state.queues),
        gangs=jax.tree.map(repl, state.gangs),
        running=jax.tree.map(repl, state.running))


def _entry_seed_specs(spec: tp.ProbeSpec, env, closed) -> list:
    """Flat per-invar :class:`Spec` seeds for one registered entry —
    built from the SAME ``make_args``/kwargs-filter path as
    ``trace_probe.trace_entry``, so the flattened seed list lines up
    with ``closed.jaxpr.invars`` by construction (and a structural
    drift raises instead of silently seeding replicated)."""
    args, kwargs = spec.make_args(env)
    trace_kwargs = {k: v for k, v in kwargs.items()
                    if k in ("k_value",)}

    def seed_arg(a):
        if isinstance(a, ClusterState):
            return seed_state_specs(a)
        return jax.tree.map(lambda x: _replicated(_ndim(x)), a)

    seed_tree = (tuple(seed_arg(a) for a in args),
                 {k: _replicated(_ndim(v))
                  for k, v in trace_kwargs.items()})
    leaves = jax.tree_util.tree_leaves(seed_tree)
    invars = closed.jaxpr.invars
    if len(leaves) != len(invars):
        raise RuntimeError(
            f"{spec.name}: seed-spec structure drifted — "
            f"{len(leaves)} seed leaves vs {len(invars)} jaxpr "
            f"invars (make_args and trace_entry must flatten alike)")
    out = []
    for s, v in zip(leaves, invars):
        nd = _ndim(getattr(v, "aval", None))
        out.append(s if len(s.dims) == nd else _replicated(nd))
    return out


# ---------------------------------------------------------------------------
# the abstract interpreter

@dataclasses.dataclass
class _Site:
    """One modeled collective: ``nbytes`` is the FULL array size the
    collective moves (the byte model scales it by ring cost), ``mult``
    the trip-count multiplier at the recording site."""

    kind: str            # all_reduce | all_gather | reduce_scatter | reshard
    primitive: str
    nbytes: int
    mult: int
    in_while: bool


@dataclasses.dataclass
class _Ctx:
    config: CommConfig
    node_extent: int
    sites: list
    conservative: Counter
    #: (nbytes, primitive, aval-str) replicated node-axis candidates
    node_candidates: list


def _site_cost(s: _Site, num_devices: int) -> int:
    return collective_bytes(s.kind, s.nbytes, num_devices) * s.mult


def _spec_of(env: dict, v) -> Spec:
    if not _is_var(v):                       # inline Literal
        return _replicated(_ndim(getattr(v, "aval", v.val)))
    return env.get(v) or _replicated(_ndim(v.aval))


def _emit(ctx: _Ctx, kind: str, prim: str, nbytes: int, mult: int,
          in_while: bool) -> None:
    if nbytes > 0:
        ctx.sites.append(_Site(kind=kind, primitive=prim,
                               nbytes=int(nbytes), mult=int(mult),
                               in_while=in_while))


def _gather_sharded_inputs(eqn, in_specs, ctx, mult, in_while) -> None:
    for v, s in zip(eqn.invars, in_specs):
        if s.sharded:
            _emit(ctx, "all_gather", eqn.primitive.name,
                  _aval_bytes(getattr(v, "aval", None)), mult, in_while)


def _conservative(eqn, in_specs, ctx, mult, in_while) -> list:
    """Unknown primitive: gather every sharded input, outputs
    replicated, and count it (reported, never silent)."""
    ctx.conservative[eqn.primitive.name] += 1
    _gather_sharded_inputs(eqn, in_specs, ctx, mult, in_while)
    return [_replicated(_ndim(getattr(v, "aval", None)))
            for v in eqn.outvars]


def _walk_closed(jaxpr_like, in_specs, ctx: _Ctx, mult: int = 1,
                 in_while: bool = False) -> list:
    """Propagate specs through one jaxpr level; returns outvar specs.
    Records collective sites / KAI301 candidates into ``ctx``."""
    inner = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    env: dict = {}
    for v in inner.constvars:
        env[v] = _replicated(_ndim(v.aval))
    for v, s in zip(inner.invars, in_specs):
        env[v] = s if len(s.dims) == _ndim(v.aval) \
            else _replicated(_ndim(v.aval))
    for eqn in inner.eqns:
        e_in = [_spec_of(env, v) for v in eqn.invars]
        e_out = _propagate_eqn(eqn, e_in, ctx, mult, in_while)
        for v, s in zip(eqn.outvars, e_out):
            if not _is_var(v) or _is_drop(v):
                continue
            env[v] = s
            aval = v.aval
            shape = getattr(aval, "shape", ())
            if (not s.sharded and ctx.node_extent > 1
                    and ctx.node_extent in shape):
                nb = _aval_bytes(aval)
                if nb >= ctx.config.node_materialize_bytes:
                    ctx.node_candidates.append(
                        (nb, eqn.primitive.name, _aval_str(aval)))
    return [_spec_of(env, v) for v in inner.outvars]


# -- control flow -----------------------------------------------------------

def _sub_ctx(ctx: _Ctx) -> _Ctx:
    return _Ctx(config=ctx.config, node_extent=ctx.node_extent,
                sites=[], conservative=Counter(), node_candidates=[])


def _fixpoint_carry(body, nconsts_specs, carry_specs, extra_specs,
                    ctx) -> list:
    """Iterate the loop body on a throwaway ctx until the carry specs
    stabilize (the meet is monotone toward replicated, so this
    terminates — capped defensively anyway)."""
    for _ in range(16):
        probe = _sub_ctx(ctx)
        outs = _walk_closed(body,
                            list(nconsts_specs) + list(carry_specs)
                            + list(extra_specs), probe)
        new = [_meet(c, o) for c, o in
               zip(carry_specs, outs[:len(carry_specs)])]
        if new == list(carry_specs):
            return new
        carry_specs = new
    return [_replicated(len(c.dims)) for c in carry_specs]


def _rule_scan(eqn, in_specs, ctx, mult, in_while) -> list:
    num_consts = int(eqn.params["num_consts"])
    num_carry = int(eqn.params["num_carry"])
    length = max(1, int(eqn.params.get("length", 1) or 1))
    body = eqn.params["jaxpr"]
    consts = in_specs[:num_consts]
    carry = in_specs[num_consts:num_consts + num_carry]
    xs = in_specs[num_consts + num_carry:]
    xs_vars = eqn.invars[num_consts + num_carry:]
    slices = []
    for v, s in zip(xs_vars, xs):
        if s.dims and s.dims[0] is not None:
            # scanning over a sharded leading dim serializes the whole
            # array through every device: gather it once up front
            _emit(ctx, "all_gather", "scan",
                  _aval_bytes(getattr(v, "aval", None)), mult, in_while)
        slices.append(Spec(tuple(s.dims[1:])))
    carry = _fixpoint_carry(body, consts, carry, slices, ctx)
    outs = _walk_closed(body, list(consts) + list(carry) + slices,
                        ctx, mult=mult * length, in_while=in_while)
    ys = [Spec((None,) + tuple(s.dims))
          for s in outs[num_carry:]]
    return list(carry) + ys


def _rule_while(eqn, in_specs, ctx, mult, in_while) -> list:
    cn = int(eqn.params["cond_nconsts"])
    bn = int(eqn.params["body_nconsts"])
    cond = eqn.params["cond_jaxpr"]
    body = eqn.params["body_jaxpr"]
    cond_consts = in_specs[:cn]
    body_consts = in_specs[cn:cn + bn]
    carry = in_specs[cn + bn:]
    carry = _fixpoint_carry(body, body_consts, carry, (), ctx)
    # trip count is dynamic: charge ONE trip but mark every collective
    # in_while so KAI303 and the loop budget still see it
    _walk_closed(body, list(body_consts) + list(carry), ctx,
                 mult=mult, in_while=True)
    _walk_closed(cond, list(cond_consts) + list(carry), ctx,
                 mult=mult, in_while=True)
    return list(carry)


def _rule_cond(eqn, in_specs, ctx, mult, in_while) -> list:
    branches = eqn.params["branches"]
    ops = in_specs[1:]                       # invars = [pred] + ops
    results = []
    for br in branches:
        sub = _sub_ctx(ctx)
        outs = _walk_closed(br, ops, sub, mult=mult, in_while=in_while)
        results.append((sub, outs))
    # charge the worst branch's collectives (upper bound, like the
    # cost model's worst-branch FLOPs)
    worst = max(results, key=lambda t: sum(
        _site_cost(s, ctx.config.num_devices) for s in t[0].sites))
    ctx.sites.extend(worst[0].sites)
    ctx.conservative.update(worst[0].conservative)
    ctx.node_candidates.extend(worst[0].node_candidates)
    outs = results[0][1]
    for _, o in results[1:]:
        outs = [_meet(a, b) for a, b in zip(outs, o)]
    return outs


# -- leaf rules -------------------------------------------------------------

#: sharding-transparent elementwise family (rank-preserving, per-dim
#: shape match) — the cost model's table plus pure data movement that
#: keeps layout
_COMM_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow",
    "integer_pow", "exp", "exp2", "log", "log1p", "expm1", "tanh",
    "logistic", "sqrt", "rsqrt", "cbrt", "abs", "neg", "sign", "floor",
    "ceil", "round", "is_finite", "not", "and", "or", "xor",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "eq_to", "ne_to", "lt_to",
    "le_to", "gt_to", "ge_to", "select_n", "clamp",
    "convert_element_type", "erf", "erf_inv", "erfc", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "nextafter",
    "population_count", "clz", "square", "real", "imag", "conj",
    "add_any", "copy", "stop_gradient", "device_put",
    "reduce_precision",
})

_COMM_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
})

_COMM_CUMULATIVE = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})


def _rule_elementwise(eqn, in_specs, ctx, mult, in_while) -> list:
    out = eqn.outvars[0]
    out_shape = getattr(out.aval, "shape", ())
    rank = len(out_shape)
    dims: list = []
    for j in range(rank):
        nm = None
        for v, s in zip(eqn.invars, in_specs):
            sh = getattr(getattr(v, "aval", None), "shape", ())
            if (len(sh) == rank and sh[j] == out_shape[j]
                    and s.dims[j] is not None):
                nm = s.dims[j]
                break
        dims.append(nm)
    spec = _dedupe(dims)
    # an input whose sharded dim did not survive at its position needs
    # a reshard first (cannot happen on a single-axis mesh with
    # rank-matched operands, kept for robustness)
    for v, s in zip(eqn.invars, in_specs):
        sh = getattr(getattr(v, "aval", None), "shape", ())
        if len(sh) != rank:
            continue
        for j, d in enumerate(s.dims):
            if d is not None and spec.dims[j] != d:
                _emit(ctx, "reshard", eqn.primitive.name,
                      _aval_bytes(v.aval), mult, in_while)
                break
    return [spec for _ in eqn.outvars]


def _rule_leaf(eqn, in_specs, ctx, mult, in_while) -> list:
    name = eqn.primitive.name
    params = eqn.params
    out_avals = [getattr(v, "aval", None) for v in eqn.outvars]

    if name in _COMM_ELEMENTWISE:
        return _rule_elementwise(eqn, in_specs, ctx, mult, in_while)

    if name == "iota":
        return [_replicated(_ndim(a)) for a in out_avals]

    if name == "broadcast_in_dim":
        src = in_specs[0]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        out_shape = params["shape"]
        bdims = params["broadcast_dimensions"]
        dims = [None] * len(out_shape)
        for i, j in enumerate(bdims):
            if in_shape[i] == out_shape[j]:
                dims[j] = src.dims[i]
        return [_dedupe(dims)]

    if name == "transpose":
        perm = params["permutation"]
        return [Spec(tuple(in_specs[0].dims[p] for p in perm))]

    if name == "squeeze":
        drop = set(params["dimensions"])
        return [Spec(tuple(d for i, d in enumerate(in_specs[0].dims)
                           if i not in drop))]

    if name == "expand_dims":
        newdims = set(params["dimensions"])
        src = iter(in_specs[0].dims)
        dims = [None if j in newdims else next(src)
                for j in range(_ndim(out_avals[0]))]
        return [Spec(tuple(dims))]

    if name == "reshape":
        if params.get("dimensions") is not None:
            return _conservative(eqn, in_specs, ctx, mult, in_while)
        src = in_specs[0]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        out_shape = params["new_sizes"]
        sharded = [(i, d) for i, d in enumerate(src.dims)
                   if d is not None]
        if not sharded:
            return [_replicated(len(out_shape))]
        if len(sharded) > 1:
            _gather_sharded_inputs(eqn, in_specs, ctx, mult, in_while)
            return [_replicated(len(out_shape))]
        i, nm = sharded[0]
        pre = int(np.prod(in_shape[:i], dtype=np.int64))
        for j in range(len(out_shape)):
            if (out_shape[j] == in_shape[i]
                    and int(np.prod(out_shape[:j],
                                    dtype=np.int64)) == pre):
                dims = [None] * len(out_shape)
                dims[j] = nm
                return [Spec(tuple(dims))]
        _emit(ctx, "all_gather", name,
              _aval_bytes(eqn.invars[0].aval), mult, in_while)
        return [_replicated(len(out_shape))]

    if name == "concatenate":
        dim = int(params["dimension"])
        rank = _ndim(out_avals[0])
        gathered = False
        for v, s in zip(eqn.invars, in_specs):
            if s.dims[dim] is not None:
                _emit(ctx, "all_gather", name, _aval_bytes(v.aval),
                      mult, in_while)
                gathered = True
        dims = []
        for j in range(rank):
            if j == dim:
                dims.append(None)
                continue
            nm = None
            for s in in_specs:
                if s.dims[j] is not None:
                    nm = s.dims[j]
                    break
            dims.append(nm)
        del gathered
        return [_dedupe(dims)]

    if name == "split":
        axis = int(params["axis"])
        src = in_specs[0]
        if src.dims[axis] is not None:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[0].aval), mult, in_while)
            dims = list(src.dims)
            dims[axis] = None
            return [Spec(tuple(dims)) for _ in eqn.outvars]
        return [src for _ in eqn.outvars]

    if name == "slice":
        src = in_specs[0]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        starts = params["start_indices"]
        limits = params["limit_indices"]
        strides = params.get("strides") or (1,) * len(in_shape)
        dims = []
        for j, d in enumerate(src.dims):
            full = (starts[j] == 0 and limits[j] == in_shape[j]
                    and strides[j] == 1)
            if d is not None and not full:
                _emit(ctx, "all_gather", name,
                      _aval_bytes(eqn.invars[0].aval), mult, in_while)
                dims.append(None)
            else:
                dims.append(d)
        return [Spec(tuple(dims))]

    if name == "dynamic_slice":
        src = in_specs[0]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        sizes = params["slice_sizes"]
        dims = []
        for j, d in enumerate(src.dims):
            if d is not None and sizes[j] != in_shape[j]:
                _emit(ctx, "all_gather", name,
                      _aval_bytes(eqn.invars[0].aval), mult, in_while)
                dims.append(None)
            else:
                dims.append(d)
        return [Spec(tuple(dims))]

    if name == "dynamic_update_slice":
        operand, update = in_specs[0], in_specs[1]
        if update.sharded and update.dims != operand.dims[:len(
                update.dims)] and update.dims != operand.dims:
            _emit(ctx, "reshard", name,
                  _aval_bytes(eqn.invars[1].aval), mult, in_while)
        elif operand.sharded:
            op_shape = getattr(eqn.invars[0].aval, "shape", ())
            up_shape = getattr(eqn.invars[1].aval, "shape", ())
            if any(operand.dims[j] is not None
                   and up_shape[j] != op_shape[j]
                   for j in range(len(op_shape))):
                # updating a window of a sharded dim crosses shards
                _emit(ctx, "reshard", name,
                      _aval_bytes(eqn.invars[1].aval), mult, in_while)
        return [operand]

    if name == "pad":
        src = in_specs[0]
        cfg = params["padding_config"]
        dims = []
        for j, d in enumerate(src.dims):
            if d is not None and tuple(cfg[j]) != (0, 0, 0):
                _emit(ctx, "all_gather", name,
                      _aval_bytes(eqn.invars[0].aval), mult, in_while)
                dims.append(None)
            else:
                dims.append(d)
        return [Spec(tuple(dims))]

    if name == "rev":
        src = in_specs[0]
        if any(src.dims[j] is not None for j in params["dimensions"]):
            # reversing a sharded dim permutes shard ownership
            _emit(ctx, "reshard", name,
                  _aval_bytes(eqn.invars[0].aval), mult, in_while)
        return [src]

    if name in _COMM_REDUCE:
        axes = params.get("axes")
        src = in_specs[0]
        if axes is None:
            return [src for _ in eqn.outvars]
        axes = set(int(a) for a in axes)
        if any(src.dims[a] is not None for a in axes):
            _emit(ctx, "all_reduce", name,
                  sum(_aval_bytes(a) for a in out_avals), mult,
                  in_while)
        dims = tuple(d for j, d in enumerate(src.dims)
                     if j not in axes)
        return [Spec(dims) for _ in eqn.outvars]

    if name in _COMM_CUMULATIVE:
        axis = int(params.get("axis", 0))
        src = in_specs[0]
        if src.dims[axis] is not None:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[0].aval), mult, in_while)
            dims = list(src.dims)
            dims[axis] = None
            return [Spec(tuple(dims))]
        return [src]

    if name == "sort":
        dim = int(params.get("dimension", -1))
        outs = []
        for v, s in zip(eqn.invars, in_specs):
            if s.dims[dim] is not None:
                _emit(ctx, "all_gather", name, _aval_bytes(v.aval),
                      mult, in_while)
                dims = list(s.dims)
                dims[dim] = None
                outs.append(Spec(tuple(dims)))
            else:
                outs.append(s)
        return outs[:len(eqn.outvars)] or [
            _replicated(_ndim(a)) for a in out_avals]

    if name == "top_k":
        src = in_specs[0]
        if src.dims[-1] is not None:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[0].aval), mult, in_while)
        dims = Spec(tuple(src.dims[:-1]) + (None,))
        return [dims for _ in eqn.outvars]

    if name == "dot_general":
        (lc, rc), (lb, rb) = params["dimension_numbers"]
        lhs, rhs = in_specs[0], in_specs[1]
        lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
        rhs_shape = getattr(eqn.invars[1].aval, "shape", ())
        dims = []
        for dl, dr in zip(lb, rb):
            dims.append(lhs.dims[dl]
                        if lhs.dims[dl] is not None else rhs.dims[dr])
        for d in range(len(lhs_shape)):
            if d not in set(lc) | set(lb):
                dims.append(lhs.dims[d])
        for d in range(len(rhs_shape)):
            if d not in set(rc) | set(rb):
                dims.append(rhs.dims[d])
        if (any(lhs.dims[d] is not None for d in lc)
                or any(rhs.dims[d] is not None for d in rc)):
            _emit(ctx, "all_reduce", name,
                  sum(_aval_bytes(a) for a in out_avals), mult,
                  in_while)
        return [_dedupe(dims)]

    if name == "gather":
        dnums = params["dimension_numbers"]
        sizes = params["slice_sizes"]
        operand, indices = in_specs[0], in_specs[1]
        op_shape = getattr(eqn.invars[0].aval, "shape", ())
        if indices.sharded:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[1].aval), mult, in_while)
        start_map = set(dnums.start_index_map)
        bad = [d for d in range(len(op_shape))
               if operand.dims[d] is not None
               and (d in start_map or sizes[d] != op_shape[d])]
        if bad:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[0].aval), mult, in_while)
            return [_replicated(_ndim(out_avals[0]))]
        collapsed = set(dnums.collapsed_slice_dims)
        kept = [d for d in range(len(op_shape)) if d not in collapsed]
        dims = [None] * _ndim(out_avals[0])
        for off, d in zip(dnums.offset_dims, kept):
            if off < len(dims):
                dims[off] = operand.dims[d]
        return [_dedupe(dims)]

    if name.startswith("scatter"):
        dnums = params["dimension_numbers"]
        operand, indices, updates = in_specs[0], in_specs[1], in_specs[2]
        if any(operand.dims[d] is not None
               for d in dnums.scatter_dims_to_operand_dims):
            _emit(ctx, "reshard", name,
                  _aval_bytes(eqn.invars[2].aval), mult, in_while)
        if indices.sharded:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[1].aval), mult, in_while)
        if updates.sharded:
            _emit(ctx, "all_gather", name,
                  _aval_bytes(eqn.invars[2].aval), mult, in_while)
        return [operand]

    if name == "bitcast_convert_type":
        # same rank: layout-preserving; rank±1: the split/merged
        # trailing dim is the itemsize factor (never the node axis)
        src = in_specs[0]
        out_nd = _ndim(out_avals[0])
        if len(src.dims) == out_nd:
            return [src]
        if out_nd == len(src.dims) + 1:
            return [Spec(tuple(src.dims) + (None,))]
        if out_nd == len(src.dims) - 1 and src.dims[-1] is None:
            return [Spec(tuple(src.dims[:-1]))]
        return _conservative(eqn, in_specs, ctx, mult, in_while)

    return _conservative(eqn, in_specs, ctx, mult, in_while)


def _propagate_eqn(eqn, in_specs, ctx, mult, in_while) -> list:
    name = eqn.primitive.name
    if name == "scan":
        return _rule_scan(eqn, in_specs, ctx, mult, in_while)
    if name == "while":
        return _rule_while(eqn, in_specs, ctx, mult, in_while)
    if name == "cond":
        return _rule_cond(eqn, in_specs, ctx, mult, in_while)
    if name.startswith("scatter"):
        # scatter's update_jaxpr param would otherwise divert it into
        # the generic sub-jaxpr branch — its rule is exact from the
        # dimension numbers, use it
        return _rule_leaf(eqn, in_specs, ctx, mult, in_while)
    subs = tp.eqn_sub_jaxprs(eqn)
    if subs:
        # pjit / closed_call / remat / custom_jvp|vjp: recurse 1:1
        # into the call jaxpr when the arity lines up
        inner = getattr(subs[0], "jaxpr", subs[0])
        if (len(inner.invars) == len(in_specs)
                and len(inner.outvars) == len(eqn.outvars)):
            return _walk_closed(subs[0], in_specs, ctx, mult, in_while)
        return _conservative(eqn, in_specs, ctx, mult, in_while)
    return _rule_leaf(eqn, in_specs, ctx, mult, in_while)


# ---------------------------------------------------------------------------
# per-entry report

@dataclasses.dataclass
class CommReport:
    """One entry's static comm profile (the ``--comms`` unit)."""

    name: str
    num_devices: int
    #: number of modeled collective sites (loop sites count once here;
    #: their BYTES are trip-count-charged)
    collective_sites: int
    #: total modeled cross-device bytes (trip-count-charged)
    comm_bytes: int
    #: the slice of ``comm_bytes`` under scan/while (the KAI303 mass)
    loop_comm_bytes: int
    #: sorted collective kinds present (the lowering stage's predicted
    #: set)
    kinds: list
    #: top-K largest collectives: {kind, primitive, bytes, total_bytes,
    #: mult, in_while}
    top_collectives: list
    #: primitive -> eqn count handled conservatively (gather+replicate)
    conservative_prims: dict
    #: KAI301/KAI303 findings (engine.Finding), pre-baseline
    findings: list
    #: raw _Site list (scaling mode re-prices these per device count);
    #: not part of ``doc()``
    sites: list

    def doc(self) -> dict:
        return {
            "name": self.name,
            "num_devices": self.num_devices,
            "collective_sites": self.collective_sites,
            "comm_bytes": self.comm_bytes,
            "loop_comm_bytes": self.loop_comm_bytes,
            "kinds": list(self.kinds),
            "top_collectives": list(self.top_collectives),
            "conservative_prims": dict(self.conservative_prims),
        }


def analyze_closed(name: str, closed, seed_specs: list, *,
                   config: CommConfig = DEFAULT_CONFIG,
                   node_extent: int = 0) -> CommReport:
    """Run the sharding interpreter over one ClosedJaxpr — the shared
    back half of production entries and the KAI301/KAI303 fixtures."""
    ctx = _Ctx(config=config, node_extent=int(node_extent), sites=[],
               conservative=Counter(), node_candidates=[])
    _walk_closed(closed, seed_specs, ctx)
    d = config.num_devices
    comm = sum(_site_cost(s, d) for s in ctx.sites)
    loop_sites = [s for s in ctx.sites if s.mult > 1 or s.in_while]
    loop_comm = sum(_site_cost(s, d) for s in loop_sites)
    ranked = sorted(ctx.sites, key=lambda s: -_site_cost(s, d))
    top = [{"kind": s.kind, "primitive": s.primitive,
            "bytes": collective_bytes(s.kind, s.nbytes, d),
            "total_bytes": _site_cost(s, d), "mult": s.mult,
            "in_while": s.in_while}
           for s in ranked[:config.top_k]]

    findings: list[Finding] = []
    if ctx.node_candidates:
        worst = max(ctx.node_candidates)
        findings.append(Finding(
            file=f"jaxpr:{name}", line=0, col=0, code="KAI301",
            message=(
                f"{len(ctx.node_candidates)} intermediate(s) "
                f"materialize the full node axis REPLICATED on every "
                f"device above {config.node_materialize_bytes}B; "
                f"worst: {worst[2]} ({worst[0]}B) from `{worst[1]}` — "
                f"a replicated node-axis buffer is the footprint "
                f"mesh-sharding exists to remove (ROADMAP 2); keep "
                f"the node axis sharded through the op, or absorb a "
                f"justified baseline row"),
            function=name))
    if loop_sites and loop_comm > config.loop_comm_bytes:
        worst_s = max(loop_sites, key=lambda s: _site_cost(s, d))
        findings.append(Finding(
            file=f"jaxpr:{name}", line=0, col=0, code="KAI303",
            message=(
                f"{len(loop_sites)} collective(s) under scan/while "
                f"charged trip-count x: {loop_comm}B modeled loop "
                f"comm (> {config.loop_comm_bytes}B); worst: "
                f"{worst_s.kind} of {worst_s.nbytes}B from "
                f"`{worst_s.primitive}` x{worst_s.mult} — hoist the "
                f"collective out of the loop, or absorb a justified "
                f"baseline row"),
            function=name))
    return CommReport(
        name=name, num_devices=d, collective_sites=len(ctx.sites),
        comm_bytes=comm, loop_comm_bytes=loop_comm,
        kinds=sorted({s.kind for s in ctx.sites}),
        top_collectives=top,
        conservative_prims=dict(sorted(ctx.conservative.items())),
        findings=findings, sites=ctx.sites)


def registered_comm_entries() -> list[str]:
    """Comm coverage == probe coverage == cost coverage: ONE registry."""
    return tp.registered_ops()


def run_comms(names: list[str] | None = None, *,
              traces: list | None = None,
              config: CommConfig = DEFAULT_CONFIG,
              env=None) -> list[CommReport]:
    """Audit the selected (default: all) registered entries.

    ``traces`` accepts pre-built :class:`trace_probe.EntryTrace`
    objects (the shared walk) so a combined probe+cost+comms run
    traces each entry once.  ``env`` accepts an abstract
    ``ShapeDtypeStruct`` state (the bench's dispatch-free re-trace).
    """
    if env is None:
        env = tp._canonical_env(now=1000.0)
    if traces is None:
        traces = tp.trace_entries(names, env=env)
    elif names:
        sel = set(names)
        traces = [t for t in traces if t.name in sel]
    specs = {s.name: s for s in tp._registry()}
    node_extent = int(env[0].nodes.valid.shape[0])
    reports = []
    for t in traces:
        seeds = _entry_seed_specs(specs[t.name], env, t.closed)
        reports.append(analyze_closed(t.name, t.closed, seeds,
                                      config=config,
                                      node_extent=node_extent))
    return reports


# ---------------------------------------------------------------------------
# KAI302 — declared vs inferred sharding drift

def _sharding_dims(sharding, ndim: int) -> tuple:
    """A NamedSharding's PartitionSpec as per-dim axis names, padded
    to rank (P() / P(axis) are rank prefixes)."""
    spec = tuple(getattr(sharding, "spec", ()) or ())
    out = []
    for j in range(ndim):
        el = spec[j] if j < len(spec) else None
        if isinstance(el, (tuple, list)):
            el = el[0] if el else None
        out.append(el)
    return tuple(out)


def check_declared_shardings(state: ClusterState | None = None, *,
                             mesh=None, seeds=None,
                             declared=None) -> list[Finding]:
    """Leaf-exact, both-direction compare of ``mesh.state_shardings``
    against :func:`seed_state_specs` — one KAI302 finding per
    divergent leaf ([] = the two registries agree).  ``seeds`` /
    ``declared`` overrides exist for the rule fixtures."""
    if state is None:
        state, _ = tp._canonical_env(now=1000.0)
    if mesh is None:
        # spec extraction only needs mesh axis NAMES — a 1-device mesh
        # works on any host (the 8-device lowering stage is separate)
        mesh = mesh_mod.make_mesh(list(jax.devices())[:1])
    if declared is None:
        declared = mesh_mod.state_shardings(state, mesh)
    if seeds is None:
        seeds = seed_state_specs(state)
    paths = jax.tree_util.tree_flatten_with_path(state)[0]
    decl_leaves = jax.tree_util.tree_leaves(declared)
    seed_leaves = jax.tree_util.tree_leaves(seeds)
    findings: list[Finding] = []
    if not (len(paths) == len(decl_leaves) == len(seed_leaves)):
        findings.append(Finding(
            file="mesh:state_shardings", line=0, col=0, code="KAI302",
            message=(
                f"declared/inferred sharding pytrees do not even "
                f"flatten alike ({len(decl_leaves)} vs "
                f"{len(seed_leaves)} leaves over {len(paths)} state "
                f"leaves) — state_shardings and seed_state_specs "
                f"have structurally diverged"),
            function="<structure>"))
        return findings
    for (path, leaf), decl, seed in zip(paths, decl_leaves,
                                        seed_leaves):
        nd = _ndim(leaf)
        ddims = _sharding_dims(decl, nd)
        if ddims != tuple(seed.dims):
            where = jax.tree_util.keystr(path)
            findings.append(Finding(
                file="mesh:state_shardings", line=0, col=0,
                code="KAI302",
                message=(
                    f"declared sharding {ddims} != inferred seed "
                    f"{tuple(seed.dims)} for state leaf `{where}` — "
                    f"mesh.state_shardings and the kai-comms seed "
                    f"registry must agree leaf-exact (whichever side "
                    f"is wrong, fix it there; drift in either "
                    f"direction ships a silently mis-sharded solver)"),
                function=where))
    return findings


# ---------------------------------------------------------------------------
# baseline

def load_comm_baseline(path: str = COMM_BASELINE_PATH) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def check_against_comm_baseline(reports: list[CommReport],
                                baseline: dict, *,
                                full_coverage: bool = True
                                ) -> list[str]:
    """Numeric budget regressions ([] = clean) — collective sites and
    comm bytes against the checked-in per-entry stats, via the shared
    tolerance helper.  KAI301/KAI303 surface as findings instead
    (:func:`comm_findings`), not here."""
    entries = baseline.get("entries", {})
    problems: list[str] = []
    base_d = baseline.get("num_devices")
    if base_d is not None and any(r.num_devices != base_d
                                  for r in reports):
        problems.append(
            f"comm baseline modeled at {base_d} devices but this run "
            f"models {sorted({r.num_devices for r in reports})} — "
            f"refresh with --comms --update-baseline")
    for row in baseline.get("baselined", []):
        if (str(row.get("code", "")).startswith("KAI3")
                and not str(row.get("justification", "")).strip()):
            problems.append(
                f"baselined row {row.get('file')}/{row.get('code')} "
                f"lacks a non-empty justification — a KAI3xx "
                f"absorption must say WHY the comm hazard is "
                f"acceptable")
    for r in reports:
        base = entries.get(r.name)
        if base is None:
            problems.append(
                f"{r.name}: no comm baseline entry — run "
                f"`python -m kai_scheduler_tpu.analysis --comms "
                f"--update-baseline`")
            continue
        for metric, value, key, slack, unit in (
                ("collective sites", r.collective_sites,
                 "collective_sites", SITE_SLACK, " sites"),
                ("modeled comm bytes", r.comm_bytes, "comm_bytes",
                 COMM_SLACK_BYTES, "B"),
                ("loop comm bytes", r.loop_comm_bytes,
                 "loop_comm_bytes", COMM_SLACK_BYTES, "B")):
            p = budgets.budget_problem(
                r.name, metric, value, base[key],
                tolerance=COMM_TOLERANCE, slack=slack, unit=unit,
                hint="a new collective changed the entry's mesh "
                     "traffic profile — check top_collectives before "
                     "absorbing" if key == "comm_bytes" else "")
            if p:
                problems.append(p)
    if full_coverage:
        for name in sorted(set(entries) - {r.name for r in reports}):
            problems.append(
                f"comm baseline lists unknown entry `{name}` — "
                f"stale, refresh with --comms --update-baseline")
    return problems


def comm_findings(reports: list[CommReport],
                  baseline: dict | None = None, *,
                  extra=()) -> list[Finding]:
    """All KAI3xx findings (per-entry KAI301/KAI303 plus any ``extra``
    such as the KAI302 drift check), filtered through the engine's
    count-based baseline rows (``comm_baseline.json`` ``"baselined"``
    — shipped empty; absorptions additionally require a justification,
    enforced in :func:`check_against_comm_baseline`)."""
    findings = sorted(list(extra)
                      + [f for r in reports for f in r.findings])
    rows = (baseline or {}).get("baselined", [])
    if rows:
        findings, _eaten = _apply_baseline(findings, rows)
    return findings


def update_comm_baseline(reports: list[CommReport],
                         path: str = COMM_BASELINE_PATH) -> None:
    """MERGE the reports' stats (an ``--ops`` subset must not drop the
    other entries' budgets); stale entries pruned only on a
    full-registry update.  The ``baselined`` rows are preserved
    verbatim."""
    data = {"baselined": [], "entries": {}}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    if reports:
        data["num_devices"] = reports[0].num_devices
    entries = data.setdefault("entries", {})
    entries.update({
        r.name: {"collective_sites": r.collective_sites,
                 "comm_bytes": r.comm_bytes,
                 "loop_comm_bytes": r.loop_comm_bytes}
        for r in sorted(reports, key=lambda r: r.name)})
    live = set(registered_comm_entries())
    if {r.name for r in reports} >= live:
        for name in sorted(set(entries) - live):
            del entries[name]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# lowering cross-validation — compile with REAL in_shardings on the
# virtual CPU mesh and diff the HLO's collectives against the model

_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start|-done)?\b")

_HLO_TO_MODEL = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "reshard",
    "collective-permute": "reshard",
}

#: GSPMD freely rewrites between these forms (an all-reduce may lower
#: as reduce-scatter + all-gather; a reshard as gather + slice), so a
#: predicted kind licenses its decompositions in the compiled HLO
_MODEL_KIND_IMPLIES = {
    "all_reduce": frozenset({"reduce_scatter", "all_gather"}),
    "all_gather": frozenset(),
    "reduce_scatter": frozenset(),
    "reshard": frozenset({"all_gather"}),
}


def _compiled_hlo_text(compiled) -> str | None:
    """Compiled-executable HLO text, ``None`` when the jax build
    exposes no introspection (report UNVERIFIABLE, never silently
    pass) — same access pattern as the KAI202 donation check."""
    try:
        mods = compiled.runtime_executable().hlo_modules()
        return "\n".join(m.to_string() for m in mods)
    except Exception:  # noqa: BLE001 — jax/jaxlib API drift
        try:
            return compiled.as_text()
        except Exception:  # noqa: BLE001
            return None


def _hlo_collective_kinds(text: str) -> set:
    return {_HLO_TO_MODEL[m.group(1)]
            for m in _HLO_COLLECTIVE_RE.finditer(text)}


def _allowed_hlo_kinds(predicted) -> set:
    allowed = set(predicted)
    for k in predicted:
        allowed |= _MODEL_KIND_IMPLIES.get(k, frozenset())
    return allowed


def lowering_check(names=LOWERING_ENTRIES, *,
                   num_devices: int | None = None,
                   config: CommConfig = DEFAULT_CONFIG,
                   reports: list | None = None,
                   env=None) -> list[dict]:
    """Jit each named entry with the REAL ``mesh.state_shardings``
    ``in_shardings`` on a ``num_devices`` virtual CPU mesh, compile,
    and assert the collective kinds in the HLO fall inside the model's
    predicted set (the model is a conservative upper bound).  A doc
    with ``verified: False`` always fails the gate and blocks
    ``--update-baseline`` — mirroring KAI202's UNVERIFIABLE rule."""
    n = int(num_devices or config.num_devices)
    unknown = set(names) - set(registered_comm_entries())
    if unknown:
        raise ValueError(
            f"lowering_check: unknown entries {sorted(unknown)} — "
            f"not in the probe/cost/comms registry")
    mesh_mod.ensure_virtual_cpu_devices(n)
    try:
        devs = jax.devices("cpu")
    except RuntimeError:
        devs = []
    if len(devs) < n:
        return [{"entry": nm, "num_devices": n, "verified": False,
                 "error": (f"only {len(devs)} CPU devices — the "
                           f"backend initialised before "
                           f"ensure_virtual_cpu_devices could set "
                           f"XLA_FLAGS")} for nm in names]
    mesh = mesh_mod.make_mesh(list(devs[:n]))
    if env is None:
        env = tp._canonical_env(now=1000.0)
    by_name = {r.name: r for r in (reports or [])}
    specs = {s.name: s for s in tp._registry()}
    docs = []
    for nm in names:
        rep = by_name.get(nm)
        if rep is None:
            rep = run_comms([nm], config=config, env=env)[0]
        predicted = set(rep.kinds)
        spec = specs[nm]
        args, kwargs = spec.make_args(env)
        trace_kwargs = {k: v for k, v in kwargs.items()
                        if k in ("k_value",)}
        fn = (functools.partial(spec.trace_fn, **trace_kwargs)
              if trace_kwargs else spec.trace_fn)
        in_sh = tuple(
            mesh_mod.state_shardings(a, mesh)
            if isinstance(a, ClusterState) else mesh_mod.replicated(mesh)
            for a in args)
        doc = {"entry": nm, "num_devices": n,
               "predicted": sorted(predicted)}
        try:
            with warnings.catch_warnings():
                # sharding-propagation chatter is expected while
                # compiling with explicit in_shardings
                warnings.simplefilter("ignore")
                # audit-time jit, built per check on purpose: it is
                # lowered+compiled exactly once per audit and never
                # dispatched, so the KAI032 per-call cache-miss
                # hazard does not apply
                jit_fn = jax.jit(  # kai-lint: disable=KAI032
                    fn, in_shardings=in_sh)
                compiled = jit_fn.lower(*args).compile()
        except Exception as exc:  # noqa: BLE001 — report, don't crash
            doc.update(verified=False,
                       error=f"{type(exc).__name__}: {exc}")
            docs.append(doc)
            continue
        text = _compiled_hlo_text(compiled)
        if text is None:
            doc.update(verified=False,
                       error="compiled executable exposes no HLO "
                             "introspection")
        else:
            hlo = _hlo_collective_kinds(text)
            unexplained = sorted(hlo - _allowed_hlo_kinds(predicted))
            doc.update(hlo=sorted(hlo), unexplained=unexplained,
                       verified=not unexplained)
        docs.append(doc)
    return docs


def lowering_problems(docs: list[dict]) -> list[str]:
    """Gate messages for the cross-validation docs ([] = clean) —
    UNVERIFIABLE always fails, exactly like the KAI202 donation rule."""
    problems = []
    for d in docs:
        if d.get("unexplained"):
            problems.append(
                f"{d['entry']}: compiled HLO contains collective "
                f"kind(s) {d['unexplained']} the sharding model did "
                f"not predict (predicted {d.get('predicted')}) — the "
                f"model's primitive table has a blind spot; extend "
                f"it, don't baseline around it")
        elif not d.get("verified"):
            problems.append(
                f"{d['entry']}: {d['num_devices']}-device lowering "
                f"cross-validation is UNVERIFIABLE "
                f"({d.get('error', 'no HLO introspection')}) — "
                f"re-wire the introspection, don't skip the check")
    return problems


# ---------------------------------------------------------------------------
# scaling mode — modeled comm bytes vs device count

def comm_scaling_report(names=LOWERING_ENTRIES,
                        device_counts=(2, 4, 8), *,
                        config: CommConfig = DEFAULT_CONFIG,
                        reports: list | None = None) -> dict:
    """Re-price each entry's collective sites at several mesh widths
    and fit the comm-bytes growth exponent.  ``sublinear`` entries
    (exponent < :data:`SUBLINEAR_EXPONENT_BAR`) are the ROADMAP-2 "go"
    signal: ring collectives cost ``b·(d-1)/d``, so healthy comm
    plateaus instead of growing with the mesh."""
    unknown = set(names) - set(registered_comm_entries())
    if unknown:
        raise ValueError(
            f"comm_scaling_report: unknown entries {sorted(unknown)} "
            f"— not in the probe/cost/comms registry")
    by_name = {r.name: r for r in (reports or [])}
    missing = [nm for nm in names if nm not in by_name]
    if missing:
        for r in run_comms(missing, config=config):
            by_name[r.name] = r
    out: dict = {"device_counts": list(device_counts),
                 "threshold": SUBLINEAR_EXPONENT_BAR, "entries": {}}
    for nm in names:
        r = by_name[nm]
        totals = [sum(collective_bytes(s.kind, s.nbytes, d) * s.mult
                      for s in r.sites) for d in device_counts]
        exp = fit_exponent(device_counts, totals)
        out["entries"][nm] = {
            "comm_bytes": totals,
            "exponent": round(exp, 3),
            "sublinear": exp < SUBLINEAR_EXPONENT_BAR,
        }
    return out


def comm_bytes_for_state(state, names: tuple = ("fused_pipeline",), *,
                         config: CommConfig = DEFAULT_CONFIG
                         ) -> dict[str, int]:
    """Modeled cross-device bytes of the named entries traced AT the
    given snapshot's shapes — the bench artifact's
    ``comm_model_bytes_per_cycle`` column.  The state is abstracted to
    ``ShapeDtypeStruct`` leaves first, so this is a pure re-trace: no
    compile, no dispatch at this shape."""
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                       jnp.result_type(x)), state)
    reps = run_comms(list(names), config=config,
                     env=(abstract, None))
    return {r.name: r.comm_bytes for r in reps}


# ---------------------------------------------------------------------------
# KAI3xx fixtures — jax functions, not AST snippets (the rules judge
# programs); tests/test_comms.py runs both directions of each,
# mirroring the engine's per-rule fixture self-tests

def _fixture_node_replication_bad(x):
    """cumsum over the sharded node axis forces an all-gather: the
    2MiB result materializes the node axis replicated."""
    return jnp.sum(jnp.cumsum(x, axis=0))


def _fixture_node_replication_good(x):
    """Elementwise + all-reduce of a scalar: the node axis stays
    sharded through the whole program."""
    return jnp.sum(x * jnp.float32(2.0))


def _fixture_loop_collective_bad(x):
    """A 512KiB all-gather trapped inside a 64-trip scan: 64× charged
    loop comm (~28MiB modeled), with each intermediate itself under
    the KAI301 size bar (no cross-fire)."""
    def body(c, _):
        return c + jnp.sum(jnp.cumsum(x, axis=0)), None
    out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=64)
    return out


def _fixture_loop_collective_good(x):
    """Elementwise-only scan body over the sharded carry: zero
    collectives under the loop."""
    def body(c, _):
        return c * jnp.float32(0.5) + jnp.float32(1.0), None
    out, _ = jax.lax.scan(body, x, None, length=64)
    return out


def audit_fixture(code: str, kind: str = "bad") -> list[Finding]:
    """Run one KAI3xx fixture through the same audit path as
    production entries and return its findings."""
    if code == "KAI301":
        fn = (_fixture_node_replication_bad if kind == "bad"
              else _fixture_node_replication_good)
        x = jnp.zeros((8192, 64), jnp.float32)        # 2MiB
        closed = jax.make_jaxpr(fn)(x)
        seeds = [Spec((mesh_mod.NODE_AXIS, None))]
        rep = analyze_closed(f"fixture_{code}_{kind}", closed, seeds,
                             node_extent=8192)
        return rep.findings
    if code == "KAI303":
        fn = (_fixture_loop_collective_bad if kind == "bad"
              else _fixture_loop_collective_good)
        x = jnp.zeros((4096, 32), jnp.float32)        # 512KiB
        closed = jax.make_jaxpr(fn)(x)
        seeds = [Spec((mesh_mod.NODE_AXIS, None))]
        rep = analyze_closed(f"fixture_{code}_{kind}", closed, seeds,
                             node_extent=4096)
        return rep.findings
    if code == "KAI302":
        state, _ = tp._canonical_env(now=1000.0)
        if kind == "bad":
            seeds = seed_state_specs(state)
            seeds = seeds.replace(nodes=seeds.nodes.replace(
                valid=_replicated(1)))
            return check_declared_shardings(state, seeds=seeds)
        return check_declared_shardings(state)
    raise ValueError(f"unknown comm rule {code}")

"""Jit-region call graph — which functions run *inside* a compiled op.

Most KAI rules only make sense inside a jit trace: ``np.asarray`` in
the CLI is fine, in ``ops/allocate.py`` it is a host sync.  Rather than
hand-maintain a module list, the region is grown from the actual
``jax.jit`` entry points:

* ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorated defs;
* module-level ``f_jit = jax.jit(f)`` and
  ``f_jit = functools.partial(jax.jit, ...)(f)`` wrappers (the
  ``allocate_jit`` / ``stale_eviction_jit`` idiom).

From those entries the graph follows direct calls (``name(...)``),
module-attribute calls (``drf.set_fair_share(...)``) and one level of
package ``__init__`` re-export (``from ..plugins import compose``).
Method calls on values (``result.replace(...)``) are not resolved —
pytree ``replace`` bodies are generated field shuffles, and anything
substantive in this codebase is a module-level function.

Resolution is best-effort by design: a missed edge only narrows the
checked region (a rule stays silent), never breaks the build, and the
trace probe (layer 2) still sees the full program at the jaxpr level.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator

#: relative source files never worth parsing (generated protobuf)
GENERATED = ("_pb2.py",)


def _iter_functions(tree: ast.Module) -> Iterator[tuple[str, ast.AST]]:
    """Yield (qualname, node) for every def in the module, including
    methods (``Class.method``) and nested defs (``outer.inner``)."""
    def walk(body, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = prefix + node.name
                yield q, node
                yield from walk(node.body, q + ".")
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, prefix + node.name + ".")
    yield from walk(tree.body, "")


@dataclasses.dataclass
class ModuleInfo:
    """One parsed source file of the package."""

    relpath: str            # posix path relative to the repo root
    modname: str            # dotted module name (kai_scheduler_tpu.x.y)
    tree: ast.Module
    source: str
    #: qualname -> def node (methods as Class.method, nested as a.b)
    functions: dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    #: local alias -> dotted module it names (import table, whole file)
    mod_aliases: dict[str, str] = dataclasses.field(default_factory=dict)
    #: local name -> (dotted module, original name) for from-imports
    sym_imports: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        self.functions = dict(_iter_functions(self.tree))
        pkg = self.modname.rsplit(".", 1)[0] if "." in self.modname else ""
        if self.modname.endswith("__init__"):
            pkg = self.modname[: -len(".__init__")]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.mod_aliases[a.asname] = a.name
                    else:
                        # `import jax.numpy` binds the ROOT name only
                        root = a.name.split(".")[0]
                        self.mod_aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_from(pkg, node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.sym_imports[a.asname or a.name] = (base, a.name)

    def alias_root(self, name: str) -> str | None:
        """Dotted module a bare name refers to (``np`` -> ``numpy``,
        ``jnp`` -> ``jax.numpy``, ``lax`` -> ``jax.lax``) or None."""
        if name in self.mod_aliases:
            return self.mod_aliases[name]
        if name in self.sym_imports:
            mod, orig = self.sym_imports[name]
            return f"{mod}.{orig}"
        return None


def _resolve_from(pkg: str, node: ast.ImportFrom) -> str | None:
    """Absolute dotted module a ``from X import ...`` targets."""
    if node.level == 0:
        return node.module
    parts = pkg.split(".") if pkg else []
    up = node.level - 1
    if up > len(parts):
        return None
    base = parts[: len(parts) - up]
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base) if base else None


def _dotted(node: ast.AST) -> str | None:
    """``jax.jit`` / ``functools.partial`` attribute chain as a string."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class PackageGraph:
    """AST index + jit entry points + reachable jit region."""

    def __init__(self, root: str, package: str = "kai_scheduler_tpu"):
        self.root = root
        self.package = package
        self.modules: dict[str, ModuleInfo] = {}      # modname -> info
        pkg_dir = os.path.join(root, package.replace(".", os.sep))
        for dirpath, _dirnames, filenames in os.walk(pkg_dir):
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn.endswith(GENERATED):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                modname = rel[:-3].replace("/", ".")
                with open(path, encoding="utf-8") as f:
                    src = f.read()
                self.modules[modname] = ModuleInfo(
                    relpath=rel, modname=modname,
                    tree=ast.parse(src, filename=rel), source=src)
        #: (modname, qualname) of every function inside the jit region
        self.jit_region: set[tuple[str, str]] = set()
        self._grow()

    # -- entry detection --------------------------------------------------

    def _is_jit_expr(self, mod: ModuleInfo, node: ast.AST) -> bool:
        """True for expressions evaluating to a jit transform:
        ``jax.jit``, ``functools.partial(jax.jit, ...)``."""
        d = _dotted(node)
        if d is not None:
            root = mod.alias_root(d.split(".")[0]) or d.split(".")[0]
            full = ".".join([root] + d.split(".")[1:])
            if full in ("jax.jit", "jax.api.jit"):
                return True
        if isinstance(node, ast.Call):
            f = _dotted(node.func)
            if f is not None:
                root = mod.alias_root(f.split(".")[0]) or f.split(".")[0]
                full = ".".join([root] + f.split(".")[1:])
                if full.endswith("partial") and node.args \
                        and self._is_jit_expr(mod, node.args[0]):
                    return True
        return False

    def _entries(self) -> Iterator[tuple[ModuleInfo, str]]:
        for mod in self.modules.values():
            for qual, fn in mod.functions.items():
                for deco in getattr(fn, "decorator_list", []):
                    if self._is_jit_expr(mod, deco):
                        yield mod, qual
            for node in ast.walk(mod.tree):
                # f_jit = jax.jit(f) / functools.partial(jax.jit, ..)(f)
                if not (isinstance(node, ast.Call) and node.args
                        and self._is_jit_expr(mod, node.func)):
                    continue
                target = node.args[0]
                resolved = self._resolve_call(mod, target)
                if resolved is not None:
                    yield self.modules[resolved[0]], resolved[1]

    # -- call resolution --------------------------------------------------

    def _lookup(self, modname: str, name: str,
                depth: int = 0) -> tuple[str, str] | None:
        """Find function ``name`` in module ``modname``, following one
        level of ``__init__`` re-export."""
        mod = self.modules.get(modname) \
            or self.modules.get(modname + ".__init__")
        if mod is None or depth > 2:
            return None
        if name in mod.functions:
            return mod.modname, name
        if name in mod.sym_imports:
            src_mod, orig = mod.sym_imports[name]
            return self._lookup(src_mod, orig, depth + 1)
        return None

    def _resolve_call(self, mod: ModuleInfo,
                      func: ast.AST) -> tuple[str, str] | None:
        if isinstance(func, ast.Name):
            if func.id in mod.functions:
                return mod.modname, func.id
            if func.id in mod.sym_imports:
                src_mod, orig = mod.sym_imports[func.id]
                return self._lookup(src_mod, orig)
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            target_mod = mod.alias_root(func.value.id)
            if target_mod is not None:
                return self._lookup(target_mod, func.attr)
        return None

    # -- region growth ----------------------------------------------------

    def _grow(self) -> None:
        work = list(dict.fromkeys(
            (m.modname, q) for m, q in self._entries()))
        seen = set(work)
        while work:
            modname, qual = work.pop()
            self.jit_region.add((modname, qual))
            mod = self.modules[modname]
            fn = mod.functions.get(qual)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = self._resolve_call(mod, node.func)
                if resolved is not None and resolved not in seen:
                    seen.add(resolved)
                    work.append(resolved)

    def jit_functions(self, modname: str) -> set[str]:
        """Qualnames of this module's functions inside the jit region."""
        return {q for m, q in self.jit_region if m == modname}

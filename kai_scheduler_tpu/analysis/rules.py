"""The KAI rule catalog.

Code families (stable — suppressions and baselines reference them):

* ``KAI000``        stale suppression (emitted by the engine itself)
* ``KAI001-KAI004`` host syncs inside the jit region
* ``KAI011-KAI012`` Python control flow on traced values
* ``KAI021-KAI022`` precision-discipline / dtype-signature hazards
* ``KAI031-KAI032`` recompile hazards
* ``KAI041``        determinism hazards
* ``KAI051-KAI052`` generic hygiene
* ``KAI061``        observability discipline (tracer calls in traces)
* ``KAI071``        wire discipline (raw device transfers outside the
  ledger choke point)
* ``KAI081``        donation discipline (host-side read of a buffer
  previously passed through a donated argnum — use-after-donate)
* ``KAI091``        intake discipline (direct hub-journal mark writes
  outside the journal's module and the kai-intake gate)
* ``KAI2xx``        kai-cost program-level family (``costmodel.py``,
  catalog in ``engine.PROGRAM_RULES``): KAI201 broadcast blowup — an
  intermediate aval exceeding ``blowup_factor ×`` the entry's largest
  input; KAI202 ineffective donation — a donated input leaf the
  compiled executable did not alias to any output.  These judge the
  traced *program*, not source: their fixtures are jax functions
  (``tests/test_costmodel.py``), their findings ride the engine's
  count-based baseline rows (``cost_baseline.json``), and inline
  source suppressions do not apply.
* ``KAI3xx``        kai-comms program-level family (``comms.py``,
  catalog in ``engine.PROGRAM_RULES``): KAI301 accidental node-axis
  replication — an intermediate materializing the full node axis
  replicated on every device above the size threshold; KAI302
  declared-vs-inferred sharding drift — a ``mesh.state_shardings``
  leaf disagreeing with the auditor's seed registry, checked
  leaf-exact both directions; KAI303 collective-under-loop — a
  collective inside ``scan``/``while`` whose trip-count-charged bytes
  exceed the loop comm budget.  Same program-level conventions as
  KAI2xx: jax-function fixtures (``tests/test_comms.py``),
  justification-required baseline rows (``comm_baseline.json``), no
  inline source suppressions.

"Jit region" is the transitive call graph grown from the package's
``jax.jit`` entry points (see ``callgraph.py``); host-only code is
exempt from the trace-safety families.  Every rule carries a
must-trigger and a must-not-trigger fixture, exercised by
``tests/test_analysis.py`` — edit a rule, keep its fixtures honest.
"""
from __future__ import annotations

import ast
from typing import Iterator

from .engine import Finding, RuleCtx, rule

# ---------------------------------------------------------------------------
# shared AST helpers

#: numpy attributes that are dtype/constant handles, not host kernels —
#: legal inside a trace (they parametrize jnp calls, nothing executes)
_NP_DTYPE_ATTRS = frozenset({
    "float16", "bfloat16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
    "dtype", "iinfo", "finfo", "ndarray", "generic", "newaxis",
})

#: method names whose call on an array forces a device→host sync
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})

#: the kai-trace recording surface (runtime/tracing.py CycleTracer) —
#: a span call inside a jit-traced function executes at TRACE time, so
#: it would record compilation (once) instead of execution (per cycle)
#: and silently measure nothing
_TRACER_METHODS = frozenset({
    "span", "cycle", "add_span", "device_sync", "begin_cycle",
    "end_cycle",
})

#: jnp functions whose output shape depends on input *values* — inside
#: jit they either fail to trace or (via fallback paths) force
#: per-value recompiles; all have ``size=`` escape hatches
_DATA_DEP_SHAPE = frozenset({
    "nonzero", "flatnonzero", "argwhere", "unique", "unique_values",
    "compress", "extract", "union1d", "intersect1d", "setdiff1d",
})


def _dotted(node: ast.AST) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _rooted(ctx: RuleCtx, node: ast.AST, roots: tuple[str, ...]
            ) -> str | None:
    """If ``node`` is an attribute chain whose base name aliases one of
    ``roots`` (prefix match), return the chain's final attribute."""
    d = _dotted(node)
    if d is None or "." not in d:
        return None
    base, rest = d.split(".", 1)
    target = ctx.mod.alias_root(base)
    if target is None:
        return None
    full = target + "." + rest
    for r in roots:
        if full == r or full.startswith(r + "."):
            return full[len(r) + 1:] if full != r else ""
    return None


def _numpy_attr(ctx: RuleCtx, node: ast.AST) -> str | None:
    return _rooted(ctx, node, ("numpy",))


def _jnp_attr(ctx: RuleCtx, node: ast.AST) -> str | None:
    return _rooted(ctx, node, ("jax.numpy",))


def _jax_attr(ctx: RuleCtx, node: ast.AST) -> str | None:
    return _rooted(ctx, node, ("jax",))


def _arrayish(ctx: RuleCtx, node: ast.AST) -> bool:
    """Does this subtree *compute on arrays* (so its truth value would
    concretize a tracer)?  Conservative: jnp/jax-family calls and
    ``.any()``/``.all()`` style reductions; plain config/name tests
    (static under jit) stay silent."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if _jax_attr(ctx, sub.func) is not None:
                return True
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("any", "all", "item")):
                return True
    return False


def _body_nodes(fn: ast.AST) -> set[ast.AST]:
    """Nodes inside a def's *body* — decorators and defaults are
    evaluated at definition time in the enclosing scope, so they must
    not count as "inside the function" (a module-level ``@jax.jit``
    decorator is not a jit-in-function hazard)."""
    if not hasattr(fn, "_descendants"):
        out: set[ast.AST] = set()
        for stmt in fn.body:
            out.add(stmt)
            out.update(ast.walk(stmt))
        fn._descendants = out
    return fn._descendants


def _in_function(ctx: RuleCtx, node: ast.AST) -> str | None:
    """Qualname of the innermost function containing ``node``, if any."""
    best = None
    for qual, fn in ctx.mod.functions.items():
        if node in _body_nodes(fn):
            if best is None or len(qual) > len(best):
                best = qual
    return best


def _index_descendants(ctx: RuleCtx) -> None:
    for fn in ctx.mod.functions.values():
        _body_nodes(fn)


def _jit_body(ctx: RuleCtx) -> Iterator[tuple[str, ast.AST]]:
    """(qualname, node) for every AST node inside a jit-region def."""
    for qual, fn in ctx.jit_nodes():
        yield from ((qual, node) for node in _body_nodes(fn))


# ---------------------------------------------------------------------------
# KAI000 — emitted by the engine's suppression bookkeeping; registered
# here so the catalog and --select know the code

@rule("KAI000", "stale suppression (disable comment with no live "
      "finding)")
def _stale_suppression(ctx: RuleCtx) -> Iterator[Finding]:
    return iter(())


# ---------------------------------------------------------------------------
# KAI001-KAI004 — host syncs in the jit region

@rule(
    "KAI001", "host-sync method (.item/.tolist/.block_until_ready) in "
    "jit region",
    bad="""
import jax

@jax.jit
def op(x):
    return x.item()
""",
    good="""
import jax

@jax.jit
def op(x):
    return x + 1
""")
def _host_sync_method(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS):
            yield ctx.finding(
                "KAI001", node,
                f".{node.func.attr}() forces a device→host sync inside "
                f"a compiled op — keep the value on device or move the "
                f"readback to the commit path", qual)


@rule(
    "KAI002", "numpy call on traced values in jit region",
    bad="""
import jax
import numpy as np

@jax.jit
def op(x):
    return np.asarray(x) * 2
""",
    good="""
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def op(x):
    return jnp.asarray(x, np.float32) * 2
""")
def _numpy_in_jit(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if not isinstance(node, ast.Call):
            continue
        attr = _numpy_attr(ctx, node.func)
        if attr and attr.split(".")[-1] not in _NP_DTYPE_ATTRS:
            yield ctx.finding(
                "KAI002", node,
                f"np.{attr} concretizes its operands (host round trip "
                f"mid-trace) — use the jnp equivalent", qual)


@rule(
    "KAI003", "python scalar cast (int/float/bool) on traced value",
    bad="""
import jax

@jax.jit
def op(x):
    return x * float(x)
""",
    good="""
import jax

@jax.jit
def op(x):
    return x * float(x.shape[0])
""")
def _scalar_cast(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, fn in ctx.jit_nodes():
        params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                  + fn.args.posonlyargs)}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and len(node.args) == 1):
                continue
            arg = node.args[0]
            # static under jit: literals and shape/len arithmetic
            sub = list(ast.walk(arg))
            if any(isinstance(s, ast.Attribute) and s.attr == "shape"
                   for s in sub):
                continue
            if any(isinstance(s, ast.Call)
                   and isinstance(s.func, ast.Name)
                   and s.func.id in ("len", "range") for s in sub):
                continue
            traced = (isinstance(arg, ast.Name) and arg.id in params) \
                or any(isinstance(s, ast.Call)
                       and _jax_attr(ctx, s.func) is not None
                       for s in sub)
            if traced:
                yield ctx.finding(
                    "KAI003", node,
                    f"{node.func.id}() on a traced value aborts the "
                    f"trace (ConcretizationError) or syncs the host — "
                    f"stay in array land or hoist to a static arg", qual)


@rule(
    "KAI004", "explicit device transfer in jit region",
    bad="""
import jax

@jax.jit
def op(x):
    return jax.device_get(x)
""",
    good="""
import jax

def host_commit(x):
    return jax.device_get(x)
""")
def _device_transfer(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if isinstance(node, ast.Call):
            attr = _jax_attr(ctx, node.func)
            if attr in ("device_get", "block_until_ready"):
                yield ctx.finding(
                    "KAI004", node,
                    f"jax.{attr} inside a compiled op is a host round "
                    f"trip — transfers belong on the commit path", qual)


# ---------------------------------------------------------------------------
# KAI011-KAI012 — Python control flow on traced values

@rule(
    "KAI011", "python branch on traced value in jit region",
    bad="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x):
    if jnp.any(x > 0):
        return x
    return -x
""",
    good="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x, flag=True):
    if flag:
        return jnp.abs(x)
    return -x
""")
def _branch_on_tracer(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        test = None
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        if test is not None and _arrayish(ctx, test):
            kind = type(node).__name__.lower()
            yield ctx.finding(
                "KAI011", node,
                f"python {kind} on an array-valued test concretizes the "
                f"tracer (recompile per value, or TracerBoolError) — use "
                f"jnp.where / lax.cond / lax.while_loop", qual)


@rule(
    "KAI012", "assert in jit region (stripped under -O)",
    bad="""
import jax

@jax.jit
def op(x, n_static=4):
    assert n_static > 0, "bad config"
    return x * n_static
""",
    good="""
import jax

@jax.jit
def op(x, n_static=4):
    if n_static <= 0:
        raise ValueError("bad config")
    return x * n_static
""")
def _assert_in_jit(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if isinstance(node, ast.Assert):
            yield ctx.finding(
                "KAI012", node,
                "assert in a kernel construction path: stripped under "
                "python -O (invariant silently vanishes), and a "
                "traced-value test would concretize — raise explicitly "
                "on static config instead", qual)


# ---------------------------------------------------------------------------
# KAI021-KAI022 — precision / dtype-signature discipline

@rule(
    "KAI021", "f64 outside the host-side allowlist (f32 device "
    "discipline, see utils/numerics.py)",
    bad="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x):
    return x.astype(jnp.float64)
""",
    good="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x):
    return x.astype(jnp.float32)
""")
def _f64_leak(ctx: RuleCtx) -> Iterator[Finding]:
    _index_descendants(ctx)
    jit_ids = set()
    for _q, fn in ctx.jit_nodes():
        jit_ids |= fn._descendants
    host_ok = ctx.mod.relpath in ctx.f64_allowlist
    # "float64" STRINGS only count in np/jnp call-argument (dtype)
    # position — a linter's own rule tables are not dtype leaks
    dtype_strings: set[ast.AST] = set()
    for node in ast.walk(ctx.mod.tree):
        if isinstance(node, ast.Call) and (
                _numpy_attr(ctx, node.func) is not None
                or _jnp_attr(ctx, node.func) is not None):
            for e in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(e, ast.Constant) and e.value == "float64":
                    dtype_strings.add(e)
    for node in ast.walk(ctx.mod.tree):
        name = None
        if isinstance(node, ast.Attribute) and node.attr in (
                "float64", "double", "complex128"):
            if _jnp_attr(ctx, node) is not None:
                name = f"jnp.{node.attr}"        # device f64: never OK
            elif _numpy_attr(ctx, node) is not None and (
                    not host_ok or node in jit_ids):
                name = f"np.{node.attr}"
        elif node in dtype_strings and (not host_ok or node in jit_ids):
            name = '"float64"'
        if name is not None:
            qual = _in_function(ctx, node) or ""
            yield ctx.finding(
                "KAI021", node,
                f"{name} breaks the f32-device / f64-host precision "
                f"boundary — device math uses compensated f32 "
                f"(utils/numerics.cumsum_ds); host f64 lives only in "
                f"allowlisted modules", qual)


@rule(
    "KAI022", "x64-flag-dependent builtin dtype (float/int/complex)",
    bad="""
import numpy as np

def table(n):
    return np.zeros(n, dtype=float)
""",
    good="""
import numpy as np

def table(n):
    return np.zeros(n, dtype=np.float32)
""")
def _builtin_dtype(ctx: RuleCtx) -> Iterator[Finding]:
    _index_descendants(ctx)
    for node in ast.walk(ctx.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if (_numpy_attr(ctx, node.func) is None
                and _jnp_attr(ctx, node.func) is None):
            continue
        exprs = list(node.args) + [k.value for k in node.keywords]
        for e in exprs:
            if isinstance(e, ast.Name) and e.id in ("float", "int",
                                                    "complex"):
                yield ctx.finding(
                    "KAI022", e,
                    f"builtin dtype `{e.id}` resolves differently under "
                    f"jax_enable_x64 — the compile signature (and f32 "
                    f"discipline) silently changes with a flag; pin an "
                    f"explicit np dtype", _in_function(ctx, node) or "")


# ---------------------------------------------------------------------------
# KAI031-KAI032 — recompile hazards

@rule(
    "KAI031", "data-dependent output shape in jit region",
    bad="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x):
    return jnp.nonzero(x)
""",
    good="""
import jax
import jax.numpy as jnp

@jax.jit
def op(x):
    return jnp.nonzero(x, size=8, fill_value=-1)
""")
def _data_dep_shape(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if not isinstance(node, ast.Call):
            continue
        attr = _jnp_attr(ctx, node.func)
        if attr is None:
            continue
        kw = {k.arg for k in node.keywords}
        if attr in _DATA_DEP_SHAPE and "size" not in kw:
            yield ctx.finding(
                "KAI031", node,
                f"jnp.{attr} without size= has a value-dependent output "
                f"shape — untraceable (or a per-value recompile); pass "
                f"size=/fill_value= at the padded bound", qual)
        elif (attr == "where" and len(node.args) == 1
                and not {"x", "y"} & kw):
            yield ctx.finding(
                "KAI031", node,
                "single-argument jnp.where is jnp.nonzero in disguise "
                "(value-dependent shape) — use the three-argument form "
                "or pass size=", qual)


@rule(
    "KAI032", "jit constructed inside a function (per-call cache miss)",
    bad="""
import jax

def run(xs):
    op = jax.jit(lambda x: x + 1)
    return [op(x) for x in xs]
""",
    good="""
import jax

_op = jax.jit(lambda x: x + 1)

def run(xs):
    return [_op(x) for x in xs]
""")
def _jit_in_function(ctx: RuleCtx) -> Iterator[Finding]:
    _index_descendants(ctx)
    for node in ast.walk(ctx.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        is_jit = _jax_attr(ctx, node.func) == "jit"
        if not is_jit:
            # functools.partial(jax.jit, ...) counts the same
            f = _dotted(node.func)
            if f is not None and f.split(".")[-1] == "partial" \
                    and node.args \
                    and _jax_attr(ctx, node.args[0]) == "jit":
                is_jit = True
        if not is_jit:
            continue
        qual = _in_function(ctx, node)
        if qual is not None:
            yield ctx.finding(
                "KAI032", node,
                "jax.jit built inside a function: each call makes a "
                "fresh callable whose closure/identity misses the "
                "compile cache — hoist the jitted wrapper to module "
                "scope", qual)


# ---------------------------------------------------------------------------
# KAI041 — determinism

@rule(
    "KAI041", "iteration over an unordered set/dict-view expression",
    bad="""
def ports(pods):
    out = []
    for p in set(pods):
        out.append(p)
    return out
""",
    good="""
def ports(pods):
    out = []
    for p in sorted(set(pods)):
        out.append(p)
    return out
""")
def _unordered_iteration(ctx: RuleCtx) -> Iterator[Finding]:
    _index_descendants(ctx)

    def is_setish(e: ast.AST) -> bool:
        if isinstance(e, (ast.Set, ast.SetComp)):
            return True
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Name) \
                and e.func.id in ("set", "frozenset"):
            return True
        if isinstance(e, ast.BinOp) and isinstance(
                e.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return any(
                is_setish(side)
                or (isinstance(side, ast.Call)
                    and isinstance(side.func, ast.Attribute)
                    and side.func.attr == "keys")
                for side in (e.left, e.right))
        return False

    iters = []
    for node in ast.walk(ctx.mod.tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                               ast.DictComp)):
            iters.extend(g.iter for g in node.generators)
    for it in iters:
        if is_setish(it):
            yield ctx.finding(
                "KAI041", it,
                "iterating an unordered set expression: order is "
                "hash-seed dependent, so anything it feeds (snapshot "
                "buffers, scheduling signatures, journals) loses "
                "determinism — wrap in sorted()",
                _in_function(ctx, it) or "")


# ---------------------------------------------------------------------------
# KAI061 — observability discipline

@rule(
    "KAI061", "tracer/span call inside the jit region (records trace "
    "time, not run time)",
    bad="""
import jax

from kai_scheduler_tpu.runtime.tracing import CycleTracer

tracer = CycleTracer()


@jax.jit
def op(x):
    with tracer.span("solve"):
        return x + 1
""",
    good="""
import jax

from kai_scheduler_tpu.runtime.tracing import CycleTracer

tracer = CycleTracer()


@jax.jit
def op(x):
    return x + 1


def run(x):
    with tracer.span("solve"):
        return op(x)
""")
def _tracer_in_jit(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, node in _jit_body(ctx):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _TRACER_METHODS):
            continue
        base = _dotted(node.func.value)
        if base is not None and "tracer" in base.lower():
            yield ctx.finding(
                "KAI061", node,
                f".{node.func.attr}() on `{base}` inside a compiled op "
                f"runs at trace time — the span would bracket "
                f"compilation, not execution, and its timestamps would "
                f"be meaningless.  Instrument around the dispatch on "
                f"the host path instead", qual)


# ---------------------------------------------------------------------------
# KAI071 — wire discipline

#: the TransferLedger choke point: the only module allowed to touch
#: the raw host↔device transfer API.  Every other call site must route
#: through ``wire_ledger.LEDGER.device_put`` so per-leaf upload
#: accounting (bytes, reasons, redundancy — the ROADMAP-1 evidence
#: layer) can never silently rot as code grows.
_WIRE_CHOKE_POINT = frozenset({
    "kai_scheduler_tpu/runtime/wire_ledger.py",
})


@rule(
    "KAI071", "raw jax.device_put/device_get outside the wire-ledger "
    "choke point",
    bad="""
import jax

def ship(x):
    return jax.device_put(x)
""",
    good="""
from kai_scheduler_tpu.runtime.wire_ledger import LEDGER

def ship(x):
    return LEDGER.device_put(x, reason="full-build")
""")
def _raw_device_transfer(ctx: RuleCtx) -> Iterator[Finding]:
    if ctx.mod.relpath in _WIRE_CHOKE_POINT:
        return
    _index_descendants(ctx)
    for node in ast.walk(ctx.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        attr = _jax_attr(ctx, node.func)
        if attr == "device_put":
            yield ctx.finding(
                "KAI071", node,
                "raw jax.device_put bypasses the TransferLedger — "
                "every host→device transfer must flow through "
                "runtime/wire_ledger.LEDGER.device_put so per-leaf "
                "bytes, reasons, and redundancy stay on the books "
                "(ROADMAP-1's measurement substrate)",
                _in_function(ctx, node) or "")
        elif attr == "device_get":
            yield ctx.finding(
                "KAI071", node,
                "raw jax.device_get is an unaccounted device→host "
                "readback — the package's D2H budget is ONE packed "
                "commit transfer per cycle (Session.gather_host); "
                "route readbacks through the packed commit bundle "
                "instead of ad-hoc transfers the wire ledger cannot "
                "see", _in_function(ctx, node) or "")


# ---------------------------------------------------------------------------
# KAI081 — donation discipline

#: jit entry points that DONATE argument buffers (``donate_argnums``):
#: the value passed at a donated position is dead the moment the call
#: dispatches — on a real accelerator the buffer is reused in place and
#: any later host read raises (or worse, reads scribbled memory).  The
#: classic donation use-after-free is invisible on backends that ignore
#: donation, so it must be caught statically.
_DONATING_CALLEES: dict[str, tuple[int, ...]] = {
    # kai-resident fused cycle entry (framework/scheduler.py): the
    # device-resident ClusterState at position 0 is donated
    "_resident_cycle": (0,),
    "resident_cycle": (0,),
}


def _target_names(node: ast.AST) -> Iterator[str]:
    """Plain names bound by an assignment target (tuples unpacked)."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _target_names(elt)
    elif isinstance(node, ast.Starred):
        yield from _target_names(node.value)


@rule(
    "KAI081", "host-side read of an array previously passed through a "
    "donated argnum (use-after-donate)",
    bad="""
def run(state, delta):
    packed = resident_cycle(state, delta)
    return state, packed
""",
    good="""
def run(state, delta):
    state, packed = resident_cycle(state, delta)
    return state, packed
""")
def _donated_buffer_read(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, fn in ctx.mod.functions.items():
        donations: list[tuple[int, str, ast.Call]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute):
                callee = node.func.attr
            spec = _DONATING_CALLEES.get(callee or "")
            if not spec:
                continue
            for pos in spec:
                if pos < len(node.args) and isinstance(node.args[pos],
                                                       ast.Name):
                    donations.append(
                        (getattr(node, "end_lineno", node.lineno)
                         or node.lineno, node.args[pos].id, node))
        if not donations:
            continue
        bind_lines: dict[str, list[int]] = {}
        for node in ast.walk(fn):
            targets: list = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.For):
                targets = [node.target]
            for t in targets:
                for nm in _target_names(t):
                    bind_lines.setdefault(nm, []).append(node.lineno)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            for call_end, var, call in donations:
                if node.id != var or node.lineno <= call_end:
                    continue
                # a rebind between the donating call and the read makes
                # the name safe again (typically the call's own
                # `state, ... = f(state, ...)` unpack)
                if any(call.lineno <= ln <= node.lineno
                       for ln in bind_lines.get(var, ())):
                    continue
                yield ctx.finding(
                    "KAI081", node,
                    f"`{var}` was passed through a donated argnum of "
                    f"`{getattr(call.func, 'id', None) or getattr(call.func, 'attr', '?')}` "
                    f"on line {call.lineno} — its device buffer is "
                    f"consumed in place by the dispatch, so this later "
                    f"read is a use-after-donate (deleted-array error "
                    f"on donating backends, silent on backends that "
                    f"ignore donation).  Rebind the name from the "
                    f"call's outputs instead", qual)
                break


# ---------------------------------------------------------------------------
# KAI091 — intake discipline

#: the hub-journal write choke point: the journal's own module plus the
#: kai-intake package (whose ``gate`` module owns the mark mapping and
#: whose router/applier are the sanctioned bulk writers).  Everything
#: else — hub mutators, binder write-backs, wire codecs, new
#: subsystems — must mark through ``intake/gate.py``, so the
#: storm-vs-sequential differential (one shared upsert/delete → mark
#: mapping) can never silently fork as code grows.  Mirrors KAI071's
#: device_put discipline.
_JOURNAL_CHOKE_POINT = frozenset({
    "kai_scheduler_tpu/state/incremental.py",
})
_JOURNAL_CHOKE_PREFIX = "kai_scheduler_tpu/intake/"

#: the MutationJournal mark surface (state/incremental.py) — calling
#: any of these on a journal object IS a hub-journal write
_JOURNAL_MARK_METHODS = frozenset({
    "mark_pod", "mark_pod_added", "mark_pod_removed", "mark_gang",
    "mark_gang_added", "mark_node", "mark_structural", "mark_time",
    "merge",
})


@rule(
    "KAI091", "direct hub-journal mark outside the intake gate",
    bad="""
def evict(cluster, name):
    cluster.journal.mark_pod(name)
""",
    good="""
from kai_scheduler_tpu.intake import gate

def evict(cluster, name):
    gate.pod_touched(cluster.journal, name)
""")
def _raw_journal_mark(ctx: RuleCtx) -> Iterator[Finding]:
    if (ctx.mod.relpath in _JOURNAL_CHOKE_POINT
            or ctx.mod.relpath.startswith(_JOURNAL_CHOKE_PREFIX)):
        return
    _index_descendants(ctx)
    for node in ast.walk(ctx.mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _JOURNAL_MARK_METHODS):
            continue
        # scope to journal receivers: `<x>.journal.mark_*` chains and
        # names that smell like a journal — `merge` alone is far too
        # generic to flag on arbitrary objects
        base = _dotted(node.func.value)
        if base is None or "journal" not in base.lower():
            continue
        yield ctx.finding(
            "KAI091", node,
            f".{node.func.attr}() writes the hub MutationJournal "
            f"directly — route the mark through the kai-intake gate "
            f"(intake/gate.py), the package's single journal-write "
            f"choke point: one shared upsert/delete→mark mapping is "
            f"what keeps the async-lane coalesce bit-identical to the "
            f"sequential classic path (KAI091, mirrors KAI071)",
            _in_function(ctx, node) or "")


# ---------------------------------------------------------------------------
# KAI051-KAI052 — generic hygiene

@rule(
    "KAI051", "mutable default argument",
    bad="""
def collect(x, acc=[]):
    acc.append(x)
    return acc
""",
    good="""
def collect(x, acc=None):
    acc = [] if acc is None else acc
    acc.append(x)
    return acc
""")
def _mutable_default(ctx: RuleCtx) -> Iterator[Finding]:
    for qual, fn in ctx.mod.functions.items():
        args = fn.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set)) \
                or (isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set",
                                            "bytearray"))
            if mutable:
                yield ctx.finding(
                    "KAI051", default,
                    "mutable default argument is shared across calls — "
                    "default to None and materialize inside", qual)


@rule(
    "KAI052", "function-level absolute import (package-relative "
    "cycle-breakers are exempt)",
    bad="""
def flush():
    import time
    return time.monotonic()
""",
    good="""
import time

def flush():
    from .sibling import helper
    return helper(time.monotonic())
""")
def _function_level_import(ctx: RuleCtx) -> Iterator[Finding]:
    _index_descendants(ctx)
    for node in ast.walk(ctx.mod.tree):
        absolute = isinstance(node, ast.Import) or (
            isinstance(node, ast.ImportFrom) and node.level == 0)
        if not absolute:
            continue
        qual = _in_function(ctx, node)
        if qual is not None:
            names = ", ".join(a.name for a in node.names)
            yield ctx.finding(
                "KAI052", node,
                f"import of `{names}` inside a function re-runs the "
                f"module lookup on every call (and hides the "
                f"dependency) — move to module scope; only "
                f"package-relative cycle-breakers stay local", qual)

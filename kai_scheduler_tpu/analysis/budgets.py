"""Shared budget/tolerance math for the baseline-diffed analysis layers.

Both the jaxpr probe (``trace_probe.py``: eqn counts, const bytes) and
the kai-cost auditor (``costmodel.py``: peak live bytes, FLOPs, memory
traffic) compare per-entry measurements against checked-in baselines
with *tolerance headroom* — a relative growth allowance plus an
absolute slack floor so tiny baselines don't fail on ±1 jitter.  The
formula was open-coded twice before PR 14; this module is the single
implementation both layers call, so the two baseline families can
never drift apart in how "allowed" is computed.
"""
from __future__ import annotations


def allowed_max(base: int | float, *, tolerance: float,
                slack: int | float = 0) -> int:
    """The largest measured value that still passes against ``base``:
    ``int(base * (1 + tolerance)) + slack``.

    ``int()`` truncates *before* adding slack — pinned by the probe
    tests' historical eqn/const budget values; keep it that way.
    """
    return int(base * (1 + tolerance)) + int(slack)


def budget_problem(entry: str, metric: str, value: int | float,
                   base: int | float, *, tolerance: float,
                   slack: int | float = 0, unit: str = "",
                   hint: str = "") -> str | None:
    """One human-readable regression message, or ``None`` when the
    value fits the budget.  Shared renderer so probe and cost failures
    read the same way in CLI/test output."""
    limit = allowed_max(base, tolerance=tolerance, slack=slack)
    if value <= limit:
        return None
    msg = (f"{entry}: {metric} grew to {value}{unit} "
           f"(baseline {base}{unit}, allowed {limit}{unit})")
    if hint:
        msg += f" — {hint}"
    return msg

"""``python -m kai_scheduler_tpu.analysis`` — the kai-lint CLI.

Default run: layer-1 AST lint over the package (the KAI0xx trace-safety
rules plus the KAI1xx kai-race concurrency pass), the layer-2 jaxpr
probe, the layer-4 kai-cost audit, and the layer-5 kai-comms sharding
audit (one shared jaxpr walk feeds probe, cost, and comms).  Exit
status is nonzero on any non-baselined finding, so the command doubles
as the CI gate (``scripts/lint.py`` wraps the lint-only fast path for
pre-commit).

    python -m kai_scheduler_tpu.analysis            # lint+probe+cost+comms
    python -m kai_scheduler_tpu.analysis --no-probe   # AST lint only
    python -m kai_scheduler_tpu.analysis --race       # kai-race only
    python -m kai_scheduler_tpu.analysis --cost       # kai-cost only
    python -m kai_scheduler_tpu.analysis --cost --scaling   # + N-growth fit
    python -m kai_scheduler_tpu.analysis --comms      # kai-comms only
    python -m kai_scheduler_tpu.analysis --comms --scaling  # + comm-vs-d fit
    python -m kai_scheduler_tpu.analysis --json       # machine output
    python -m kai_scheduler_tpu.analysis --list-rules
    python -m kai_scheduler_tpu.analysis --probe --update-baseline
    python -m kai_scheduler_tpu.analysis --update-baseline  # ALL baselines
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kai_scheduler_tpu.analysis",
        description="kai-lint: trace-safety, determinism, and "
                    "recompile-hazard analysis for the TPU hot path")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--select", default=None,
                    help="comma-separated KAI codes to run (lint)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON for the lint layer (default: "
                         "the package baseline.json)")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--no-probe", action="store_true",
                      help="skip the jaxpr probe (AST lint only)")
    mode.add_argument("--probe", action="store_true",
                      help="jaxpr probe only (skip the AST lint)")
    mode.add_argument("--race", action="store_true",
                      help="kai-race concurrency pass only (KAI1xx; "
                           "jax-free)")
    mode.add_argument("--cost", action="store_true",
                      help="kai-cost jaxpr dataflow audit only "
                           "(KAI2xx: liveness peak-memory, FLOPs, "
                           "traffic, blowup, donation)")
    mode.add_argument("--comms", action="store_true",
                      help="kai-comms sharding audit only (KAI3xx: "
                           "PartitionSpec propagation, collective "
                           "byte budgets, declared-vs-inferred "
                           "sharding drift, HLO cross-validation)")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op names for the probe/cost/"
                         "comms stages")
    ap.add_argument("--scaling", action="store_true",
                    help="scaling mode: the cost stage fits the "
                         "peak-memory growth exponent over 2-3 node "
                         "widths; the comms stage fits modeled comm "
                         "bytes over device counts {2,4,8} (reported, "
                         "never a failure)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the measured stats in baseline.json "
                         "(probe stage), cost_baseline.json (cost "
                         "stage) and comm_baseline.json (comms stage) "
                         "— a default full run refreshes all three in "
                         "one invocation, together or not at all")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from .engine import lint_package, load_baseline, rule_catalog
    if args.list_rules:
        for code, title in rule_catalog().items():
            print(f"{code}  {title}")
        return 0

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    root = args.root or os.path.dirname(os.path.dirname(pkg_dir))
    baseline_path = args.baseline or os.path.join(pkg_dir,
                                                  "baseline.json")
    out: dict = {"findings": [], "probe": []}
    failed = False

    #: stage selection — default (no mode flag) runs lint + probe +
    #: cost + comms; each mode flag narrows to its own stage
    run_probe_stage = not (args.no_probe or args.cost or args.race
                           or args.comms)
    run_cost_stage = args.cost or not (args.no_probe or args.probe
                                       or args.race or args.comms)
    run_comms_stage = args.comms or not (args.no_probe or args.probe
                                         or args.race or args.cost)

    if args.scaling and not (run_cost_stage or run_comms_stage):
        # a mode that skips both scaling-capable stages would silently
        # drop the exponent report — a clean exit with no scaling
        # output reads as "nothing super-linear / nothing to fit"
        ap.error("--scaling requires the kai-cost or kai-comms stage "
                 "(drop the mode flag, or use --cost / --comms)")
    if args.select and any(c.startswith(("KAI2", "KAI3"))
                           for c in args.select.split(",")):
        # KAI2xx/KAI3xx are program-level checks (costmodel.py /
        # comms.py), not engine rules: the lint select filter would
        # match nothing and print a FALSE "0 findings" clean bill
        ap.error("KAI2xx/KAI3xx rules are jaxpr-level — run them via "
                 "--cost / --comms (they are not --select-able lint "
                 "rules)")

    if not args.probe and not args.cost and not args.comms:
        baseline = (load_baseline(baseline_path)
                    if os.path.exists(baseline_path) else [])
        select = (args.select.split(",") if args.select else None)
        if args.race:
            from .concurrency import race_codes
            select = list(race_codes()) if select is None else [
                c for c in select if c in race_codes()]
            if not select:
                # --select named no KAI1xx code: running zero rules
                # would print a FALSE "0 findings" clean bill
                ap.error("--race with --select requires at least one "
                         "KAI1xx code")
        res = lint_package(root, select=select, baseline=baseline)
        out["findings"] = [f.__dict__ for f in res.findings]
        out["baselined"] = res.baselined
        if res.race is not None:
            # the kai-race layer's report: discovered thread roots and
            # the KAI1xx slice of the findings (consumed by the CLI
            # smoke test and any tooling watching the race surface)
            race_findings = [f.__dict__ for f in res.findings
                             if f.code.startswith("KAI1")]
            out["race"] = {
                "thread_roots": {
                    r.root_id: {"kind": r.kind, "multi": r.multi}
                    for r in res.race.roots},
                "findings": race_findings,
                "live_annotations": res.race.live_annotations,
                "declared_attrs": len(res.race.disciplines),
            }
        if not args.as_json:
            for f in res.findings:
                print(f.render())
            n = len(res.findings)
            extra = ""
            if res.race is not None:
                extra = (f", {len(res.race.roots)} thread roots, "
                         f"{res.race.live_annotations} live guarded-by "
                         f"annotations")
            print(f"kai-lint: {n} finding{'s' * (n != 1)} "
                  f"({res.raw_count} raw, {res.baselined} baselined, "
                  f"{len(res.stale_suppressions)} stale suppressions"
                  f"{extra})")
        failed |= bool(res.findings)

    if args.race:
        if args.as_json:
            json.dump(out, sys.stdout, indent=2, default=str)
            print()
        return 1 if failed else 0

    if run_comms_stage:
        # the lowering stage jits against an 8-way mesh; the flag must
        # land before the CPU backend's first init (no-op afterwards)
        from ..parallel.mesh import ensure_virtual_cpu_devices
        ensure_virtual_cpu_devices()

    names = args.ops.split(",") if args.ops else None
    shared_traces = None
    if run_probe_stage + run_cost_stage + run_comms_stage >= 2:
        # ONE shared per-entry jaxpr walk feeds every jax layer —
        # tracing the fused entries costs seconds each, never pay it
        # twice (or three times)
        from .trace_probe import trace_entries
        shared_traces = trace_entries(names)

    #: joint-refresh bookkeeping: when several stages run with
    #: --update-baseline, the files rewrite together or not at all (a
    #: half-refresh would absorb cost growth caused by the very change
    #: the probe blocked on, or vice versa) — the LAST jax stage to
    #: run performs the deferred writes
    last_jax_stage = ("comms" if run_comms_stage else
                      "cost" if run_cost_stage else "probe")
    probe_update_ok = None      # None = probe stage ran no update
    probe_reports = None
    cost_update_ok = None       # None = cost stage ran no update
    cost_reports_pending = None

    if run_probe_stage:
        from .trace_probe import (check_against_baseline,
                                  check_invariants, load_stats_baseline,
                                  run_probe, update_baseline)
        reports = run_probe(names, traces=shared_traces)
        if args.update_baseline:
            # the baseline only absorbs eqn/const stats; callbacks,
            # f64, and cache misses have no legitimate new value and
            # still fail (and block the rewrite) here
            problems = check_invariants(reports)
            probe_update_ok = not problems
            if problems:
                if not args.as_json:
                    print("probe baseline NOT updated — invariant "
                          "failures first:")
            elif last_jax_stage == "probe":
                update_baseline(reports, baseline_path)
                if not args.as_json:
                    print(f"probe baseline updated: {baseline_path}")
            else:
                # deferred until the last jax stage clears its gates
                probe_reports = reports
        else:
            stats = (load_stats_baseline(baseline_path)
                     if os.path.exists(baseline_path) else {})
            problems = check_against_baseline(
                reports, stats, full_coverage=not args.ops)
        out["probe"] = [r.__dict__ for r in reports]
        out["probe_problems"] = problems
        if not args.as_json:
            for r in reports:
                hit = {True: "cache-hit", False: "CACHE-MISS",
                       None: "cache-n/a"}[r.cache_hit]
                print(f"probe {r.name}: {r.eqns} eqns, "
                      f"{r.const_bytes}B consts, {hit}")
            for p in problems:
                print(f"PROBE FAIL: {p}")
        failed |= bool(problems)

    if run_cost_stage:
        from . import costmodel
        cost_path = costmodel.COST_BASELINE_PATH
        cost_base = (costmodel.load_cost_baseline(cost_path)
                     if os.path.exists(cost_path) else {})
        reports = costmodel.run_cost(
            names, traces=shared_traces,
            baseline=cost_base.get("entries", {}))
        findings = costmodel.cost_findings(reports, cost_base)
        if args.update_baseline:
            # stats (peak/FLOPs/traffic/blowup ratios) are absorbed;
            # KAI202 donation failures — including an UNVERIFIABLE
            # donation check — have no legitimate new value, so they
            # block the rewrite, exactly like probe invariants
            problems = costmodel.unverifiable_donations(reports)
            kai202 = [f for f in findings if f.code == "KAI202"]
            cost_update_ok = not (kai202 or problems)
            if kai202 or problems:
                # keep EVERY finding visible (a KAI201 riding along is
                # neither absorbed nor silently dropped), and hold the
                # deferred probe write back too — joint or nothing
                if not args.as_json:
                    print("cost baseline NOT updated — donation "
                          "failures first:")
                    if probe_update_ok:
                        print("probe baseline NOT updated — cost "
                              "stage blocked the joint refresh")
            elif probe_update_ok is False:
                cost_update_ok = False
                if not args.as_json:
                    print("cost baseline NOT updated — probe "
                          "invariant failures blocked the joint "
                          "refresh")
            elif last_jax_stage != "cost":
                # deferred until the comms stage verifies lowering
                cost_reports_pending = reports
                findings = []
            else:
                costmodel.update_cost_baseline(reports, cost_path)
                findings = []
                if not args.as_json:
                    print(f"cost baseline updated: {cost_path}")
                if probe_update_ok:
                    from .trace_probe import update_baseline
                    update_baseline(probe_reports, baseline_path)
                    if not args.as_json:
                        print(f"probe baseline updated: "
                              f"{baseline_path}")
        else:
            problems = costmodel.check_against_cost_baseline(
                reports, cost_base, full_coverage=not args.ops)
        scaling = (costmodel.scaling_report() if args.scaling
                   else None)
        out["cost"] = [dataclasses.asdict(r) for r in reports]
        out["cost_problems"] = problems
        out["cost_findings"] = [f.__dict__ for f in findings]
        if scaling is not None:
            out["cost_scaling"] = scaling
        if not args.as_json:
            for r in reports:
                extra = ""
                if r.unknown_prims:
                    extra += (f", {sum(r.unknown_prims.values())} "
                              f"bytes-only eqns")
                if r.donation is not None:
                    extra += (f", donation "
                              f"{r.donation['compiled_aliased']}"
                              f"/{r.donation['donated_leaves']} "
                              f"aliased")
                print(f"cost {r.name}: peak "
                      f"{r.peak_live_bytes / 1e6:.2f}MB, "
                      f"{r.flops / 1e6:.2f} MFLOP, traffic "
                      f"{r.traffic_bytes / 1e6:.2f}MB, blowup "
                      f"{r.max_blowup}x{extra}")
            if scaling is not None:
                for name, row in sorted(scaling["entries"].items()):
                    flag = ("  ** SUPER-LINEAR **"
                            if row["superlinear"] else "")
                    print(f"cost-scaling {name}: peak exponent "
                          f"{row['exponent']} over nodes "
                          f"{scaling['node_counts']}{flag}")
            for f in findings:
                print(f.render())
            for p in problems:
                print(f"COST FAIL: {p}")
        failed |= bool(problems) or bool(findings)

    if run_comms_stage:
        from . import comms
        comm_path = comms.COMM_BASELINE_PATH
        comm_base = (comms.load_comm_baseline(comm_path)
                     if os.path.exists(comm_path) else {})
        reports = comms.run_comms(names, traces=shared_traces)
        # KAI302 drift is mesh-level, not per-entry: always checked
        # when the stage runs, regardless of --ops narrowing
        drift = comms.check_declared_shardings()
        findings = comms.comm_findings(reports, comm_base, extra=drift)
        lowering_names = tuple(
            n for n in comms.LOWERING_ENTRIES
            if names is None or n in names)
        lowering = (comms.lowering_check(lowering_names,
                                         reports=reports)
                    if lowering_names else [])
        lowering_probs = comms.lowering_problems(lowering)
        if args.update_baseline:
            # measured collective counts / byte totals are absorbed;
            # KAI3xx findings (absolute-threshold rules the refresh
            # cannot absorb — only a hand-justified baseline row can)
            # and a failed (or UNVERIFIABLE) lowering cross-validation
            # have no legitimate new value, so they block the rewrite
            # — and hold the deferred probe/cost writes back too,
            # joint or nothing (KAI202 precedent)
            kai3 = [f for f in findings if f.code.startswith("KAI3")]
            problems = list(lowering_probs)
            if kai3 or problems:
                if not args.as_json:
                    print("comm baseline NOT updated — sharding "
                          "drift / lowering failures first:")
                    if cost_update_ok:
                        print("cost baseline NOT updated — comms "
                              "stage blocked the joint refresh")
                    if probe_update_ok:
                        print("probe baseline NOT updated — comms "
                              "stage blocked the joint refresh")
            elif probe_update_ok is False or cost_update_ok is False:
                blocker = ("probe invariant" if probe_update_ok is
                           False else "cost donation")
                if not args.as_json:
                    print(f"comm baseline NOT updated — {blocker} "
                          f"failures blocked the joint refresh")
            else:
                comms.update_comm_baseline(reports, comm_path)
                if not args.as_json:
                    print(f"comm baseline updated: {comm_path}")
                if cost_reports_pending is not None:
                    costmodel.update_cost_baseline(
                        cost_reports_pending, cost_path)
                    if not args.as_json:
                        print(f"cost baseline updated: {cost_path}")
                if probe_update_ok:
                    from .trace_probe import update_baseline
                    update_baseline(probe_reports, baseline_path)
                    if not args.as_json:
                        print(f"probe baseline updated: "
                              f"{baseline_path}")
        else:
            problems = comms.check_against_comm_baseline(
                reports, comm_base, full_coverage=not args.ops)
            problems += lowering_probs
        scaling = (comms.comm_scaling_report(reports=reports)
                   if args.scaling else None)
        out["comms"] = [r.doc() for r in reports]
        out["comms_problems"] = problems
        out["comms_findings"] = [f.__dict__ for f in findings]
        out["comms_lowering"] = lowering
        if scaling is not None:
            out["comms_scaling"] = scaling
        if not args.as_json:
            for r in reports:
                kinds = ",".join(r.kinds) if r.kinds else "none"
                print(f"comms {r.name}: {r.collective_sites} "
                      f"collective sites, "
                      f"{r.comm_bytes / 1e6:.2f}MB modeled "
                      f"({r.loop_comm_bytes / 1e6:.2f}MB under "
                      f"loops), kinds [{kinds}]")
            for d in lowering:
                mark = "verified" if d["verified"] else "UNVERIFIED"
                print(f"comms-lowering {d['entry']}: {mark} on "
                      f"{d['num_devices']} devices, hlo "
                      f"{d['hlo']}")
            if scaling is not None:
                for name, row in sorted(scaling["entries"].items()):
                    flag = ("" if row["sublinear"]
                            else "  ** SUPRA-LINEAR **")
                    print(f"comms-scaling {name}: comm-bytes "
                          f"exponent {row['exponent']} over devices "
                          f"{scaling['device_counts']}{flag}")
            for f in findings:
                print(f.render())
            for p in problems:
                print(f"COMMS FAIL: {p}")
        failed |= bool(problems) or bool(findings)
        if args.update_baseline and (probe_update_ok is False
                                     or cost_update_ok is False):
            failed = True

    if args.as_json:
        json.dump(out, sys.stdout, indent=2, default=str)
        print()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

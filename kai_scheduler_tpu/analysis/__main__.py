"""``python -m kai_scheduler_tpu.analysis`` — the kai-lint CLI.

Default run: layer-1 AST lint over the package (the KAI0xx trace-safety
rules plus the KAI1xx kai-race concurrency pass) and the layer-2 jaxpr
probe.  Exit status is nonzero on any non-baselined finding, so the
command doubles as the CI gate (``scripts/lint.py`` wraps the
lint-only fast path for pre-commit).

    python -m kai_scheduler_tpu.analysis              # lint + probe
    python -m kai_scheduler_tpu.analysis --no-probe   # AST lint only
    python -m kai_scheduler_tpu.analysis --race       # kai-race only
    python -m kai_scheduler_tpu.analysis --json       # machine output
    python -m kai_scheduler_tpu.analysis --list-rules
    python -m kai_scheduler_tpu.analysis --probe --update-baseline
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kai_scheduler_tpu.analysis",
        description="kai-lint: trace-safety, determinism, and "
                    "recompile-hazard analysis for the TPU hot path")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--select", default=None,
                    help="comma-separated KAI codes to run (lint)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON for the lint layer (default: "
                         "the package baseline.json)")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--no-probe", action="store_true",
                      help="skip the jaxpr probe (AST lint only)")
    mode.add_argument("--probe", action="store_true",
                      help="jaxpr probe only (skip the AST lint)")
    mode.add_argument("--race", action="store_true",
                      help="kai-race concurrency pass only (KAI1xx; "
                           "jax-free)")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op names for the probe")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the probe stats in baseline.json")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from .engine import lint_package, load_baseline, rule_catalog
    if args.list_rules:
        for code, title in rule_catalog().items():
            print(f"{code}  {title}")
        return 0

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    root = args.root or os.path.dirname(os.path.dirname(pkg_dir))
    baseline_path = args.baseline or os.path.join(pkg_dir,
                                                  "baseline.json")
    out: dict = {"findings": [], "probe": []}
    failed = False

    if not args.probe:
        baseline = (load_baseline(baseline_path)
                    if os.path.exists(baseline_path) else [])
        select = (args.select.split(",") if args.select else None)
        if args.race:
            from .concurrency import race_codes
            select = list(race_codes()) if select is None else [
                c for c in select if c in race_codes()]
            if not select:
                # --select named no KAI1xx code: running zero rules
                # would print a FALSE "0 findings" clean bill
                ap.error("--race with --select requires at least one "
                         "KAI1xx code")
        res = lint_package(root, select=select, baseline=baseline)
        out["findings"] = [f.__dict__ for f in res.findings]
        out["baselined"] = res.baselined
        if res.race is not None:
            # the kai-race layer's report: discovered thread roots and
            # the KAI1xx slice of the findings (consumed by the CLI
            # smoke test and any tooling watching the race surface)
            race_findings = [f.__dict__ for f in res.findings
                             if f.code.startswith("KAI1")]
            out["race"] = {
                "thread_roots": {
                    r.root_id: {"kind": r.kind, "multi": r.multi}
                    for r in res.race.roots},
                "findings": race_findings,
                "live_annotations": res.race.live_annotations,
                "declared_attrs": len(res.race.disciplines),
            }
        if not args.as_json:
            for f in res.findings:
                print(f.render())
            n = len(res.findings)
            extra = ""
            if res.race is not None:
                extra = (f", {len(res.race.roots)} thread roots, "
                         f"{res.race.live_annotations} live guarded-by "
                         f"annotations")
            print(f"kai-lint: {n} finding{'s' * (n != 1)} "
                  f"({res.raw_count} raw, {res.baselined} baselined, "
                  f"{len(res.stale_suppressions)} stale suppressions"
                  f"{extra})")
        failed |= bool(res.findings)

    if args.race:
        if args.as_json:
            json.dump(out, sys.stdout, indent=2, default=str)
            print()
        return 1 if failed else 0

    if not args.no_probe:
        from .trace_probe import (check_against_baseline,
                                  check_invariants, load_stats_baseline,
                                  run_probe, update_baseline)
        reports = run_probe(args.ops.split(",") if args.ops else None)
        if args.update_baseline:
            # the baseline only absorbs eqn/const stats; callbacks,
            # f64, and cache misses have no legitimate new value and
            # still fail (and block the rewrite) here
            problems = check_invariants(reports)
            if problems:
                if not args.as_json:
                    print("probe baseline NOT updated — invariant "
                          "failures first:")
            else:
                update_baseline(reports, baseline_path)
                if not args.as_json:
                    print(f"probe baseline updated: {baseline_path}")
        else:
            stats = (load_stats_baseline(baseline_path)
                     if os.path.exists(baseline_path) else {})
            problems = check_against_baseline(
                reports, stats, full_coverage=not args.ops)
        out["probe"] = [r.__dict__ for r in reports]
        out["probe_problems"] = problems
        if not args.as_json:
            for r in reports:
                hit = {True: "cache-hit", False: "CACHE-MISS",
                       None: "cache-n/a"}[r.cache_hit]
                print(f"probe {r.name}: {r.eqns} eqns, "
                      f"{r.const_bytes}B consts, {hit}")
            for p in problems:
                print(f"PROBE FAIL: {p}")
        failed |= bool(problems)

    if args.as_json:
        json.dump(out, sys.stdout, indent=2, default=str)
        print()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""Pod admission webhooks — mutation + validation.

Reference mapping:

- :class:`PodMutator` ≡ ``pod_mutator.go:54-63`` — gate on scheduler
  name, default the queue label, translate fraction annotations into the
  pod's resource request (the reference injects env vars the device
  runtime reads; here the portion is a first-class field).
- :class:`PodValidator` ≡ the gpusharing validating webhook — reject
  fractions outside (0, 1], mixed whole+fraction requests, and
  memory-based requests alongside portions.
"""
from __future__ import annotations

import dataclasses

from ..apis import types as apis

SCHEDULER_NAME = "kai-scheduler-tpu"
QUEUE_LABEL = "kai.scheduler/queue"
PORTION_ANNOTATION = "kai.scheduler/accel-fraction"
MEMORY_ANNOTATION = "kai.scheduler/accel-memory-gib"


class AdmissionError(ValueError):
    """A validating webhook rejection."""


@dataclasses.dataclass
class PodMutator:
    """Mutating webhook: defaults + fraction translation."""

    default_queue: str = "default"
    scheduler_name: str = SCHEDULER_NAME

    def mutate(self, pod: apis.Pod,
               annotations: dict[str, str] | None = None,
               labels: dict[str, str] | None = None) -> apis.Pod:
        """Apply admission mutations in place (returns the pod).

        ``annotations``/``labels`` are the pod's metadata as a workload
        operator would set them (the reference reads them off the pod
        object; our Pod keeps resources first-class).
        """
        annotations = annotations or {}
        labels = labels or {}
        if PORTION_ANNOTATION in annotations and pod.accel_portion == 0:
            pod.accel_portion = float(annotations[PORTION_ANNOTATION])
        if MEMORY_ANNOTATION in annotations and pod.accel_memory_gib == 0:
            pod.accel_memory_gib = float(annotations[MEMORY_ANNOTATION])
        if not pod.node_selector and "kai.scheduler/node-selector" in annotations:
            for kv in annotations["kai.scheduler/node-selector"].split(","):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    pod.node_selector[k.strip()] = v.strip()
        return pod

    def queue_for(self, labels: dict[str, str] | None) -> str:
        return (labels or {}).get(QUEUE_LABEL, self.default_queue)


@dataclasses.dataclass
class PodValidator:
    """Validating webhook: fraction sanity — ref gpusharing webhook."""

    def validate(self, pod: apis.Pod) -> None:
        frac = pod.accel_portion
        mem = pod.accel_memory_gib
        whole = pod.resources.accel
        if frac < 0:
            raise AdmissionError(
                f"pod {pod.name}: accel fraction {frac} is negative")
        if frac > 1:
            raise AdmissionError(
                f"pod {pod.name}: accel fraction {frac} exceeds one device"
                " — request whole devices instead")
        if mem < 0:
            raise AdmissionError(
                f"pod {pod.name}: accel memory {mem} GiB is negative")
        if frac > 0 and mem > 0:
            raise AdmissionError(
                f"pod {pod.name}: fraction and memory-based accel requests"
                " are mutually exclusive")
        if (frac > 0 or mem > 0) and whole > 0:
            raise AdmissionError(
                f"pod {pod.name}: whole-device request ({whole}) cannot be"
                " combined with a fractional/memory request")
        if whole != int(whole):
            raise AdmissionError(
                f"pod {pod.name}: whole-device accel request must be an"
                f" integer, got {whole} (use fractions for sharing)")


@dataclasses.dataclass
class RuntimeEnforcement:
    """Mutating hook — ref ``webhook/v1alpha2/runtimeenforcement``:
    accelerator pods get the accelerator runtime class unless they set
    their own (reservation pods are exempt in the reference; the TPU
    runtime's equivalent knob is the runtime-class label)."""

    name: str = "runtimeenforcement"
    accel_runtime_class: str = "tpu-runtime"
    RUNTIME_CLASS_LABEL = "kai.scheduler/runtime-class"

    def validate(self, pod: apis.Pod) -> None:
        return None

    def mutate(self, pod: apis.Pod,
               annotations: dict[str, str] | None = None,
               labels: dict[str, str] | None = None) -> apis.Pod:
        needs_accel = (pod.resources.accel > 0 or pod.accel_portion > 0
                       or pod.accel_memory_gib > 0 or pod.dra_accel_count > 0
                       or bool(pod.resource_claims))
        if needs_accel and not pod.labels.get(self.RUNTIME_CLASS_LABEL):
            pod.labels[self.RUNTIME_CLASS_LABEL] = self.accel_runtime_class
        return pod


@dataclasses.dataclass
class GpuSharingGate:
    """Validating hook — ref ``webhook/v1alpha2/gpusharing``: fractional
    requests are rejected outright when sharing is disabled cluster-wide;
    otherwise the request-shape checks of :class:`PodValidator` apply."""

    name: str = "gpusharing"
    sharing_enabled: bool = True

    def validate(self, pod: apis.Pod) -> None:
        if not self.sharing_enabled and (pod.accel_portion > 0
                                         or pod.accel_memory_gib > 0):
            raise AdmissionError(
                f"pod {pod.name} requests accelerator sharing while GPU "
                "sharing is disabled")
        PodValidator().validate(pod)

    def mutate(self, pod: apis.Pod,
               annotations: dict[str, str] | None = None,
               labels: dict[str, str] | None = None) -> apis.Pod:
        return pod


@dataclasses.dataclass
class AdmissionChain:
    """The admission plugin chain — ref ``admission/plugins/plugins.go``
    registering podhooks + gpusharing + runtimeenforcement: every
    incoming pod runs each plugin's Mutate then each plugin's Validate;
    the first :class:`AdmissionError` rejects the pod."""

    mutator: PodMutator = dataclasses.field(default_factory=PodMutator)
    plugins: list = dataclasses.field(default_factory=lambda: [
        GpuSharingGate(), RuntimeEnforcement()])

    def admit(self, pod: apis.Pod,
              annotations: dict[str, str] | None = None,
              labels: dict[str, str] | None = None) -> apis.Pod:
        pod = self.mutator.mutate(pod, annotations, labels)
        for plugin in self.plugins:
            pod = plugin.mutate(pod, annotations, labels)
        for plugin in self.plugins:
            plugin.validate(pod)
        return pod

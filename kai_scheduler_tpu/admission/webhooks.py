"""Pod admission webhooks — mutation + validation.

Reference mapping:

- :class:`PodMutator` ≡ ``pod_mutator.go:54-63`` — gate on scheduler
  name, default the queue label, translate fraction annotations into the
  pod's resource request (the reference injects env vars the device
  runtime reads; here the portion is a first-class field).
- :class:`PodValidator` ≡ the gpusharing validating webhook — reject
  fractions outside (0, 1], mixed whole+fraction requests, and
  memory-based requests alongside portions.
"""
from __future__ import annotations

import dataclasses

from ..apis import types as apis

SCHEDULER_NAME = "kai-scheduler-tpu"
QUEUE_LABEL = "kai.scheduler/queue"
PORTION_ANNOTATION = "kai.scheduler/accel-fraction"
MEMORY_ANNOTATION = "kai.scheduler/accel-memory-gib"


class AdmissionError(ValueError):
    """A validating webhook rejection."""


@dataclasses.dataclass
class PodMutator:
    """Mutating webhook: defaults + fraction translation."""

    default_queue: str = "default"
    scheduler_name: str = SCHEDULER_NAME

    def mutate(self, pod: apis.Pod,
               annotations: dict[str, str] | None = None,
               labels: dict[str, str] | None = None) -> apis.Pod:
        """Apply admission mutations in place (returns the pod).

        ``annotations``/``labels`` are the pod's metadata as a workload
        operator would set them (the reference reads them off the pod
        object; our Pod keeps resources first-class).
        """
        annotations = annotations or {}
        labels = labels or {}
        if PORTION_ANNOTATION in annotations and pod.accel_portion == 0:
            pod.accel_portion = float(annotations[PORTION_ANNOTATION])
        if MEMORY_ANNOTATION in annotations and pod.accel_memory_gib == 0:
            pod.accel_memory_gib = float(annotations[MEMORY_ANNOTATION])
        if not pod.node_selector and "kai.scheduler/node-selector" in annotations:
            for kv in annotations["kai.scheduler/node-selector"].split(","):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    pod.node_selector[k.strip()] = v.strip()
        return pod

    def queue_for(self, labels: dict[str, str] | None) -> str:
        return (labels or {}).get(QUEUE_LABEL, self.default_queue)


@dataclasses.dataclass
class PodValidator:
    """Validating webhook: fraction sanity — ref gpusharing webhook."""

    def validate(self, pod: apis.Pod) -> None:
        frac = pod.accel_portion
        mem = pod.accel_memory_gib
        whole = pod.resources.accel
        if frac < 0:
            raise AdmissionError(
                f"pod {pod.name}: accel fraction {frac} is negative")
        if frac > 1:
            raise AdmissionError(
                f"pod {pod.name}: accel fraction {frac} exceeds one device"
                " — request whole devices instead")
        if mem < 0:
            raise AdmissionError(
                f"pod {pod.name}: accel memory {mem} GiB is negative")
        if frac > 0 and mem > 0:
            raise AdmissionError(
                f"pod {pod.name}: fraction and memory-based accel requests"
                " are mutually exclusive")
        if (frac > 0 or mem > 0) and whole > 0:
            raise AdmissionError(
                f"pod {pod.name}: whole-device request ({whole}) cannot be"
                " combined with a fractional/memory request")
        if whole != int(whole):
            raise AdmissionError(
                f"pod {pod.name}: whole-device accel request must be an"
                f" integer, got {whole} (use fractions for sharing)")

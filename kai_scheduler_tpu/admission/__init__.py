"""Admission — pod mutating/validating webhooks (intake layer 5).

Reference: ``pkg/admission/webhook/v1alpha2/`` — the mutating webhook
stamps the scheduler name and injects GPU-sharing env/annotations
(``podhooks/pod_mutator.go:54-63``); validating webhooks reject
malformed fraction requests (gpusharing webhook) and enforce runtime
class rules (runtimeenforcement).
"""
from .webhooks import AdmissionError, PodMutator, PodValidator

__all__ = ["AdmissionError", "PodMutator", "PodValidator"]

"""Mesh + sharding layout — scale the *node axis* across TPU devices.

The reference scales by sharding the cluster across scheduler instances
(SchedulingShard CRD, one process per node-pool partition) and by
goroutine fan-out over nodes inside a cycle (``framework/session.go:234``).
The TPU equivalent (SURVEY.md §2.9): one logical scheduler whose
node-axis tensors are sharded over a ``jax.sharding.Mesh``; XLA inserts
the ICI collectives (the argmax/any reductions over nodes become
AllReduce) — scoring all nodes in parallel the way goroutines never
could.  DCN multi-slice would add an outer mesh axis; out of scope for
the solver itself.

Design note: the per-gang scan stays sequential (job order is semantics,
SURVEY.md §7 hard-part 1); only the node dimension is spatial.  Queue,
gang, and running-pod tensors are replicated — they are tiny next to
[N, R] and [N, K] at 10k nodes.
"""
from __future__ import annotations

import os
import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime import wire_ledger
from ..state.cluster_state import ClusterState

NODE_AXIS = "nodes"

#: the ONE virtual CPU device count every multi-device consumer forces
#: (tests/conftest.py, __graft_entry__'s dryrun, and the kai-comms
#: lowering stage) — hoisted here so two callers in one process can
#: never ask XLA for different counts
VIRTUAL_DEVICE_COUNT = 8


def ensure_virtual_cpu_devices(
        n_devices: int = VIRTUAL_DEVICE_COUNT) -> None:
    """Ask XLA for ``n_devices`` virtual CPU devices (no-op once the
    CPU backend has initialised).  Rewrites an existing smaller count
    rather than only appending, so an inherited flag can be repaired.
    Pure env-var surgery: importing this module does NOT initialise
    any jax backend, so callers (tests/conftest.py before its own
    ``import jax``, ``__graft_entry__`` at import time, the kai-comms
    lowering stage) may call it ahead of first backend use."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  flags)
    if m is None:
        flags = (flags + " --xla_force_host_platform_device_count="
                 f"{n_devices}")
    elif int(m.group(1)) < n_devices:
        flags = flags[:m.start(1)] + str(n_devices) + flags[m.end(1):]
    os.environ["XLA_FLAGS"] = flags.strip()


def make_mesh(devices: list | None = None, axis: str = NODE_AXIS) -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def node_sharding(mesh: Mesh, axis: str = NODE_AXIS) -> NamedSharding:
    """Shard dim 0 (the node axis), replicate trailing dims."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def state_shardings(state: ClusterState, mesh: Mesh, axis: str = NODE_AXIS):
    """A ClusterState-shaped pytree of NamedShardings: node-axis arrays
    sharded over the mesh, everything else replicated."""
    shard = node_sharding(mesh, axis)
    repl = replicated(mesh)
    node_shards = jax.tree.map(lambda _: shard, state.nodes)
    # per-filter-class tables carry the node axis SECOND ([X, N]); shard
    # that axis and replicate the (small, unpadded) class axis
    class_by_node = NamedSharding(mesh, P(None, axis))
    node_shards = node_shards.replace(
        filter_masks=class_by_node, soft_scores=class_by_node)
    return jax.tree.map(lambda _: repl, state).replace(nodes=node_shards)


def shard_state(state: ClusterState, mesh: Mesh, axis: str = NODE_AXIS) -> ClusterState:
    """Place a host snapshot onto the mesh with the framework layout.

    Requires the padded node axis to divide the mesh size —
    ``build_snapshot(pad=...)`` already rounds up; pass
    ``pad=mesh.size`` (or a multiple) when building snapshots destined
    for a mesh.
    """
    n = state.nodes.valid.shape[0]
    if n % mesh.size != 0:
        raise ValueError(
            f"node axis {n} not divisible by mesh size {mesh.size}; "
            f"build the snapshot with pad={mesh.size}")
    # through the kai-wire TransferLedger (KAI071): mesh placements get
    # their own residency site — sharded buffers supersede each other,
    # never the single-device snapshot's
    return wire_ledger.LEDGER.device_put(
        state, state_shardings(state, mesh, axis),
        reason=wire_ledger.REASON_MESH_SHARD, site="mesh",
        replace_site=True)

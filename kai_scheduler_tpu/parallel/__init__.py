from .mesh import (NODE_AXIS, make_mesh, node_sharding, replicated,
                   shard_state, state_shardings)

__all__ = ["NODE_AXIS", "make_mesh", "node_sharding", "replicated",
           "shard_state", "state_shardings"]

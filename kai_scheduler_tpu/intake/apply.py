"""Delta-document decomposition + sequential apply — ONE implementation
for both intake paths.

The kai-intake differential bar (ISSUE 12): a mutation storm routed
through the async lanes must produce a hub journal — and therefore
scheduling cycles — bit-identical to the same events applied
sequentially through the classic synchronous path.  The way to make
that provable rather than hopeful is to share the code: the classic
``POST /cluster/delta`` handler and the router's ``coalesce()`` both
decompose delta documents into the same ordered event stream
(:func:`decompose_delta`) and both replay it through the same
single-event applier (:func:`apply_events`).  The async path differs
ONLY in *when* events apply (at cycle boundaries, in global
sequence-number order) — never in *how*.

Journal marks batch through ``MutationJournal.merge`` (one lock
acquisition per chunk instead of one per event), with the mark mapping
owned by the gate (``intake/gate.py``, KAI091's choke point).
"""
from __future__ import annotations

import copy
import dataclasses
import enum
import gc
import math

import numpy as np

from ..apis import types as apis
from ..runtime import snapshot as snap
from . import gate

#: canonical apply order of a delta document's collections — the order
#: the classic handler has always used (dict-insertion order of its
#: parser table); the router assigns sequence numbers in this order so
#: the two paths replay identically
COLLECTIONS = gate.COLLECTIONS

_PARSERS = {
    "nodes": snap._node,
    "queues": snap._queue,
    "pod_groups": snap._pod_group,
    "pods": snap._pod,
    "bind_requests": snap._bind_request,
    "resource_claims": lambda d: apis.ResourceClaim(**d),
    "device_classes": lambda d: apis.DeviceClass(**d),
    "volume_claims": lambda d: apis.PersistentVolumeClaim(**d),
    "storage_classes": lambda d: apis.StorageClass(**d),
}

_DEFAULT_FACTORIES = {
    "nodes": lambda: apis.Node(name=""),
    "queues": lambda: apis.Queue(name=""),
    "pod_groups": lambda: apis.PodGroup(name="", queue=""),
    "pods": lambda: apis.Pod(name="", group=""),
    "bind_requests": lambda: apis.BindRequest(pod_name="",
                                              selected_node=""),
    "resource_claims": lambda: apis.ResourceClaim(name=""),
    "device_classes": lambda: apis.DeviceClass(name=""),
    "volume_claims": lambda: apis.PersistentVolumeClaim(name=""),
    "storage_classes": lambda: apis.StorageClass(name=""),
}


def _default_doc(coll: str) -> dict:
    """A FRESH default document per call — the parsers store some
    nested values (plain lists/dicts) verbatim on the constructed
    object, so a cached template would alias one container across
    every object ever defaulted from it."""
    return snap._to_jsonable(_DEFAULT_FACTORIES[coll]())


# -- fast pod construction (the storm-dominant create path) ---------------
#
# The generic path for a NEW object renders the default doc, merges,
# and re-parses EVERY field through the snapshot parser (~13 µs per
# pod) — the single biggest term in the 1M-event storm's coalesce.
# New *plain* pods skip it: shared immutable defaults + fresh mutable
# containers + the two converted fields, assembled directly.  The fast
# path must stay value-identical to ``_PARSERS["pods"](default|doc)``
# — ``tests/test_intake_router.py`` drift-guards it on randomized
# docs, and any doc touching a parser-converted irregular field
# (tolerations/affinity) or an unknown key falls back to the parser.

#: doc keys that force the generic parser (list-of-struct conversions)
_POD_SLOW_KEYS = frozenset({"tolerations", "node_affinity",
                            "pod_affinity"})


def _pod_fast_tables() -> tuple[dict, list, frozenset]:
    pod = _DEFAULT_FACTORIES["pods"]()
    shared: dict = {}
    fresh: list = []
    for f in dataclasses.fields(pod):
        v = getattr(pod, f.name)
        if isinstance(v, (list, dict, set)):
            fresh.append((f.name, type(v)))
        elif v is None or isinstance(v, (str, int, float, bool, tuple,
                                         enum.Enum)):
            shared[f.name] = v
        elif type(v)() == v:
            # default-constructed value object (ResourceVec()): a
            # fresh instance per pod, never shared across objects
            fresh.append((f.name, type(v)))
        else:
            # non-trivial non-scalar default: deep-copied per object
            fresh.append((f.name, lambda v=v: copy.deepcopy(v)))
    known = frozenset(shared) | frozenset(n for n, _f in fresh) \
        | {"resources", "status"}
    return shared, fresh, known


_POD_SHARED, _POD_FRESH, _POD_KNOWN_KEYS = None, None, None


def _fast_new_pod(doc: dict):
    """A brand-new pod from a delta doc, bypassing the default-doc
    render + full re-parse.  Returns None when the doc needs the
    generic parser (irregular/unknown fields)."""
    global _POD_SHARED, _POD_FRESH, _POD_KNOWN_KEYS
    if _POD_SHARED is None:
        _POD_SHARED, _POD_FRESH, _POD_KNOWN_KEYS = _pod_fast_tables()
    keys = doc.keys()
    if not (keys <= _POD_KNOWN_KEYS) or keys & _POD_SLOW_KEYS:
        return None
    d = dict(_POD_SHARED)
    for name, factory in _POD_FRESH:
        if name not in keys:  # doc values land below; don't build twice
            d[name] = factory()
    for k, v in doc.items():
        if k == "resources":
            v = apis.ResourceVec(**v)
        elif k == "status":
            v = apis.PodStatus(v)
        d[k] = v
    obj = object.__new__(apis.Pod)
    obj.__dict__ = d
    return obj


class IntakeEvent:
    """One decomposed mutation: an upsert/delete of one object, or a
    clock advance.  ``seq`` is the router-assigned global sequence
    number (submission order); ``key`` the lane-routing key (the
    entity's identity — same entity, same lane, so per-entity ordering
    survives sharding)."""

    __slots__ = ("seq", "op", "coll", "key", "payload")

    def __init__(self, seq: int, op: str, coll: str, key: str, payload):
        self.seq = seq
        self.op = op          # "upsert" | "delete" | "now"
        self.coll = coll      # collection attr; "" for "now"
        self.key = key        # routing key; "" for "now"
        self.payload = payload  # upsert doc | delete name | now float

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"IntakeEvent(seq={self.seq}, op={self.op!r}, "
                f"coll={self.coll!r}, key={self.key!r})")


def decompose_delta(delta: dict) -> list[tuple[str, str, str, object]]:
    """A delta document → ordered ``(op, coll, key, payload)`` list, in
    the canonical collection order (upserts before deletes per
    collection, matching the classic handler's iteration)."""
    out: list[tuple[str, str, str, object]] = []
    for coll in COLLECTIONS:
        for doc in delta.get(f"{coll}_upsert", []):
            key = ""
            if isinstance(doc, dict):
                key = doc.get("name") or doc.get("pod_name") or ""
            out.append(("upsert", coll, key, doc))
        for name in delta.get(f"{coll}_delete", []):
            out.append(("delete", coll, name, name))
    if "now" in delta:
        out.append(("now", "", "", delta["now"]))
    return out


def apply_event(cluster, op: str, coll: str, payload,
                marks: list) -> None:
    """Apply ONE event to the hub, appending its journal mark ops to
    ``marks`` (the caller merges them in batch).  Exactly the classic
    per-event semantics: partial upsert docs merge over the existing
    object when the key is stored, over defaults for new objects."""
    if op == "now":
        cluster.now = float(payload)
        marks.append(("time", ""))
        return
    store = getattr(cluster, coll)
    if op == "upsert":
        doc = payload
        key0 = doc.get("name") or doc.get("pod_name")
        obj = None
        if coll == "pods" and key0 not in store:
            obj = _fast_new_pod(doc)
        if obj is None:
            if key0 in store:
                full = snap._to_jsonable(store[key0])
            else:
                full = _default_doc(coll)  # fresh per call
            full.update(doc)
            obj = _PARSERS[coll](full)
        key = getattr(obj, "name", None) or obj.pod_name
        gate.upsert_marks(coll, key, obj, key in store, marks)
        store[key] = obj
    else:
        name = payload
        gate.delete_marks(coll, name, name in store, marks)
        store.pop(name, None)


#: flush journal marks every this-many events during a bulk apply so a
#: 1M-event coalesce never holds a million mark tuples at once
_MARK_CHUNK = 65536


def apply_events(cluster, events, errors: list | None = None) -> int:
    """Replay decomposed events against the hub in order, merging their
    journal marks in chunked batches.  ``events`` may be raw
    ``(op, coll, key, payload)`` tuples or :class:`IntakeEvent`\\ s.

    Error policy: with ``errors=None`` (the classic synchronous path)
    the first failing event raises — the caller gets its HTTP 400 and
    the applied prefix stays journaled.  With an ``errors`` list (the
    router's coalesce, where submitters were already acknowledged and
    one client's poisoned doc must never destroy other clients'
    accepted events) failing events are skipped and recorded as
    ``(seq, reason)``.

    The generational GC is suspended for the duration: a bulk apply
    allocates one long-lived object graph per event (pods, docs, mark
    tuples) and produces no reference cycles, but the allocation rate
    trips collection thresholds constantly — measured ~3x slowdown on
    a 100k-create storm with the collector left running."""
    journal = cluster.journal
    # kai-twin choke point: when a recorder is attached to the hub,
    # every event this call successfully applies is mirrored into its
    # stream (AFTER the journal merge below) — recording the APPLIED
    # sequence, never the offered one, is what makes a recorded stream
    # replayable bit-exact through this same function
    recorder = getattr(cluster, "twin_recorder", None)
    applied: list | None = [] if recorder is not None else None
    marks: list = []
    n = 0
    gc_was_on = gc.isenabled()
    if gc_was_on:
        gc.disable()
    try:
        for ev in events:
            if isinstance(ev, IntakeEvent):
                op, coll, key, payload = ev.op, ev.coll, ev.key, ev.payload
            else:
                op, coll, key, payload = ev
            if errors is None:
                apply_event(cluster, op, coll, payload, marks)
            else:
                try:
                    apply_event(cluster, op, coll, payload, marks)
                except Exception as exc:  # noqa: BLE001 — skip-and-
                    # record: the event was admitted, but admission is
                    # a door check, not a proof the applier accepts it
                    errors.append((getattr(ev, "seq", n), str(exc)))
                    n += 1
                    continue
            if applied is not None:
                applied.append((op, coll, key, payload))
            n += 1
            if len(marks) >= _MARK_CHUNK:
                # swap-before-merge: if the merge raises mid-chunk the
                # chunk is NOT retried (at-most-once — duplicate list
                # marks would corrupt cursors, while a lost mark is
                # caught by the snapshotter's drift sweep and falls
                # back to a full rebuild)
                chunk, marks = marks, []
                gate.merge_marks(journal, chunk)
    finally:
        # the merge runs even when an event mid-batch raises (a
        # malformed doc aborting a delta): every store mutation that
        # DID apply must reach the journal, or the incremental
        # snapshotter serves a silently stale patch — the exact
        # invariant the per-event marking this replaced maintained.
        # The nested finally keeps gc.enable() unconditional: a merge
        # failure must never leave the process with the collector off.
        try:
            chunk, marks = marks, []
            gate.merge_marks(journal, chunk)
        finally:
            if gc_was_on:
                gc.enable()
        # record the applied PREFIX even when a classic-path event
        # raised mid-batch: what reached the journal is what the twin
        # must replay
        if recorder is not None and applied:
            recorder.record_events(applied)
            from ..framework import metrics
            metrics.twin_recorded_events.inc(by=len(applied))
    return n


def apply_cluster_delta(cluster, delta: dict) -> int:
    """The classic synchronous path: decompose + apply in one call
    (``POST /cluster/delta``'s body).  Returns the event count."""
    return apply_events(cluster, decompose_delta(delta))


# ---------------------------------------------------------------------------
# batched admission
# ---------------------------------------------------------------------------

#: scalar pod fields that must be finite and non-negative
_POD_SCALARS = ("accel_portion", "accel_memory_gib", "dra_accel_count")

#: an absurd per-object resource bound — a fat-fingered 1e30-CPU pod
#: must bounce at the door, not poison every fair-share division
RESOURCE_CAP = 1.0e9


def admit_batch(batch) -> tuple[list[bool], list[str | None]]:
    """Vectorized admission over one staged lane batch of
    :class:`IntakeEvent`\\ s.

    Structural checks (known collection, dict-shaped upsert doc,
    non-empty key) run per event; the numeric sanity sweep — every
    resource scalar finite, non-negative, below :data:`RESOURCE_CAP`,
    fractional shares within [0, 1] — gathers across the WHOLE batch
    into two flat arrays and judges them in one NumPy pass, replacing
    the per-request field-by-field checks the single-lock intake did.

    Returns ``(ok, reasons)`` aligned with ``batch`` (reason ``None``
    for admitted events).
    """
    n = len(batch)
    ok = [True] * n
    reasons: list[str | None] = [None] * n
    idx: list[int] = []
    vals: list[float] = []
    frac_idx: list[int] = []
    frac_vals: list[float] = []
    for i, ev in enumerate(batch):
        op, coll, key, payload = ev.op, ev.coll, ev.key, ev.payload
        if op == "now":
            try:
                t = float(payload)
            except (TypeError, ValueError):
                t = float("nan")
            if not math.isfinite(t):  # non-numeric / NaN / inf clock
                ok[i], reasons[i] = False, "now: not a finite number"
            continue
        if coll not in _PARSERS:
            ok[i], reasons[i] = False, f"unknown collection {coll!r}"
            continue
        if op == "delete":
            if not isinstance(payload, str) or not payload:
                ok[i], reasons[i] = False, "delete: empty name"
            continue
        doc = payload
        if not isinstance(doc, dict):
            ok[i], reasons[i] = False, "upsert: document must be a mapping"
            continue
        if not key:
            ok[i], reasons[i] = False, "upsert: missing name"
            continue
        try:
            # float() here, not at the np.asarray: a JSON integer wider
            # than a double (1e400 as an int literal) raises
            # OverflowError — per-event that is a clean rejection,
            # inside the batched asarray it would kill the whole batch
            # (and, unguarded, the lane's drain worker)
            bad_shape = False
            for field in ("resources", "allocatable", "capacity"):
                src = doc.get(field)
                if src is None:
                    continue
                if not isinstance(src, dict):
                    # a scalar where a vector doc belongs would pass
                    # admission and then crash the applier at coalesce
                    ok[i], reasons[i] = False, f"{field}: not a mapping"
                    bad_shape = True
                    break
                for v in src.values():
                    if isinstance(v, (int, float)):
                        idx.append(i)
                        vals.append(float(v))
            if bad_shape:
                continue
            for field in _POD_SCALARS:
                v = doc.get(field)
                if isinstance(v, (int, float)):
                    idx.append(i)
                    vals.append(float(v))
            v = doc.get("accel_portion")
            if isinstance(v, (int, float)):
                frac_idx.append(i)
                frac_vals.append(float(v))
        except OverflowError:
            ok[i], reasons[i] = False, "resource value out of range"
            continue
    # f64 on purpose (host-side, allowlisted): a float32 sweep has a
    # 64-unit ulp at the 1e9 cap, so RESOURCE_CAP + 63 (or a portion
    # of 1 + 1e-8) would round ONTO the bound and slip past the door
    # check — the exact class of input it exists to bounce
    if vals:
        arr = np.asarray(vals, dtype=np.float64)
        bad = ~np.isfinite(arr) | (arr < 0.0) | (arr > RESOURCE_CAP)
        for i in np.asarray(idx, dtype=np.int64)[bad].tolist():
            if ok[i]:
                ok[i] = False
                reasons[i] = "resource value out of range"
    if frac_vals:
        arr = np.asarray(frac_vals, dtype=np.float64)
        bad = ~np.isfinite(arr) | (arr < 0.0) | (arr > 1.0)
        for i in np.asarray(frac_idx, dtype=np.int64)[bad].tolist():
            if ok[i]:
                ok[i] = False
                reasons[i] = "accel_portion outside [0, 1]"
    return ok, reasons

"""kai-intake: async, load-shedding, multi-lane mutation intake.

Three modules:

- :mod:`.gate` — the hub-journal write choke point (lint rule KAI091):
  every ``MutationJournal`` mark outside ``state/incremental.py``
  routes through it.  Dependency-free, imported eagerly so the hub's
  own mutators (``runtime/cluster.py``) can use it without cycles.
- :mod:`.apply` — delta decomposition + the single-event applier both
  the classic synchronous path and the router's coalesce share (the
  storm-vs-sequential differential bar holds by shared code, not by
  parallel reimplementation), plus the vectorized admission sweep.
- :mod:`.router` — :class:`IntakeRouter`: hash-sharded bounded lanes,
  per-lane drain workers, batched NumPy admission, cycle-boundary
  coalesce, shed/degrade backpressure.

``IntakeRouter``/``IntakeConfig`` resolve lazily: ``.apply`` imports
the snapshot codec, which imports the cluster hub, which imports
``.gate`` — eager re-export here would close that loop.
"""
from . import gate  # noqa: F401  (dependency-free; the choke point)

_LAZY = ("IntakeRouter", "IntakeConfig")


def __getattr__(name: str):
    if name in _LAZY:
        from . import router
        return getattr(router, name)
    raise AttributeError(name)


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

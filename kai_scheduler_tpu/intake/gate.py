"""The hub-journal mutation gate — kai-intake's write choke point.

Every write into a cluster's :class:`~..state.incremental.MutationJournal`
outside the journal's own module flows through THIS module (lint rule
KAI091 enforces it, mirroring KAI071's wire discipline): the hub's own
mutators (``runtime/cluster.py``), the binder's commit write-backs, the
wire codec's delta appliers, and the intake router's coalesce step all
mark through these helpers.  One choke point buys two things:

- **ordering discipline** — the kai-intake differential bar (a storm
  coalesced through the lanes must be bit-identical to the sequential
  classic path) only holds while every journal write follows the same
  upsert/delete → mark mapping; scattering that mapping across call
  sites is how the two paths drift apart silently;
- **a place to stand** — future per-origin write accounting (the
  TransferLedger precedent) lands here once instead of N times.

The helpers are deliberately thin pass-throughs: the journal's locking
and cursor fan-out live with the journal (``state/incremental.py``);
the gate owns only the *semantic mapping* from object mutations to mark
kinds.  Dependency-free by design so ``runtime/cluster.py`` (which
everything imports) can route through it without cycles.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover — typing (and kai-race) only
    from ..state.incremental import MutationJournal

#: collections whose upsert/delete journal mapping the gate knows; the
#: order is the canonical apply order of a delta document (see
#: ``intake/apply.py`` — both the classic path and the router's
#: coalesce decompose deltas in this order)
COLLECTIONS = ("nodes", "queues", "pod_groups", "pods", "bind_requests",
               "resource_claims", "device_classes", "volume_claims",
               "storage_classes")


# -- hub-mutator marks (runtime/cluster.py, binder) -----------------------

def pod_touched(journal: "MutationJournal", name: str) -> None:
    journal.mark_pod(name)


def pod_added(journal: "MutationJournal", name: str) -> None:
    journal.mark_pod_added(name)


def pod_removed(journal: "MutationJournal", name: str) -> None:
    journal.mark_pod_removed(name)


def gang_touched(journal: "MutationJournal", name: str) -> None:
    journal.mark_gang(name)


def gang_added(journal: "MutationJournal", name: str) -> None:
    journal.mark_gang_added(name)


def node_touched(journal: "MutationJournal", name: str) -> None:
    journal.mark_node(name)


def structural(journal: "MutationJournal", reason: str) -> None:
    journal.mark_structural(reason)


def time_advanced(journal: "MutationJournal") -> None:
    journal.mark_time()


def merge_marks(journal: "MutationJournal", marks) -> None:
    """Bulk-replay an ordered ``(kind, name)`` mark batch — the
    coalesce step's single-lock-acquisition merge (see
    ``MutationJournal.merge``)."""
    journal.merge(marks)


# -- delta-document marks (wire codec + classic/lane delta apply) ---------

def upsert_marks(coll: str, key: str, obj, existed: bool,
                 out: list) -> None:
    """Append the ``(kind, name)`` mark ops an upsert of ``key`` into
    ``coll`` records, to ``out`` — the single source of the wire-delta
    journal mapping (formerly ``wire/codec._journal_upsert``)."""
    if coll == "pods":
        out.append(("pod", key) if existed else ("pod_added", key))
    elif coll == "pod_groups":
        out.append(("gang", key) if existed else ("gang_added", key))
    elif coll == "bind_requests":
        # a Pending BindRequest changes its pod's snapshot presentation
        out.append(("pod", obj.pod_name))
    elif coll == "nodes":
        # node rows anchor vocabularies/masks/device tables — dirty
        # nodes force a full snapshot rebuild either way
        out.append(("node", key) if existed
                   else ("structural", "node-added"))
    elif coll == "queues":
        if not existed:
            out.append(("structural", "queue-added"))
        # field updates on an existing queue re-encode every refresh
    else:
        out.append(("structural", f"{coll}-upsert"))


def delete_marks(coll: str, name: str, existed: bool, out: list) -> None:
    """Append the mark ops a delete records (formerly
    ``wire/codec._journal_delete``)."""
    if not existed:
        return
    if coll == "pods":
        out.append(("pod_removed", name))
    elif coll == "bind_requests":
        out.append(("pod", name))
    else:
        out.append(("structural", f"{coll}-delete"))

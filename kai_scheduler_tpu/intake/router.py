"""kai-intake — async, load-shedding, multi-lane mutation intake.

The reference scheduler targets thousands of nodes and "millions of
users"; at that rate the bottleneck moves from the solve to intake.
Until this module every cluster mutation serialized under
``SchedulerServer._state_lock`` — correct (PR 4), but a single-writer
wall: one slow POST convoys every other mutation behind the commit
lock, with no shed valve and no visibility.

:class:`IntakeRouter` decouples ingest from the scheduler cycle:

- **lanes** — submitted events hash-shard by entity key (pod/gang/node
  name) into N bounded lanes.  Same entity → same lane → FIFO, so
  per-entity ordering survives sharding; cross-entity ordering is
  restored at coalesce time by the global sequence number every event
  gets at submission.
- **workers** — one daemon thread per lane drains queued events in
  batches: structural validation plus a NumPy pass over the whole
  batch's resource scalars (:func:`~.apply.admit_batch`) replaces the
  old per-request checks.  Admitted events stage in the lane, off the
  commit path.
- **coalesce** — at cycle boundaries (the ``POST /cycle/stored``
  handler, under the now commit-side-only ``_state_lock``) the staged
  events of every lane merge, sort by sequence number, and replay
  through the SAME single-event applier as the classic synchronous
  path (``intake/apply.py``), with journal marks bulk-merged into the
  hub ``MutationJournal`` one lock acquisition per chunk.  PR 1's
  journal semantics and PR 11's packed-delta path see an ordinary —
  just batched — mutation stream.
- **backpressure** — a lane is bounded by ``lane_capacity`` counting
  queued AND staged events.  Overflow either sheds (the whole offered
  group, atomically — a shed request never half-writes; HTTP maps it
  to 429) or degrades to sync (``policy="sync"``: the submitter drains
  the lanes inline, flushes a coalesce through the server's commit
  lock, and retries — the old single-writer behavior, now the
  *fallback* instead of the steady state).  Shed/depth/degrade are
  metered (``kai_intake_*``) and served by ``GET /debug/intake``.

The differential bar — a storm through the lanes must yield a hub
journal and next-cycle binds/DecisionLog bit-identical to the same
events applied sequentially through the classic path — holds by
construction (shared applier, global seq order) and is pinned by
``tests/test_intake_router.py``.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from operator import attrgetter

from ..framework import metrics
from . import apply as _apply
from .apply import IntakeEvent


@dataclasses.dataclass(frozen=True)
class IntakeConfig:
    """Router knobs (``SchedulerConfig.intake_*`` / conf ``intake.*``)."""

    #: hash-shard lane count (one drain worker per lane)
    lanes: int = 4
    #: per-lane bound on queued + staged events; overflow sheds or
    #: degrades to sync
    lane_capacity: int = 65536
    #: overflow policy: "shed" (atomic per-group refusal, HTTP 429) or
    #: "sync" (drain inline + flush a coalesce, then retry — degrade to
    #: the classic single-writer behavior instead of dropping)
    policy: str = "shed"
    #: max events a worker pops per drain round (the admission batch —
    #: the NumPy sweep vectorizes over it)
    batch: int = 512

    def __post_init__(self):
        if self.lanes < 1:
            raise ValueError("intake lanes must be >= 1")
        if self.lane_capacity < 1:
            raise ValueError("intake lane_capacity must be >= 1")
        if self.policy not in ("shed", "sync"):
            raise ValueError(f"unknown intake policy {self.policy!r}")
        if self.batch < 1:
            raise ValueError("intake batch must be >= 1")


class _Lane:
    """One bounded intake lane.  All mutable state lives under the
    lane's own lock; holders never call out while holding it (no
    nested locks, no blocking calls — kai-race KAI103/KAI105)."""

    __slots__ = ("idx", "capacity", "wake", "drain_lock", "_lock",
                 "queued", "staged", "inflight", "accepted", "shed",
                 "rejected", "errors")

    #: bounded per-lane ring of recent admission rejections
    ERROR_RING = 32

    def __init__(self, idx: int, capacity: int):
        self.idx = idx
        self.capacity = capacity
        #: drain worker's doorbell (sync object, not shared state)
        self.wake = threading.Event()
        #: serializes whole pop→admit→stage drain rounds: with the
        #: lane's worker and an inline helper (drain_inline, the sync
        #: degrade path) draining concurrently, a later batch could
        #: stage BEFORE an earlier in-flight one — and a coalesce
        #: landing in that gap would apply same-key events out of
        #: order across windows.  One drainer at a time keeps stage
        #: order == pop order == FIFO; parallelism is across lanes.
        self.drain_lock = threading.Lock()
        self._lock = threading.Lock()
        self.queued: list = []      # kai-race: guarded-by=_lock
        self.staged: list = []      # kai-race: guarded-by=_lock
        #: events popped by a worker but not yet staged (quiesce gate)
        self.inflight = 0           # kai-race: guarded-by=_lock
        self.accepted = 0           # kai-race: guarded-by=_lock
        self.shed = 0               # kai-race: guarded-by=_lock
        self.rejected = 0           # kai-race: guarded-by=_lock
        self.errors: list = []      # kai-race: guarded-by=_lock

    def would_fit(self, n: int) -> bool:
        """Capacity probe for the all-or-nothing submit: the caller
        holds the router lock — as do every other submission path AND
        coalesce's take→restage window (the only operation that can
        GROW a lane's load from outside a submit) — so between a
        positive probe and the offer the load can only shrink, and a
        probe-then-offer can't oversubscribe or half-accept."""
        with self._lock:
            load = len(self.queued) + len(self.staged) + self.inflight
            return load + n <= self.capacity

    def offer(self, events: list) -> bool:
        """Queue a group of events atomically: either the whole group
        fits under the lane bound or the whole group is shed — a
        backpressured request never half-lands (and therefore never
        half-journals).  Shed ACCOUNTING is the router's job
        (:meth:`count_shed`): a refusal the sync degrade path then
        delivers must not show up as dropped events."""
        with self._lock:
            load = len(self.queued) + len(self.staged) + self.inflight
            if load + len(events) > self.capacity:
                return False
            self.queued.extend(events)
            self.accepted += len(events)
        self.wake.set()
        return True

    def count_shed(self, n: int) -> None:
        with self._lock:
            self.shed += n

    def take_queued(self, limit: int) -> list:
        with self._lock:
            batch = self.queued[:limit]
            del self.queued[:len(batch)]
            self.inflight += len(batch)
            return batch

    def stage(self, admitted: list, errors: list, taken: int) -> None:
        """Land one drained batch: admitted events append to the staged
        list (seq-ascending — the queue was FIFO), rejections count."""
        with self._lock:
            self.staged.extend(admitted)
            self.rejected += len(errors)
            self.inflight -= taken
            if errors:
                self.errors.extend(errors)
                del self.errors[:-self.ERROR_RING]

    def take_staged(self) -> list:
        with self._lock:
            out = self.staged
            self.staged = []
            return out

    def restage(self, events: list) -> None:
        """Put taken-but-deferred events back at the FRONT of the
        staged list (the coalesce watermark cut): they carry the
        lane's lowest outstanding seqs, so prepending preserves the
        list's seq-ascending order."""
        with self._lock:
            self.staged[:0] = events

    def snapshot(self) -> dict:
        """Point-in-time stats (its own lock only — a scrape can never
        block behind the commit lock or another lane)."""
        with self._lock:
            return {
                "lane": self.idx,
                "queued": len(self.queued) + self.inflight,
                "staged": len(self.staged),
                "capacity": self.capacity,
                "accepted": self.accepted,
                "shed": self.shed,
                "rejected": self.rejected,
                "errors": [{"seq": s, "reason": r}
                           for s, r in self.errors[-8:]],
            }

    def quiet(self) -> bool:
        with self._lock:
            return not self.queued and self.inflight == 0

    def backlog(self) -> int:
        """Events submitted but not yet staged — the coalesce
        pre-drain's per-lane bound."""
        with self._lock:
            return len(self.queued) + self.inflight


class IntakeRouter:
    """The multi-lane front end.  See the module docstring.

    ``sync_flush`` (optional) is the degrade-to-sync valve: a callable
    that runs ``coalesce`` against the owning cluster under its commit
    lock.  The server wires it; a router without one sheds even under
    ``policy="sync"`` (counted, never silent).
    """

    def __init__(self, config: IntakeConfig | None = None,
                 sync_flush=None):
        self.config = config or IntakeConfig()
        self._lanes = tuple(
            _Lane(i, self.config.lane_capacity)
            for i in range(self.config.lanes))
        self._sync_flush = sync_flush
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._seq = 0              # kai-race: guarded-by=_lock
        self._coalesces = 0        # kai-race: guarded-by=_lock
        self._coalesced_events = 0  # kai-race: guarded-by=_lock
        self._sync_degrades = 0    # kai-race: guarded-by=_lock
        self._apply_errors = 0     # kai-race: guarded-by=_lock
        #: drain workers; started/stopped from the owning thread only,
        #: handler-thread reads are liveness probes on the list binding
        self._threads: list = []   # kai-race: guarded-by=single-writer

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "IntakeRouter":
        if self._threads:
            return self
        self._stop.clear()
        for lane in self._lanes:
            t = threading.Thread(target=self._worker, args=(lane,),
                                 daemon=True,
                                 name=f"kai-intake-lane-{lane.idx}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for lane in self._lanes:
            lane.wake.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    # -- submission (producer side) ------------------------------------------

    def _lane_index(self, key: str) -> int:
        return zlib.crc32(key.encode("utf-8")) % len(self._lanes)

    def _lane_of(self, key: str) -> _Lane:
        return self._lanes[self._lane_index(key)]

    # NOTE: these one-line wrappers are deliberate, not dead seams —
    # kai-race resolves attribute accesses through annotated
    # parameters, and `self._lanes[idx].offer(...)` (a subscript) is
    # opaque to it.  Routing every lane call through a `lane: _Lane`
    # annotated helper is what keeps the lane lock discipline on the
    # analyzer's surface (tests/test_analysis.py pins that coverage).

    def _offer(self, lane: _Lane, events: list) -> bool:
        return lane.offer(events)

    def _count_shed(self, lane: _Lane, n: int) -> None:
        lane.count_shed(n)

    def _lane_backlog(self, lane: _Lane) -> int:
        return lane.backlog()

    def _restage(self, lane: _Lane, events: list) -> None:
        lane.restage(events)

    def _would_fit(self, lane: _Lane, n: int) -> bool:
        return lane.would_fit(n)

    def _submit_atomic(self, ops, all_or_nothing: bool = False
                       ) -> tuple[int, list]:
        """Assign the sequence block AND offer every lane group while
        holding the router lock, so offer order == seq order globally.
        Without that atomicity two racing submitters could offer out of
        seq order, and a coalesce landing between their offers would
        apply a later-seq same-key event a window before an earlier
        one — inverting the order a sequential replay would produce.
        Offers are pure list appends; nothing blocks under the lock,
        and the O(n) prep — lane hashing, event construction — happens
        BEFORE it so racing submitters convoy only on seq stamping and
        the appends themselves."""
        order: list = []
        groups: dict[int, list] = {}
        for op, coll, key, payload in ops:
            ev = IntakeEvent(0, op, coll, key, payload)
            order.append(ev)
            groups.setdefault(self._lane_index(key), []).append(ev)
        with self._lock:
            if all_or_nothing:
                # the HTTP contract: a 429 means NOTHING of the request
                # was queued, so a client's blind full retry can never
                # double-apply a partially accepted delta.  Probing is
                # sound under the router lock: submits AND coalesce's
                # restage serialize here, and drains only free capacity.
                # Lanes that actually overflowed are flagged so shed
                # accounting blames the saturated lane, not the healthy
                # ones collaterally refused with it.
                causing = [idx for idx, events in sorted(groups.items())
                           if not self._would_fit(self._lanes[idx],
                                                  len(events))]
                if causing:
                    return 0, [(idx, events, idx in causing)
                               for idx, events in sorted(groups.items())]
            base = self._seq
            self._seq = base + len(order)
            for off, ev in enumerate(order):
                ev.seq = base + off
            shed_groups = []
            accepted = 0
            for idx, events in sorted(groups.items()):
                if self._offer(self._lanes[idx], events):
                    accepted += len(events)
                else:
                    # a per-lane refusal is always its own lane's doing
                    shed_groups.append((idx, events, True))
        return accepted, shed_groups

    def submit_ops(self, ops, all_or_nothing: bool = False) -> dict:
        """Queue decomposed ``(op, coll, key, payload)`` operations.

        Sequence numbers are assigned in list order, atomically with
        the lane offers (see ``_submit_atomic``), so a later coalesce
        restores exactly this submission order across lanes.  Each
        lane's slice is offered atomically; ``all_or_nothing=True``
        (the HTTP boundary) extends that to the whole request, so a
        429 guarantees nothing was queued and a blind full retry is
        safe.  In-process callers keep per-lane partial accept and
        retry the ``shed_ops`` echo exactly."""
        n = len(ops)
        accepted, shed_groups = self._submit_atomic(ops, all_or_nothing)
        if shed_groups and self.config.policy == "sync" \
                and self._sync_flush is not None:
            # degrade to sync: become the old single-writer intake for
            # one request — drain every lane inline, flush a coalesce
            # through the commit lock, then retry on the emptied lanes.
            # The retry re-enters _submit_atomic, so it gets FRESH
            # sequence numbers: everything staged before the flush has
            # already applied, and a retry keeping its pre-flush seqs
            # would claim an ordering the hub no longer honors.
            self.drain_inline()
            self._sync_flush()
            with self._lock:
                self._sync_degrades += 1
            metrics.intake_sync_degrades.inc()
            retry_ops = [(e.op, e.coll, e.key, e.payload)
                         for _idx, events, _causing in shed_groups
                         for e in events]
            more, shed_groups = self._submit_atomic(retry_ops,
                                                    all_or_nothing)
            accepted += more
        # shed accounting happens HERE, on the final outcome only — a
        # refusal the degrade path then delivered is not a drop.  The
        # per-lane counters blame only CAUSING lanes (the saturated
        # ones): an all-or-nothing refusal also refuses groups bound
        # for healthy lanes, and charging those lanes would point an
        # operator at the wrong place.  The request-level `shed` count
        # is the full refusal either way.
        shed = sum(len(events) for _idx, events, _causing in shed_groups)
        for idx, events, causing in shed_groups:
            if causing:
                self._count_shed(self._lanes[idx], len(events))
                metrics.intake_shed.inc(str(idx),
                                        by=float(len(events)))
        if accepted:
            metrics.intake_accepted.inc(by=float(accepted))
        # shed_ops: exactly the refused operations (sheds are atomic
        # per lane group, so a mixed-lane submit can be PARTIALLY
        # accepted — callers that retry must retry these, not guess)
        return {"accepted": accepted, "shed": shed, "total": n,
                "shed_ops": [(e.op, e.coll, e.key, e.payload)
                             for _idx, events, _causing in shed_groups
                             for e in events]}

    def submit_delta(self, delta: dict,
                     all_or_nothing: bool = False) -> dict:
        """Queue one delta document (the ``POST /intake`` body — the
        same schema ``POST /cluster/delta`` applies synchronously)."""
        return self.submit_ops(_apply.decompose_delta(delta),
                               all_or_nothing)

    # -- drain (worker side) --------------------------------------------------

    def _worker(self, lane: _Lane) -> None:
        """One lane's drain loop (daemon thread, one per lane)."""
        while not self._stop.is_set():
            lane.wake.clear()
            if self._drain_lane(lane) == 0:
                lane.wake.wait(0.05)

    def _drain_lane(self, lane: _Lane) -> int:
        """Pop one batch, admission-check it (vectorized), stage the
        admitted events — one whole round under the lane's drain lock
        (see ``_Lane.drain_lock``).  Returns the events popped."""
        with lane.drain_lock:
            batch = lane.take_queued(self.config.batch)
            if not batch:
                return 0
            try:
                ok, reasons = _apply.admit_batch(batch)
            except Exception as exc:  # noqa: BLE001 — a poisoned batch
                # must never kill the lane's worker (the lane would
                # stop draining forever) or leak the inflight count:
                # reject the whole batch, with the reason on the ring
                ok = [False] * len(batch)
                reasons = [f"admission error: {exc}"] * len(batch)
            admitted = [ev for ev, good in zip(batch, ok) if good]
            errors = [(ev.seq, reasons[i])
                      for i, ev in enumerate(batch) if not ok[i]]
            lane.stage(admitted, errors, len(batch))
        if errors:
            metrics.intake_rejected.inc(str(lane.idx),
                                        by=float(len(errors)))
        return len(batch)

    def drain_inline(self, timeout: float = 30.0) -> bool:
        """Quiesce the queues from the calling thread: help-drain every
        lane until nothing is queued or in flight (used by the sync
        degrade path, tests, and the bench's honest end-to-end clock).
        Safe alongside live workers — whoever pops a batch stages it."""
        deadline = time.monotonic() + timeout
        while True:
            moved = 0
            for lane in self._lanes:
                moved += self._drain_lane(lane)
            if moved == 0 and all(lane.quiet() for lane in self._lanes):
                return True
            if time.monotonic() > deadline:
                return False

    # -- coalesce (commit side) -----------------------------------------------

    def _take_staged(self, lane: _Lane) -> list:
        return lane.take_staged()

    def coalesce(self, cluster) -> dict:
        """Merge every lane's staged events into the hub, in global
        sequence order, through the shared applier.  The caller holds
        the cluster's commit lock (``SchedulerServer._state_lock``) —
        this is the ONLY point where intake touches shared cluster
        state, which is what lets ``_state_lock`` shrink from
        per-mutation to per-cycle-boundary."""
        t0 = time.perf_counter()
        # the watermark is the window's cut: a submit is atomic (seq
        # block + every lane offer under the router lock), so every
        # event with seq < watermark was FULLY offered before this
        # boundary and every event >= watermark belongs wholly to the
        # next window — a racing submit can never have half its delta
        # in this cycle and half in the next, whichever lanes the
        # sweep visits first.
        with self._lock:
            watermark = self._seq
        # pre-drain: everything submitted BEFORE this boundary joins
        # this window.  Without it, one delta's events could split
        # across cycles by worker timing (pods staged from one lane, a
        # still-queued gang in another) — a state the sequential
        # classic path can never produce.  Bounded by each lane's
        # backlog at entry: events racing in DURING the coalesce go to
        # the next window, so a sustained storm cannot livelock the
        # cycle.  Draining waits on a mid-round worker (drain_lock),
        # so nothing submitted-before-boundary is left in flight.
        for lane in self._lanes:
            target = self._lane_backlog(lane)
            moved = 0
            while moved < target:
                n = self._drain_lane(lane)
                if n == 0:
                    break
                moved += n
        # the take→cut→restage window runs under the ROUTER lock: the
        # all-or-nothing probe's soundness premise is that between its
        # capacity check and the offer, lane load can only shrink —
        # restage grows it, so restage must serialize with the probes
        # (both sit under the same lock; lane-lock nesting stays
        # router→lane, the one direction used everywhere)
        staged: list = []
        with self._lock:
            for lane in self._lanes:
                taken = self._take_staged(lane)
                cut = len(taken)
                while cut > 0 and taken[cut - 1].seq >= watermark:
                    cut -= 1
                if cut < len(taken):
                    self._restage(lane, taken[cut:])
                staged.extend(taken[:cut])
        staged.sort(key=attrgetter("seq"))
        apply_errors: list = []
        n = _apply.apply_events(cluster, staged, errors=apply_errors)
        applied = n - len(apply_errors)
        dt = time.perf_counter() - t0
        with self._lock:
            self._coalesces += 1
            self._coalesced_events += applied
            self._apply_errors += len(apply_errors)
        if applied:
            metrics.intake_coalesced.inc(by=float(applied))
        if apply_errors:
            # admitted-but-unappliable docs: skipped so one client's
            # poisoned event can never destroy other clients' accepted
            # mutations or fail the scheduling cycle
            metrics.intake_apply_errors.inc(by=float(len(apply_errors)))
        metrics.intake_coalesce_seconds.observe(value=dt)
        for lane in self._lanes:
            snap = lane.snapshot()
            metrics.intake_lane_depth.set(
                str(snap["lane"]),
                value=float(snap["queued"] + snap["staged"]))
        return {"events": applied, "seconds": dt,
                "apply_errors": apply_errors[:8]}

    # -- observability ----------------------------------------------------------

    def _totals(self, lanes: list[dict]) -> dict:
        """Aggregate one pass of lane snapshots + router counters."""
        with self._lock:
            coalesces = self._coalesces
            merged = self._coalesced_events
            degrades = self._sync_degrades
            apply_errors = self._apply_errors
        return {
            "lanes": len(lanes),
            "queued": sum(s["queued"] for s in lanes),
            "staged": sum(s["staged"] for s in lanes),
            "accepted": sum(s["accepted"] for s in lanes),
            "shed": sum(s["shed"] for s in lanes),
            "rejected": sum(s["rejected"] for s in lanes),
            "coalesces": coalesces,
            "coalesced_events": merged,
            "apply_errors": apply_errors,
            "sync_degrades": degrades,
        }

    def health(self) -> dict:
        """The ``/healthz`` intake slice: totals only, cheap."""
        return self._totals([lane.snapshot() for lane in self._lanes])

    def debug_doc(self) -> dict:
        """The ``GET /debug/intake`` document.  Reads only per-lane and
        router locks — never the server's commit lock, so a scrape can
        never block behind intake lanes or a running cycle.  Each lane
        is snapshotted ONCE and the totals derive from those same
        snapshots, so the document is internally consistent: its
        top-level counts always equal the sum of its lane rows."""
        lanes = [lane.snapshot() for lane in self._lanes]
        doc = self._totals(lanes)
        doc.update(
            policy=self.config.policy,
            lane_capacity=self.config.lane_capacity,
            batch=self.config.batch,
            workers_alive=sum(t.is_alive() for t in self._threads),
            lane_stats=lanes,
        )
        return doc

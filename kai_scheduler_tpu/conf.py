"""Config layering — a YAML/JSON document merged over compiled defaults.

The reference loads a ``SchedulerConfiguration`` document from a
ConfigMap and merges it over built-in defaults
(``conf_util/scheduler_conf_util.go:36-90``: the default actions string
and plugin tiers; absent fields keep defaults), with a pflag CLI on top
(``cmd/scheduler/app/options/options.go:90-131``).  This module is that
stack for the TPU scheduler: ``load_config`` parses the same document
shape (``actions`` string, ``tiers`` with per-plugin ``arguments``,
``queueDepthPerAction``, usage-db / kValue knobs) into a
:class:`~kai_scheduler_tpu.framework.scheduler.SchedulerConfig`, and
``kai_scheduler_tpu.__main__`` is the CLI entry point.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any

from .framework.scheduler import SchedulerConfig, action_names
from .framework.session import SessionConfig
from .ops.scoring import PlacementConfig
from .plugins import registry

#: ref ``conf_util/scheduler_conf_util.go:37`` defaultSchedulerConf
DEFAULT_ACTIONS = "allocate, consolidation, reclaim, preempt, stalegangeviction"


def parse_document(text: str) -> dict:
    """Parse a YAML (or JSON — a YAML subset) config document."""
    # lazy on purpose: PyYAML is optional — JSON-only deployments (and
    # the sidecar wire path) never pay or require the dependency
    import yaml  # kai-lint: disable=KAI052
    doc = yaml.safe_load(text)
    if doc is None:
        return {}
    if not isinstance(doc, dict):
        raise ValueError("scheduler config document must be a mapping")
    return doc


def _parse_actions(spec: str) -> tuple[str, ...]:
    acts = tuple(s for s in spec.replace(",", " ").split() if s)
    known = set(action_names())
    unknown = [a for a in acts if a not in known]
    if unknown:
        raise ValueError(
            f"unknown actions {unknown}; registered: {sorted(known)}")
    return acts


def _merge_tiers(doc_tiers: list, session: SessionConfig) -> SessionConfig:
    """Apply the ConfigMap ``tiers`` list: plugin ORDER/selection for the
    score registry, plus per-plugin ``arguments`` (nodeplacement's
    binpack/spread — ref ``conf_util/scheduler_conf_util.go:54-57`` —
    gpupack/gpuspread, and proportion's kValue)."""
    names: list[str] = []
    placement = session.allocate.placement
    k_value = session.k_value
    for tier in doc_tiers or []:
        for plugin in tier.get("plugins", []):
            name = plugin["name"]
            args = plugin.get("arguments") or {}
            if name == "nodeplacement":
                placement = dataclasses.replace(
                    placement,
                    binpack_accel=args.get("gpu", "binpack") == "binpack",
                    binpack_cpu=args.get("cpu", "binpack") == "binpack")
            elif name == "gpupack":
                placement = dataclasses.replace(placement, device_pack=True)
            elif name == "gpuspread":
                placement = dataclasses.replace(placement,
                                                device_pack=False)
            elif name == "proportion":
                k_value = float(args.get("kValue", k_value))
            names.append(name)
    # score-registry plugins keep the configured order; the rest of the
    # reference's plugin list is compiled into the kernels (predicates,
    # topology, elastic, ... — see SURVEY §2.5 rows) and participates
    # whenever the snapshot carries the matching constraints.
    scoreable = set(registry.available_plugins())
    tiers = tuple(n for n in names if n in scoreable)
    if tiers:
        placement = dataclasses.replace(placement, tiers=tiers)
    return dataclasses.replace(
        session, k_value=k_value,
        allocate=dataclasses.replace(session.allocate, placement=placement),
        # VictimConfig.placement is the victim solver's AllocateConfig;
        # the strategy knobs sit one level deeper
        victims=dataclasses.replace(
            session.victims,
            placement=dataclasses.replace(session.victims.placement,
                                          placement=placement)))


def load_config(doc: dict | str | None,
                base: SchedulerConfig | None = None) -> SchedulerConfig:
    """Merge a scheduler-configuration document over defaults.

    Accepts the reference ConfigMap schema::

        actions: "allocate, reclaim"
        tiers:
        - plugins:
          - name: nodeplacement
            arguments: {gpu: spread, cpu: binpack}
        queueDepthPerAction: {allocate: 100, reclaim: 10}
        kValue: 0.5
        schedulePeriod: 1.0

    Absent fields keep the compiled defaults (ref
    ``conf_util/scheduler_conf_util.go:80-90`` merge semantics).
    """
    if isinstance(doc, str):
        doc = parse_document(doc)
    doc = doc or {}
    cfg = base or SchedulerConfig()
    session = cfg.session
    if "tiers" in doc:
        session = _merge_tiers(doc["tiers"], session)
    if "kValue" in doc:
        session = dataclasses.replace(session,
                                      k_value=float(doc["kValue"]))
    depths: dict[str, Any] = doc.get("queueDepthPerAction") or {}
    if depths:
        def depth(action, current):
            # explicit 0 means "attempt nothing", distinct from absent
            # (keep default) — never collapse it to unlimited; null IS
            # unlimited, so the effective doc round-trips (kai-twin
            # replays a recorded stream through its own header config)
            if action not in depths:
                return current
            v = depths[action]
            return None if v is None else int(v)

        allocate = dataclasses.replace(
            session.allocate,
            queue_depth=depth("allocate", session.allocate.queue_depth))
        victims = dataclasses.replace(
            session.victims,
            queue_depth=depth("reclaim", session.victims.queue_depth),
            queue_depth_preempt=depth(
                "preempt", session.victims.queue_depth_preempt))
        session = dataclasses.replace(session, allocate=allocate,
                                      victims=victims)
    victims_doc = doc.get("victims") or {}
    if victims_doc:
        # kai-twin tuner surface: the victim solver's sparse-scatter
        # unit (KU) and the per-cycle victim pool bound
        sk = victims_doc.get("sparseUnitK",
                             session.victims.sparse_unit_k)
        session = dataclasses.replace(
            session, victims=dataclasses.replace(
                session.victims,
                sparse_unit_k=None if sk is None else int(sk),
                max_victim_pods=int(victims_doc.get(
                    "maxVictimPods", session.victims.max_victim_pods))))
    if "staleGangGracePeriodSeconds" in doc:
        session = dataclasses.replace(
            session, stale_grace_s=float(doc["staleGangGracePeriodSeconds"]))
    if "rackLevel" in doc:
        # THE rack-domain knob: one document key sets the topology level
        # the kai-pulse fragmentation gauges AND the kai-repack solver
        # treat as the rack.  Repack has no rack knob of its own — it
        # derives its domains from this AnalyticsConfig by construction
        # (ops/repack.RepackConfig embeds it), so a mismatch between
        # trigger and solver is unrepresentable.
        session = dataclasses.replace(
            session, analytics=dataclasses.replace(
                session.analytics, rack_level=int(doc["rackLevel"])))
    out = dataclasses.replace(cfg, session=session)
    repack_doc = doc.get("repack") or {}
    if repack_doc:
        out = dataclasses.replace(
            out,
            repack_enable=bool(repack_doc.get(
                "enabled", out.repack_enable)),
            repack_frag_threshold=float(repack_doc.get(
                "fragThreshold", out.repack_frag_threshold)),
            repack_trigger_cycles=int(repack_doc.get(
                "triggerCycles", out.repack_trigger_cycles)),
            repack_cooldown=int(repack_doc.get(
                "cooldownCycles", out.repack_cooldown)),
            repack_max_migrations=int(repack_doc.get(
                "maxMigrations", out.repack_max_migrations)))
    intake_doc = doc.get("intake") or {}
    if intake_doc:
        # kai-intake multi-lane mutation front end (intake/router.py):
        # lane fan-out, per-lane bound, and the overflow policy the
        # server's POST /intake route enforces
        out = dataclasses.replace(
            out,
            intake_lanes=int(intake_doc.get("lanes", out.intake_lanes)),
            intake_lane_capacity=int(intake_doc.get(
                "laneCapacity", out.intake_lane_capacity)),
            intake_policy=str(intake_doc.get(
                "policy", out.intake_policy)),
            intake_batch=int(intake_doc.get("batch", out.intake_batch)))
    if "actions" in doc:
        out = dataclasses.replace(out,
                                  actions=_parse_actions(doc["actions"]))
    if "schedulePeriod" in doc:
        out = dataclasses.replace(
            out, schedule_period_s=float(doc["schedulePeriod"]))
    if "incremental" in doc:
        out = dataclasses.replace(out,
                                  incremental=bool(doc["incremental"]))
    if "resident" in doc:
        # kai-resident device-resident cluster state (ops/resident.py):
        # patched cycles ship packed journal deltas into donated device
        # buffers and run the whole cycle as one fused dispatch
        out = dataclasses.replace(out, resident=bool(doc["resident"]))
    if "verifyIncremental" in doc:
        out = dataclasses.replace(
            out, verify_incremental=bool(doc["verifyIncremental"]))
    if "incrementalDirtyThreshold" in doc:
        out = dataclasses.replace(
            out, incremental_dirty_threshold=float(
                doc["incrementalDirtyThreshold"]))
    if "analyticsEvery" in doc:
        out = dataclasses.replace(
            out, analytics_every=int(doc["analyticsEvery"]))
    if "starvationAlarmCycles" in doc:
        out = dataclasses.replace(
            out, starvation_alarm_cycles=int(doc["starvationAlarmCycles"]))
    if "seed" in doc:
        # the kai-twin determinism anchor: every cycle derives its
        # cycle_seed from (seed, cycle_index), so replaying a recorded
        # stream with the same header seed reproduces the run bit-exact
        out = dataclasses.replace(out, seed=int(doc["seed"]))
    if "twinRecord" in doc:
        out = dataclasses.replace(out, twin_record=bool(doc["twinRecord"]))
    if "pyroscopeAddress" in doc:
        out = dataclasses.replace(
            out, pyroscope_address=str(doc["pyroscopeAddress"] or ""))
    if "profilerSampleHz" in doc:
        hz = doc["profilerSampleHz"]
        out = dataclasses.replace(
            out, profiler_sample_hz=None if hz is None else float(hz))
    return out


def effective_config_doc(cfg: SchedulerConfig) -> dict:
    """The fully-resolved configuration, for ``--print-config`` and the
    operator's shard rendering."""
    placement = cfg.session.allocate.placement
    return {
        "actions": ", ".join(cfg.actions),
        "schedulePeriod": cfg.schedule_period_s,
        "kValue": cfg.session.k_value,
        "queueDepthPerAction": {
            "allocate": cfg.session.allocate.queue_depth,
            "reclaim": cfg.session.victims.queue_depth,
            "preempt": (cfg.session.victims.queue_depth_preempt
                        if cfg.session.victims.queue_depth_preempt
                        is not None else cfg.session.victims.queue_depth),
        },
        "placement": {
            "gpu": "binpack" if placement.binpack_accel else "spread",
            "cpu": "binpack" if placement.binpack_cpu else "spread",
            "device": "pack" if placement.device_pack else "spread",
            "tiers": list(placement.tiers),
        },
        "staleGangGracePeriodSeconds": cfg.session.stale_grace_s,
        "rackLevel": cfg.session.analytics.rack_level,
        "repack": {
            "enabled": cfg.repack_enable,
            "fragThreshold": cfg.repack_frag_threshold,
            "triggerCycles": cfg.repack_trigger_cycles,
            "cooldownCycles": cfg.repack_cooldown,
            "maxMigrations": cfg.repack_max_migrations,
        },
        "intake": {
            "lanes": cfg.intake_lanes,
            "laneCapacity": cfg.intake_lane_capacity,
            "policy": cfg.intake_policy,
            "batch": cfg.intake_batch,
        },
        "victims": {
            "sparseUnitK": cfg.session.victims.sparse_unit_k,
            "maxVictimPods": cfg.session.victims.max_victim_pods,
        },
        "analyticsEvery": cfg.analytics_every,
        "starvationAlarmCycles": cfg.starvation_alarm_cycles,
        "seed": cfg.seed,
        "twinRecord": cfg.twin_record,
        "incremental": cfg.incremental,
        "resident": cfg.resident,
        "verifyIncremental": cfg.verify_incremental,
        "incrementalDirtyThreshold": cfg.incremental_dirty_threshold,
        "pyroscopeAddress": cfg.pyroscope_address,
        # None (unset) round-trips as null: an address alone means
        # 100 Hz, while an explicit 0 disables — collapsing unset to
        # 0.0 would silently turn the sampler off on reload
        "profilerSampleHz": cfg.profiler_sample_hz,
    }


def dumps_effective(cfg: SchedulerConfig) -> str:
    return json.dumps(effective_config_doc(cfg), indent=2)

"""Operator — assembles and reconciles every runtime component.

The reference operator (``pkg/operator``) watches the ``Config`` and
``SchedulingShard`` CRDs and deploys/configures one scheduler per shard
plus the binder, podgrouper, controllers and scale adjuster (operands in
``pkg/operator/operands/``).  In-process that deployment role becomes a
composition root: ``Operator.reconcile()`` (re)builds the component set
from the current ``Config``, and ``run_cycle`` drives one full control
loop — intake → status controllers → per-shard scheduling → binding →
scale adjustment — the same dataflow the reference runs as separate
binaries around the API server.
"""
from __future__ import annotations

import dataclasses

from .apis import types as apis
from .binder.binder import Binder
from .controllers.nodescale_adjuster import ScaleAdjuster
from .controllers.podgroup_controller import PodGroupController
from .controllers.queue_controller import QueueController
from .framework.scheduler import CycleResult, Scheduler, SchedulerConfig
from .framework.session import SessionConfig
from .podgrouper.reconciler import PodGroupReconciler
from .runtime.cluster import Cluster
from .runtime.usagedb import (UsageLister, UsageParams,
                              cluster_allocation_client,
                              cluster_capacity_fn)


class Operator:
    """Deploys (instantiates) and reconciles the component set."""

    def __init__(self, config: apis.Config | None = None,
                 cluster: Cluster | None = None,
                 usage_params: UsageParams | None = None):
        self.config = config or apis.Config()
        self.cluster = cluster or Cluster()
        self.podgrouper = PodGroupReconciler()
        self.podgroup_controller = PodGroupController()
        self.queue_controller = QueueController()
        self.binder = Binder()
        self.scale_adjuster = ScaleAdjuster(
            cool_down_s=self.config.stale_gang_grace_s)
        self.usage_lister = None
        if usage_params is not None:
            self.usage_lister = UsageLister(
                cluster_allocation_client(self.cluster), usage_params,
                capacity_fn=cluster_capacity_fn(self.cluster))
        self.schedulers: dict[str, Scheduler] = {}
        self.reconcile()

    def reconcile(self) -> None:
        """Render one Scheduler per shard from the Config — the operand
        reconciliation (``pkg/operator/controller/schedulingshard_controller``).
        A config with no shards gets the default (partition-less) one."""
        shards = list(self.config.shards) or [apis.SchedulingShard()]
        desired = {s.name for s in shards}
        for name in list(self.schedulers):
            if name not in desired:
                del self.schedulers[name]
        for shard in shards:
            self.schedulers[shard.name] = Scheduler(
                SchedulerConfig(
                    session=SessionConfig(),
                    schedule_period_s=self.config.schedule_period_s,
                    shard=shard),
                usage_lister=self.usage_lister)

    def run_cycle(self) -> dict[str, CycleResult]:
        """One full control-plane sweep over every component."""
        cluster = self.cluster
        self.podgrouper.reconcile(cluster)
        self.podgroup_controller.reconcile(cluster)
        self.queue_controller.reconcile(cluster)
        results = {name: sched.run_once(cluster)
                   for name, sched in self.schedulers.items()}
        self.binder.reconcile(cluster)
        self.scale_adjuster.adjust(cluster)
        return results


def run(operator: Operator, cycles: int, tick_s: float | None = None):
    """Drive the operator for ``cycles`` control loops (simulation aid)."""
    out = []
    for _ in range(cycles):
        out.append(operator.run_cycle())
        operator.cluster.tick(tick_s if tick_s is not None
                              else operator.config.schedule_period_s)
    return out

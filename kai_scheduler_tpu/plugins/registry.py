"""The score-plugin registry machinery.

Plugins are pure functions over a :class:`ScoreContext`; registration
mirrors ``framework.RegisterPluginBuilder`` (``plugins/factory.go``) and
tier configuration mirrors the ConfigMap's plugin lists — a
``tuple[str, ...]`` of names, resolvable from a comma-separated string.
They run inside jit-traced kernels, so a tier tuple is part of the
static kernel configuration: changing it recompiles, exactly like the
reference restarting on ConfigMap change.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class ScoreContext:
    """Everything a scoring plugin may consult for one task.

    ``nodes`` is the snapshot's NodeState; ``free`` the live free tensor
    at this point of the cycle; masks are the predicate outputs
    (``fit_idle`` ⊆ ``fit_pipe``).  ``placement`` carries the
    binpack/spread knobs (ref nodeplacement args).
    """

    nodes: object                 # NodeState
    free: jax.Array               # f32 [N, R]
    task_req: jax.Array           # f32 [R]
    fit_idle: jax.Array           # bool [N]
    fit_pipe: jax.Array           # bool [N]
    placement: object             # scoring.PlacementConfig


ScorePlugin = Callable[[ScoreContext], jax.Array]

_SCORE_REGISTRY: dict[str, ScorePlugin] = {}


def register_score_plugin(name: str):
    """ref ``framework.RegisterPluginBuilder`` (``plugins/factory.go:47``)."""
    def deco(fn: ScorePlugin) -> ScorePlugin:
        _SCORE_REGISTRY[name] = fn
        return fn
    return deco


def available_plugins() -> list[str]:
    _ensure_builtins()
    return sorted(_SCORE_REGISTRY)


def resolve(names: tuple[str, ...]) -> list[ScorePlugin]:
    _ensure_builtins()
    missing = [n for n in names if n not in _SCORE_REGISTRY]
    if missing:
        raise KeyError(
            f"unknown score plugins {missing}; available: "
            f"{available_plugins()}")
    return [_SCORE_REGISTRY[n] for n in names]


def parse_tiers(spec: str) -> tuple[str, ...]:
    """Comma/whitespace-separated plugin list → tier tuple (the ConfigMap
    string form, ref ``conf_util/scheduler_conf_util.go``)."""
    return tuple(s for s in spec.replace(",", " ").split() if s)


def compose(ctx: ScoreContext, names: tuple[str, ...]) -> jax.Array:
    """Sum the selected plugins' bands — [N] f32 (no feasibility mask)."""
    total = jnp.zeros_like(ctx.fit_pipe, dtype=jnp.float32)
    for fn in resolve(names):
        total = total + fn(ctx)
    return total


def _ensure_builtins() -> None:
    """Builtin plugins live in ops.scoring; import lazily to avoid the
    circular import (scoring uses this registry for composition)."""
    if "nodeplacement" not in _SCORE_REGISTRY:
        from ..ops import scoring  # noqa: F401  (registers on import)

"""Score-plugin registry — composable score-tensor plugins.

The reference composes 22 plugins through name→builder registries and
configurable tiers (``plugins/factory.go:47-75``, tier/args config merged
from a ConfigMap in ``conf_util/scheduler_conf_util.go:36-90``).  The TPU
design promised the same shape with pure functions (SURVEY.md §7c): a
scoring plugin is a pure ``ScoreContext -> [N] score band`` function, the
configuration is a tuple of plugin names (string-selectable, orderable,
disableable without code edits), and composition is a sum — each plugin
already scales itself into its score band (``plugins/scores/scores.go``),
so band priority is preserved under any ordering.
"""
from .registry import (ScoreContext, available_plugins, compose,
                       parse_tiers, register_score_plugin, resolve)

__all__ = [
    "ScoreContext", "available_plugins", "compose", "parse_tiers",
    "register_score_plugin", "resolve",
]

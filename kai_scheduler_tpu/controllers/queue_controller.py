"""Queue status controller.

Reference: ``pkg/queuecontroller/controllers/queue_controller.go:51``
maintains each Queue's status — ``Allocated`` / ``AllocatedNonPreemptible``
/ ``Requested`` per resource, rolled up the queue hierarchy — and exports
the per-queue usage metrics that feed time-based fairshare
(``pkg/queuecontroller/metrics/metrics.go:33-39``).
"""
from __future__ import annotations

import dataclasses

from ..apis import types as apis
from ..runtime.cluster import Cluster

_ACTIVE = (apis.PodStatus.BOUND, apis.PodStatus.RUNNING)


@dataclasses.dataclass
class QueueStatus:
    """Mirror of Queue.status (``queue_types.go`` QueueStatus)."""

    allocated: apis.ResourceVec = dataclasses.field(
        default_factory=apis.ResourceVec)
    allocated_non_preemptible: apis.ResourceVec = dataclasses.field(
        default_factory=apis.ResourceVec)
    requested: apis.ResourceVec = dataclasses.field(
        default_factory=apis.ResourceVec)


class QueueController:
    """Derives queue status from pods + pod groups; feeds metrics/usagedb."""

    def reconcile(self, cluster: Cluster) -> dict[str, QueueStatus]:
        status = {name: QueueStatus() for name in cluster.queues}
        for group in cluster.pod_groups.values():
            if group.queue not in status:
                continue
            st = status[group.queue]
            nonpreempt = (group.preemptibility
                          == apis.Preemptibility.NON_PREEMPTIBLE)
            for pod in cluster.pods_of_group(group.name):
                if pod.status in _ACTIVE:
                    st.allocated = st.allocated + pod.resources
                    if nonpreempt:
                        st.allocated_non_preemptible = (
                            st.allocated_non_preemptible + pod.resources)
                    st.requested = st.requested + pod.resources
                elif pod.status == apis.PodStatus.PENDING:
                    st.requested = st.requested + pod.resources
        # roll up the hierarchy (children before parents)
        order = sorted(
            cluster.queues.values(),
            key=lambda q: -self._depth(cluster, q))
        for q in order:
            if q.parent and q.parent in status:
                parent = status[q.parent]
                child = status[q.name]
                parent.allocated = parent.allocated + child.allocated
                parent.allocated_non_preemptible = (
                    parent.allocated_non_preemptible
                    + child.allocated_non_preemptible)
                parent.requested = parent.requested + child.requested
        return status

    @staticmethod
    def _depth(cluster: Cluster, q: apis.Queue) -> int:
        d, cur = 0, q
        while cur.parent is not None and cur.parent in cluster.queues:
            d, cur = d + 1, cluster.queues[cur.parent]
        return d

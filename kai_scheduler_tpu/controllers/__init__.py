"""Status controllers — the control layer (SURVEY.md §1 layer 4).

Host-side reconcilers over the runtime ``Cluster`` hub, mirroring the
reference's controller binaries:

- :class:`PodGroupController` — ``pkg/podgroupcontroller``
- :class:`QueueController`    — ``pkg/queuecontroller``
"""
from .podgroup_controller import PodGroupController
from .queue_controller import QueueController, QueueStatus

__all__ = ["PodGroupController", "QueueController", "QueueStatus"]

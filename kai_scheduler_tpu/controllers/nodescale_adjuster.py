"""Node-scale adjuster — autoscaler hinting for fractional accelerators.

Reference (``pkg/nodescaleadjuster/scale_adjuster/scale_adjuster.go:50-70``):
cluster autoscalers cannot reason about fractional-GPU requests, so for
every unschedulable fractional pod the adjuster creates a *scaling pod*
(``cmd/scalingpod`` — an intentionally inert sleeper) that requests the
equivalent number of WHOLE devices; the autoscaler sees a plain
unschedulable GPU pod and provisions a node, after which the real pod
schedules and the scaling pod is deleted.  A cool-down window bounds
churn.

Here scaling pods are inert ``Pod`` objects in the hub whose group is
the reserved ``SCALING_GROUP`` — the snapshot builder drops pods of
unknown groups, so the scheduler never sees them; a simulated (or real)
autoscaler watches them instead.
"""
from __future__ import annotations

import dataclasses
import math

from ..apis import types as apis
from ..runtime.cluster import Cluster

#: reserved group name — not a PodGroup, so snapshots ignore these pods
SCALING_GROUP = "kai-scale-adjust"
_PREFIX = "scaling-pod-"


@dataclasses.dataclass
class ScaleAdjuster:
    """ref ScaleAdjuster: Adjust() creates/deletes scaling pods."""

    cool_down_s: float = 30.0
    #: GiB of device memory equated to one whole device when translating
    #: memory-based requests (ref gpuMemoryToFractionRatio)
    gpu_memory_to_fraction_gib: float = 16.0
    _last_scale_up: float = dataclasses.field(default=-1.0)

    def adjust(self, cluster: Cluster) -> dict[str, list[str]]:
        """One reconcile sweep.  Returns {"created": [...], "deleted": [...]}."""
        created: list[str] = []
        deleted: list[str] = []

        # fractional pods currently unschedulable (their group was marked
        # by the scheduler's fit-failure status flow)
        needy: list[apis.Pod] = []
        for pod in cluster.pods.values():
            if pod.status != apis.PodStatus.PENDING:
                continue
            if pod.group == SCALING_GROUP:
                continue
            if pod.accel_portion <= 0 and pod.accel_memory_gib <= 0:
                continue
            group = cluster.pod_groups.get(pod.group)
            if group is not None and (group.unschedulable
                                      or group.fit_failures > 0):
                needy.append(pod)

        # delete scaling pods whose trigger pod is gone or schedulable
        needy_names = {p.name for p in needy}
        for name in list(cluster.pods):
            pod = cluster.pods[name]
            if pod.group != SCALING_GROUP:
                continue
            trigger = name[len(_PREFIX):]
            if trigger not in needy_names:
                del cluster.pods[name]
                deleted.append(name)

        in_cooldown = (self._last_scale_up >= 0 and
                       cluster.now - self._last_scale_up < self.cool_down_s)
        if in_cooldown:
            return {"created": created, "deleted": deleted}

        for pod in needy:
            name = _PREFIX + pod.name
            if name in cluster.pods:
                continue
            whole = (pod.accel_portion if pod.accel_portion > 0
                     else pod.accel_memory_gib
                     / max(self.gpu_memory_to_fraction_gib, 1e-9))
            scaling = apis.Pod(
                name=name, group=SCALING_GROUP,
                resources=apis.ResourceVec(
                    accel=float(math.ceil(whole - 1e-9)),
                    cpu=pod.resources.cpu, memory=pod.resources.memory),
                creation_timestamp=cluster.now)
            cluster.pods[name] = scaling
            created.append(name)
        if created:
            self._last_scale_up = cluster.now
        return {"created": created, "deleted": deleted}

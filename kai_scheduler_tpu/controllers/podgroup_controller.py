"""PodGroup status controller.

Reference: ``pkg/podgroupcontroller/controllers/pod_group_controller.go:56``
derives each PodGroup's phase and resource status from its pods.  Here the
reconciler additionally stamps ``stale_since`` — the staleness signal the
stalegangeviction action consumes (the reference computes staleness inside
the scheduler's PodGroupInfo; keeping it on the controller keeps the
snapshot pure).
"""
from __future__ import annotations

from ..apis import types as apis
from ..runtime.cluster import Cluster

_ACTIVE = (apis.PodStatus.BOUND, apis.PodStatus.RUNNING)


class PodGroupController:
    """Reconciles PodGroup phase + staleness from pod states."""

    def reconcile(self, cluster: Cluster) -> None:
        for group in cluster.pod_groups.values():
            pods = cluster.pods_of_group(group.name)
            active = sum(p.status in _ACTIVE for p in pods)
            running = sum(p.status == apis.PodStatus.RUNNING for p in pods)
            pending = sum(p.status == apis.PodStatus.PENDING for p in pods)

            # clear the UnschedulableOnNodePool condition when the group's
            # pod set changes shape (ref: the condition is re-evaluated on
            # pod churn; a resubmitted/scaled workload gets a fresh try)
            if group.unschedulable and pending != group.observed_pending:
                group.unschedulable = False
                group.fit_failures = 0
                group.unschedulable_reason = ""
            group.observed_pending = pending

            attained = group.phase in (apis.PodGroupPhase.SCHEDULED,
                                       apis.PodGroupPhase.RUNNING,
                                       apis.PodGroupPhase.STALE)
            if active >= max(group.min_member, 1):
                if group.last_start_timestamp is None:
                    group.last_start_timestamp = cluster.now
                group.stale_since = None
                group.phase = (apis.PodGroupPhase.RUNNING if running
                               else apis.PodGroupPhase.SCHEDULED)
            elif attained and active > 0:
                # reached minMember before, then lost pods: stale.  A gang
                # still scaling toward its first quorum is NOT stale
                # (last_start_timestamp alone is stamped at first bind and
                # must not trigger staleness).
                if group.stale_since is None:
                    group.stale_since = cluster.now
                group.phase = apis.PodGroupPhase.STALE
            else:
                group.stale_since = None
                # the scheduler's UnschedulableOnNodePool condition owns
                # the phase while it stands (cleared above on pod churn)
                group.phase = (apis.PodGroupPhase.UNSCHEDULABLE
                               if group.unschedulable
                               else apis.PodGroupPhase.PENDING)

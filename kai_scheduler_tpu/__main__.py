"""Scheduler CLI — the ``cmd/scheduler`` entry point.

Mirrors the reference's flag surface (``cmd/scheduler/app/options/
options.go:90-131``: schedule period, node-pool partition, config file)
over the config-layering stack (``conf.py`` ≡ ``conf_util``).  Because
the TPU framework's API hub is an in-process document store rather than
a kube-apiserver, the CLI operates on snapshot documents (the same JSON
the snapshot plugin emits) and can:

  print-config  resolve flags + config file into the effective config
  cycle         run one scheduling cycle over a snapshot file (replay)
  serve         run the sidecar HTTP server for a snapshot file

Usage::

  python -m kai_scheduler_tpu print-config --config sched.yaml
  python -m kai_scheduler_tpu cycle --snapshot cluster.json.gz
  python -m kai_scheduler_tpu serve --snapshot cluster.json.gz --port 8080
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time


def _build_config(args) -> "SchedulerConfig":
    from . import conf
    from .apis import types as apis

    cfg = None
    if args.config:
        with open(args.config) as fh:
            cfg = conf.load_config(fh.read())
    else:
        cfg = conf.load_config(None)
    if args.schedule_period is not None:
        cfg = dataclasses.replace(cfg,
                                  schedule_period_s=args.schedule_period)
    if args.partition_label_value is not None or args.queue_depth:
        shard = apis.SchedulingShard(
            name="cli",
            partition_label_value=args.partition_label_value,
            queue_depth_per_action={
                k: int(v) for k, v in
                (kv.split("=", 1) for kv in args.queue_depth)})
        cfg = dataclasses.replace(cfg, shard=shard)
    if args.node_pool_label_key:
        cfg = dataclasses.replace(
            cfg, node_pool_label_key=args.node_pool_label_key)
    if args.pyroscope_address is not None:
        cfg = dataclasses.replace(
            cfg, pyroscope_address=args.pyroscope_address)
    if args.profiler_sample_hz is not None:
        cfg = dataclasses.replace(
            cfg, profiler_sample_hz=args.profiler_sample_hz)
    return cfg


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="kai_scheduler_tpu")
    parser.add_argument("command",
                        choices=("print-config", "cycle", "serve"))
    parser.add_argument("--config", help="scheduler config YAML/JSON file")
    parser.add_argument("--schedule-period", type=float, default=None,
                        help="seconds between cycles (ref options.go:33)")
    parser.add_argument("--node-pool-label-key", default=None)
    parser.add_argument("--partition-label-value", default=None,
                        help="serve only this node-pool partition")
    parser.add_argument("--queue-depth", action="append", default=[],
                        metavar="ACTION=N",
                        help="per-action queue depth override")
    parser.add_argument("--snapshot", help="cluster snapshot JSON(.gz)")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--pyroscope-address", default=None,
                        help="continuous-profile push URL (ref "
                             "pyroscope-address, options.go:110)")
    parser.add_argument("--profiler-sample-hz", type=float, default=None,
                        help="continuous profiler wall-stack sample "
                             "rate; 0 disables")
    args = parser.parse_args(argv)

    from . import conf
    cfg = _build_config(args)
    if args.command == "print-config":
        print(conf.dumps_effective(cfg))
        return 0

    from .framework.scheduler import Scheduler
    from .runtime import snapshot
    if not args.snapshot:
        parser.error(f"{args.command} requires --snapshot")
    cluster = snapshot.load(args.snapshot)
    scheduler = Scheduler(cfg)

    if args.command == "cycle":
        result = scheduler.run_once(cluster)
        print(json.dumps({
            "bind_requests": len(result.bind_requests),
            "evictions": len(result.evictions),
            "open_seconds": round(result.open_seconds, 4),
            "commit_seconds": round(result.commit_seconds, 4),
            "total_seconds": round(result.session_seconds, 4),
            "phase_seconds": {k: round(v, 4)
                              for k, v in result.phase_seconds.items()},
        }))
        return 0

    from .framework.server import SchedulerServer
    server = SchedulerServer(cluster, scheduler, port=args.port).start()
    print(f"serving on 127.0.0.1:{server.port}", file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CRD-equivalent API objects.

These dataclasses are the framework's "wire protocol" between intake
(podgrouper / admission), the scheduler core, and the binder — the role
played in the reference by the CRDs under ``pkg/apis``:

- ``Queue``        ref ``pkg/apis/scheduling/v2/queue_types.go:31-73``
- ``PodGroup``     ref ``pkg/apis/scheduling/v2alpha2/podgroup_types.go:34-77``
- ``BindRequest``  ref ``pkg/apis/scheduling/v1alpha2/bindrequest_types.go:12-51``
- ``Topology``     ref ``pkg/apis/kai/v1alpha1/topology_types.go:53-81``
- ``SchedulingShard`` ref ``pkg/apis/kai/v1/schedulingshard_types.go:34-64``
- ``Config``       ref ``pkg/apis/kai/v1/config_types.go``

They are host-side (pure Python) objects; ``state.cluster_state`` flattens
them into device tensors for the solver kernels.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any

# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

#: Resource vector layout used across every tensor in the framework.
#: Units are chosen so float32 is exact enough at cluster scale:
#: accelerators in device counts, CPU in cores, memory in GiB.
RESOURCE_ACCEL = 0  #: accelerator devices (TPU chips; "GPU" in the reference)
RESOURCE_CPU = 1    #: CPU cores (float)
RESOURCE_MEM = 2    #: memory, GiB (float)
NUM_RESOURCES = 3
RESOURCE_NAMES = ("accel", "cpu", "memory")

#: Sentinel meaning "no limit" — ref ``commonconstants.UnlimitedResourceQuantity``.
UNLIMITED = -1.0


@dataclasses.dataclass(frozen=True)
class ResourceVec:
    """A (accel, cpu, mem) triple — ref ``api/resource_info/resource_info.go:34-37``."""

    accel: float = 0.0
    cpu: float = 0.0
    memory: float = 0.0

    def as_tuple(self) -> tuple[float, float, float]:
        return (self.accel, self.cpu, self.memory)

    def __add__(self, other: "ResourceVec") -> "ResourceVec":
        return ResourceVec(self.accel + other.accel, self.cpu + other.cpu,
                           self.memory + other.memory)


# ---------------------------------------------------------------------------
# Queue (ref pkg/apis/scheduling/v2/queue_types.go)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class QueueResource:
    """Per-resource queue knobs — quota / overQuotaWeight / limit.

    Ref ``queue_types.go`` ``QueueResource{Quota,OverQuotaWeight,Limit}``.
    ``quota`` is the deserved (guaranteed) amount; ``limit`` the hard cap
    (``UNLIMITED`` for none); ``over_quota_weight`` the share weight for
    dividing surplus.
    """

    quota: float = 0.0
    over_quota_weight: float = 1.0
    limit: float = UNLIMITED


@dataclasses.dataclass
class Queue:
    """A scheduling queue; 2+-level hierarchy via ``parent``.

    Ref ``pkg/apis/scheduling/v2/queue_types.go:31-73``.
    """

    name: str
    parent: str | None = None
    priority: int = 0
    accel: QueueResource = dataclasses.field(default_factory=QueueResource)
    #: cpu/memory deserved quota defaults to UNLIMITED — accelerators are
    #: the managed resource; an unspecified cpu/mem quota must not gate
    #: non-preemptible workloads (matches the reference treating absent
    #: queue resources as unbounded deserved share).
    cpu: QueueResource = dataclasses.field(
        default_factory=lambda: QueueResource(quota=UNLIMITED))
    memory: QueueResource = dataclasses.field(
        default_factory=lambda: QueueResource(quota=UNLIMITED))
    #: minimum runtime before a job in this queue may be preempted / reclaimed
    #: (seconds) — ref queue_types.go ``PreemptMinRuntime``/``ReclaimMinRuntime``.
    preempt_min_runtime: float = 0.0
    reclaim_min_runtime: float = 0.0
    creation_timestamp: float = 0.0

    def resource(self, r: int) -> QueueResource:
        return (self.accel, self.cpu, self.memory)[r]


# ---------------------------------------------------------------------------
# Pods & PodGroups (ref pkg/apis/scheduling/v2alpha2/podgroup_types.go)
# ---------------------------------------------------------------------------

class PodStatus(enum.IntEnum):
    """Lifecycle of a task, reduced to what the scheduler needs.

    Ref ``pkg/scheduler/api/pod_status`` (Pending/Bound/Running/Releasing...).
    """

    PENDING = 0
    BOUND = 1      # scheduled this cycle or earlier, pod not yet running
    RUNNING = 2
    RELEASING = 3  # terminating; resources count as "releasing"
    SUCCEEDED = 4
    FAILED = 5


@dataclasses.dataclass
class Pod:
    """One task of a pod group — ref ``api/pod_info/pod_info.go:68-106``."""

    name: str
    group: str
    resources: ResourceVec = dataclasses.field(default_factory=ResourceVec)
    priority: int = 0
    status: PodStatus = PodStatus.PENDING
    node: str | None = None              # set when bound/running
    subgroup: str | None = None          # hierarchical gang subgroup name
    #: fraction of one accelerator requested (GPU-sharing); 0 => whole devices
    #: ref api/resource_info/gpu_resource_requirment.go portion
    accel_portion: float = 0.0
    #: memory-based share request, GiB of one device's memory (converted to
    #: a per-node portion against Node.accel_memory_gib) — ref
    #: gpu_resource_requirment.go gpuMemory
    accel_memory_gib: float = 0.0
    #: concrete device indices occupied on the bound node — whole-device
    #: pods list each device; fractional pods list their shared device.
    #: Assigned by the binder (ref SelectedGPUGroups + reservation pod).
    accel_devices: list[int] = dataclasses.field(default_factory=list)
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    #: pod labels — the match target of other pods' PodAffinityTerms
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: list["Toleration"] = dataclasses.field(default_factory=list)
    #: required node-affinity matchExpressions, ANDed
    node_affinity: list["AffinityExpr"] = dataclasses.field(
        default_factory=list)
    pod_affinity: list["PodAffinityTerm"] = dataclasses.field(
        default_factory=list)
    #: preempted pods carry the node their preemption cleared — the
    #: nominatednode plugin gives it a dominating score bonus
    nominated_node: str | None = None
    #: extended scalar requests — MIG profiles etc. (ref migResources)
    extended: dict[str, float] = dataclasses.field(default_factory=dict)
    #: accelerators requested through DRA ResourceClaims — added to the
    #: accel accounting like whole devices (ref draGpuCounts; the claim
    #: allocation is recorded on the BindRequest)
    dra_accel_count: int = 0
    #: names of ResourceClaim objects this pod consumes (ref
    #: pod.spec.resourceClaims); when set, the claims' counts and their
    #: DeviceClass constraints drive the DRA accounting instead of
    #: ``dra_accel_count``
    resource_claims: list[str] = dataclasses.field(default_factory=list)
    #: PersistentVolumeClaim names (ref pod volumes → the VolumeBinding
    #: predicate + the binder's volume binding plugin)
    volume_claims: list[str] = dataclasses.field(default_factory=list)
    #: host ports the pod needs exclusively on its node (ref the
    #: NodePorts predicate)
    host_ports: list[int] = dataclasses.field(default_factory=list)
    creation_timestamp: float = 0.0


class Preemptibility(str, enum.Enum):
    """Ref podgroup_types.go ``Preemptibility``."""

    PREEMPTIBLE = "Preemptible"
    NON_PREEMPTIBLE = "NonPreemptible"


# ---------------------------------------------------------------------------
# Node-filter vocabulary: taints, tolerations, affinity
# (ref k8s_internal/predicates/predicates.go:70-140 — the upstream
# TaintToleration / NodeAffinity / InterPodAffinity filter surface)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Taint:
    """A node taint — upstream corev1.Taint semantics."""

    key: str
    value: str = ""
    #: "NoSchedule" | "PreferNoSchedule" | "NoExecute"
    effect: str = "NoSchedule"


@dataclasses.dataclass(frozen=True)
class Toleration:
    """A pod toleration — upstream corev1.Toleration semantics.

    ``key=None`` with operator "Exists" tolerates every taint;
    ``effect=None`` matches all effects.
    """

    key: str | None = None
    operator: str = "Equal"    # "Equal" | "Exists"
    value: str = ""
    effect: str | None = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect is not None and self.effect != taint.effect:
            return False
        if self.key is None:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        return self.operator == "Exists" or self.value == taint.value


@dataclasses.dataclass(frozen=True)
class AffinityExpr:
    """One node-affinity matchExpression (requiredDuringScheduling term).

    Operators: In / NotIn / Exists / DoesNotExist / Gt / Lt — upstream
    NodeSelectorRequirement semantics.  A pod's expressions are ANDed.
    """

    key: str
    operator: str = "In"
    values: tuple[str, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        present = self.key in labels
        val = labels.get(self.key)
        if self.operator == "In":
            return present and val in self.values
        if self.operator == "NotIn":
            return not present or val not in self.values
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator in ("Gt", "Lt"):
            if not present or not self.values:
                return False
            try:
                lhs, rhs = int(val), int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        raise ValueError(f"unknown affinity operator {self.operator!r}")


@dataclasses.dataclass(frozen=True)
class PodAffinityTerm:
    """Inter-pod (anti-)affinity term — upstream PodAffinityTerm reduced
    to a label-equality selector over existing pods plus a topology key
    (ref ``plugins/podaffinity``, upstream InterPodAffinity).

    ``topology_key`` names a Topology level label; an unknown key means
    per-node (hostname) granularity.  ``required=False`` terms contribute
    score instead of filtering.
    """

    match_labels: tuple[tuple[str, str], ...] = ()
    topology_key: str = "kubernetes.io/hostname"
    anti: bool = False
    required: bool = True

    def selects(self, labels: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.match_labels)


@dataclasses.dataclass
class TopologyConstraint:
    """Gang placement constraint against a Topology tree.

    Ref ``podgroup_types.go:366-381`` — ``Required`` level: every pod of the
    gang must land inside one domain at that level; ``Preferred``: best-effort
    locality at that level.
    """

    topology: str | None = None
    required_level: str | None = None
    preferred_level: str | None = None


@dataclasses.dataclass
class SubGroup:
    """Hierarchical gang subgroup — ref podgroup_types.go ``SubGroups``."""

    name: str
    min_member: int = 0
    parent: str | None = None
    topology_constraint: TopologyConstraint | None = None


class PodGroupPhase(str, enum.Enum):
    """Ref ``podgroup_types.go`` PodGroupPhase / podgroupcontroller."""

    PENDING = "Pending"
    SCHEDULED = "Scheduled"
    RUNNING = "Running"
    UNSCHEDULABLE = "Unschedulable"
    STALE = "Stale"          # below minMember after having started


@dataclasses.dataclass
class PodGroup:
    """The gang unit — ref ``podgroup_types.go:34-77``."""

    name: str
    queue: str
    min_member: int = 1
    priority: int = 0
    #: object labels — the shard partition selector matches these (ref
    #: SchedulingNodePoolParams.GetLabelSelector, conf/scheduler_conf.go:96)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    preemptibility: Preemptibility = Preemptibility.PREEMPTIBLE
    topology_constraint: TopologyConstraint | None = None
    sub_groups: list[SubGroup] = dataclasses.field(default_factory=list)
    #: number of failed scheduling cycles before the group is marked
    #: unschedulable — ref podgroup_types.go:69-70 ``SchedulingBackoff``
    #: (the reference supports -1 = never and 1; any positive value works
    #: here).  See ``utils/pod_group_utils.go`` NoSchedulingBackoff.
    scheduling_backoff: int = -1
    creation_timestamp: float = 0.0
    # --- status (written by the scheduler / podgroup controller) ---------
    #: consecutive cycles every action failed to place the group
    fit_failures: int = 0
    #: the UnschedulableOnNodePool condition: the snapshot skips the group
    #: until the condition is cleared (pod-set or capacity change)
    unschedulable: bool = False
    #: human-readable fit failure explanation — ref api/unschedule_info.go
    unschedulable_reason: str = ""
    #: pending-pod count observed when the condition was last evaluated —
    #: pod churn clears the unschedulable mark (podgroup controller)
    observed_pending: int = -1
    #: wall-clock the gang became running (for minruntime protection)
    last_start_timestamp: float | None = None
    #: status maintained by the podgroup controller
    phase: PodGroupPhase = PodGroupPhase.PENDING
    #: wall-clock the gang dropped below minMember while started — feeds
    #: the stalegangeviction action (ref PodGroupInfo staleness tracking).
    stale_since: float | None = None


# ---------------------------------------------------------------------------
# Nodes & Topology (ref pkg/apis/kai/v1alpha1/topology_types.go)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Node:
    """Schedulable machine — ref ``api/node_info/node_info.go:68-96``."""

    name: str
    allocatable: ResourceVec = dataclasses.field(default_factory=ResourceVec)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    taints: list["Taint"] = dataclasses.field(default_factory=list)
    #: accelerator memory per device, GiB (for memory-based sharing)
    accel_memory_gib: float = 16.0
    #: extended scalar resources — MIG profiles
    #: (e.g. {"nvidia.com/mig-1g.5gb": 4}) and any other named scalar
    #: (ref GpuResourceRequirement.migResources / Resource.scalars)
    extended: dict[str, float] = dataclasses.field(default_factory=dict)
    unschedulable: bool = False


@dataclasses.dataclass
class Topology:
    """Ordered physical levels, outermost first — ref topology_types.go:53-81.

    ``levels`` holds node-label keys, e.g. ["cloud.provider.com/block",
    "cloud.provider.com/rack", "kubernetes.io/hostname"].
    """

    name: str
    levels: list[str] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# BindRequest (ref pkg/apis/scheduling/v1alpha2/bindrequest_types.go)
# ---------------------------------------------------------------------------

class ReceivedResourceType(str, enum.Enum):
    REGULAR = "Regular"
    FRACTION = "Fraction"


@dataclasses.dataclass
class BindRequest:
    """The scheduler->binder contract — ref bindrequest_types.go:12-51."""

    pod_name: str
    selected_node: str
    received_resource_type: ReceivedResourceType = ReceivedResourceType.REGULAR
    received_accel_count: int = 0
    received_accel_portion: float = 0.0
    #: memory-based share request, GiB — makes the bind record
    #: self-contained for memory-based fractions (ref ReceivedGpuMemory)
    received_accel_memory_gib: float = 0.0
    #: device indices chosen by the scheduler (fractional: the shared
    #: device; whole: filled by the binder) — ref SelectedGPUGroups
    selected_accel_groups: list[int] = dataclasses.field(default_factory=list)
    #: DRA claims this bind must allocate — claim NAMES when the pod
    #: declares ResourceClaims (the binder resolves concrete devices and
    #: records them on the claim objects), legacy integer placeholders
    #: for bare ``dra_accel_count`` pods — ref ResourceClaimAllocations
    resource_claim_allocations: list = dataclasses.field(
        default_factory=list)
    backoff_limit: int = 3
    #: filled by the binder
    phase: str = "Pending"   # Pending | Succeeded | Failed
    failures: int = 0


@dataclasses.dataclass
class StorageClass:
    """ref ``api/storageclass_info`` — bind mode + topology restriction
    (the storagecapacity/csidriver surface reduced to what placement
    actually consumes)."""

    name: str
    #: "Immediate" or "WaitForFirstConsumer" (volume binds at PreBind)
    bind_mode: str = "WaitForFirstConsumer"
    #: node-label constraints where volumes of this class can exist
    #: (allowedTopologies)
    allowed_topology: dict[str, str] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class PersistentVolumeClaim:
    """ref ``api/storageclaim_info`` — the VolumeBinding predicate's
    subject.  A BOUND claim pins pods to its volume's topology
    (``node_affinity``); an unbound WaitForFirstConsumer claim restricts
    to its class's allowed topology and binds at PreBind."""

    name: str
    storage_class: str = ""
    capacity_gib: float = 0.0
    bound: bool = False
    #: the bound volume's topology (zone/hostname labels) — pods using
    #: the claim must land on matching nodes
    node_affinity: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DeviceClass:
    """DRA device selection — ref resource.k8s.io DeviceClass with CEL
    selectors (``plugins/dynamicresources/dynamicresources.go:30-70``).
    On the structured device model the CEL surface degenerates to the
    attributes devices actually expose here: per-device memory and the
    owning node's labels."""

    name: str
    #: device must have at least this much memory (CEL
    #: ``device.capacity['memory']`` comparisons)
    min_memory_gib: float = 0.0
    #: this class allocates ACCELERATOR devices (counts toward the accel
    #: request and the queue's gpu quota); False = a non-gpu device
    #: class, ignored by the accel accounting (ref allocate_dra_test.go
    #: "non gpu claims doesn't count for gpu limit")
    accel: bool = True
    #: node-label constraints (CEL node attribute selectors)
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ResourceClaim:
    """DRA ResourceClaim — ref resource.k8s.io ResourceClaim; allocation
    status is written by the binder (ref ``bindResourceClaims`` in the
    k8s-plugins binder plugin)."""

    name: str
    device_class: str = ""
    #: devices requested (ref exactCount)
    count: int = 1
    #: allocation status — set by the binder, cleared on rollback
    node: str | None = None
    devices: list[int] = dataclasses.field(default_factory=list)
    owner_pod: str | None = None
    #: claim labels — SHARED gpu claims must carry the pod's queue under
    #: ``kai.scheduler/queue`` (ref dynamicresources.go
    #: validateSharedGpuClaimQueueLabel)
    labels: dict = dataclasses.field(default_factory=dict)
    #: created from a ResourceClaimTemplate (per-pod): exempt from the
    #: shared-claim queue-label rule
    from_template: bool = True
    #: existing consumers in Status.ReservedFor — the scheduler may not
    #: admit pods past ``RESERVED_FOR_MAX`` total (ref
    #: dynamicresources.go preFilter)
    reserved_for: int = 0


#: resource.k8s.io ResourceClaimReservedForMaxSize — the consumer cap a
#: claim may never exceed (ref dynamicresources.go:149)
RESERVED_FOR_MAX = 256

#: queue label key shared claims must carry (ref common/constants
#: DefaultQueueLabel)
QUEUE_LABEL = "kai.scheduler/queue"


@dataclasses.dataclass
class Eviction:
    """A victim eviction decision emitted by reclaim/preempt/consolidation."""

    pod_name: str
    group: str
    reason: str = ""
    #: consolidation move target: the victim was verified to fit on this
    #: node and gets a pipelined rebind there (ref the consolidation
    #: Statement evicting and re-pipelining victims atomically,
    #: ``consolidation.go`` allPodsReallocated).  None = plain eviction.
    move_to: str | None = None


# ---------------------------------------------------------------------------
# Operator-level config CRDs (ref pkg/apis/kai/v1)
# ---------------------------------------------------------------------------

class PlacementStrategy(str, enum.Enum):
    """binpack vs spread — ref schedulingshard_types.go ``PlacementStrategy``."""

    BINPACK = "binpack"
    SPREAD = "spread"


#: label key partitioning nodes/pod-groups into shards (ref the
#: --nodepool-label-key flag default)
NODE_POOL_LABEL_KEY = "kai.scheduler/node-pool"


@dataclasses.dataclass
class SchedulingShard:
    """One scheduler instance over a node-pool partition.

    Ref ``pkg/apis/kai/v1/schedulingshard_types.go:34-64``.
    """

    name: str = "default"
    #: nodes/pod-groups whose NODE_POOL_LABEL_KEY label equals this value
    #: belong to the shard; None = the default shard (objects WITHOUT the
    #: label — ref SchedulingNodePoolParams DoesNotExist selector)
    partition_label_value: str | None = None
    placement_strategy_accel: PlacementStrategy = PlacementStrategy.BINPACK
    placement_strategy_cpu: PlacementStrategy = PlacementStrategy.BINPACK
    queue_depth_per_action: dict[str, int] = dataclasses.field(default_factory=dict)
    k_value: float = 1.0
    args: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Config:
    """Operator-level global configuration — ref config_types.go."""

    schedule_period_s: float = 1.0
    stale_gang_grace_s: float = 60.0
    default_scheduler_name: str = "kai-scheduler-tpu"
    shards: list[SchedulingShard] = dataclasses.field(default_factory=list)

"""Podgrouper reconciler — pods without a PodGroup get one.

Reference: ``pkg/podgrouper/pod_controller.go:70`` ``PodReconciler.
Reconcile`` — for each pod missing a PodGroup, resolve the top owner,
pick a grouper, create/update the PodGroup CR, and annotate the pod.
Here the reconciler sweeps the runtime ``Cluster`` hub the same way the
controller sweeps the informer cache.
"""
from __future__ import annotations

from ..apis import types as apis
from ..runtime.cluster import Cluster
from .hub import GrouperHub, Workload


class PodGroupReconciler:
    """Creates PodGroups for submitted workloads — the intake layer."""

    def __init__(self, hub: GrouperHub | None = None):
        self.hub = hub or GrouperHub()

    def submit_workload(self, cluster: Cluster, workload: Workload,
                        pods: list[apis.Pod]) -> apis.PodGroup:
        """Workload CR + its pods → PodGroup in the cluster hub.

        The reference flow (operator creates pods → webhook mutates →
        podgrouper reconciles) collapses into one call against the hub.
        """
        group = self.hub.group(workload, pods)
        cluster.submit(group, pods)
        return group

    def reconcile(self, cluster: Cluster) -> list[apis.PodGroup]:
        """Sweep: any pod whose group is missing gets a default PodGroup
        (grouper fallback) — mirrors the reconciler picking up bare pods."""
        created: list[apis.PodGroup] = []
        by_group: dict[str, list[apis.Pod]] = {}
        for pod in cluster.pods.values():
            if pod.group and pod.group not in cluster.pod_groups:
                by_group.setdefault(pod.group, []).append(pod)
        for name, pods in by_group.items():
            workload = Workload(kind="Pod", name=name)
            group = self.hub.group(workload, pods)
            group.name = name  # keep the pods' existing reference
            for p in pods:
                p.group = name
            cluster.submit(group, [])
            created.append(group)
        return created

"""Grouper hub — the per-workload-kind PodGroup metadata catalog.

Reference: ``pkg/podgrouper/podgrouper/hub/hub.go`` ``DefaultPluginsHub``
maps GroupVersionKind → grouper plugin; each plugin's
``GetPodGroupMetadata`` (one dir per kind under
``podgrouper/podgrouper/plugins/``) derives minMember / queue / priority /
subgroups from the workload spec.  The workload catalog covered here is
the reference's (SURVEY.md §2.8): default, pod/podjob, batch Job,
CronJob, Deployment, RunaiJob, AML, JobSet, LeaderWorkerSet, Grove,
Kubeflow (PyTorch/TF/XGBoost/MPI/Notebook/JAX), Ray
(RayCluster/RayJob/RayService), Spark, Knative, SpotRequest,
SkipTopOwner.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

from ..apis import types as apis

#: queue selection labels — ref ``constants.QueueLabelKey``
QUEUE_LABEL = "kai.scheduler/queue"
PRIORITY_LABEL = "priorityClassName"
DEFAULT_QUEUE = "default"

#: workload kinds whose top-owner resolution must skip to the parent —
#: ref ``skiptopowner`` grouper (Argo Workflows etc.)
SKIP_TOP_OWNER_KINDS = ("Workflow", "PipelineRun", "VirtualMachineInstance",
                       "DevWorkspace")


@dataclasses.dataclass
class Workload:
    """A workload CR as the intake layer sees it (the owner of pods).

    Stands in for the unstructured object + GVK the reference resolves
    through ``topowner/`` (``pkg/podgrouper/pod_controller.go:70``).
    """

    kind: str
    name: str
    api_version: str = "v1"
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    spec: dict[str, Any] = dataclasses.field(default_factory=dict)
    owner: "Workload | None" = None


@dataclasses.dataclass
class PodGroupMetadata:
    """ref ``podgrouper/podgroup/metadata.go`` Metadata."""

    queue: str = DEFAULT_QUEUE
    min_member: int = 1
    priority: int = 0
    preemptibility: apis.Preemptibility = apis.Preemptibility.PREEMPTIBLE
    topology_constraint: apis.TopologyConstraint | None = None
    sub_groups: list[apis.SubGroup] = dataclasses.field(default_factory=list)


Grouper = Callable[[Workload, list[apis.Pod]], PodGroupMetadata]


def _queue_of(workload: Workload) -> str:
    return (workload.labels.get(QUEUE_LABEL)
            or workload.annotations.get(QUEUE_LABEL)
            or DEFAULT_QUEUE)


def _priority_of(workload: Workload, default: int = 0) -> int:
    raw = workload.labels.get(PRIORITY_LABEL)
    try:
        return int(raw) if raw is not None else default
    except ValueError:
        return default


def _topology_of(workload: Workload) -> apis.TopologyConstraint | None:
    """ref PodGroup TopologyConstraint annotations."""
    req = workload.annotations.get("kai.scheduler/topology-required-level")
    pref = workload.annotations.get("kai.scheduler/topology-preferred-level")
    topo = workload.annotations.get("kai.scheduler/topology")
    if req or pref:
        return apis.TopologyConstraint(
            topology=topo, required_level=req, preferred_level=pref)
    return None


def _base(workload: Workload, min_member: int,
          sub_groups: list[apis.SubGroup] | None = None) -> PodGroupMetadata:
    return PodGroupMetadata(
        queue=_queue_of(workload),
        min_member=max(1, min_member),
        priority=_priority_of(workload),
        topology_constraint=_topology_of(workload),
        sub_groups=sub_groups or [],
    )


# ---------------------------------------------------------------------------
# Groupers (one per reference plugin dir)
# ---------------------------------------------------------------------------

def default_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/defaultgrouper`` — minMember 1, queue from labels."""
    return _base(workload, 1)


def pod_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/podjob`` — a bare pod is its own gang of one."""
    return _base(workload, 1)


def batch_job_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/job`` (batch/v1 Job) — minMember = parallelism."""
    parallelism = int(workload.spec.get("parallelism", 1) or 1)
    return _base(workload, parallelism)


def cronjob_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/cronjobs`` — group by the child Job template."""
    tmpl = workload.spec.get("jobTemplate", {}).get("spec", {})
    return _base(workload, int(tmpl.get("parallelism", 1) or 1))


def deployment_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/deployment`` — each replica schedules independently
    (minMember 1); the group exists for queue/fairness accounting."""
    return _base(workload, 1)


def runai_job_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/runaijob`` — legacy RunaiJob: like batch Job."""
    return _base(workload, int(workload.spec.get("parallelism", 1) or 1))


def aml_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/aml`` — AMLJob: all pods gang together."""
    return _base(workload, len(pods) or 1)


def kubeflow_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/kubeflow`` (PyTorchJob/TFJob/XGBoostJob/MPIJob/
    JAXJob) — minMember = Σ replicas over replica specs (or the
    ``minAvailable`` override); one subgroup per replica type."""
    spec = workload.spec
    replica_specs = (spec.get("pytorchReplicaSpecs")
                     or spec.get("tfReplicaSpecs")
                     or spec.get("xgbReplicaSpecs")
                     or spec.get("mpiReplicaSpecs")
                     or spec.get("jaxReplicaSpecs")
                     or spec.get("replicaSpecs") or {})
    total = 0
    subs: list[apis.SubGroup] = []
    for role, rs in replica_specs.items():
        n = int(rs.get("replicas", 1) or 1)
        total += n
        subs.append(apis.SubGroup(name=role.lower(), min_member=n))
    if "minAvailable" in spec.get("runPolicy", {}):
        total = int(spec["runPolicy"]["minAvailable"])
    return _base(workload, total or 1, subs)


def notebook_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/kubeflow/notebook`` — interactive single pod,
    non-preemptible by default (build/interactive workload)."""
    md = _base(workload, 1)
    md.preemptibility = apis.Preemptibility.NON_PREEMPTIBLE
    return md


def ray_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/ray`` (RayCluster/RayJob/RayService) — head + min
    replicas of each worker group."""
    spec = workload.spec
    cluster = (spec.get("rayClusterSpec")      # RayJob / RayService
               or spec)                        # RayCluster itself
    total = 1  # head
    subs = [apis.SubGroup(name="head", min_member=1)]
    for wg in cluster.get("workerGroupSpecs", []) or []:
        n = int(wg.get("minReplicas", wg.get("replicas", 1)) or 1)
        total += n
        subs.append(apis.SubGroup(
            name=str(wg.get("groupName", "workers")), min_member=n))
    return _base(workload, total, subs)


def spark_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/spark`` — driver + executor instances."""
    spec = workload.spec
    executors = int(spec.get("executor", {}).get("instances", 1) or 1)
    subs = [apis.SubGroup(name="driver", min_member=1),
            apis.SubGroup(name="executor", min_member=executors)]
    return _base(workload, 1 + executors, subs)


def jobset_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/jobset`` — Σ (replicas × parallelism) over
    replicatedJobs."""
    total, subs = 0, []
    for rj in workload.spec.get("replicatedJobs", []) or []:
        n = (int(rj.get("replicas", 1) or 1)
             * int(rj.get("template", {}).get("spec", {})
                   .get("parallelism", 1) or 1))
        total += n
        subs.append(apis.SubGroup(name=str(rj.get("name", "job")),
                                  min_member=n))
    return _base(workload, total or 1, subs)


def lws_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/leaderworkerset`` — leader + (size-1) workers per
    replica group."""
    size = int(workload.spec.get("leaderWorkerTemplate", {})
               .get("size", 1) or 1)
    subs = [apis.SubGroup(name="leader", min_member=1),
            apis.SubGroup(name="workers", min_member=max(0, size - 1))]
    return _base(workload, size, subs)


def grove_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/grove`` (PodGangSet) — Σ clique sizes."""
    total = 0
    for clique in (workload.spec.get("template", {})
                   .get("cliques", []) or []):
        total += int(clique.get("spec", {}).get("replicas", 1) or 1)
    return _base(workload, total or 1)


def knative_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/knative`` — serving revision; min-scale annotation."""
    min_scale = int(workload.annotations.get(
        "autoscaling.knative.dev/min-scale", 1) or 1)
    return _base(workload, min_scale)


def spot_request_grouper(workload: Workload, pods: list[apis.Pod]) -> PodGroupMetadata:
    """ref ``plugins/spotrequest`` — preemptible by definition."""
    md = _base(workload, 1)
    md.preemptibility = apis.Preemptibility.PREEMPTIBLE
    return md


# ---------------------------------------------------------------------------
# Hub
# ---------------------------------------------------------------------------

class GrouperHub:
    """kind → grouper dispatch — ref ``hub.go:59`` DefaultPluginsHub."""

    def __init__(self) -> None:
        self._groupers: dict[str, Grouper] = {}
        self.default: Grouper = default_grouper
        for kind, fn in {
            "Pod": pod_grouper,
            "Job": batch_job_grouper,
            "CronJob": cronjob_grouper,
            "Deployment": deployment_grouper,
            "ReplicaSet": deployment_grouper,
            "StatefulSet": deployment_grouper,
            "RunaiJob": runai_job_grouper,
            "TrainingWorkload": runai_job_grouper,
            "AMLJob": aml_grouper,
            "PyTorchJob": kubeflow_grouper,
            "TFJob": kubeflow_grouper,
            "XGBoostJob": kubeflow_grouper,
            "MPIJob": kubeflow_grouper,
            "JAXJob": kubeflow_grouper,
            "Notebook": notebook_grouper,
            "RayCluster": ray_grouper,
            "RayJob": ray_grouper,
            "RayService": ray_grouper,
            "SparkApplication": spark_grouper,
            "JobSet": jobset_grouper,
            "LeaderWorkerSet": lws_grouper,
            "PodGangSet": grove_grouper,
            "Revision": knative_grouper,
            "Service": knative_grouper,
            "SpotRequest": spot_request_grouper,
        }.items():
            self._groupers[kind] = fn

    def register(self, kind: str, grouper: Grouper) -> None:
        self._groupers[kind] = grouper

    def kinds(self) -> list[str]:
        return sorted(self._groupers)

    def top_owner(self, workload: Workload) -> Workload:
        """Resolve the owner chain — ref ``topowner/`` + the skiptopowner
        plugin (stop *below* kinds that merely orchestrate, e.g. Argo
        Workflow)."""
        cur = workload
        while cur.owner is not None:
            if cur.owner.kind in SKIP_TOP_OWNER_KINDS:
                return cur
            cur = cur.owner
        return cur

    def group(self, workload: Workload,
              pods: list[apis.Pod]) -> apis.PodGroup:
        """GetPodGroupMetadata + PodGroup construction for a workload."""
        top = self.top_owner(workload)
        grouper = self._groupers.get(top.kind, self.default)
        md = grouper(top, pods)
        group = apis.PodGroup(
            name=f"pg-{top.kind.lower()}-{top.name}",
            queue=md.queue,
            min_member=md.min_member,
            priority=md.priority,
            preemptibility=md.preemptibility,
            topology_constraint=md.topology_constraint,
            sub_groups=md.sub_groups,
        )
        for pod in pods:
            pod.group = group.name
        # attribute pods to declared subgroups (ref: the reference reads
        # the pod's subgroup annotation, stamped by the workload
        # operator; here pods without an explicit subgroup fill the
        # declared subgroups' minMember slots in order)
        if md.sub_groups:
            untagged = [p for p in pods if not p.subgroup]
            cursor = 0
            for sg in md.sub_groups:
                want = sg.min_member - sum(
                    1 for p in pods if p.subgroup == sg.name)
                for p in untagged[cursor:cursor + max(want, 0)]:
                    p.subgroup = sg.name
                cursor += max(want, 0)
            # leftovers (elastic scale-up pods) join the last subgroup
            for p in untagged[cursor:]:
                p.subgroup = md.sub_groups[-1].name
        return group

"""Podgrouper — workload intake: framework CRs → gang PodGroups.

Reference: ``pkg/podgrouper`` (14.8k LoC) walks pod → owner chain
(``topowner/``) → picks a grouper plugin by the owner's GroupVersionKind
(``podgrouper/hub/hub.go DefaultPluginsHub``) → creates/updates a
PodGroup with minMember, queue, priority, topology constraints and
subgroups.  This package is that catalog for the TPU framework: every
workload kind the reference can gang-group (SURVEY.md §2.8) has a
grouper here, keyed by ``kind``.
"""
from .hub import GrouperHub, PodGroupMetadata, Workload
from .reconciler import PodGroupReconciler

__all__ = ["GrouperHub", "PodGroupMetadata", "Workload",
           "PodGroupReconciler"]

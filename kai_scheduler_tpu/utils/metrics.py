"""Minimal Prometheus-style metrics registry (no external deps).

The reference exports its scheduler metrics through prometheus client_go
(``pkg/scheduler/metrics/metrics.go:39-58``; catalog in
``docs/metrics/METRICS.md``).  This module provides the same shapes —
Counter / Gauge / Histogram with label vectors — plus a text exposition
renderer, so a sidecar can serve ``/metrics`` verbatim.
"""
from __future__ import annotations

import bisect
import dataclasses
import re
import threading


@dataclasses.dataclass
class _Metric:
    name: str
    help: str
    label_names: tuple[str, ...] = ()
    #: exposition type — overridden per subclass
    kind = "untyped"

    def _key(self, labels: tuple[str, ...]) -> tuple[str, ...]:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {labels}")
        return labels


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, tuple(label_names))
        self._values: dict[tuple[str, ...], float] = {}  # kai-race: guarded-by=_lock
        self._lock = threading.Lock()

    def inc(self, *labels: str, by: float = 1.0) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        # render from an immutable copy: a /metrics scrape thread must
        # not iterate a dict the cycle thread is growing
        with self._lock:
            values = dict(self._values)
        return _render_simple(self, "counter", values)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, tuple(label_names))
        # discipline declared in analysis/guarded_by.json (the cycle's
        # gauge updates go through loop variables the static pass
        # cannot type, so an inline annotation would read as stale)
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, *labels: str, value: float) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            values = dict(self._values)
        return _render_simple(self, "gauge", values)


_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", label_names=(),
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}  # kai-race: guarded-by=_lock
        self._sums: dict[tuple[str, ...], float] = {}  # kai-race: guarded-by=_lock
        self._lock = threading.Lock()

    def observe(self, *labels: str, value: float) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1))
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def count(self, *labels: str) -> int:
        with self._lock:
            return sum(self._counts.get(self._key(labels), []))

    def render(self) -> list[str]:
        # snapshot under the lock (bucket lists mutate in place), render
        # from the copy
        with self._lock:
            counts_copy = {k: list(v) for k, v in self._counts.items()}
            sums_copy = dict(self._sums)
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for key, counts in sorted(counts_copy.items()):
            cum = 0
            for le, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket{_labels(self, key, le=le)} {cum}")
            cum += counts[-1]
            lines.append(
                f'{self.name}_bucket{_labels(self, key, le="+Inf")} {cum}')
            lines.append(f"{self.name}_sum{_labels(self, key)} "
                         f"{sums_copy[key]}")
            lines.append(f"{self.name}_count{_labels(self, key)} {cum}")
        return lines


def _labels(metric: _Metric, key: tuple[str, ...], **extra) -> str:
    pairs = list(zip(metric.label_names, key)) + [
        (k, v) for k, v in extra.items()]
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in pairs)
    return "{" + inner + "}"


def _render_simple(metric: _Metric, kind: str, values: dict) -> list[str]:
    lines = [f"# HELP {metric.name} {metric.help}",
             f"# TYPE {metric.name} {kind}"]
    for key, v in sorted(values.items()):
        lines.append(f"{metric.name}{_labels(metric, key)} {v}")
    return lines


class Registry:
    """A metric collection with text exposition.

    Render is safe against concurrent registration and observation: the
    metric list is copied under the registry lock and each metric
    renders from a copy taken under its own lock, so the text a scrape
    thread sees is an immutable point-in-time snapshot.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: list[_Metric] = []  # kai-race: guarded-by=_lock

    def counter(self, name, help="", label_names=()) -> Counter:
        m = Counter(name, help, label_names)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name, help="", label_names=()) -> Gauge:
        m = Gauge(name, help, label_names)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name, help="", label_names=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help, label_names, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def metrics(self) -> list[_Metric]:
        """Point-in-time copy of the registered metric list (the
        catalog surface — see ``render_catalog``)."""
        with self._lock:
            return list(self._metrics)

    def render(self) -> str:
        metrics = self.metrics()
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# catalog exposition — docs/metrics/METRICS.md is GENERATED from the
# registry through these two functions, and a tier-1 meta-test plus
# scripts/lint.py assert the committed file and the registry agree
# exactly (name, type, labels, help), so the catalog can never silently
# drift.  Pure string code, importable jax-free.
# ---------------------------------------------------------------------------

_CATALOG_HEADER = """# Metrics catalog

Every metric the scheduler registry exposes through ``/metrics``
(Prometheus text exposition).  GENERATED — do not edit by hand:

    python -m kai_scheduler_tpu.framework.metrics > docs/metrics/METRICS.md

``tests/test_metrics_catalog.py`` (tier-1) and ``scripts/lint.py``
both fail when this file and the registry disagree.

| metric | type | labels | help |
|---|---|---|---|
"""


def render_catalog(rows: list[dict]) -> str:
    """``[{name, type, labels, help}]`` -> the METRICS.md document."""
    lines = [_CATALOG_HEADER.rstrip("\n")]
    for r in sorted(rows, key=lambda r: r["name"]):
        labels = ", ".join(f"`{l}`" for l in r["labels"]) or "—"
        # escape cell delimiters: a '|' in help text would split the
        # row into >4 cells and parse_catalog would drop it — turning
        # the drift gate into a permanent, unfixable failure
        help_text = " ".join(str(r["help"]).split()).replace("|", "\\|")
        lines.append(
            f"| `{r['name']}` | {r['type']} | {labels} | {help_text} |")
    return "\n".join(lines) + "\n"


def parse_catalog(text: str) -> list[dict]:
    """The inverse of ``render_catalog`` — parse the committed
    METRICS.md back into ``[{name, type, labels, help}]`` rows for the
    drift checks."""
    rows: list[dict] = []
    for line in text.splitlines():
        if not line.startswith("| `"):
            continue
        # split on UNESCAPED pipes only (render escapes '|' in help)
        cells = [c.strip().replace("\\|", "|") for c in
                 re.split(r"(?<!\\)\|", line.strip().strip("|"))]
        if len(cells) != 4:
            continue
        name, kind, labels_cell, help_text = cells
        labels = [] if labels_cell == "—" else [
            l.strip().strip("`") for l in labels_cell.split(",")]
        rows.append({"name": name.strip("`"), "type": kind,
                     "labels": labels, "help": help_text})
    return rows

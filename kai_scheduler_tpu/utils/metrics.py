"""Minimal Prometheus-style metrics registry (no external deps).

The reference exports its scheduler metrics through prometheus client_go
(``pkg/scheduler/metrics/metrics.go:39-58``; catalog in
``docs/metrics/METRICS.md``).  This module provides the same shapes —
Counter / Gauge / Histogram with label vectors — plus a text exposition
renderer, so a sidecar can serve ``/metrics`` verbatim.
"""
from __future__ import annotations

import bisect
import dataclasses
import threading


@dataclasses.dataclass
class _Metric:
    name: str
    help: str
    label_names: tuple[str, ...] = ()

    def _key(self, labels: tuple[str, ...]) -> tuple[str, ...]:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {labels}")
        return labels


class Counter(_Metric):
    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, tuple(label_names))
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels: str, by: float = 1.0) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *labels: str) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        return _render_simple(self, "counter", self._values)


class Gauge(_Metric):
    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, tuple(label_names))
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, *labels: str, value: float) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, *labels: str) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        return _render_simple(self, "gauge", self._values)


_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    def __init__(self, name, help="", label_names=(),
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def observe(self, *labels: str, value: float) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1))
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def count(self, *labels: str) -> int:
        return sum(self._counts.get(self._key(labels), []))

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for key, counts in sorted(self._counts.items()):
            cum = 0
            for le, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket{_labels(self, key, le=le)} {cum}")
            cum += counts[-1]
            lines.append(
                f'{self.name}_bucket{_labels(self, key, le="+Inf")} {cum}')
            lines.append(f"{self.name}_sum{_labels(self, key)} "
                         f"{self._sums[key]}")
            lines.append(f"{self.name}_count{_labels(self, key)} {cum}")
        return lines


def _labels(metric: _Metric, key: tuple[str, ...], **extra) -> str:
    pairs = list(zip(metric.label_names, key)) + [
        (k, v) for k, v in extra.items()]
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in pairs)
    return "{" + inner + "}"


def _render_simple(metric: _Metric, kind: str, values: dict) -> list[str]:
    lines = [f"# HELP {metric.name} {metric.help}",
             f"# TYPE {metric.name} {kind}"]
    for key, v in sorted(values.items()):
        lines.append(f"{metric.name}{_labels(metric, key)} {v}")
    return lines


class Registry:
    """A metric collection with text exposition."""

    def __init__(self):
        self._metrics: list[_Metric] = []

    def counter(self, name, help="", label_names=()) -> Counter:
        m = Counter(name, help, label_names)
        self._metrics.append(m)
        return m

    def gauge(self, name, help="", label_names=()) -> Gauge:
        m = Gauge(name, help, label_names)
        self._metrics.append(m)
        return m

    def histogram(self, name, help="", label_names=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help, label_names, buckets)
        self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: list[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

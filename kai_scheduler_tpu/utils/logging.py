"""Leveled, scoped logging — ref ``pkg/scheduler/log/log.go`` InfraLogger.

The reference uses a zap logger with numeric verbosity (``V(n)``) and
stamps every line with the session/action scope
(``scheduler.go:130-131``).  Same surface over stdlib logging: verbosity
gates at call time, scopes compose via ``with_scope``.
"""
from __future__ import annotations

import logging
import os
import sys


class InfraLogger:
    """``logger.V(3).infof(...)`` — zap-style verbosity levels."""

    def __init__(self, name: str = "kai", verbosity: int | None = None,
                 scope: str = ""):
        self._logger = logging.getLogger(name)
        if not self._logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s %(message)s"))
            self._logger.addHandler(handler)
            self._logger.setLevel(logging.INFO)
        if verbosity is None:
            verbosity = int(os.environ.get("KAI_LOG_V", "2"))
        self.verbosity = verbosity
        self.scope = scope

    def with_scope(self, **kv: object) -> "InfraLogger":
        """A child logger stamping e.g. session/action ids on every line."""
        scope = " ".join(f"{k}={v}" for k, v in kv.items())
        child = InfraLogger.__new__(InfraLogger)
        child._logger = self._logger
        child.verbosity = self.verbosity
        child.scope = f"{self.scope} {scope}".strip()
        return child

    class _V:
        def __init__(self, parent: "InfraLogger", enabled: bool):
            self._parent = parent
            self._enabled = enabled

        def infof(self, fmt: str, *args: object) -> None:
            if self._enabled:
                self._parent._emit(logging.INFO, fmt, args)

        def warnf(self, fmt: str, *args: object) -> None:
            if self._enabled:
                self._parent._emit(logging.WARNING, fmt, args)

    def V(self, level: int) -> "_V":  # noqa: N802 — zap-style name
        return InfraLogger._V(self, level <= self.verbosity)

    def errorf(self, fmt: str, *args: object) -> None:
        self._emit(logging.ERROR, fmt, args)

    def _emit(self, level: int, fmt: str, args: tuple) -> None:
        msg = fmt % args if args else fmt
        if self.scope:
            msg = f"[{self.scope}] {msg}"
        self._logger.log(level, msg)


logger = InfraLogger()

"""Precision helpers for long f32 reductions on TPU.

The reference's fairness/victim arithmetic runs in Go float64
(``pkg/scheduler/plugins/proportion/resource_division/resource_division.go:26-41``).
TPU kernels run f32; a plain f32 cumulative sum over the 50k-unit
victim tables with GiB-scale values carries ~1e-7 relative error —
measured ~1.4 GiB absolute at the tail, larger than a small pod's
request, so a capacity comparison within that band of its bound could
flip versus exact arithmetic (SURVEY §7 hard-part 5).

``cumsum_ds`` keeps the scan in f32 but carries a double-single
(compensated) error term through an associative two-sum, squaring the
effective precision (~1e-14 relative) for 2× the flops of the plain
scan — the TPU-native answer to "compute it in float64".
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _two_sum(a: jax.Array, b: jax.Array):
    """Knuth two-sum: s + err == a + b exactly (all f32)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def cumsum_ds(x: jax.Array, axis: int = 0) -> jax.Array:
    """Compensated (double-single) cumulative sum along ``axis``.

    Associative, so it lowers to the same parallel-scan structure XLA
    uses for ``jnp.cumsum``; each combine carries the rounding residue
    of the partial sums instead of dropping it."""

    def combine(ca, cb):
        s_a, e_a = ca
        s_b, e_b = cb
        s, e = _two_sum(s_a, s_b)
        return s, e + e_a + e_b

    s, e = jax.lax.associative_scan(
        combine, (x, jnp.zeros_like(x)), axis=axis)
    return s + e

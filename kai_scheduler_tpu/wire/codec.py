"""apis dataclasses ↔ sidecar protobuf messages, by reflection.

The .proto mirrors ``kai_scheduler_tpu.apis.types`` field-for-field
(see ``sidecar.proto``), so one generic descriptor-driven converter
covers every message instead of N hand-written mappers — the proto file
stays the single schema source and drift shows up as an AttributeError
in the round-trip test, not as silently dropped fields.

Special cases the reflection cannot infer:
- enum fields ride as their ``.value`` (int for ``PodStatus``, string
  for the str-enums) and are reconstructed through the enum type;
- ``PodAffinityTerm.match_labels`` is a tuple-of-pairs in the API and a
  proto map;
- ``BindRequest.resource_claim_allocations`` holds claim names OR
  legacy integer placeholders — integers are stringified on the wire.
"""
from __future__ import annotations

import dataclasses
import enum
import functools

from ..apis import types as apis
from ..intake import gate
from ..runtime.cluster import Cluster
from . import sidecar_pb2 as pb


def _is_repeated(fd) -> bool:
    # protobuf >=5: property; some versions: method; older: label enum
    attr = getattr(fd, "is_repeated", None)
    if attr is not None:
        return attr() if callable(attr) else bool(attr)
    return fd.label == fd.LABEL_REPEATED

#: dataclass type per message name (only non-trivial nested types need
#: registering; scalars and maps convert directly)
_DATACLASS_BY_MSG = {
    "ResourceVec": apis.ResourceVec,
    "QueueResource": apis.QueueResource,
    "Queue": apis.Queue,
    "Taint": apis.Taint,
    "Node": apis.Node,
    "Toleration": apis.Toleration,
    "AffinityExpr": apis.AffinityExpr,
    "PodAffinityTerm": apis.PodAffinityTerm,
    "TopologyConstraint": apis.TopologyConstraint,
    "SubGroup": apis.SubGroup,
    "PodGroup": apis.PodGroup,
    "Pod": apis.Pod,
    "BindRequest": apis.BindRequest,
    "ResourceClaim": apis.ResourceClaim,
    "DeviceClass": apis.DeviceClass,
    "PersistentVolumeClaim": apis.PersistentVolumeClaim,
    "StorageClass": apis.StorageClass,
    "Topology": apis.Topology,
    "Eviction": apis.Eviction,
}

_ENUM_FIELDS = {
    ("Pod", "status"): apis.PodStatus,
    ("PodGroup", "preemptibility"): apis.Preemptibility,
    ("PodGroup", "phase"): apis.PodGroupPhase,
    ("BindRequest", "received_resource_type"): apis.ReceivedResourceType,
}


def to_msg(obj, msg):
    """Fill proto ``msg`` from dataclass ``obj`` (returns ``msg``)."""
    mname = msg.DESCRIPTOR.name
    for fd in msg.DESCRIPTOR.fields:
        val = getattr(obj, fd.name)
        if val is None:
            continue  # optional stays unset
        if isinstance(val, enum.Enum):
            val = val.value
        if (mname, fd.name) == ("PodAffinityTerm", "match_labels"):
            getattr(msg, fd.name).update(dict(val))
        elif (mname, fd.name) == ("BindRequest",
                                  "resource_claim_allocations"):
            getattr(msg, fd.name).extend(str(v) for v in val)
        elif fd.message_type is not None and fd.message_type.GetOptions(
                ).map_entry:
            getattr(msg, fd.name).update(val)
        elif _is_repeated(fd):
            if fd.message_type is not None:
                for item in val:
                    to_msg(item, getattr(msg, fd.name).add())
            else:
                getattr(msg, fd.name).extend(val)
        elif fd.message_type is not None:
            to_msg(val, getattr(msg, fd.name))
        else:
            setattr(msg, fd.name, val)
    return msg


@functools.lru_cache(maxsize=None)
def _none_default_fields(cls) -> frozenset:
    out = set()
    for f in dataclasses.fields(cls):
        if f.default is None:
            out.add(f.name)
    return frozenset(out)


def from_msg(msg):
    """Proto message → apis dataclass instance."""
    mname = msg.DESCRIPTOR.name
    cls = _DATACLASS_BY_MSG[mname]
    kw = {}
    for fd in msg.DESCRIPTOR.fields:
        if fd.has_presence and not msg.HasField(fd.name):
            # unset presence field: None only where the dataclass says
            # None; otherwise fall back to the dataclass default — a
            # foreign client omitting Queue.accel or Node.allocatable
            # must get the API defaults (UNLIMITED quotas, empty vec),
            # never a crashing None or a proto3 zero
            if fd.name in _none_default_fields(cls):
                kw[fd.name] = None
            continue
        val = getattr(msg, fd.name)
        if (mname, fd.name) == ("PodAffinityTerm", "match_labels"):
            kw[fd.name] = tuple(sorted(val.items()))
        elif (mname, fd.name) == ("BindRequest",
                                  "resource_claim_allocations"):
            kw[fd.name] = [int(v) if v.isdigit() else v for v in val]
        elif fd.message_type is not None and fd.message_type.GetOptions(
                ).map_entry:
            kw[fd.name] = dict(val)
        elif _is_repeated(fd):
            if fd.message_type is not None:
                kw[fd.name] = [from_msg(m) for m in val]
            else:
                kw[fd.name] = list(val)
        elif fd.message_type is not None:
            kw[fd.name] = from_msg(val)
        else:
            ecls = _ENUM_FIELDS.get((mname, fd.name))
            kw[fd.name] = ecls(val) if ecls is not None else val
    return cls(**kw)


# -- document-level converters -------------------------------------------

_COLLECTIONS = (
    ("nodes", "nodes"), ("queues", "queues"),
    ("pod_groups", "pod_groups"), ("pods", "pods"),
    ("bind_requests", "bind_requests"),
    ("resource_claims", "resource_claims"),
    ("device_classes", "device_classes"),
    ("volume_claims", "volume_claims"),
    ("storage_classes", "storage_classes"),
)


def cluster_to_msg(cluster: Cluster) -> "pb.ClusterDoc":
    doc = pb.ClusterDoc(now=cluster.now)
    for pb_field, attr in _COLLECTIONS:
        store = getattr(cluster, attr)
        for obj in store.values():
            to_msg(obj, getattr(doc, pb_field).add())
    if cluster.topology is not None:
        to_msg(cluster.topology, doc.topology)
    return doc


def cluster_from_msg(doc: "pb.ClusterDoc") -> Cluster:
    from ..runtime.snapshot import rebuild_reservations
    topo = from_msg(doc.topology) if doc.HasField("topology") else None
    cluster = Cluster.from_objects(
        [from_msg(m) for m in doc.nodes],
        [from_msg(m) for m in doc.queues],
        [from_msg(m) for m in doc.pod_groups],
        [from_msg(m) for m in doc.pods],
        topo)
    for pb_field, attr in _COLLECTIONS[4:]:
        store = getattr(cluster, attr)
        for m in getattr(doc, pb_field):
            obj = from_msg(m)
            key = getattr(obj, "name", None) or obj.pod_name
            store[key] = obj
    cluster.now = doc.now
    rebuild_reservations(cluster)
    return cluster


def apply_delta_msg(cluster: Cluster, delta: "pb.ClusterDelta") -> None:
    """Apply a proto delta: upserts carry COMPLETE objects (proto3 has
    no partial-field presence for scalars; the JSON wire keeps the
    partial-merge form), deletes are names.  Every change is recorded in
    the cluster's mutation journal — marks flow through the kai-intake
    gate and bulk-merge per delta (one journal lock acquisition),
    exactly the coalesce path's discipline."""
    journal = cluster.journal
    marks: list = []
    try:
        for pb_field, attr in _COLLECTIONS:
            store = getattr(cluster, attr)
            for m in getattr(delta, f"{pb_field}_upsert"):
                obj = from_msg(m)
                key = getattr(obj, "name", None) or obj.pod_name
                gate.upsert_marks(attr, key, obj, key in store, marks)
                store[key] = obj
            for name in getattr(delta, f"{pb_field}_delete"):
                gate.delete_marks(attr, name, name in store, marks)
                store.pop(name, None)
        if delta.HasField("now"):
            cluster.now = delta.now
            marks.append(("time", ""))
    finally:
        # merge even when a later message raises mid-delta (an unknown
        # enum value, a malformed doc): every store mutation that DID
        # apply must reach the journal or the incremental snapshotter
        # serves a silently stale patch
        gate.merge_marks(journal, marks)


def commit_to_msg(result) -> "pb.CommitSet":
    out = pb.CommitSet()
    for br in result.bind_requests:
        to_msg(br, out.bind_requests.add())
    for ev in result.evictions:
        to_msg(ev, out.evictions.add())
    for k, v in result.action_seconds.items():
        out.action_seconds[k] = v
    return out

"""Sidecar wire protocol: protobuf schema + codec (see sidecar.proto)."""
from . import codec, sidecar_pb2  # noqa: F401

"""Pod×node feasibility masks — the predicates plugin, tensorized.

The reference checks each candidate node for a task through a chain of
predicate functions (``plugins/predicates/predicates.go:104-130`` wrapping
upstream kube-scheduler filters, dispatched per node in
``framework/session.go:201-232`` ``FittingNode``).  That is an O(nodes)
host loop per task; here the whole chain is a single broadcast expression
producing a boolean ``[..., N]`` mask, evaluated for every task at once
(vmapped over the task axis) on the MXU-adjacent vector units.

Covered predicate surface (the resource+label subset per SURVEY.md §7
"hard parts" (6); exotic predicates stay host-side fallbacks):

- node validity (schedulable, in-partition)
- resource fit against ``free`` (idle) resources
- resource fit against ``free + releasing`` (the *pipeline* variant the
  reference uses to queue a task behind terminating pods)
- nodeSelector equality matching via the label-vocabulary encoding
- fractional accelerator fit (portion ≤ free accel, cf. gpu_sharing)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..apis.types import RESOURCE_ACCEL
from ..state.cluster_state import NodeState

EPS = 1e-6


def selector_mask(node_labels: jax.Array, task_selector: jax.Array) -> jax.Array:
    """nodeSelector match — ref upstream NodeAffinity/selector filter.

    ``node_labels``  i32 [N, K]  label value-id per selector key (-1 unset)
    ``task_selector`` i32 [..., K] required value-id per key (-1 = any)

    Returns bool [..., N]: True where every required key matches.
    """
    required = task_selector[..., None, :] >= 0              # [..., 1, K]
    matches = node_labels == task_selector[..., None, :]     # [..., N, K]
    return jnp.all(~required | matches, axis=-1)


def resource_fit_mask(
    available: jax.Array,      # f32 [N, R]
    task_req: jax.Array,       # f32 [..., R]
) -> jax.Array:
    """True where the task's request fits the node's available vector.

    The accel component of ``task_req`` already carries fractional /
    memory-based shares (set at snapshot build), so this is a pure
    broadcast compare; device-granular accel checks are layered on by
    :func:`accel_fit_mask`.
    """
    req = jnp.asarray(task_req)
    return jnp.all(available + EPS >= req[..., None, :], axis=-1)


def node_portion(
    nodes: NodeState,
    task_portion: jax.Array,    # f32 [...]
    task_accel_mem: jax.Array | None,  # f32 [...]
) -> jax.Array:
    """Per-node effective share of one device — f32 [..., N].

    Plain fractions are node-independent; memory-based requests divide by
    each node's per-device memory (ref memory-based GPU sharing,
    ``gpu_resource_requirment.go`` gpuMemory / MemoryOfEveryGpuOnNode).
    """
    p = jnp.asarray(task_portion)[..., None] * jnp.ones_like(
        nodes.device_memory_gib)
    if task_accel_mem is not None:
        mem = jnp.asarray(task_accel_mem)[..., None]
        # NO clamp to 1.0: a request larger than a node's device memory
        # yields portion > 1 and is correctly infeasible on that node
        by_mem = mem / jnp.maximum(nodes.device_memory_gib, EPS)
        p = jnp.where(mem > 0, by_mem, p)
    return p


def _accel_pool_ok(
    df: jax.Array,              # f32 [N, D]  the device pool to check
    p: jax.Array,               # f32 [..., N] per-node fractional share
    is_frac: jax.Array,         # bool [...]
    req_accel: jax.Array,       # f32 [...]
) -> jax.Array:
    """Core device-pool check shared by :func:`accel_fit_mask` and the
    allocator's fused :func:`feasible_nodes_dual`: a fractional task needs
    ONE device with enough free share; a whole-device task needs enough
    fully-free devices.  bool [..., N]."""
    frac_ok = jnp.max(df, axis=-1) >= p - EPS                  # [..., N]
    whole_free = jnp.sum((df >= 1.0 - EPS).astype(jnp.float32), axis=-1)
    whole_ok = whole_free + EPS >= jnp.asarray(req_accel)[..., None]
    return jnp.where(jnp.asarray(is_frac)[..., None], frac_ok, whole_ok)


def accel_fit_mask(
    nodes: NodeState,
    task_req: jax.Array,        # f32 [..., R]
    task_portion: jax.Array | None,
    task_accel_mem: jax.Array | None,
    device_free: jax.Array,     # f32 [N, D]
    include_releasing: bool,
) -> jax.Array:
    """Device-granular accel feasibility — the ``FittingGPUs`` check
    (``gpu_sharing/gpu_sharing.go``).  bool [..., N]."""
    df = device_free
    if include_releasing:
        df = df + nodes.device_releasing
    req_accel = jnp.asarray(task_req)[..., RESOURCE_ACCEL]
    if task_portion is None:
        is_frac = jnp.zeros(jnp.shape(req_accel), bool)
        p = jnp.zeros(jnp.shape(req_accel) + (nodes.n,))
    else:
        mem = (jnp.zeros_like(task_portion) if task_accel_mem is None
               else jnp.asarray(task_accel_mem))
        is_frac = (jnp.asarray(task_portion) > 0) | (mem > 0)
        p = node_portion(nodes, task_portion, task_accel_mem)  # [..., N]
    return _accel_pool_ok(df, p, is_frac, req_accel)


def feasible_nodes(
    nodes: NodeState,
    task_req: jax.Array,        # f32 [..., R]
    task_selector: jax.Array,   # i32 [..., K]
    task_portion: jax.Array | None = None,
    task_accel_mem: jax.Array | None = None,
    *,
    task_class: jax.Array | None = None,  # i32 [...] node-filter class
    free: jax.Array | None = None,
    device_free: jax.Array | None = None,
    include_releasing: bool = False,
) -> jax.Array:
    """Full predicate chain → bool [..., N].

    ``free`` / ``device_free`` override the snapshot's idle tensors (the
    allocation kernel passes its *running* tensors as allocation
    proceeds).  ``include_releasing`` gives the pipeline variant: a node
    qualifies if the task fits once terminating pods release their
    resources (ref ``pod_info.IsTaskAllocatableOnReleasingOrIdle``).
    """
    avail = nodes.free if free is None else free
    df = nodes.device_free if device_free is None else device_free
    if include_releasing:
        avail = avail + nodes.releasing
    req = jnp.asarray(task_req)
    if task_portion is not None:
        # fractional / memory-based accel is checked at device granularity
        # (the canonical accel quantity is a cluster-wide accounting value
        # whose per-node share differs) — drop it from the node-sum check
        mem = (jnp.zeros_like(task_portion) if task_accel_mem is None
               else jnp.asarray(task_accel_mem))
        is_frac = (jnp.asarray(task_portion) > 0) | (mem > 0)
        req = req.at[..., RESOURCE_ACCEL].set(
            jnp.where(is_frac, 0.0, req[..., RESOURCE_ACCEL]))
    fit = resource_fit_mask(avail, req)
    accel = accel_fit_mask(nodes, task_req, task_portion, task_accel_mem,
                           df, include_releasing)
    sel = selector_mask(nodes.labels, task_selector)
    out = fit & accel & sel & nodes.valid
    if task_class is not None:
        # taints/affinity/pod-affinity, host-evaluated per filter class
        out = out & nodes.filter_masks[task_class]
    return out


def feasible_nodes_dual(
    nodes: NodeState,
    task_req: jax.Array,        # f32 [R]
    task_selector: jax.Array,   # i32 [K]
    task_portion: jax.Array,    # f32 []
    task_accel_mem: jax.Array,  # f32 []
    *,
    free: jax.Array,            # f32 [N, R]
    device_free: jax.Array,     # f32 [N, D]
    extra_releasing: jax.Array,        # f32 [N, R]
    extra_device_releasing: jax.Array, # f32 [N, D]
    devices: bool = True,
    task_class: jax.Array | None = None,  # i32 [] node-filter class
) -> tuple[jax.Array, jax.Array]:
    """(fit_idle, fit_pipe) in one pass — the allocation kernel's hot
    check, sharing the selector/validity work between the idle pool and
    the idle+releasing (pipeline) pool instead of two full chains.

    ``devices=False`` skips the device-granular table (valid when the
    snapshot holds no fractional/memory-based tasks — the node-level
    accel vector is then exact)."""
    mem = jnp.asarray(task_accel_mem)
    portion = jnp.asarray(task_portion)
    is_frac = (portion > 0) | (mem > 0)
    req = jnp.asarray(task_req)
    sel = selector_mask(nodes.labels, task_selector) & nodes.valid     # [N]
    if task_class is not None:
        sel = sel & nodes.filter_masks[task_class]

    if not devices:
        fit_idle = jnp.all(free + EPS >= req[None, :], axis=-1) & sel
        avail = free + nodes.releasing + extra_releasing
        fit_pipe = jnp.all(avail + EPS >= req[None, :], axis=-1) & sel
        return fit_idle, fit_pipe

    req_nosum = req.at[RESOURCE_ACCEL].set(
        jnp.where(is_frac, 0.0, req[RESOURCE_ACCEL]))
    p = node_portion(nodes, portion, mem)                              # [N]
    req_accel = req[RESOURCE_ACCEL]

    def pools(avail, df):
        return (resource_fit_mask(avail, req_nosum)
                & _accel_pool_ok(df, p, is_frac, req_accel))

    fit_idle = pools(free, device_free) & sel
    fit_pipe = pools(
        free + nodes.releasing + extra_releasing,
        device_free + nodes.device_releasing + extra_device_releasing) & sel
    return fit_idle, fit_pipe


def gang_feasibility(
    nodes: NodeState,
    task_req: jax.Array,       # f32 [T, R]
    task_valid: jax.Array,     # bool [T]
    task_selector: jax.Array,  # i32 [T, K]
    min_member: jax.Array,     # i32 []
    *,
    free: jax.Array | None = None,
) -> jax.Array:
    """Cheap whole-gang prefilter — ref ``actions/common/feasible_nodes.go:11``
    (FeasibleNodesForJob) and the MinimalJobRepresentatives skip logic.

    A gang is *hopeless* this cycle if fewer than ``min_member`` of its
    tasks have any feasible node at all, counting each node's capacity only
    coarsely (no cross-task capacity interaction — that is the allocation
    kernel's job).  Returns a scalar bool (True = worth attempting).
    """
    per_task = feasible_nodes(nodes, task_req, task_selector, free=free)  # [T, N]
    has_node = jnp.any(per_task, axis=-1) & task_valid
    return jnp.sum(has_node.astype(jnp.int32)) >= min_member

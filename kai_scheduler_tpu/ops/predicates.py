"""Pod×node feasibility masks — the predicates plugin, tensorized.

The reference checks each candidate node for a task through a chain of
predicate functions (``plugins/predicates/predicates.go:104-130`` wrapping
upstream kube-scheduler filters, dispatched per node in
``framework/session.go:201-232`` ``FittingNode``).  That is an O(nodes)
host loop per task; here the whole chain is a single broadcast expression
producing a boolean ``[..., N]`` mask, evaluated for every task at once
(vmapped over the task axis) on the MXU-adjacent vector units.

Covered predicate surface (the resource+label subset per SURVEY.md §7
"hard parts" (6); exotic predicates stay host-side fallbacks):

- node validity (schedulable, in-partition)
- resource fit against ``free`` (idle) resources
- resource fit against ``free + releasing`` (the *pipeline* variant the
  reference uses to queue a task behind terminating pods)
- nodeSelector equality matching via the label-vocabulary encoding
- fractional accelerator fit (portion ≤ free accel, cf. gpu_sharing)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..apis.types import RESOURCE_ACCEL
from ..state.cluster_state import NodeState

EPS = 1e-6


def selector_mask(node_labels: jax.Array, task_selector: jax.Array) -> jax.Array:
    """nodeSelector match — ref upstream NodeAffinity/selector filter.

    ``node_labels``  i32 [N, K]  label value-id per selector key (-1 unset)
    ``task_selector`` i32 [..., K] required value-id per key (-1 = any)

    Returns bool [..., N]: True where every required key matches.
    """
    required = task_selector[..., None, :] >= 0              # [..., 1, K]
    matches = node_labels == task_selector[..., None, :]     # [..., N, K]
    return jnp.all(~required | matches, axis=-1)


def resource_fit_mask(
    available: jax.Array,      # f32 [N, R]
    task_req: jax.Array,       # f32 [..., R]
    task_portion: jax.Array | None = None,  # f32 [...]
) -> jax.Array:
    """True where the task's request fits the node's available vector.

    A fractional task (portion > 0) requests ``portion`` of one device in
    the accel slot instead of its whole-device count (the reference keeps
    these in separate fields of GpuResourceRequirement; here the portion
    overrides the accel component of the request when set).
    """
    req = jnp.asarray(task_req)
    if task_portion is not None:
        accel = jnp.where(task_portion > 0, task_portion, req[..., RESOURCE_ACCEL])
        req = req.at[..., RESOURCE_ACCEL].set(accel)
    return jnp.all(available + EPS >= req[..., None, :], axis=-1)


def feasible_nodes(
    nodes: NodeState,
    task_req: jax.Array,        # f32 [..., R]
    task_selector: jax.Array,   # i32 [..., K]
    task_portion: jax.Array | None = None,
    *,
    free: jax.Array | None = None,
    include_releasing: bool = False,
) -> jax.Array:
    """Full predicate chain → bool [..., N].

    ``free`` overrides the snapshot's idle vector (the allocation kernel
    passes its *running* free tensor as allocation proceeds).
    ``include_releasing`` gives the pipeline variant: a node qualifies if
    the task fits once terminating pods release their resources
    (ref ``pod_info.IsTaskAllocatableOnReleasingOrIdle``).
    """
    avail = nodes.free if free is None else free
    if include_releasing:
        avail = avail + nodes.releasing
    fit = resource_fit_mask(avail, task_req, task_portion)
    sel = selector_mask(nodes.labels, task_selector)
    return fit & sel & nodes.valid


def gang_feasibility(
    nodes: NodeState,
    task_req: jax.Array,       # f32 [T, R]
    task_valid: jax.Array,     # bool [T]
    task_selector: jax.Array,  # i32 [T, K]
    min_member: jax.Array,     # i32 []
    *,
    free: jax.Array | None = None,
) -> jax.Array:
    """Cheap whole-gang prefilter — ref ``actions/common/feasible_nodes.go:11``
    (FeasibleNodesForJob) and the MinimalJobRepresentatives skip logic.

    A gang is *hopeless* this cycle if fewer than ``min_member`` of its
    tasks have any feasible node at all, counting each node's capacity only
    coarsely (no cross-task capacity interaction — that is the allocation
    kernel's job).  Returns a scalar bool (True = worth attempting).
    """
    per_task = feasible_nodes(nodes, task_req, task_selector, free=free)  # [T, N]
    has_node = jnp.any(per_task, axis=-1) & task_valid
    return jnp.sum(has_node.astype(jnp.int32)) >= min_member

"""Hierarchical DRF fair-share division — the proportion plugin's core math.

TPU-native rebuild of the reference algorithm in
``pkg/scheduler/plugins/proportion/resource_division/resource_division.go``
(see also ``docs/fairness/README.md:43-60``):

1. **Deserved pass** — every queue gets ``min(deserved, requestable)``.
2. **Over-quota pass** — the surplus is divided among still-unsatisfied
   queues, highest priority tier first; within a tier an iterative
   water-fill hands each queue ``remaining * shareWeight_i / sum(shareWeight)``
   where ``shareWeight = max(0, w + k*(w - usage))`` (w = normalized
   over-quota weight, usage = normalized historical usage — the
   time-based-fairshare hook).  Unsatisfied queues are floored to whole
   units per round ("round numbers" rule in the reference).
3. **Remainder pass** — leftover whole units go one per queue, ordered by
   priority, then largest fractional remainder, then creation order
   (ref ``divideRemainingResource`` + ``remainingRequestedOrderFn``).

The reference runs this per resource with Go maps and heaps; here every
pass is a masked segment-reduction over the queue axis, so all sibling
groups (segments keyed by parent queue) and all resources (via ``vmap``)
divide concurrently.  Hierarchy is handled level-by-level: a parent's
fair share becomes the "total" for dividing among its children.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..apis.types import UNLIMITED
from ..state.cluster_state import ClusterState, QueueState

_NEG_INF = jnp.iinfo(jnp.int32).min


def _segment_sum(values: jax.Array, seg: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_sum(values, seg, num_segments=num_segments)


def _divide_one_resource(
    seg_total: jax.Array,      # f32 [S]   total amount per sibling segment
    quota: jax.Array,          # f32 [Q]   deserved; UNLIMITED => segment total
    weight: jax.Array,         # f32 [Q]   over-quota weight
    limit: jax.Array,          # f32 [Q]   maxAllowed; UNLIMITED => none
    request: jax.Array,        # f32 [Q]
    usage: jax.Array,          # f32 [Q]   normalized historical usage
    priority: jax.Array,       # i32 [Q]
    seg: jax.Array,            # i32 [Q]   sibling-segment id (parent+1)
    creation: jax.Array,       # i32 [Q]   tie-break, lower = older
    active: jax.Array,         # bool [Q]  queue participates at this level
    k_value: jax.Array,        # f32 []
) -> jax.Array:
    """Fair share for one resource across all sibling segments at one level."""
    S = seg_total.shape[0]
    q_total = seg_total[seg]                      # segment total seen by queue

    unlimited_limit = limit <= UNLIMITED + 0.5
    requestable = jnp.where(unlimited_limit, request, jnp.minimum(request, limit))
    requestable = jnp.maximum(requestable, 0.0)
    deserved = jnp.where(quota <= UNLIMITED + 0.5, q_total, quota)

    # -- pass 1: deserved (ref setDeservedResource) ------------------------
    fs = jnp.where(active, jnp.minimum(deserved, requestable), 0.0)
    remaining = jnp.maximum(seg_total - _segment_sum(fs, seg, S), 0.0)

    def unsatisfied(fs):
        # ref isQueueSatisfied, inverted
        sat = (request <= fs) | (~unlimited_limit & (limit <= fs))
        return active & ~sat

    # -- pass 2: over-quota by priority tier (ref divideOverQuotaResource) -
    def tier_cond(carry):
        fs, remaining, rem_frac, processed = carry
        cand = unsatisfied(fs) & (weight > 0) & ~processed
        return jnp.any(cand & (remaining[seg] > 0))

    def tier_body(carry):
        fs, remaining, rem_frac, processed = carry
        cand = unsatisfied(fs) & (weight > 0) & ~processed
        # highest unprocessed priority per segment forms the current tier
        pr = jnp.where(cand, priority, _NEG_INF)
        cur_p = jax.ops.segment_max(pr, seg, num_segments=S)
        tier = cand & (priority == cur_p[seg])

        def fill_cond(c):
            fs, remaining, rem_frac, again = c
            return again

        def fill_body(c):
            fs, remaining, rem_frac, _ = c
            unsat = unsatisfied(fs) & tier
            remreq = jnp.where(unsat, jnp.maximum(requestable - fs, 0.0), 0.0)
            wants = unsat & (remreq > 0)
            # normalize weights among wanting queues (ref calcShareWeights)
            tot_w = _segment_sum(jnp.where(wants, weight, 0.0), seg, S)
            n_w = jnp.where(wants & (tot_w[seg] > 0), weight / jnp.maximum(tot_w[seg], 1e-30), 0.0)
            share_w = jnp.maximum(0.0, n_w + k_value * (n_w - usage)) * wants
            sum_w = _segment_sum(share_w, seg, S)
            ok = wants & (sum_w[seg] > 0)
            fair = jnp.where(ok, remaining[seg] * share_w / jnp.maximum(sum_w[seg], 1e-30), 0.0)
            satisfied_now = remreq <= fair
            give = jnp.where(ok, jnp.where(satisfied_now, remreq, jnp.floor(fair)), 0.0)
            new_rem = jnp.where(ok & ~satisfied_now, fair - jnp.floor(fair), 0.0)
            # keep earlier remainder if this round gave this queue nothing new
            rem_frac = jnp.where(ok, new_rem, jnp.where(tier & satisfied_now, 0.0, rem_frac))
            fs = fs + give
            gave = _segment_sum(give, seg, S)
            remaining = jnp.maximum(remaining - gave, 0.0)
            # another round only if someone was capped by request below its
            # round fair share (freed amount can be re-divided) — ref
            # shouldRunAnotherRound
            freed = _segment_sum(jnp.where(ok & satisfied_now & (remreq < fair), 1.0, 0.0), seg, S)
            again = jnp.any((freed > 0) & (remaining > 0) & (gave > 0))
            return fs, remaining, rem_frac, again

        fs, remaining, rem_frac, _ = lax.while_loop(
            fill_cond, fill_body,
            (fs, remaining, rem_frac, jnp.asarray(True)))
        processed = processed | tier
        return fs, remaining, rem_frac, processed

    rem_frac = jnp.zeros_like(fs)
    processed = jnp.zeros_like(active)
    fs, remaining, rem_frac, _ = lax.while_loop(
        tier_cond, tier_body, (fs, remaining, rem_frac, processed))

    # -- pass 3: whole-unit remainders (ref divideRemainingResource) -------
    # order: priority desc, fractional remainder desc, creation asc.
    has_rem = active & (rem_frac > 0)
    # pairwise in-segment rank (Q is small; Q^2 is cheap on device)
    same_seg = seg[:, None] == seg[None, :]
    pi, pj = priority[:, None], priority[None, :]
    ri, rj = rem_frac[:, None], rem_frac[None, :]
    ci, cj = creation[:, None], creation[None, :]
    j_before_i = (pj > pi) | ((pj == pi) & (rj > ri)) | \
                 ((pj == pi) & (rj == ri) & (cj < ci))
    rank = jnp.sum(same_seg & has_rem[None, :] & j_before_i, axis=1)
    give3 = jnp.where(has_rem, jnp.clip(remaining[seg] - rank, 0.0, 1.0), 0.0)
    fs = fs + give3
    return fs


def divide_level(
    queues: QueueState,
    seg_total: jax.Array,   # f32 [Q+1, R]  totals per segment (slot 0 = root)
    level_mask: jax.Array,  # bool [Q]
    k_value: jax.Array,
) -> jax.Array:
    """Run the three-pass division for every resource at one hierarchy level."""
    seg = jnp.where(queues.parent >= 0, queues.parent + 1, 0)
    fs = jax.vmap(
        _divide_one_resource,
        in_axes=(1, 1, 1, 1, 1, 1, None, None, None, None, None),
        out_axes=1,
    )(
        seg_total, queues.quota, queues.over_quota_weight, queues.limit,
        queues.request, queues.usage, queues.priority, seg,
        queues.creation_order, level_mask, k_value,
    )
    return fs


def set_fair_share(
    state: ClusterState,
    *,
    num_levels: int,
    k_value: float = 0.0,
) -> jax.Array:
    """Compute ``fair_share [Q, R]`` for the whole hierarchy.

    TPU analogue of ``SetResourcesShare`` (``resource_division.go:26-41``)
    plus the hierarchical recursion described in ``docs/fairness/README.md``:
    level 0 divides the cluster total; level d divides each parent's fair
    share among its children.  ``num_levels`` is static (snapshot-known).
    """
    q = state.queues
    k = jnp.asarray(k_value, q.quota.dtype)
    total = state.total_capacity                      # [R]
    fair_share = jnp.zeros_like(q.quota)
    for depth in range(num_levels):
        seg_total = jnp.concatenate([total[None, :], fair_share], axis=0)
        level_mask = q.valid & (q.depth == depth)
        fs_level = divide_level(q, seg_total, level_mask, k)
        fair_share = jnp.where(level_mask[:, None], fs_level, fair_share)
    return fair_share

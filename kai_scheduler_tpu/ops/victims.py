"""Victim-scenario engine — reclaim & preempt as compiled scenario search.

Reference (``actions/common/solvers/job_solver.go:47-120``,
``by_pod_solver.go:20-90``): for a pending *preemptor* gang, grow a victim
set one eviction unit at a time (``PodAccumulatedScenarioBuilder``), and
for each scenario simulate "evict victims, re-run allocation" inside a
Statement; the first scenario whose simulation places the preemptor and
passes the scenario validators wins.  The eviction *unit*
(``api/podgroup_info/eviction_info.go:14`` GetTasksToEvict) is a single
task while the victim gang is elastic (above minMember), then the whole
remaining gang at once.  The ``idle_gpus`` accumulated filter
(``accumulated_scenario_filters/idle_gpus.go``) prunes scenarios whose
freed capacity still cannot fit the preemptor.

TPU-native design: victims are *ranked once* per preemptor — victim jobs
by a lexsort over gang keys (the ordered victim-queue generator), pods
within a gang by reverse task order — giving every candidate pod a global
*unit rank*; a scenario is a unit-rank prefix.  A ``lax.while_loop``
walks scenarios in order, each iteration:

1. masks pods with ``unit_rank <= k`` and segment-sums their requests
   into per-node freed capacity (no [scenarios, N, R] materialization),
2. checks the reclaim strategy for the unit being added (against the
   leveled queue's remaining share — see below),
3. runs the same gang-placement kernel the allocate action uses
   (``_attempt_gang``) on ``free + freed`` — first success wins,
   mirroring the reference's minimal-victim greedy.

The idle-capacity prefilter fast-forwards ``k`` to the first scenario
whose aggregate freed + idle covers the preemptor's request.

Validation semantics implemented (see
``plugins/proportion/reclaimable/reclaimable.go`` and
``reclaimable/strategies/strategies.go``):

- **CanReclaimResources gate**: reclaimer queue (and ancestors) must stay
  within fair share after the allocation; a non-preemptible reclaimer's
  non-preemptible allocation must stay within deserved quota.
- **Per-eviction strategy** at the *leveled* queue (the victim-side
  ancestor just below the LCA with the reclaimer —
  ``reclaimable.go getLeveledQueues``): evictable only while that queue
  is above fair share (MaintainFairShare) or, when the reclaimer is under
  deserved quota, above deserved (GuaranteeDeservedQuota) — evaluated
  against the remaining share before the step, exactly like the
  reference's running ``remainingResourcesMap``.
- **Preempt gate** (``actions/preempt/preempt.go:100-110``): a
  non-preemptible preemptor must keep the queue's non-preemptible
  allocation within deserved quota.
- Sibling saturation-order checks degenerate to true under the gate
  (reclaimer saturation ≤ 1) and are omitted; ``minruntime`` victim
  protection is a candidate filter here rather than a separate validator.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..apis.types import UNLIMITED
from ..state.cluster_state import ClusterState
from . import ordering
from .allocate import (AllocateConfig, AllocationResult, _ancestor_gate,
                       _attempt_gang, _chain_membership, init_result)

EPS = 1e-6
BIG = jnp.int32(2**30)


@dataclasses.dataclass(frozen=True)
class VictimConfig:
    """Knobs of the victim actions (ref reclaim/preempt action args)."""

    placement: AllocateConfig = AllocateConfig(dynamic_order=False)
    #: reclaimerSaturationMultiplier (``plugins/proportion/proportion.go:67-95``)
    saturation_multiplier: float = 1.0
    #: max preemptor gangs attempted per cycle (QueueDepthPerAction)
    queue_depth: int | None = None
    #: cap on eviction units per consolidation scenario — ref
    #: ``MaxNumberConsolidationPreemptees`` (consolidation.go)
    max_consolidation_preemptees: int = 64


def freed_by_mask(state: ClusterState, mask: jax.Array, chain: jax.Array):
    """Resources released by evicting the masked running pods.

    Returns (freed_nodes [N, R], freed_devices [N, D], freed_queues
    [Q, R], freed_queues_nonpreemptible [Q, R]) with the queue tensors
    rolled up the hierarchy via ``chain`` — shared by the victim solver
    and the stalegangeviction action.
    """
    r = state.running
    n, q = state.nodes, state.queues
    D = n.d
    req_m = jnp.where(mask[:, None], r.req, 0.0)
    freed_nodes = jax.ops.segment_sum(
        req_m, jnp.where(mask, jnp.maximum(r.node, 0), n.n),
        num_segments=n.n + 1)[:n.n]
    # device table: fractional pods return their held share to their
    # device; whole-device pods return 1.0 per devices_mask bit
    frac = mask & (r.device >= 0)
    flat = jnp.maximum(r.node, 0) * D + jnp.maximum(r.device, 0)
    freed_dev = jax.ops.segment_sum(
        jnp.where(frac, r.accel_held, 0.0),
        jnp.where(frac, flat, n.n * D),
        num_segments=n.n * D + 1)[:n.n * D].reshape(n.n, D)
    bits = ((r.devices_mask[:, None] >> jnp.arange(D)[None, :]) & 1)
    whole_bits = bits.astype(req_m.dtype) * (mask & (r.device < 0))[:, None]
    freed_dev = freed_dev + jax.ops.segment_sum(
        whole_bits, jnp.where(mask, jnp.maximum(r.node, 0), n.n),
        num_segments=n.n + 1)[:n.n]
    leaf = jax.ops.segment_sum(
        req_m, jnp.where(mask, jnp.maximum(r.queue, 0), q.q),
        num_segments=q.q + 1)[:q.q]
    leaf_np = jax.ops.segment_sum(
        jnp.where((mask & ~r.preemptible)[:, None], r.req, 0.0),
        jnp.where(mask & ~r.preemptible, jnp.maximum(r.queue, 0), q.q),
        num_segments=q.q + 1)[:q.q]
    chain_f = chain.astype(leaf.dtype)
    freed_q = jnp.einsum("qa,qr->ar", chain_f, leaf)
    freed_q_np = jnp.einsum("qa,qr->ar", chain_f, leaf_np)
    return freed_nodes, freed_dev, freed_q, freed_q_np


def victim_candidates(
    state: ClusterState,
    gang_idx: jax.Array,
    *,
    mode: str,
    already_victim: jax.Array,   # bool [M]
) -> jax.Array:
    """bool [M] — pods eligible as victims for this preemptor.

    Reclaim filter (``actions/reclaim/reclaim.go`` victim generator +
    ``ReclaimVictimFilter``): preemptible running pods of *other* queues
    that have run at least their queue's ``reclaimMinRuntime``.
    Preempt filter (``buildFilterFuncForPreempt``): preemptible running
    pods of the *same* queue whose gang priority is strictly lower, past
    ``preemptMinRuntime``.
    Consolidation (``actions/consolidation``): any preemptible running pod
    of another gang — victims are *moved*, not lost, so no queue or
    priority constraint applies (minruntime still protects).
    """
    r = state.running
    g = state.gangs
    q = state.queues
    G = g.g
    base = (r.valid & ~r.releasing & (r.node >= 0) & r.preemptible
            & (r.gang >= 0) & ~already_victim)
    my_queue = g.queue[gang_idx]
    # gang-level minruntime protection (hierarchy/LCA-resolved at
    # snapshot build — ref plugins/minruntime/resolver.go).  A protected
    # gang may still shed ELASTIC surplus pods; only its quorum unit is
    # off-limits (ref reclaimFilterFn returning true for elastic jobs +
    # the scenario validator) — enforced by the unit ranking, which gives
    # protected gangs no whole-gang unit.
    gang_runtime = jax.ops.segment_max(
        jnp.where(r.valid & (r.gang >= 0), r.runtime_s, -1.0),
        jnp.where(r.gang >= 0, r.gang, G), num_segments=G + 1)[:G]
    gq = jnp.maximum(g.queue, 0)
    if mode == "reclaim":
        mrt_g = q.reclaim_min_runtime_eff[gq, my_queue]          # [G]
    else:
        mrt_g = q.preempt_min_runtime_eff[gq]
    protected = (gang_runtime >= 0) & (gang_runtime < mrt_g)     # [G]
    if mode == "reclaim":
        return base & (r.queue != my_queue), protected
    if mode == "consolidate":
        return base & (r.gang != gang_idx), protected
    return (base & (r.queue == my_queue)
            & (r.priority < g.priority[gang_idx])), protected


def _rank_eviction_units(
    state: ClusterState,
    cand: jax.Array,             # bool [M]
    queue_allocated: jax.Array,  # f32 [Q, R]
    fair_share: jax.Array,       # f32 [Q, R]
    already_victim: jax.Array,   # bool [M]  victims accumulated this cycle
    protected: jax.Array | None = None,  # bool [G]  minruntime-protected
):
    """Assign every candidate pod a global eviction-unit rank.

    Victim *jobs* are ordered by a lexsort over gang keys — the reference
    generates victims queue-by-queue in reversed queue order (most
    over-fair-share first) and job-by-job in reversed job order (lowest
    priority, newest first).  Within a gang, pods are ordered by reverse
    task order (shortest-running ≈ newest first); each of the first
    ``allocated - minMember`` pods is its own unit (elastic shrink), the
    remaining ``minMember`` pods form one final unit
    (``eviction_info.go GetTasksToEvict``).

    Returns (unit_rank [M] i32 — BIG for non-candidates, num_units []).
    """
    g = state.gangs
    r = state.running
    G, M = g.g, r.m

    gang_of_pod = jnp.where(cand, r.gang, G)                   # [M], G = junk
    pods_per_gang = jax.ops.segment_sum(
        cand.astype(jnp.int32), gang_of_pod, num_segments=G + 1)[:G]
    victim_gang = pods_per_gang > 0

    # ---- job-level ordering ---------------------------------------------
    sat = jnp.max(
        queue_allocated / jnp.maximum(fair_share, EPS), axis=-1)  # [Q]
    gq = jnp.maximum(g.queue, 0)
    # lexsort: last key most significant — non-victim gangs last, most
    # saturated queue first, lowest priority first, newest first.
    rank_gang = jnp.lexsort((
        -g.creation_order.astype(jnp.float32),
        g.priority.astype(jnp.float32),
        -sat[gq],
        (~victim_gang).astype(jnp.float32),
    ))                                                          # [G] gang @ rank
    job_rank = jnp.zeros((G,), jnp.int32).at[rank_gang].set(
        jnp.arange(G, dtype=jnp.int32))                         # [G]

    # ---- pod order within gang (reverse task order: newest first) -------
    perm = jnp.lexsort((r.runtime_s, gang_of_pod))              # [M]
    pos = jnp.zeros((M,), jnp.int32).at[perm].set(
        jnp.arange(M, dtype=jnp.int32))
    first_pos = jax.ops.segment_min(
        jnp.where(cand, pos, BIG), gang_of_pod, num_segments=G + 1)[:G]
    seq = pos - first_pos[jnp.minimum(gang_of_pod, G - 1)]      # [M]

    # ---- unit ids --------------------------------------------------------
    # Surplus is sized from the gang's *effective* active pod count:
    # running_count minus pods already victimised by earlier actions this
    # cycle — the reference's Statement.Evict updates the active-task
    # counts GetTasksToEvict reads, so a gang reclaimed down to minMember
    # by one action is NOT elastic-shrinkable again by the next; the
    # final unit (whole remaining gang) triggers at the right threshold.
    # Pods excluded from candidacy for other reasons (unknown node) still
    # hold the gang above minMember.
    victims_in_gang = jax.ops.segment_sum(
        (already_victim & (r.gang >= 0)).astype(jnp.int32),
        jnp.where(r.gang >= 0, r.gang, G), num_segments=G + 1)[:G]
    effective_active = g.running_count - victims_in_gang        # [G]
    surplus = jnp.clip(
        effective_active - g.min_member, 0, pods_per_gang)      # [G]
    # a minruntime-protected gang keeps its quorum: it exposes only its
    # elastic-surplus units, never the final whole-gang unit (ref the
    # minruntime scenario validators protecting below-minAvailable)
    whole_unit = pods_per_gang > surplus
    if protected is not None:
        whole_unit = whole_unit & ~protected
    units_per_gang = jnp.where(
        victim_gang, surplus + whole_unit, 0)                   # [G]
    units_by_rank = units_per_gang[rank_gang]                   # [G]
    offsets = jnp.cumsum(units_by_rank) - units_by_rank         # [G] excl
    gsafe = jnp.minimum(gang_of_pod, G - 1)
    unit_in_gang = jnp.minimum(seq, surplus[gsafe])
    in_range = unit_in_gang < units_per_gang[gsafe]
    unit_rank = jnp.where(
        cand & in_range,
        offsets[job_rank[gsafe]] + unit_in_gang,
        BIG)
    return unit_rank, jnp.sum(units_per_gang)


def _leveled_queue(chain: jax.Array, depth: jax.Array,
                   vq: jax.Array, rq: jax.Array) -> jax.Array:
    """The victim-side ancestor just below the LCA with the reclaimer —
    ref ``reclaimable.go getLeveledQueues``.  i32 scalar queue index."""
    vchain = chain[vq]                        # bool [Q]
    rchain = chain[rq]
    cand_q = vchain & ~rchain
    d = jnp.where(cand_q, depth, BIG)
    # -1 when every victim ancestor is shared with the reclaimer (victim
    # queue is an ancestor of the reclaimer's) — callers treat -1 as
    # "no leveled queue, strategy check passes".
    return jnp.where(jnp.any(cand_q), jnp.argmin(d), -1)


def solve_for_preemptor(
    state: ClusterState,
    gang_idx: jax.Array,
    result: AllocationResult,
    fair_share: jax.Array,
    chain: jax.Array,            # bool [Q, Q]
    *,
    num_levels: int,
    mode: str,                   # "reclaim" | "preempt" | "consolidate"
    config: VictimConfig,
):
    """One preemptor's scenario search — returns updated commit-set fields.

    (success, victim_mask [M], task placements [T], pipelined [T],
    moves [M], free', qa', qan')
    """
    reclaim = mode == "reclaim"
    consolidate = mode == "consolidate"
    g, q, n, r = state.gangs, state.queues, state.nodes, state.running
    free = result.free
    dev = result.device_free
    extra = result.releasing_extra
    extra_dev = result.device_releasing_extra
    qa = result.queue_allocated
    qan = result.queue_allocated_nonpreemptible
    queue = g.queue[gang_idx]
    task_req = jnp.where(g.task_valid[gang_idx][:, None],
                         g.task_req[gang_idx], 0.0)
    total_req = task_req.sum(0)                                # [R]
    nonpreempt = ~g.preemptible[gang_idx]

    # ---- gates (before any scenario work) -------------------------------
    nonpreempt_quota_ok = jnp.where(
        nonpreempt,
        _ancestor_gate(q.parent, queue, num_levels, qan, q.quota, total_req),
        True)
    if reclaim:
        # CanReclaimResources: stay within fair share along the chain
        gate = _ancestor_gate(q.parent, queue, num_levels, qa,
                              fair_share, total_req) & nonpreempt_quota_ok
    elif consolidate:
        # consolidation only serves pending *preemptible* jobs
        # (``consolidation.go`` pending-preemptible filter)
        gate = ~nonpreempt
    else:
        gate = nonpreempt_quota_ok

    cand, protected = victim_candidates(
        state, gang_idx, mode=mode, already_victim=result.victim)
    gate &= jnp.any(cand)

    # moved (consolidated) victims stay active gang members — they restart
    # on their target node — so only *removed* victims shrink the gang's
    # effective active count for unit sizing
    removed_victims = result.victim & (result.victim_move < 0)
    unit_rank, num_units = _rank_eviction_units(
        state, cand, qa, fair_share, removed_victims, protected)
    if consolidate:
        num_units = jnp.minimum(num_units,
                                config.max_consolidation_preemptees)
    reclaimer_under_quota = _ancestor_gate(
        q.parent, queue, num_levels, qa, q.quota, total_req)
    quota_eff = jnp.where(q.quota <= UNLIMITED + 0.5, jnp.inf, q.quota)
    m_req = jnp.where(cand[:, None], r.req, 0.0)               # [M, R]
    leveled = jax.vmap(
        lambda vq: _leveled_queue(chain, q.depth, vq, queue))(
            jnp.maximum(r.queue, 0))                           # [M]

    # idle_gpus-style prefilter: fast-forward to the first scenario whose
    # aggregate free + freed covers the preemptor's total request.
    unit_freed = jax.ops.segment_sum(
        m_req, jnp.minimum(unit_rank, r.m), num_segments=r.m + 1)[:r.m]
    cum_freed = jnp.cumsum(unit_freed, axis=0)                 # [M, R]
    cluster_free = jnp.sum(
        jnp.where(n.valid[:, None], free + n.releasing + extra, 0.0),
        axis=0)
    enough = jnp.all(cluster_free[None, :] + cum_freed + EPS
                     >= total_req[None, :], axis=-1)           # [M]
    gate_prefilter = jnp.any(enough)  # no scenario can ever fit => skip all

    T = g.t
    alloc_cfg = config.placement

    def freed_tensors(mask):
        """(freed_nodes [N, R], freed_devices [N, D], freed_queues [Q, R])."""
        freed_nodes, freed_dev, freed_q, _ = freed_by_mask(state, mask, chain)
        return freed_nodes, freed_dev, freed_q

    def unit_strategy_ok(k, freed_q_excl):
        """FitsReclaimStrategy for the unit being added at rank ``k``,
        against remaining shares *before* this step."""
        if not reclaim:
            return jnp.asarray(True)
        in_unit = cand & (unit_rank == k)
        # leveled queue of this unit's pods (all share one gang => one queue)
        lq = jnp.max(jnp.where(in_unit, leveled, -1))
        lq_safe = jnp.maximum(lq, 0)
        remaining = qa[lq_safe] - freed_q_excl[lq_safe]        # [R]
        over_fs = jnp.any(remaining > fair_share[lq_safe] + EPS)
        over_quota = jnp.any(remaining > quota_eff[lq_safe] + EPS)
        return (lq < 0) | over_fs | (reclaimer_under_quota & over_quota)

    no_moves = jnp.full((r.m,), -1, jnp.int32)

    def cond(carry):
        k, done, prefix_ok, _ = carry
        return (~done) & prefix_ok & (k < num_units)

    def body(carry):
        k, done, prefix_ok, best = carry
        if reclaim:
            mask_excl = cand & (unit_rank < k)
            _, _, freed_q_excl = freed_tensors(mask_excl)
            prefix_ok = prefix_ok & unit_strategy_ok(k, freed_q_excl)

        def run(_):
            mask_k = cand & (unit_rank <= k)
            freed_nodes, freed_dev, freed_queues = freed_tensors(mask_k)
            # victim capacity is *releasing* until the pods terminate:
            # the preemptor's tasks that land on it pipeline, tasks that
            # fit genuinely idle capacity bind now (stmt.Allocate vs
            # stmt.Pipeline).
            extra_eff = extra + freed_nodes
            extra_dev_eff = extra_dev + freed_dev
            # consolidation victims are moved, not removed — their queue
            # allocation stays (allPodsReallocated validator below)
            qa_eff = qa if consolidate else qa - freed_queues
            # victim search attempts gangs one at a time, so the
            # wavefront bind-claim tensors are not needed; the preemptor's
            # extended (MIG/DRA) debit IS kept so later gangs see the
            # shrunken pool (victims' extended resources are
            # conservatively NOT credited back)
            (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, success,
             _, _, ext2, _) = \
                _attempt_gang(state, gang_idx, free, dev, qa_eff, qan,
                              num_levels, alloc_cfg, extra_eff,
                              extra_dev_eff, chain=chain,
                              ext_free=result.extended_free)
            if consolidate:
                free3, dev3, moves, all_ok = _replace_victims(
                    state, mask_k, free2, dev2, n.releasing + extra_eff,
                    state.nodes.device_releasing + extra_dev_eff)
                return (free3, dev3, qa2, qan2, nodes_t, dev_t, pipe_t,
                        moves, extra_eff, extra_dev_eff, ext2,
                        success & all_ok)
            return (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t,
                    no_moves, extra_eff, extra_dev_eff, ext2, success)

        def skip(_):
            return (free, dev, qa, qan, jnp.full((T,), -1, jnp.int32),
                    jnp.full((T,), -1, jnp.int32),
                    jnp.zeros((T,), bool), no_moves, extra, extra_dev,
                    result.extended_free, jnp.asarray(False))

        (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, moves, extra2,
         extra_dev2, ext2, success) = \
            lax.cond(prefix_ok & enough[jnp.minimum(k, r.m - 1)],
                     run, skip, None)
        best = jax.tree.map(
            lambda new, old: jnp.where(success, new, old),
            (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, moves,
             extra2, extra_dev2, ext2, k),
            best)
        return k + 1, success, prefix_ok, best

    empty = (free, dev, qa, qan, jnp.full((T,), -1, jnp.int32),
             jnp.full((T,), -1, jnp.int32),
             jnp.zeros((T,), bool), no_moves, extra, extra_dev,
             result.extended_free, jnp.asarray(0, jnp.int32))

    def search(_):
        _, done, _, best = lax.while_loop(
            cond, body,
            (jnp.asarray(0, jnp.int32), jnp.asarray(False),
             jnp.asarray(True), empty))
        return done, best

    def no_search(_):
        return jnp.asarray(False), empty

    success, (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, moves,
              extra2, extra_dev2, ext2, k_win) = lax.cond(
                  gate & gate_prefilter, search, no_search, None)

    victim_mask = cand & (unit_rank <= k_win) & success
    return (success, victim_mask, nodes_t, dev_t, pipe_t, moves,
            free2, dev2, extra2, extra_dev2, qa2, qan2, ext2)


def _replace_victims(state: ClusterState, mask: jax.Array, free: jax.Array,
                     device_free: jax.Array, releasing: jax.Array,
                     device_releasing: jax.Array):
    """Greedy re-placement of evicted consolidation victims — the
    ``allPodsReallocated`` validator (``consolidation.go:115-120``): the
    scenario is valid only if *every* victim fits somewhere on the
    post-preemptor state.  Feasibility = resources + the pod's node-filter
    class (taints/affinity); binpack by least free accel.  Moves may
    draw on releasing capacity (including other victims' freed spots) —
    they are always pipelined rebinds, waiting for the old pods to vacate.

    Returns (free' [N, R], device_free' [N, D], moves [M] i32 node per
    victim, all_ok [])."""
    r, n = state.running, state.nodes
    M = r.m
    D = n.d

    def body(m, carry):
        free_l, dev_l, moves, all_ok = carry
        needed = mask[m]
        req = r.req[m]
        is_frac = r.device[m] >= 0
        # memory-based portions are node-relative: recompute for every
        # candidate target (a 40GiB share is 0.5 of an 80GiB device but
        # 2.5 of a 16GiB one)
        p_n = jnp.where(
            r.accel_mem[m] > 0,
            r.accel_mem[m] / jnp.maximum(n.device_memory_gib, EPS),
            r.accel_held[m])                                   # [N]
        avail = free_l + releasing
        dev_avail = dev_l + device_releasing
        fit = (jnp.all(avail + EPS >= req[None, :], axis=-1) & n.valid
               & n.filter_masks[r.filter_class[m]])
        frac_fit = jnp.max(dev_avail, axis=-1) >= p_n - EPS
        whole_free = jnp.sum((dev_avail >= 1.0 - EPS).astype(free_l.dtype),
                             axis=-1)
        whole_fit = whole_free + EPS >= req[0]
        fit = fit & jnp.where(is_frac, frac_fit, whole_fit)
        score = jnp.where(fit, -avail[:, 0], -jnp.inf)
        node = jnp.argmax(score)
        placed = needed & jnp.any(fit)
        p = p_n[node]
        delta = jnp.where(placed, req, 0.0)
        delta = delta.at[0].set(
            jnp.where(placed, jnp.where(is_frac, p, req[0]), 0.0))
        free_l = free_l.at[node].add(-delta)
        # device debit: fraction joins its best-fitting device; whole
        # takes the first fully-free devices
        dev_row = dev_avail[node]
        frac_dev = jnp.argmax(dev_row)
        k = jnp.round(req[0]).astype(jnp.int32)
        fully = dev_row >= 1.0 - EPS
        take = fully & (jnp.cumsum(fully.astype(jnp.int32)) <= k)
        dev_delta = jnp.where(
            is_frac, p * (jnp.arange(D) == frac_dev),
            take.astype(dev_row.dtype))
        dev_l = dev_l.at[node].add(-jnp.where(placed, dev_delta, 0.0))
        moves = moves.at[m].set(jnp.where(placed, node, -1))
        all_ok = all_ok & (~needed | placed)
        return free_l, dev_l, moves, all_ok

    return lax.fori_loop(
        0, M, body,
        (free, device_free, jnp.full((M,), -1, jnp.int32),
         jnp.asarray(True)))


def run_victim_action(
    state: ClusterState,
    fair_share: jax.Array,
    result: AllocationResult,
    *,
    num_levels: int,
    mode: str,                   # "reclaim" | "preempt" | "consolidate"
    config: VictimConfig = VictimConfig(),
) -> AllocationResult:
    """The reclaim / preempt / consolidation action: scan pending
    unallocated gangs in fairness order, solving victim scenarios for each.

    Functional equivalent of ``reclaim.Execute`` / ``preempt.Execute`` /
    ``consolidation.Execute``.  Successful preemptors are committed as
    *pipelined* placements (they wait for their victims' pods to
    terminate — the reference pipelines preemptors onto releasing
    resources the same way); consolidation victims additionally get a
    planned re-placement node in ``victim_move``.
    """
    assert mode in ("reclaim", "preempt", "consolidate"), mode
    g, q, r = state.gangs, state.queues, state.running
    G = g.g
    total = state.total_capacity
    chain = _chain_membership(q.parent, num_levels)
    steps = G if config.queue_depth is None else min(G, config.queue_depth)

    def step(carry):
        res, remaining, fuel = carry
        gi = ordering.select_next_gang(
            g, q, res.queue_allocated, fair_share, total, remaining)
        runnable = remaining[gi] & g.valid[gi] & (g.backoff[gi] <= 0) \
            & ~res.allocated[gi]

        def attempt(_):
            return solve_for_preemptor(
                state, gi, res, fair_share, chain,
                num_levels=num_levels, mode=mode, config=config)

        def skip(_):
            T = g.t
            return (jnp.asarray(False), jnp.zeros_like(res.victim),
                    jnp.full((T,), -1, jnp.int32),
                    jnp.full((T,), -1, jnp.int32), jnp.zeros((T,), bool),
                    jnp.full((state.running.m,), -1, jnp.int32),
                    res.free, res.device_free, res.releasing_extra,
                    res.device_releasing_extra, res.queue_allocated,
                    res.queue_allocated_nonpreemptible, res.extended_free)

        (success, victims, nodes_t, dev_t, pipe_t, moves,
         free2, dev2, extra2, extra_dev2, qa2, qan2, ext2) = lax.cond(
             runnable, attempt, skip, None)
        res = res.replace(
            extended_free=jnp.where(success, ext2, res.extended_free),
            free=jnp.where(success, free2, res.free),
            device_free=jnp.where(success, dev2, res.device_free),
            releasing_extra=jnp.where(success, extra2, res.releasing_extra),
            device_releasing_extra=jnp.where(
                success, extra_dev2, res.device_releasing_extra),
            queue_allocated=jnp.where(success, qa2, res.queue_allocated),
            queue_allocated_nonpreemptible=jnp.where(
                success, qan2, res.queue_allocated_nonpreemptible),
            placements=res.placements.at[gi].set(
                jnp.where(success, nodes_t, res.placements[gi])),
            placement_device=res.placement_device.at[gi].set(
                jnp.where(success, dev_t, res.placement_device[gi])),
            # tasks on victim/releasing capacity pipeline; tasks that fit
            # genuinely idle capacity bind now (stmt.Allocate vs Pipeline)
            pipelined=res.pipelined.at[gi].set(
                jnp.where(success, pipe_t, res.pipelined[gi])),
            allocated=res.allocated.at[gi].set(res.allocated[gi] | success),
            attempted=res.attempted.at[gi].set(res.attempted[gi] | runnable),
            victim=res.victim | victims,
            victim_move=jnp.where(success & (moves >= 0), moves,
                                  res.victim_move),
        )
        remaining = remaining.at[gi].set(False)
        return res, remaining, fuel - 1

    remaining0 = g.valid & (g.backoff <= 0) & ~result.allocated

    # ---- vectorized viability prefilter ---------------------------------
    # The per-gang scan is the expensive part (a fairness re-sort per
    # step); gangs that cannot possibly preempt are dropped upfront.
    # Sound because queue allocation only GROWS within the action, so the
    # capacity/fair-share gates (re-checked live per attempt) only get
    # stricter — a gang failing them at action start can never pass later.
    base = (r.valid & ~r.releasing & (r.node >= 0) & r.preemptible
            & (r.gang >= 0))
    rq = jnp.where(base, r.queue, q.q)
    cnt_q = jax.ops.segment_sum(base.astype(jnp.int32), rq,
                                num_segments=q.q + 1)[:q.q]       # [Q]
    total_cnt = jnp.sum(cnt_q)
    gq = jnp.maximum(g.queue, 0)
    if mode == "reclaim":
        has_cand = (total_cnt - cnt_q[gq]) > 0
    elif mode == "consolidate":
        own = jax.ops.segment_sum(
            base.astype(jnp.int32), jnp.where(base, r.gang, G),
            num_segments=G + 1)[:G]
        has_cand = (total_cnt - own) > 0
    else:  # preempt: a lower-priority candidate in the gang's own queue
        minprio = jax.ops.segment_min(
            jnp.where(base, r.priority, BIG), rq,
            num_segments=q.q + 1)[:q.q]
        has_cand = minprio[gq] < g.priority
    task_req_g = jnp.sum(
        jnp.where(g.task_valid[:, :, None], g.task_req, 0.0), axis=1)
    gate_np = jax.vmap(
        lambda qi, tr: _ancestor_gate(
            q.parent, qi, num_levels,
            result.queue_allocated_nonpreemptible, q.quota, tr)
    )(gq, task_req_g)
    viable = has_cand & jnp.where(~g.preemptible, gate_np, True)
    if mode == "reclaim":
        # the fair-share gate must use a LOWER bound of future queue
        # allocation — reclaim evictions SHRINK allocation as the action
        # proceeds, so gating on the live value would wrongly exclude
        # reclaimers whose chain drops under fair share once victims
        # free up.  Lower bound: current allocation minus everything any
        # candidate could ever free along the chain.
        cand_leaf = jax.ops.segment_sum(
            jnp.where(base[:, None], r.req, 0.0), rq,
            num_segments=q.q + 1)[:q.q]                        # [Q, R]
        freeable = jnp.einsum("qa,qr->ar", chain.astype(cand_leaf.dtype),
                              cand_leaf)
        qa_lower = jnp.maximum(result.queue_allocated - freeable, 0.0)
        viable = viable & jax.vmap(
            lambda qi, tr: _ancestor_gate(
                q.parent, qi, num_levels, qa_lower,
                fair_share, tr))(gq, task_req_g)
    elif mode == "consolidate":
        viable = viable & g.preemptible
    remaining0 = remaining0 & viable

    res, _, _ = lax.while_loop(
        lambda c: jnp.any(c[1]) & (c[2] > 0), step,
        (result, remaining0, jnp.asarray(steps, jnp.int32)))
    return res


@functools.partial(jax.jit,
                   static_argnames=("num_levels", "mode", "config"))
def run_victim_action_jit(state, fair_share, result, *, num_levels,
                          mode, config=VictimConfig()):
    return run_victim_action(state, fair_share, result,
                             num_levels=num_levels, mode=mode,
                             config=config)

"""Victim-scenario engine — reclaim & preempt as compiled scenario search.

Reference (``actions/common/solvers/job_solver.go:47-120``,
``by_pod_solver.go:20-90``): for a pending *preemptor* gang, grow a victim
set one eviction unit at a time (``PodAccumulatedScenarioBuilder``), and
for each scenario simulate "evict victims, re-run allocation" inside a
Statement; the first scenario whose simulation places the preemptor and
passes the scenario validators wins.  The eviction *unit*
(``api/podgroup_info/eviction_info.go:14`` GetTasksToEvict) is a single
task while the victim gang is elastic (above minMember), then the whole
remaining gang at once.  The ``idle_gpus`` accumulated filter
(``accumulated_scenario_filters/idle_gpus.go``) prunes scenarios whose
freed capacity still cannot fit the preemptor.

TPU-native design: victims are *ranked once* per preemptor — victim jobs
by a lexsort over gang keys (the ordered victim-queue generator), pods
within a gang by reverse task order — giving every candidate pod a global
*unit rank*; a scenario is a unit-rank prefix.  A ``lax.while_loop``
walks scenarios in order, each iteration:

1. masks pods with ``unit_rank <= k`` and segment-sums their requests
   into per-node freed capacity (no [scenarios, N, R] materialization),
2. checks the reclaim strategy for the unit being added (against the
   leveled queue's remaining share — see below),
3. runs the same gang-placement kernel the allocate action uses
   (``_attempt_gang``) on ``free + freed`` — first success wins,
   mirroring the reference's minimal-victim greedy.

The idle-capacity prefilter fast-forwards ``k`` to the first scenario
whose aggregate freed + idle covers the preemptor's request.

Validation semantics implemented (see
``plugins/proportion/reclaimable/reclaimable.go`` and
``reclaimable/strategies/strategies.go``):

- **CanReclaimResources gate**: reclaimer queue (and ancestors) must stay
  within fair share after the allocation; a non-preemptible reclaimer's
  non-preemptible allocation must stay within deserved quota.
- **Per-eviction strategy** at the *leveled* queue (the victim-side
  ancestor just below the LCA with the reclaimer —
  ``reclaimable.go getLeveledQueues``): evictable only while that queue
  is above fair share (MaintainFairShare) or, when the reclaimer is under
  deserved quota, above deserved (GuaranteeDeservedQuota) — evaluated
  against the remaining share before the step, exactly like the
  reference's running ``remainingResourcesMap``.
- **Preempt gate** (``actions/preempt/preempt.go:100-110``): a
  non-preemptible preemptor must keep the queue's non-preemptible
  allocation within deserved quota.
- Sibling saturation-order checks degenerate to true under the gate
  (reclaimer saturation ≤ 1) and are omitted; ``minruntime`` victim
  protection is a candidate filter here rather than a separate validator.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..apis.types import UNLIMITED
from ..runtime import compile_watch
from ..utils.numerics import cumsum_ds
from ..state.cluster_state import ClusterState
from . import ordering
from .allocate import (AllocateConfig, AllocationResult, _ancestor_gate,
                       _attempt_gang, _chain_membership, anti_defer_lanes,
                       anti_domain_tables, anti_forbid_nodes,
                       anti_mark_placements, attract_allow_nodes,
                       attract_defer_lanes, init_result,
                       sparse_accept_first_bad)
from .scoring import W_OWN_FREED

EPS = 1e-6
BIG = jnp.int32(2**30)


@dataclasses.dataclass(frozen=True)
class VictimConfig:
    """Knobs of the victim actions (ref reclaim/preempt action args)."""

    placement: AllocateConfig = AllocateConfig(dynamic_order=False)
    #: reclaimerSaturationMultiplier (``plugins/proportion/proportion.go:67-95``)
    saturation_multiplier: float = 1.0
    #: max preemptor gangs attempted per QUEUE (QueueDepthPerAction) for
    #: reclaim/consolidation; None = unlimited
    queue_depth: int | None = None
    #: preempt's own depth; None = inherit ``queue_depth``
    queue_depth_preempt: int | None = None
    #: cap on eviction units per consolidation scenario — ref
    #: ``MaxNumberConsolidationPreemptees`` (consolidation.go)
    max_consolidation_preemptees: int = 64
    #: preemptor gangs attempted per wavefront chunk (reclaim/preempt).
    #: Each pod of the frozen eviction-unit order is consumed by the
    #: FIRST lane whose budget covers it and whose queue may evict it
    #: (reclaim: other-queue flow; preempt: queue-segmented per-lane
    #: watermarks), so victim assignment cannot conflict; an
    #: allocate-style accept-prefix re-verifies composed capacity and
    #: queue gates.  1 = fully sequential (reference-exact order).
    #: 64 measured fastest at the 10k-node × 50k-pod baseline.
    batch_size: int = 64
    #: preempt's own chunk width; None = inherit ``batch_size``.
    #: Preempt chunks pack lanes across queues (queue-segmented budget
    #: math), so a many-queue snapshot wants chunks at least as wide as
    #: its preemptor spread, while junk lanes past the live preemptor
    #: count only add freed-pool cost — the Session auto-tunes this
    #: from the snapshot's pending-gang spread and padded node count
    #: (see ``Session.from_state``; measured sweep in BASELINE.md).
    batch_size_preempt: int | None = None
    #: reclaim may use the chunked path — False when the snapshot
    #: carries per-(victim,reclaimer) reclaim-minruntime protection,
    #: whose lane-dependent tables need the sequential path.  The
    #: Session derives this from the snapshot.
    chunk_reclaim: bool = False
    #: cap on victims re-placed per consolidation scenario — ONE knob
    #: for both the ``_replace_victims`` default and the consolidation
    #: call site's ``max(max_victim_pods, max_consolidation_preemptees
    #: * T)`` sizing (was a hard-coded 512 in two places)
    max_victim_pods: int = 512
    #: preempt sparse-lane wavefront: solve each lane against its OWN
    #: queue's freed capacity only (queue-disjoint optimistic solve) and
    #: verify composition with sparse (node-id, delta) segments instead
    #: of dense [B, N, *] lane-prefix cumsums.  None = auto (enabled
    #: whenever the snapshot shape supports the sparse placement
    #: protocol — uniform tasks, no device table, no extended
    #: resources, no subgroup topology); False forces the dense
    #: composed path.  True still requires the structural conditions.
    optimistic_preempt: bool | None = None
    #: width of the compact per-queue eviction-unit tables the sparse
    #: preempt path probes (top-K units per queue, the sparse analogue
    #: of the dense [U, Q, R] cumulative tables).  An action whose
    #: frozen unit order gives any queue more candidate units than this
    #: falls back to the dense composed path at run time (counted by
    #: the ``kai_victim_wavefront_sparse_fallbacks`` gauge).  None =
    #: auto: the Session derives it from running-pod density per leaf
    #: queue (non-Session callers get 256); an explicit value is
    #: honored as-is, e.g. to bound table memory or force the dense
    #: fallback for debugging.
    sparse_unit_k: int | None = None


def freed_by_mask(state: ClusterState, mask: jax.Array, chain: jax.Array):
    """Resources released by evicting the masked running pods.

    Returns (freed_nodes [N, R], freed_devices [N, D], freed_queues
    [Q, R], freed_queues_nonpreemptible [Q, R], freed_extended [N, E])
    with the queue tensors rolled up the hierarchy via ``chain`` — shared
    by the victim solver and the stalegangeviction action.
    """
    r = state.running
    n, q = state.nodes, state.queues
    D = n.d
    req_m = jnp.where(mask[:, None], r.req, 0.0)
    freed_nodes = jax.ops.segment_sum(
        req_m, jnp.where(mask, jnp.maximum(r.node, 0), n.n),
        num_segments=n.n + 1)[:n.n]
    # device table: fractional pods return their held share to their
    # device; whole-device pods return 1.0 per devices_mask bit
    frac = mask & (r.device >= 0)
    flat = jnp.maximum(r.node, 0) * D + jnp.maximum(r.device, 0)
    freed_dev = jax.ops.segment_sum(
        jnp.where(frac, r.accel_held, 0.0),
        jnp.where(frac, flat, n.n * D),
        num_segments=n.n * D + 1)[:n.n * D].reshape(n.n, D)
    bits = ((r.devices_mask[:, None] >> jnp.arange(D)[None, :]) & 1)
    whole_bits = bits.astype(req_m.dtype) * (mask & (r.device < 0))[:, None]
    freed_dev = freed_dev + jax.ops.segment_sum(
        whole_bits, jnp.where(mask, jnp.maximum(r.node, 0), n.n),
        num_segments=n.n + 1)[:n.n]
    leaf = jax.ops.segment_sum(
        req_m, jnp.where(mask, jnp.maximum(r.queue, 0), q.q),
        num_segments=q.q + 1)[:q.q]
    leaf_np = jax.ops.segment_sum(
        jnp.where((mask & ~r.preemptible)[:, None], r.req, 0.0),
        jnp.where(mask & ~r.preemptible, jnp.maximum(r.queue, 0), q.q),
        num_segments=q.q + 1)[:q.q]
    chain_f = chain.astype(leaf.dtype)
    freed_q = jnp.einsum("qa,qr->ar", chain_f, leaf)
    freed_q_np = jnp.einsum("qa,qr->ar", chain_f, leaf_np)
    # extended (MIG) scalars held by the victims return to their node's
    # pool — the credit-back that lets a preemptor reclaim a MIG slice
    freed_ext = jax.ops.segment_sum(
        jnp.where(mask[:, None], r.extended, 0.0),
        jnp.where(mask, jnp.maximum(r.node, 0), n.n),
        num_segments=n.n + 1)[:n.n]
    return freed_nodes, freed_dev, freed_q, freed_q_np, freed_ext


def _pod_order_static(state: ClusterState):
    """Within-gang pod order (newest first) — preemptor-independent, so
    it is computed ONCE per action instead of a [M] lexsort per
    preemptor.  Returns (perm0 [M], gang_perm [M])."""
    r = state.running
    G = state.gangs.g
    gang_all = jnp.where(r.valid & (r.gang >= 0), r.gang, G)
    perm0 = jnp.lexsort((r.runtime_s, gang_all))
    return perm0, gang_all[perm0]


def victim_statics(state: ClusterState):
    """Preemptor-independent victim-search inputs, hoisted out of the
    per-preemptor solve (the per-step cost is what bounds cycle latency):

    - ``base0`` [M]: the candidate filter minus the per-preemptor parts
    - ``gang_runtime`` [G]: max pod runtime per gang (minruntime input);
      -1 when the gang never started (nil LastStartTimestamp => NOT
      protected, ref minruntime.go)
    - ``pod_order``: within-gang newest-first order (see
      :func:`_pod_order_static`)
    """
    r = state.running
    G = state.gangs.g
    base0 = (r.valid & ~r.releasing & (r.node >= 0) & r.preemptible
             & (r.gang >= 0))
    gang_runtime = jax.ops.segment_max(
        jnp.where(r.valid & (r.gang >= 0), r.runtime_s, -1.0),
        jnp.where(r.gang >= 0, r.gang, G), num_segments=G + 1)[:G]
    return base0, gang_runtime, _pod_order_static(state)


def frozen_job_rank(state: ClusterState, queue_allocated: jax.Array,
                    fair_share: jax.Array) -> jax.Array:
    """Victim-JOB ordering, frozen at action start — the reference
    regenerates the victim queue order from live shares per preemptor;
    freezing it trades that re-sort for one [G] lexsort per ACTION
    (bounded drift: within one action, shares only move monotonically).
    Most-saturated queue first, lowest priority first, newest first.
    Gangs that turn out to expose no units occupy rank slots but
    contribute nothing to the unit cumsum, so unit ranks stay dense."""
    g = state.gangs
    G = g.g
    sat = jnp.max(
        queue_allocated / jnp.maximum(fair_share, EPS), axis=-1)  # [Q]
    gq = jnp.maximum(g.queue, 0)
    rank_gang = jnp.lexsort((
        -g.creation_order.astype(jnp.float32),
        g.priority.astype(jnp.float32),
        -sat[gq],
    ))
    return jnp.zeros((G,), jnp.int32).at[rank_gang].set(
        jnp.arange(G, dtype=jnp.int32))


def victim_candidates(
    state: ClusterState,
    gang_idx: jax.Array,
    *,
    mode: str,
    already_victim: jax.Array,   # bool [M]
    statics=None,                # victim_statics(state) output
) -> jax.Array:
    """bool [M] — pods eligible as victims for this preemptor.

    Reclaim filter (``actions/reclaim/reclaim.go`` victim generator +
    ``ReclaimVictimFilter``): preemptible running pods of *other* queues
    that have run at least their queue's ``reclaimMinRuntime``.
    Preempt filter (``buildFilterFuncForPreempt``): preemptible running
    pods of the *same* queue whose gang priority is strictly lower, past
    ``preemptMinRuntime``.
    Consolidation (``actions/consolidation``): any preemptible running pod
    of another gang — victims are *moved*, not lost, so no queue or
    priority constraint applies (minruntime still protects).
    """
    r = state.running
    g = state.gangs
    q = state.queues
    if statics is None:
        statics = victim_statics(state)
    base0, gang_runtime, _ = statics
    base = base0 & ~already_victim
    my_queue = g.queue[gang_idx]
    # gang-level minruntime protection (hierarchy/LCA-resolved at
    # snapshot build — ref plugins/minruntime/resolver.go).  A protected
    # gang may still shed ELASTIC surplus pods; only its quorum unit is
    # off-limits (ref reclaimFilterFn returning true for elastic jobs +
    # the scenario validator) — enforced by the unit ranking, which gives
    # protected gangs no whole-gang unit.
    gq = jnp.maximum(g.queue, 0)
    if mode == "reclaim":
        mrt_g = q.reclaim_min_runtime_eff[gq, my_queue]          # [G]
    else:
        mrt_g = q.preempt_min_runtime_eff[gq]
    protected = (gang_runtime >= 0) & (gang_runtime < mrt_g)     # [G]
    if mode == "reclaim":
        return base & (r.queue != my_queue), protected
    if mode == "consolidate":
        return base & (r.gang != gang_idx), protected
    return (base & (r.queue == my_queue)
            & (r.priority < g.priority[gang_idx])), protected


def _rank_eviction_units(
    state: ClusterState,
    cand: jax.Array,             # bool [M]
    queue_allocated: jax.Array,  # f32 [Q, R]
    fair_share: jax.Array,       # f32 [Q, R]
    already_victim: jax.Array,   # bool [M]  victims accumulated this cycle
    protected: jax.Array | None = None,  # bool [G]  minruntime-protected
    pod_order=None,              # (perm0, gang_perm) from _pod_order_static
    job_rank: jax.Array | None = None,   # frozen_job_rank output
):
    """Assign every candidate pod a global eviction-unit rank.

    Victim *jobs* follow ``frozen_job_rank`` — the reference generates
    victims queue-by-queue in reversed queue order (most over-fair-share
    first) and job-by-job in reversed job order (lowest priority, newest
    first).  Within a gang, pods are ordered by reverse task order
    (shortest-running ≈ newest first); each of the first
    ``allocated - minMember`` pods is its own unit (elastic shrink), the
    remaining ``minMember`` pods form one final unit
    (``eviction_info.go GetTasksToEvict``).

    Returns (unit_rank [M] i32 — BIG for non-candidates, num_units []).
    """
    g = state.gangs
    r = state.running
    G, M = g.g, r.m

    gang_of_pod = jnp.where(cand, r.gang, G)                   # [M], G = junk
    pods_per_gang = jax.ops.segment_sum(
        cand.astype(jnp.int32), gang_of_pod, num_segments=G + 1)[:G]
    victim_gang = pods_per_gang > 0

    if job_rank is None:
        job_rank = frozen_job_rank(state, queue_allocated, fair_share)

    # ---- pod order within gang (reverse task order: newest first) -------
    # seq = rank among this gang's CANDIDATES in the hoisted static order:
    # gather→cumsum→scatter instead of a per-preemptor [M] lexsort
    if pod_order is None:
        pod_order = _pod_order_static(state)
    perm0, gang_perm = pod_order
    cand_p = cand[perm0].astype(jnp.int32)
    excl = jnp.cumsum(cand_p) - cand_p                          # [M]
    base = jax.ops.segment_min(excl, gang_perm, num_segments=G + 1)[:G]
    seq_p = excl - base[jnp.minimum(gang_perm, G - 1)]
    seq = jnp.zeros((M,), jnp.int32).at[perm0].set(seq_p)       # [M]

    # ---- unit ids --------------------------------------------------------
    # Surplus is sized from the gang's *effective* active pod count:
    # running_count minus pods already victimised by earlier actions this
    # cycle — the reference's Statement.Evict updates the active-task
    # counts GetTasksToEvict reads, so a gang reclaimed down to minMember
    # by one action is NOT elastic-shrinkable again by the next; the
    # final unit (whole remaining gang) triggers at the right threshold.
    # Pods excluded from candidacy for other reasons (unknown node) still
    # hold the gang above minMember.
    victims_in_gang = jax.ops.segment_sum(
        (already_victim & (r.gang >= 0)).astype(jnp.int32),
        jnp.where(r.gang >= 0, r.gang, G), num_segments=G + 1)[:G]
    effective_active = g.running_count - victims_in_gang        # [G]
    surplus = jnp.clip(
        effective_active - g.min_member, 0, pods_per_gang)      # [G]
    # a minruntime-protected gang keeps its quorum: it exposes only its
    # elastic-surplus units, never the final whole-gang unit (ref the
    # minruntime scenario validators protecting below-minAvailable)
    whole_unit = pods_per_gang > surplus
    if protected is not None:
        whole_unit = whole_unit & ~protected
    units_per_gang = jnp.where(
        victim_gang, surplus + whole_unit, 0)                   # [G]
    units_by_rank = jnp.zeros((G,), units_per_gang.dtype).at[
        job_rank].set(units_per_gang)                           # [G]
    offsets = jnp.cumsum(units_by_rank) - units_by_rank         # [G] excl
    gsafe = jnp.minimum(gang_of_pod, G - 1)
    unit_in_gang = jnp.minimum(seq, surplus[gsafe])
    in_range = unit_in_gang < units_per_gang[gsafe]
    unit_rank = jnp.where(
        cand & in_range,
        offsets[job_rank[gsafe]] + unit_in_gang,
        BIG)
    return unit_rank, jnp.sum(units_per_gang)


def _leveled_queue(chain: jax.Array, depth: jax.Array,
                   vq: jax.Array, rq: jax.Array) -> jax.Array:
    """The victim-side ancestor just below the LCA with the reclaimer —
    ref ``reclaimable.go getLeveledQueues``.  i32 scalar queue index."""
    vchain = chain[vq]                        # bool [Q]
    rchain = chain[rq]
    cand_q = vchain & ~rchain
    d = jnp.where(cand_q, depth, BIG)
    # -1 when every victim ancestor is shared with the reclaimer (victim
    # queue is an ancestor of the reclaimer's) — callers treat -1 as
    # "no leveled queue, strategy check passes".
    return jnp.where(jnp.any(cand_q), jnp.argmin(d), -1)


def solve_for_preemptor(
    state: ClusterState,
    gang_idx: jax.Array,
    result: AllocationResult,
    fair_share: jax.Array,
    chain: jax.Array,            # bool [Q, Q]
    *,
    num_levels: int,
    mode: str,                   # "reclaim" | "preempt" | "consolidate"
    config: VictimConfig,
    statics=None,                # hoisted victim_statics output
    job_rank: jax.Array | None = None,   # hoisted frozen_job_rank
    domain_mask: jax.Array | None = None,   # bool [N] in-cycle anti mask
):
    """One preemptor's scenario search — returns updated commit-set fields.

    (success, victim_mask [M], task placements [T], devices [T],
    pipelined [T], moves [M], free', dev', extra', extra_dev', qa',
    qan', ext', ext_extra')
    """
    reclaim = mode == "reclaim"
    consolidate = mode == "consolidate"
    g, q, n, r = state.gangs, state.queues, state.nodes, state.running
    free = result.free
    dev = result.device_free
    extra = result.releasing_extra
    extra_dev = result.device_releasing_extra
    qa = result.queue_allocated
    qan = result.queue_allocated_nonpreemptible
    queue = g.queue[gang_idx]
    task_req = jnp.where(g.task_valid[gang_idx][:, None],
                         g.task_req[gang_idx], 0.0)
    total_req = task_req.sum(0)                                # [R]
    nonpreempt = ~g.preemptible[gang_idx]

    # ---- gates (before any scenario work) -------------------------------
    nonpreempt_quota_ok = jnp.where(
        nonpreempt,
        _ancestor_gate(q.parent, queue, num_levels, qan, q.quota, total_req),
        True)
    if reclaim:
        # CanReclaimResources: the chain stays within fair share in the
        # POST-SCENARIO state (victims' releases credited) — checked per
        # attempt below against qa_eff, NOT against live qa: a dept at
        # its full fair share must still be able to reclaim WITHIN
        # itself (same-dept victims free the very allocation the
        # reclaimer adds)
        gate = nonpreempt_quota_ok
    elif consolidate:
        # consolidation only serves pending *preemptible* jobs
        # (``consolidation.go`` pending-preemptible filter)
        gate = ~nonpreempt
    else:
        gate = nonpreempt_quota_ok

    if statics is None:
        statics = victim_statics(state)
    cand, protected = victim_candidates(
        state, gang_idx, mode=mode, already_victim=result.victim,
        statics=statics)
    gate &= jnp.any(cand)

    # moved (consolidated) victims stay active gang members — they restart
    # on their target node — so only *removed* victims shrink the gang's
    # effective active count for unit sizing
    removed_victims = result.victim & (result.victim_move < 0)
    unit_rank, num_units = _rank_eviction_units(
        state, cand, qa, fair_share, removed_victims, protected,
        statics[2], job_rank)
    if consolidate:
        num_units = jnp.minimum(num_units,
                                config.max_consolidation_preemptees)
    reclaimer_under_quota = _ancestor_gate(
        q.parent, queue, num_levels, qa, q.quota, total_req)
    quota_eff = jnp.where(q.quota <= UNLIMITED + 0.5, jnp.inf, q.quota)
    m_req = jnp.where(cand[:, None], r.req, 0.0)               # [M, R]
    M = r.m
    urank_safe = jnp.minimum(unit_rank, M)

    # ---- per-unit tables, vectorized over ALL unit ranks at once --------
    unit_req = jax.ops.segment_sum(
        m_req, urank_safe, num_segments=M + 1)[:M]             # [U, R]
    cum_freed = cumsum_ds(unit_req, axis=0)                    # [U, R]
    # idle_gpus-style prefilter: the first scenario whose aggregate
    # free + freed covers the preemptor's request lower-bounds the search
    cluster_free = jnp.sum(
        jnp.where(n.valid[:, None], free + n.releasing + extra, 0.0),
        axis=0)
    enough = jnp.all(cluster_free[None, :] + cum_freed + EPS
                     >= total_req[None, :], axis=-1)           # [U] monotone
    gate_prefilter = jnp.any(enough)

    # FitsReclaimStrategy per unit (the reference's running
    # remainingResourcesMap check), vectorized: unit u passes iff its
    # leveled queue's remaining share BEFORE u (qa minus the freed
    # prefix inside that queue's subtree) is still above fair share /
    # deserved quota.  Scenario validity needs every unit of the prefix
    # to pass, so the first failing unit truncates the search range.
    if reclaim:
        unit_leaf = jax.ops.segment_max(
            jnp.where(cand, r.queue, -1), urank_safe,
            num_segments=M + 1)[:M]                            # [U]
        leaf_safe = jnp.maximum(unit_leaf, 0)
        lq_u = jax.vmap(
            lambda vq: _leveled_queue(chain, q.depth, vq, queue))(
                leaf_safe)                                     # [U]
        contrib = chain[leaf_safe] & (unit_leaf >= 0)[:, None]  # [U, Q]
        inc = contrib[:, :, None] * unit_req[:, None, :]       # [U, Q, R]
        csum_excl = cumsum_ds(inc, axis=0) - inc
        lq_safe = jnp.maximum(lq_u, 0)
        freed_excl = csum_excl[jnp.arange(M), lq_safe]         # [U, R]
        remaining_u = qa[lq_safe] - freed_excl
        over_fs = jnp.any(remaining_u > fair_share[lq_safe] + EPS, -1)
        over_q = jnp.any(remaining_u > quota_eff[lq_safe] + EPS, -1)
        pass_u = (lq_u < 0) | over_fs | (reclaimer_under_quota & over_q)
    else:
        pass_u = jnp.ones((M,), bool)
    bad = (jnp.arange(M) < num_units) & ~pass_u
    first_bad = jnp.where(jnp.any(bad), jnp.argmax(bad), num_units)
    hi = jnp.minimum(num_units, first_bad) - 1   # largest admissible k
    lo = jnp.argmax(enough)                      # smallest k that can fit
    can_search = gate & gate_prefilter & (hi >= lo)

    T = g.t
    alloc_cfg = config.placement
    no_moves = jnp.full((M,), -1, jnp.int32)
    ext_extra = result.extended_releasing_extra

    def attempt(k):
        """Simulate scenario prefix ``k``: evict, credit, re-place."""
        mask_k = cand & (unit_rank <= k)
        freed_nodes, freed_dev, freed_q, _, freed_ext = freed_by_mask(
            state, mask_k, chain)
        # victim capacity is *releasing* until the pods terminate: the
        # preemptor's tasks that land on it pipeline, tasks that fit
        # genuinely idle capacity bind now (stmt.Allocate vs Pipeline)
        extra_eff = extra + freed_nodes
        extra_dev_eff = extra_dev + freed_dev
        ext_extra_eff = ext_extra + freed_ext
        # consolidation victims are moved, not removed — their queue
        # allocation stays (allPodsReallocated validator below)
        qa_eff = qa if consolidate else qa - freed_q
        (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, success,
         _, _, ext2, _) = \
            _attempt_gang(state, gang_idx, free, dev, qa_eff, qan,
                          num_levels, alloc_cfg, extra_eff,
                          extra_dev_eff, chain=chain,
                          ext_free=result.extended_free,
                          extra_extended_releasing=ext_extra_eff,
                          domain_mask=domain_mask)
        if reclaim:
            # CanReclaimResources against the post-scenario state
            success &= _ancestor_gate(q.parent, queue, num_levels,
                                      qa_eff, fair_share, total_req)
        if consolidate:
            free3, dev3, ext3, moves, all_ok = _replace_victims(
                state, mask_k, free2, dev2, n.releasing + extra_eff,
                state.nodes.device_releasing + extra_dev_eff,
                ext2, state.nodes.extended_releasing + ext_extra_eff,
                max_pods=max(config.max_victim_pods,
                             config.max_consolidation_preemptees * T))
            return success & all_ok, (
                free3, dev3, qa2, qan2, nodes_t, dev_t, pipe_t, moves,
                extra_eff, extra_dev_eff, ext3, ext_extra_eff, k)
        return success, (
            free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, no_moves,
            extra_eff, extra_dev_eff, ext2, ext_extra_eff, k)

    empty = (free, dev, qa, qan, jnp.full((T,), -1, jnp.int32),
             jnp.full((T,), -1, jnp.int32),
             jnp.zeros((T,), bool), no_moves, extra, extra_dev,
             result.extended_free, ext_extra, jnp.asarray(0, jnp.int32))

    # ---- search over the unit prefix ------------------------------------
    # Freed capacity grows monotonically with k, so placement success is
    # monotone for capacity-style constraints (reclaim/preempt); the
    # search probes the capacity lower bound first (tight in the common
    # case — ONE attempt), then ``hi`` (failing preemptors cost one more)
    # and bisects to the smallest succeeding prefix — the minimal victim
    # set the reference's one-unit-at-a-time walk finds, in O(log U)
    # placement attempts.  Consolidation's allPodsReallocated validator
    # is NOT monotone (extra victims must also re-place), so it keeps
    # the reference's linear first-success walk — num_units is already
    # capped by max_consolidation_preemptees.  Subgroup-topology
    # placement through the per-task kernel is not monotone either: the
    # aggregate-capacity domain gate can pass while the fill fails on a
    # fragmented domain, so attempt(hi) may fail where a smaller prefix
    # succeeds, and the bisect can settle on a non-minimal k — those
    # snapshots take the linear walk too (the uniform kernel's domain
    # pick counts real per-node replica capacities, so it stays
    # monotone and keeps the bisect).
    linear_walk = consolidate or (
        config.placement.subgroup_topology
        and not config.placement.uniform_tasks)
    if linear_walk:
        def search(_):
            def cond_l(c):
                k, done, _ = c
                return (~done) & (k <= hi)

            def body_l(c):
                k, done, best = c
                s, tm = attempt(k)
                best = jax.tree.map(
                    lambda a, b: jnp.where(s, a, b), tm, best)
                return k + 1, s, best

            _, done, best = lax.while_loop(
                cond_l, body_l,
                (lo, jnp.asarray(False), empty))
            return done, best
    else:
        def search(_):
            s_lo, t_lo = attempt(lo)

            def refine(_):
                s_hi, t_hi = attempt(hi)

                def bcond(c):
                    lo_c, hi_c, _ = c
                    return lo_c + 1 < hi_c

                def bbody(c):
                    # invariant: lo_c fails, hi_c succeeds
                    lo_c, hi_c, best = c
                    mid = (lo_c + hi_c) // 2
                    s, tm = attempt(mid)
                    best = jax.tree.map(
                        lambda a, b: jnp.where(s, a, b), tm, best)
                    return (jnp.where(s, lo_c, mid),
                            jnp.where(s, mid, hi_c), best)

                def run_bisect(_):
                    _, _, best = lax.while_loop(bcond, bbody,
                                                (lo, hi, t_hi))
                    return jnp.asarray(True), best

                return lax.cond(s_hi, run_bisect,
                                lambda _: (jnp.asarray(False), empty),
                                None)

            return lax.cond(s_lo, lambda _: (jnp.asarray(True), t_lo),
                            refine, None)

    success, (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, moves,
              extra2, extra_dev2, ext2, ext_extra2, k_win) = lax.cond(
                  can_search, search,
                  lambda _: (jnp.asarray(False), empty), None)

    victim_mask = cand & (unit_rank <= k_win) & success
    return (success, victim_mask, nodes_t, dev_t, pipe_t, moves,
            free2, dev2, extra2, extra_dev2, qa2, qan2, ext2, ext_extra2)


def _replace_victims(state: ClusterState, mask: jax.Array, free: jax.Array,
                     device_free: jax.Array, releasing: jax.Array,
                     device_releasing: jax.Array,
                     ext_free: jax.Array, ext_releasing: jax.Array,
                     max_pods: int):
    """Greedy re-placement of evicted consolidation victims — the
    ``allPodsReallocated`` validator (``consolidation.go:115-120``): the
    scenario is valid only if *every* victim fits somewhere on the
    post-preemptor state.  Feasibility = resources + extended (MIG)
    scalars + the pod's node-filter class (taints/affinity); binpack by
    least free accel.  Moves may draw on releasing capacity (including
    other victims' freed spots) — they are always pipelined rebinds,
    waiting for the old pods to vacate.

    The loop runs over the (bounded) victim set, not the whole pod axis —
    an M-length device loop at 50k running pods faults the TPU.  A
    scenario with more than ``max_pods`` victims is rejected
    (``all_ok=False``), mirroring MaxNumberConsolidationPreemptees-style
    caps; the cap comes from ``VictimConfig.max_victim_pods`` (one knob
    for every call site).

    Returns (free' [N, R], device_free' [N, D], extended_free' [N, E],
    moves [M] i32 node per victim, all_ok [])."""
    r, n = state.running, state.nodes
    M = r.m
    D = n.d
    K = max(1, min(M, max_pods))
    n_vic = jnp.sum(mask.astype(jnp.int32))
    idxs = jnp.nonzero(mask, size=K, fill_value=0)[0]          # [K]
    kvalid = jnp.arange(K) < n_vic

    def body(kk, carry):
        free_l, dev_l, ext_l, moves, all_ok = carry
        m = idxs[kk]
        needed = kvalid[kk] & mask[m]
        req = r.req[m]
        is_frac = r.device[m] >= 0
        # memory-based portions are node-relative: recompute for every
        # candidate target (a 40GiB share is 0.5 of an 80GiB device but
        # 2.5 of a 16GiB one)
        p_n = jnp.where(
            r.accel_mem[m] > 0,
            r.accel_mem[m] / jnp.maximum(n.device_memory_gib, EPS),
            r.accel_held[m])                                   # [N]
        avail = free_l + releasing
        dev_avail = dev_l + device_releasing
        fit = (jnp.all(avail + EPS >= req[None, :], axis=-1) & n.valid
               & n.filter_masks[r.filter_class[m]])
        # extended (MIG) scalars the victim holds must fit the target too
        ext_req = r.extended[m]                                # [E]
        fit &= jnp.all(ext_l + ext_releasing + EPS >= ext_req[None, :],
                       axis=-1)
        frac_fit = jnp.max(dev_avail, axis=-1) >= p_n - EPS
        whole_free = jnp.sum((dev_avail >= 1.0 - EPS).astype(free_l.dtype),
                             axis=-1)
        whole_fit = whole_free + EPS >= req[0]
        fit = fit & jnp.where(is_frac, frac_fit, whole_fit)
        score = jnp.where(fit, -avail[:, 0], -jnp.inf)
        node = jnp.argmax(score)
        placed = needed & jnp.any(fit)
        p = p_n[node]
        delta = jnp.where(placed, req, 0.0)
        delta = delta.at[0].set(
            jnp.where(placed, jnp.where(is_frac, p, req[0]), 0.0))
        free_l = free_l.at[node].add(-delta)
        ext_l = ext_l.at[node].add(-jnp.where(placed, ext_req, 0.0))
        # device debit: fraction joins its best-fitting device; whole
        # takes the first fully-free devices
        dev_row = dev_avail[node]
        frac_dev = jnp.argmax(dev_row)
        k = jnp.round(req[0]).astype(jnp.int32)
        fully = dev_row >= 1.0 - EPS
        take = fully & (jnp.cumsum(fully.astype(jnp.int32)) <= k)
        dev_delta = jnp.where(
            is_frac, p * (jnp.arange(D) == frac_dev),
            take.astype(dev_row.dtype))
        dev_l = dev_l.at[node].add(-jnp.where(placed, dev_delta, 0.0))
        # junk iterations (kk >= n_vic gather the fill index 0) must NOT
        # touch pod 0's recorded move — an unconditional set clobbered a
        # real victim's rebind target back to -1, shipping its eviction
        # without the pipelined re-placement (caught by the scenario
        # catalog's MIG consolidation case)
        moves = moves.at[m].set(
            jnp.where(needed, jnp.where(placed, node, -1), moves[m]))
        all_ok = all_ok & (~needed | placed)
        return free_l, dev_l, ext_l, moves, all_ok

    free2, dev2, ext2, moves, all_ok = lax.fori_loop(
        0, K, body,
        (free, device_free, ext_free,
         jnp.full((M,), -1, jnp.int32), n_vic <= K))
    return free2, dev2, ext2, moves, all_ok


def _freed_by_lane(state: ClusterState, lane: jax.Array, B: int,
                   chain: jax.Array, *, compose: bool = True,
                   track_devices: bool = True, extended: bool = True):
    """Per-lane freed tensors from a pod→lane assignment.

    ``lane`` [M] gives each pod the FIRST wavefront lane that consumes
    it (``B`` = not consumed this chunk).  With ``compose=True`` lane
    ``b``'s pool is the union of lanes ``<= b``: every per-lane prefix
    is a cumsum of per-lane sums — ONE segment_sum over the pod axis
    instead of a vmapped scatter per lane (vmapped scatters dominate
    the chunk cost on TPU).  With ``compose=False`` (the sparse
    preempt wavefront) each lane's pool is its OWN assignment only and
    the lane-prefix cumsum over the dense [B, N, *] tensors is skipped
    entirely — composition is re-verified later on sparse (node, delta)
    segments at the chunk's claim sites.

    The device and extended tables are built only when the placement
    config tracks them: a snapshot without fractional or MIG pods frees
    nothing there, and the dense [B, N, D] table is the single biggest
    HBM tensor of a chunk.

    Returns (freed_nodes [B,N,R], freed_dev [B,N,D] | None,
    freed_queues [B,Q,R], freed_ext [B,N,E] | None, own_incr [B,N] —
    nodes where lane b's OWN assignment freed capacity, the
    W_OWN_FREED score-bias input).
    """
    r, n, q = state.running, state.nodes, state.queues
    N, D, Q = n.n, n.d, q.q
    live = lane < B
    lane_s = jnp.where(live, lane, B)
    req_m = jnp.where(live[:, None], r.req, 0.0)
    node_s = jnp.where(live, jnp.maximum(r.node, 0), N)
    seg_n = lane_s * (N + 1) + node_s
    per_n = jax.ops.segment_sum(
        req_m, seg_n, num_segments=(B + 1) * (N + 1))
    own_n = per_n.reshape(B + 1, N + 1, -1)[:B, :N]            # [B, N, R]
    freed_n = jnp.cumsum(own_n, axis=0) if compose else own_n
    freed_d = None
    if track_devices:
        frac = live & (r.device >= 0)
        seg_d = (jnp.where(frac, lane_s, B) * (N * D + 1)
                 + jnp.where(frac, node_s * D + jnp.maximum(r.device, 0),
                             N * D))
        per_d = jax.ops.segment_sum(
            jnp.where(frac, r.accel_held, 0.0), seg_d,
            num_segments=(B + 1) * (N * D + 1))
        per_d = per_d.reshape(B + 1, N * D + 1)[:B, :N * D].reshape(
            B, N, D)
        bits = ((r.devices_mask[:, None] >> jnp.arange(D)[None, :]) & 1)
        whole = bits.astype(req_m.dtype) * (live & (r.device < 0))[:, None]
        per_w = jax.ops.segment_sum(
            whole, seg_n, num_segments=(B + 1) * (N + 1))
        own_d = per_d + per_w.reshape(B + 1, N + 1, D)[:B, :N]
        freed_d = jnp.cumsum(own_d, axis=0) if compose else own_d
    seg_q = lane_s * (Q + 1) + jnp.where(live, jnp.maximum(r.queue, 0), Q)
    per_q = jax.ops.segment_sum(
        req_m, seg_q, num_segments=(B + 1) * (Q + 1))
    leaf_own = per_q.reshape(B + 1, Q + 1, -1)[:B, :Q]         # [B, Q, R]
    leaf_cum = jnp.cumsum(leaf_own, axis=0) if compose else leaf_own
    freed_q = jnp.einsum("qa,bqr->bar", chain.astype(req_m.dtype),
                         leaf_cum)
    freed_e = None
    if extended:
        per_e = jax.ops.segment_sum(
            jnp.where(live[:, None], r.extended, 0.0), seg_n,
            num_segments=(B + 1) * (N + 1))
        own_e = per_e.reshape(B + 1, N + 1, -1)[:B, :N]
        freed_e = jnp.cumsum(own_e, axis=0) if compose else own_e
    own_incr = jnp.sum(own_n, axis=-1) > EPS                   # [B, N]
    return freed_n, freed_d, freed_q, freed_e, own_incr


def _sparse_preempt_ok(config: VictimConfig) -> bool:
    """Static gate of the sparse/optimistic preempt wavefront — the
    same structural conditions as the allocate chunk's sparse protocol
    (lanes emit placements only; a placement's claim is exactly its
    gang's uniform replica request), which is also exactly when the
    per-lane pools can skip the dense composition: uniform tasks, no
    device table, no extended resources, no subgroup topology.
    ``VictimConfig.optimistic_preempt=False`` forces the dense path;
    ``True``/``None`` still require the structural conditions."""
    p = config.placement
    ok = (p.uniform_tasks and not p.track_devices and not p.extended
          and not p.subgroup_topology)
    if config.optimistic_preempt is not None:
        ok = ok and config.optimistic_preempt
    return ok


#: ``AllocationResult.wavefront_stats`` row per chunked action
_STATS_ROW = {"reclaim": 0, "preempt": 1}


def _run_victim_action_chunked(
    state: ClusterState,
    fair_share: jax.Array,
    result: AllocationResult,
    *,
    num_levels: int,
    mode: str,                   # "reclaim" | "preempt"
    config: VictimConfig,
    remaining0: jax.Array,       # bool [G] viability-prefiltered
    chain: jax.Array,
    statics,
    job_rank: jax.Array,
    lq_tab: jax.Array | None,
    cnt_q: jax.Array,
    task_req_g: jax.Array,
) -> AllocationResult:
    """Wavefront victim search: B preemptors per iteration, in frozen
    fairness order, with EXACT per-lane own-queue exclusion.

    The sequential scan's per-step cost is dominated by fixed per-
    preemptor machinery, so latency ∝ steps; on the target hardware a
    loop iteration's cost is ∝ its op count, so everything preemptor-
    independent is hoisted OUT of the loop:

    - the eviction-unit order is frozen once per action.  It is stable
      under per-queue prefix consumption (consuming a prefix of a
      queue's units and re-ranking yields the identical suffix), so the
      per-chunk consumed state is just a per-queue pointer ``c [Q]``
      over the frozen global rank space.
    - all per-unit tables (requests, per-leaf-queue cumulative freed,
      the strategy-bound subtree cumulative ``S_cols``, leaf
      positions/counts) are built once; chunks probe them with
      searchsorted/gathers only.
    - the preemptor order is frozen once (``job_order_perm`` at action
      start) — the fairness interleaving across queues is baked into
      the order; within a queue the job keys are static anyway.

    Each chunk takes the first B remaining gangs in frozen order (for
    preempt, the first B of the head gang's queue — preempt budgets and
    consumption are own-queue-local, so its lanes must share one
    queue).  Lane
    ``b`` gets a nondecreasing global-rank budget ``K_b`` — the
    smallest rank whose cumulative freed capacity, EXCLUDING lane b's
    own queue (reclaim; own-queue ONLY for preempt), covers the chunk's
    cumulative request — and always covers at least one new unit (the
    scenario builder never yields an empty victim set).  A pod is
    consumed by the first lane whose budget covers it AND whose queue
    may evict it, so a unit skipped by its own queue's lane flows to
    the next other-queue lane instead of being lost — no range-
    collision retirement (the round-3 advisor finding).  Placements
    run vmapped against chunk-start state with a score bias toward the
    lane's own freed nodes (the sequential solver implicitly places
    each preemptor onto its own victims' capacity), and an allocate-
    style strict accept-prefix re-verifies the composed capacity,
    queue-cap and fair-share gates.  Per-pair reclaim-minruntime
    snapshots use the sequential path (``VictimConfig.chunk_reclaim``).

    SPARSE LANE WAVEFRONT (preempt, ``_sparse_preempt_ok``): preempt
    victims are same-queue only, so lanes from distinct queues share
    nothing but node free capacity, and the problem is queue-disjoint
    by construction.  The sparse path exploits that structure:

    - the dense [U, Q, R] cumulative-freed tables (and their [B, U, R]
      per-chunk gathers) shrink to compact per-queue top-K unit tables
      ``Cq [Q, KU, R]`` / ``pos_c [Q, KU+1]`` / ``prio_c [Q, KU]``
      probed with tiny searchsorteds;
    - every lane solves OPTIMISTICALLY against its OWN queue's freed
      capacity only (``_freed_by_lane(compose=False)``) — no [B, N, *]
      lane-prefix cumsum is ever materialized;
    - lanes emit placements only (the allocate chunk's sparse
      protocol, ``sparse_out=True``) and composed node capacity is
      re-verified on sparse (node, delta) segments: claim entries sort
      by node, each entry checks its node-cumulative demand against
      chunk-start capacity PLUS the lane-prefix of the sparse freed
      deltas gathered at the claim sites (``sparse_entry_tables``) —
      node-capacity over-subscription between lanes surfaces as a
      first-bad-lane, the non-conflicting prefix commits in frozen
      fairness order, and the conflicted tail retries next chunk where
      the leading lane's inputs compose exactly;
    - only the LEADING valid lane's gate/placement failure is final
      (a later lane may have failed merely because the optimistic solve
      hid earlier lanes' freed capacity from it);
    - the deficit direction of that hiding is caught by the sparse
      accept (over-subscription), and the SURPLUS direction by LEFTOVER
      DEMOTION (both preempt paths): a committing lane whose victims
      free more than its claims consume exposes net capacity the
      sequential scan would offer every later preemptor, so every lane
      after the first such lane conflict-retries and re-runs as the
      leading lane of the next chunk, where inputs compose exactly.
      The leading lane also solves WITHOUT the ``W_OWN_FREED`` score
      band (a de-collision heuristic with no sequential counterpart
      that outranks the density band), making its solve
      reference-exact.  Demotions are counted in ``wavefront_stats``
      (``kai_victim_wavefront_leftover_demotions``).

    An action whose frozen unit order gives any queue more candidate
    units than ``VictimConfig.sparse_unit_k`` falls back to the dense
    composed path at run time (one ``lax.cond``, counted in
    ``wavefront_stats`` — the incremental engine's auto-fallback
    pattern); snapshots whose shape rejects the sparse placement
    protocol (devices / extended / subgroup topology / non-uniform
    gangs) take the dense path statically.

    Remaining deviations from the reference's one-preemptor-at-a-time
    walk, all chunk-granular: the preemptor and victim-job orders are
    frozen per action, and a lane's budget ignores units of its own
    queue freed by earlier lanes of the same chunk (bounded
    over-eviction, re-synced next chunk).
    """
    reclaim = mode == "reclaim"
    g, q, n, r = state.gangs, state.queues, state.nodes, state.running
    G, T, M, Q = g.g, g.t, r.m, q.q
    R_ = n.free.shape[1]
    bs = (config.batch_size_preempt
          if mode == "preempt" and config.batch_size_preempt is not None
          else config.batch_size)
    B = max(1, min(bs, G))
    total = state.total_capacity
    pcfg = config.placement
    track_dev = pcfg.track_devices
    track_ext = pcfg.extended
    depth = (config.queue_depth_preempt
             if mode == "preempt" and config.queue_depth_preempt is not None
             else config.queue_depth)
    base0, gang_runtime, pod_order = statics
    quota_eff_q = jnp.where(q.quota <= UNLIMITED + 0.5, jnp.inf, q.quota)
    limit_eff_q = jnp.where(q.limit <= UNLIMITED + 0.5, jnp.inf, q.limit)
    gq = jnp.maximum(g.queue, 0)
    chain_f = chain.astype(jnp.float32)
    ROW = _STATS_ROW[mode]
    # minruntime protection: preempt's resolved value is victim-side only
    # (lane-independent); chunked reclaim is gated on no reclaim
    # minruntime, so zeros there
    if reclaim:
        protected = jnp.zeros((G,), bool)
    else:
        mrt_g = q.preempt_min_runtime_eff[gq]
        protected = (gang_runtime >= 0) & (gang_runtime < mrt_g)
    gang_prio_pod = g.priority[jnp.maximum(r.gang, 0)]          # [M]
    anti = pcfg.anti_groups
    if anti:
        dom_static, _TA = anti_domain_tables(state)

    # ---- hoisted: frozen eviction-unit order + per-unit inputs ----------
    cand0 = base0 & ~result.victim                               # [M]
    removed0 = result.victim & (result.victim_move < 0)
    unit_rank, num_units = _rank_eviction_units(
        state, cand0, result.queue_allocated, fair_share, removed0,
        protected, pod_order, job_rank)
    urank_safe = jnp.minimum(unit_rank, M)
    unit_req = jax.ops.segment_sum(
        jnp.where(cand0[:, None], r.req, 0.0), urank_safe,
        num_segments=M + 1)[:M]                                  # [U, R]
    unit_leaf = jax.ops.segment_max(
        jnp.where(cand0, r.queue, -1), urank_safe,
        num_segments=M + 1)[:M]                                  # [U]
    leaf_safe = jnp.maximum(unit_leaf, 0)
    has_leaf = unit_leaf >= 0
    if reclaim:
        C_all = cumsum_ds(unit_req, axis=0)                      # inclusive
        unit_prio = None
    else:
        C_all = None
        unit_prio = jax.ops.segment_max(
            jnp.where(cand0, gang_prio_pod, -BIG), urank_safe,
            num_segments=M + 1)[:M].astype(jnp.float32)          # [U]

    # ---- hoisted: frozen preemptor order ---------------------------------
    order0 = ordering.job_order_perm(
        g, q, result.queue_allocated, fair_share, total, remaining0)

    lanes = jnp.arange(B, dtype=jnp.int32)
    qidx = jnp.arange(Q)
    pod_leaf = jnp.clip(r.queue, 0, Q - 1)                       # [M]

    sparse_able = (not reclaim) and _sparse_preempt_ok(config)
    # an explicit sparse_unit_k is honored as-is (the documented way to
    # bound table memory or force the dense fallback for debugging);
    # only the non-Session default is floored
    KU = (max(1, int(config.sparse_unit_k))
          if config.sparse_unit_k is not None else 256)

    def make_run(sparse: bool, fell_back: bool):
        """Build one flavor of the chunk loop.  The per-mode hoisted
        tables live INSIDE the closure so the un-taken ``lax.cond``
        branch never materializes the other flavor's tensors."""

        if sparse:
            # compact per-queue unit tables — the sparse analogue of the
            # dense [U, Q, *] cumulatives.  Each unit's ordinal within
            # its queue comes from one stable [M] argsort (rank order is
            # preserved within a queue), then tiny [Q, KU] scatters.
            leaf_key = jnp.where(has_leaf, leaf_safe, Q)
            perm_u = jnp.argsort(leaf_key.astype(jnp.int32), stable=True)
            lk_p = leaf_key[perm_u]
            first_u = jnp.concatenate(
                [jnp.ones((1,), bool), lk_p[1:] != lk_p[:-1]])
            seg_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(first_u, jnp.arange(M), -1))
            r_p = (jnp.arange(M) - seg_start).astype(jnp.int32)
            r_in_q = jnp.zeros((M,), jnp.int32).at[perm_u].set(r_p)
            # pos_c[q, j] = global unit rank of queue q's j-th unit; the
            # KU column (and every missing slot) is the junk rank M —
            # ordinal overflow clamps there, which the action-level
            # overflow cond has already excluded
            rk = jnp.minimum(r_in_q, KU)
            pos_c = jnp.full((Q + 1, KU + 1), M, jnp.int32).at[
                jnp.where(has_leaf, leaf_safe, Q),
                jnp.where(has_leaf, rk, KU)].set(
                jnp.where(has_leaf & (r_in_q < KU),
                          jnp.arange(M, dtype=jnp.int32), M))[:Q]
            pos_k = pos_c[:, :KU]                                # [Q, KU]
            valid_pos = pos_k < M
            pos_safe = jnp.minimum(pos_k, M - 1)
            # per-queue inclusive cumulative unit requests / priorities
            Cq = cumsum_ds(jnp.where(valid_pos[..., None],
                                     unit_req[pos_safe], 0.0),
                           axis=1)                               # [Q, KU, R]
            prio_c = jnp.where(valid_pos, unit_prio[pos_safe],
                               jnp.float32(1e30))                # [Q, KU]
        else:
            onehot_leaf = ((unit_leaf[:, None] == jnp.arange(Q)[None, :])
                           & has_leaf[:, None])                  # [U, Q]
            C_leaf = cumsum_ds(
                onehot_leaf[:, :, None] * unit_req[:, None, :],
                axis=0)                                          # [U, Q, R]
            cnt_leaf = jnp.cumsum(onehot_leaf.astype(jnp.int32), axis=0)
            cl = jnp.concatenate(
                [jnp.zeros((1, Q), jnp.int32), cnt_leaf])        # [U+1, Q]
            r_in_q = cl[jnp.arange(M), leaf_safe]                # [U]
            pos_q = jnp.full((Q + 1, M), M, jnp.int32).at[
                jnp.where(has_leaf, leaf_safe, Q), r_in_q].set(
                    jnp.arange(M, dtype=jnp.int32))[:Q]          # [Q, U]
            if reclaim:
                # EXCLUSIVE-before-u subtree-cumulative freed (strategy
                # bounds)
                inc_sub = ((chain[leaf_safe] & has_leaf[:, None])[:, :, None]
                           * unit_req[:, None, :])               # [U, Q, R]
                S_cols = (cumsum_ds(inc_sub, axis=0)
                          - inc_sub).reshape(M, Q * R_)
            else:
                prio_by_q = jnp.full((Q + 1, M), jnp.float32(1e30)).at[
                    jnp.where(has_leaf, leaf_safe, Q), r_in_q].set(
                        unit_prio)[:Q]                           # [Q, U]

        def chunk(carry):
            res, remaining, c, q_att, fuel = carry
            free, dev = res.free, res.device_free
            qa = res.queue_allocated
            qan = res.queue_allocated_nonpreemptible
            extra = res.releasing_extra
            extra_dev = res.device_releasing_extra
            ext = res.extended_free
            ext_extra = res.extended_releasing_extra

            # ---- lanes: first B remaining gangs in frozen order ---------
            # (any queue mix: preempt's own-queue-local budgets/
            # consumption are kept exact by QUEUE-SEGMENTED cumulative
            # pricing, unit ranks, watermarks and pointers below — a
            # 256-preemptor burst in one queue packs B lanes per chunk
            # like the single-queue code always did, AND 512 queues × 1
            # preemptor each share chunks instead of degrading to one
            # queue per chunk)
            flags = remaining[order0]                            # [G]
            rnk = jnp.cumsum(flags.astype(jnp.int32)) - 1
            pos = jnp.where(flags & (rnk < B), rnk, B)
            cand_g = jnp.full((B + 1,), G, jnp.int32).at[pos].set(
                order0)[:B]
            cand_valid = jnp.zeros((B + 1,), bool).at[pos].set(True)[:B]
            gsafe_b = jnp.minimum(cand_g, G - 1)
            q_b = gq[gsafe_b]                                    # [B]
            # lanes of the same queue (preempt's segmented per-queue math)
            same_q_b = (q_b[None, :] == q_b[:, None])            # [B, B]

            # ---- lane budgets over the frozen unit order ----------------
            lane_req = jnp.where(cand_valid[:, None],
                                 task_req_g[gsafe_b], 0.0)       # [B, R]
            cluster_free = jnp.sum(
                jnp.where(n.valid[:, None],
                          free + n.releasing + extra, 0.0),
                axis=0)
            if reclaim:
                cum_req = jnp.cumsum(lane_req, axis=0)
                targets = cum_req - cluster_free[None, :] - EPS  # [B, R]
            else:
                # QUEUE-SEGMENTED cumulative pricing: a lane's target is
                # the cumulative request of its OWN queue's lanes so far
                # (its victims can only come from there), optimistically
                # assuming the whole idle pool (queues double-counting
                # free under-evict, which the accept prefix rejects and
                # the lane retries next chunk — over-eviction never
                # happens).  For a single-queue chunk this is exactly
                # the full cumulative.
                seg_incl = (same_q_b & (lanes[None, :] <= lanes[:, None])
                            & cand_valid[None, :])               # [B, B]
                cum_req_q = jnp.einsum(
                    "bc,cr->br", seg_incl.astype(lane_req.dtype), lane_req)
                targets = cum_req_q - cluster_free[None, :] - EPS
            need_b = cand_valid & jnp.any(targets > 0, axis=-1)
            if sparse:
                # probe the compact per-queue tables: own-queue consumed
                # base at the pointer, then a [KU]-searchsorted per
                # (lane, resource) instead of the dense [B, U, R] gather
                j_c = jax.vmap(
                    lambda row, cv: jnp.searchsorted(
                        row, cv, side="right"))(pos_k, c)        # [Q]
                Cv_c = jnp.where(
                    (j_c > 0)[:, None],
                    Cq[qidx, jnp.maximum(j_c - 1, 0)], 0.0)      # [Q, R]
                base_b = Cv_c[q_b]                               # [B, R]
                v_b = targets + base_b
                pos_full_b = pos_c[q_b]                          # [B, KU+1]
                j_rb = jax.vmap(jax.vmap(jnp.searchsorted,
                                         in_axes=(1, 0)))(
                    Cq[q_b], v_b)                                # [B, R]
                # a non-positive target is already covered by rank 0
                # (the dense searchsorted's answer on the step function)
                k_rb = jnp.where(
                    v_b > 0,
                    jnp.take_along_axis(pos_full_b,
                                        jnp.minimum(j_rb, KU), axis=1),
                    0)
            else:
                csafe = jnp.clip(c, 0, M - 1)
                Cv_at_c = jnp.where((c >= 0)[:, None],
                                    C_leaf[csafe, qidx], 0.0)    # [Q, R]
                if reclaim:
                    arr_b = C_all[None] - C_leaf[:, q_b].transpose(1, 0, 2)
                    base_b = (jnp.sum(Cv_at_c, axis=0)[None, :]
                              - Cv_at_c[q_b])                    # [B, R]
                else:
                    arr_b = C_leaf[:, q_b].transpose(1, 0, 2)    # [B, U, R]
                    base_b = Cv_at_c[q_b]
                k_rb = jax.vmap(jax.vmap(jnp.searchsorted,
                                         in_axes=(1, 0)))(
                    arr_b, targets + base_b)                     # [B, R]
            K_cap = jnp.where(need_b, jnp.max(k_rb, axis=1), -1
                              ).astype(jnp.int32)                # [B]
            # a victim scenario always contains >= 1 NEW eviction unit
            # (the sequential search's smallest scenario is unit-prefix
            # 0 — the scenario builder never yields an empty victim
            # set): lane b consumes at least the (b+1)-th unit still
            # available TO IT
            if reclaim:
                vrank = jnp.cumsum(cand_valid.astype(jnp.int32)) - 1  # [B]
            else:
                # ordinal among the lane's OWN queue's valid lanes: the
                # (k+1)-th same-queue lane needs k+1 available own units
                vrank = jnp.sum(
                    same_q_b & (lanes[None, :] < lanes[:, None])
                    & cand_valid[None, :], axis=1).astype(jnp.int32)
            if sparse:
                av_c = (valid_pos & (pos_k < num_units)
                        & (pos_k > c[:, None]))                  # [Q, KU]
                cav = jnp.cumsum(av_c.astype(jnp.int32), axis=1)
                j_min = jax.vmap(jnp.searchsorted)(cav[q_b], vrank + 1)
                K_min = jnp.take_along_axis(
                    pos_full_b, jnp.minimum(j_min, KU)[:, None],
                    axis=1)[:, 0].astype(jnp.int32)              # [B]
            else:
                avail_u = (has_leaf & (jnp.arange(M) < num_units)
                           & (jnp.arange(M)
                              > c[jnp.clip(unit_leaf, 0, Q - 1)]))
                cum_av_leaf = jnp.cumsum(
                    (avail_u[:, None] & onehot_leaf).astype(jnp.int32),
                    axis=0)
                cum_av = jnp.cumsum(avail_u.astype(jnp.int32))   # [U]
                if reclaim:
                    cum_av_b = cum_av[None, :] - cum_av_leaf[:, q_b].T
                else:
                    cum_av_b = cum_av_leaf[:, q_b].T             # [B, U]
                K_min = jax.vmap(jnp.searchsorted)(
                    cum_av_b, vrank + 1).astype(jnp.int32)       # [B]
            K_raw = jnp.where(cand_valid, jnp.maximum(K_cap, K_min), -1)
            K_b = jax.lax.associative_scan(jnp.maximum, K_raw)   # sorted
            insufficient_b = cand_valid & (K_raw >= num_units)

            # ---- strategy / priority admissibility bound ----------------
            if reclaim:
                # FitsReclaimStrategy, probed on the hoisted subtree
                # cumulative: unit u passes while its leveled queue's
                # remaining share BEFORE u (live qa corrected by the
                # already-consumed rollup S_cons) stays above fair share
                # — or above deserved quota when the reclaimer is under
                # its own quota.
                S_cons = jnp.einsum("va,vr->ar", chain_f, Cv_at_c)  # [Q, R]
                thr_fs = (qa - fair_share - EPS + S_cons).reshape(-1)
                bnd_fs = jnp.max(jax.vmap(
                    jnp.searchsorted, in_axes=(1, 0))(
                    S_cols, thr_fs).reshape(Q, R_), axis=1)      # [Q]
                thr_qt = (jnp.where(jnp.isinf(quota_eff_q), -jnp.inf,
                                    qa - quota_eff_q - EPS)
                          + S_cons).reshape(-1)
                bnd_qt = jnp.max(jax.vmap(
                    jnp.searchsorted, in_axes=(1, 0))(
                    S_cols, thr_qt).reshape(Q, R_), axis=1)      # [Q]
                under_b = jax.vmap(
                    lambda qi, tr: _ancestor_gate(
                        q.parent, qi, num_levels, qa, q.quota, tr))(
                            q_b, lane_req)
                bnd_eff = jnp.where(
                    under_b[None, :],
                    jnp.maximum(bnd_fs, bnd_qt)[:, None],
                    bnd_fs[:, None])                             # [Q, B]
                lq_vb = lq_tab[:, q_b]                           # [Q, B]
                x_vb = jnp.clip(jnp.take_along_axis(
                    bnd_eff, jnp.clip(lq_vb, 0, Q - 1), axis=0), 0, M)
                cnt_before = cl[x_vb, qidx[:, None]]             # [Q, B]
                first_bad_vb = pos_q[qidx[:, None],
                                     jnp.clip(cnt_before, 0, M - 1)]
                first_bad_vb = jnp.where(lq_vb >= 0, first_bad_vb, M)
                hi_b = jnp.minimum(jnp.min(first_bad_vb, axis=0),
                                   num_units) - 1                # [B]
            elif sparse:
                # victim units are priority-ascending within the queue;
                # a lane may only consume own-queue units strictly below
                # its priority — probed on the compact table
                allowed = jax.vmap(jnp.searchsorted)(
                    prio_c[q_b],
                    g.priority[gsafe_b].astype(jnp.float32))     # [B]
                hi_b = jnp.take_along_axis(
                    pos_full_b, jnp.clip(allowed, 0, KU)[:, None],
                    axis=1)[:, 0] - 1
                hi_b = jnp.where(allowed > 0, hi_b, -1)
            else:
                # victim units are priority-ascending within the queue; a
                # lane may only consume own-queue units strictly below its
                # priority
                allowed = jax.vmap(jnp.searchsorted)(
                    prio_by_q[q_b],
                    g.priority[gsafe_b].astype(jnp.float32))     # [B]
                hi_b = pos_q[q_b, jnp.clip(allowed, 0, M - 1)] - 1
                hi_b = jnp.where(allowed > 0, hi_b, -1)

            # ---- lane gates ---------------------------------------------
            nonpre_b = ~g.preemptible[gsafe_b]
            gate_np_b = jax.vmap(
                lambda qi, tr: _ancestor_gate(
                    q.parent, qi, num_levels, qan, q.quota, tr))(
                        q_b, lane_req)
            gate_b = jnp.where(nonpre_b, gate_np_b, True)
            gate_b &= cand_valid & (K_raw <= hi_b) & ~insufficient_b

            # ---- pod → lane assignment + per-lane freed pools -----------
            live0 = cand0 & (unit_rank > c[pod_leaf])
            if reclaim:
                # first lane whose budget covers the pod AND whose queue
                # may evict it: a unit skipped by its own queue's lane
                # flows to the next other-queue lane instead of being
                # lost
                may = q_b[None, :] != jnp.arange(Q)[:, None]     # [Q, B]
                may = may & cand_valid[None, :]
                nxt = jnp.where(may, lanes[None, :], B)          # [Q, B]
                next_ok = jnp.flip(jax.lax.associative_scan(
                    jnp.minimum, jnp.flip(nxt, axis=1), axis=1),
                    axis=1)                                      # [Q, B]
                next_ok = jnp.concatenate(
                    [next_ok, jnp.full((Q, 1), B, jnp.int32)],
                    axis=1)                                      # [Q, B+1]
                lane0 = jnp.searchsorted(K_b, unit_rank)         # [M] 0..B
                lane_of_pod = jnp.where(
                    live0, next_ok[pod_leaf, jnp.minimum(lane0, B)], B)
            else:
                # PER-QUEUE running-max watermark: a unit flows to the
                # first same-queue lane whose watermark covers its rank
                # (exactly the old single-queue assignment, segmented
                # per queue — no cross-queue leak).  [M, B] compare-and-
                # min; B is small.
                K_wm = jnp.max(jnp.where(
                    same_q_b & (lanes[None, :] <= lanes[:, None])
                    & cand_valid[None, :], K_raw[None, :], -1),
                    axis=1)                                      # [B]
                cand_lane = ((pod_leaf[:, None] == q_b[None, :])
                             & cand_valid[None, :]
                             & (K_wm[None, :] >= urank_safe[:, None]))
                lane_of_pod = jnp.where(
                    live0,
                    jnp.min(jnp.where(cand_lane, lanes[None, :], B),
                            axis=1), B)
            (freed_n_b, freed_d_b, freed_q_b, freed_e_b,
             own_incr_b) = _freed_by_lane(
                state, lane_of_pod, B, chain, compose=not sparse,
                track_devices=track_dev, extended=track_ext)
            extra_b = extra[None] + freed_n_b                    # [B, N, R]
            if track_dev:
                extra_dev_b = extra_dev[None] + freed_d_b
                dev_ax = 0
            else:
                extra_dev_b = extra_dev
                dev_ax = None
            if track_ext:
                ext_extra_b = ext_extra[None] + freed_e_b
                ext_ax = 0
            else:
                ext_extra_b = ext_extra
                ext_ax = None
            qa_eff_b = qa[None] - freed_q_b                      # [B, Q, R]
            if reclaim:
                # CanReclaimResources against the POST-SCENARIO state
                # (the lane's own victim credit applied): a dept at its
                # full fair share can still reclaim within itself
                gate_b &= jax.vmap(
                    lambda qi, tr, qae: _ancestor_gate(
                        q.parent, qi, num_levels, qae, fair_share, tr))(
                            q_b, lane_req, qa_eff_b)
            lead = cand_valid & (jnp.cumsum(
                cand_valid.astype(jnp.int32)) == 1)              # [B]
            bias_b = W_OWN_FREED * own_incr_b.astype(jnp.float32)  # [B, N]
            if not reclaim:
                # the LEADING valid lane's inputs compose exactly, so
                # its solve must be reference-exact: the own-freed band
                # is a cross-lane de-collision heuristic with no
                # sequential counterpart, and at 9.5 it outranks the
                # density band (max 9) — keeping it on the leading lane
                # flips placements the sequential scan scores purely by
                # density (e.g. toward an earlier preemptor's leftover
                # freed node)
                bias_b = jnp.where(lead[:, None], 0.0, bias_b)
            if anti:
                dmask_b = ~anti_forbid_nodes(state, res.anti_used,
                                             dom_static, cand_g)  # [B, N]
                dup_b = anti_defer_lanes(state, cand_g, cand_valid)
                if pcfg.attract_groups:
                    dmask_b = dmask_b & attract_allow_nodes(
                        state, res.anti_used, dom_static, cand_g)
                    dup_b = dup_b | attract_defer_lanes(
                        state, cand_g, cand_valid, res.anti_used)
            else:
                dmask_b = jnp.ones((B, n.n), bool)
                dup_b = jnp.zeros((B,), bool)
            if sparse:
                # lanes emit placements only (the allocate chunk's
                # sparse wavefront protocol) — no dense [B, N, R]
                # carries through the vmap
                (qa2_b, qan2_b, nodes_b, pipe_b, succ_b) = jax.vmap(
                    lambda gi, lane, ex_n, ex_d, ex_e, qae, sb, dm:
                        _attempt_gang(
                            state, gi, free, dev, qae, qan, num_levels,
                            pcfg, ex_n, ex_d, lane, chain, ext_free=ext,
                            extra_extended_releasing=ex_e, score_bias=sb,
                            domain_mask=dm, sparse_out=True),
                    in_axes=(0, 0, 0, dev_ax, ext_ax, 0, 0, 0))(
                    cand_g, lanes, extra_b, extra_dev_b, ext_extra_b,
                    qa_eff_b, bias_b, dmask_b)
                devt_b = jnp.full((B, T), -1, jnp.int32)
            else:
                (free2_b, dev2_b, qa2_b, qan2_b, nodes_b, devt_b, pipe_b,
                 succ_b, bind_b, devbind_b, ext2_b, extbind_b) = jax.vmap(
                    lambda gi, lane, ex_n, ex_d, ex_e, qae, sb, dm:
                        _attempt_gang(
                            state, gi, free, dev, qae, qan, num_levels,
                            pcfg, ex_n, ex_d, lane, chain, ext_free=ext,
                            extra_extended_releasing=ex_e, score_bias=sb,
                            domain_mask=dm),
                    in_axes=(0, 0, 0, dev_ax, ext_ax, 0, 0, 0))(
                    cand_g, lanes, extra_b, extra_dev_b, ext_extra_b,
                    qa_eff_b, bias_b, dmask_b)

            # an anti-deferred lane is CONFLICT-rejected (retries next
            # chunk against the updated claimed-domain table), never
            # terminal
            succ_b = succ_b & ~dup_b
            ok_pre = gate_b & succ_b                             # [B]
            okm = ok_pre[:, None, None]
            d_qa = jnp.where(okm, qa2_b - qa_eff_b, 0.0)
            d_qan = jnp.where(okm, qan2_b - qan[None], 0.0)
            cum_qa = jnp.cumsum(d_qa, axis=0)
            cum_qan = jnp.cumsum(d_qan, axis=0)

            if sparse:
                # sparse accept: claim entries sort by node; each entry
                # checks its node-cumulative demand against chunk-start
                # capacity plus the lane-prefix of the sparse freed
                # deltas gathered AT THE CLAIM SITES — the composed-
                # capacity test without any [B, N, R] cumsum
                req_b = g.task_req[gsafe_b, 0]                   # [B, R]
                ent_ok = ok_pre[:, None] & (nodes_b >= 0)        # [B, T]
                first_bad_cap, node_e, lane_e = sparse_accept_first_bad(
                    nodes_b, ent_ok, pipe_b, req_b, free,
                    free + n.releasing + extra, n.n,
                    credit=lambda lane_s, nsafe: jnp.cumsum(
                        freed_n_b[:, nsafe, :], axis=0)[
                        lane_s, jnp.arange(lane_s.shape[0])])
                accept = lanes < first_bad_cap                   # [B]
                qa_comp = (qa[None] - jnp.cumsum(freed_q_b, axis=0)
                           + cum_qa)                             # [B, Q, R]
                # per-lane NET leftover: freed capacity the lane's own
                # claims do not consume (freed_b - claims_b > 0 on any
                # node).  Uniform tasks make claims a per-node entry
                # count times the replica request — no dense [B, N, R]
                # claim grid beyond the own-freed table that already
                # exists.
                nsafe_bt = jnp.where(ent_ok, nodes_b, n.n)       # [B, T]
                cnt_bn = jnp.zeros((B, n.n + 1), req_b.dtype).at[
                    lanes[:, None], nsafe_bt].add(1.0)[:, :n.n]  # [B, N]
                leftover_b = jnp.any(
                    freed_n_b - cnt_bn[:, :, None] * req_b[:, None, :]
                    > EPS, axis=(1, 2))                          # [B]
            else:
                d_free = jnp.where(okm, free[None] - free2_b, 0.0)
                d_bind = jnp.where(okm, bind_b, 0.0)
                cum_free_d = jnp.cumsum(d_free, axis=0)
                cum_bind = jnp.cumsum(d_bind, axis=0)
                rel_floor_b = -(n.releasing[None] + extra_b) - EPS
                ok_node = jnp.all(free[None] - cum_free_d >= rel_floor_b,
                                  axis=(1, 2))
                ok_bind = jnp.all(
                    cum_bind <= jnp.maximum(free[None], 0.0) + EPS,
                    axis=(1, 2))
                accept = ok_node & ok_bind
                qa_comp = qa[None] - freed_q_b + cum_qa          # [B, Q, R]
                if not reclaim:
                    # per-lane NET leftover for the dense composed
                    # fallback: own freed is the lane-diff of the
                    # composed cumsum, claims are d_free — both already
                    # materialized here
                    own_n = freed_n_b - jnp.concatenate(
                        [jnp.zeros_like(freed_n_b[:1]), freed_n_b[:-1]])
                    leftover_b = jnp.any(own_n - d_free > EPS,
                                         axis=(1, 2))            # [B]
            ok_qa = jnp.all((qa_comp <= limit_eff_q[None] + EPS)
                            | (cum_qa <= EPS), axis=(1, 2))
            ok_qan = jnp.all((qan[None] + cum_qan
                              <= quota_eff_q[None] + EPS)
                             | (cum_qan <= EPS), axis=(1, 2))
            accept = accept & ok_qa & ok_qan
            if reclaim:
                chain_b = chain[q_b]                             # [B, Q]
                accept &= jnp.all(
                    (qa_comp <= fair_share[None] + EPS)
                    | ~chain_b[:, :, None], axis=(1, 2))
            if (not sparse) and pcfg.track_devices:
                d_dev = jnp.where(okm, dev[None] - dev2_b, 0.0)
                d_devbind = jnp.where(okm, devbind_b, 0.0)
                cum_dev = jnp.cumsum(d_dev, axis=0)
                if not reclaim:
                    own_d = freed_d_b - jnp.concatenate(
                        [jnp.zeros_like(freed_d_b[:1]), freed_d_b[:-1]])
                    leftover_b |= jnp.any(own_d - d_dev > EPS,
                                          axis=(1, 2))
                accept &= jnp.all(
                    dev[None] - cum_dev
                    >= -(n.device_releasing[None] + extra_dev_b) - EPS,
                    axis=(1, 2))
                accept &= jnp.all(
                    jnp.cumsum(d_devbind, axis=0)
                    <= jnp.maximum(dev[None], 0.0) + EPS, axis=(1, 2))
            if (not sparse) and pcfg.extended:
                d_ext = jnp.where(okm, ext[None] - ext2_b, 0.0)
                cum_ext = jnp.cumsum(d_ext, axis=0)
                if not reclaim:
                    own_e = freed_e_b - jnp.concatenate(
                        [jnp.zeros_like(freed_e_b[:1]), freed_e_b[:-1]])
                    leftover_b |= jnp.any(own_e - d_ext > EPS,
                                          axis=(1, 2))
                accept &= jnp.all(
                    ext[None] - cum_ext
                    >= -(n.extended_releasing[None] + ext_extra_b) - EPS,
                    axis=(1, 2))
                accept &= jnp.all(
                    jnp.cumsum(jnp.where(okm, extbind_b, 0.0), axis=0)
                    <= jnp.maximum(ext[None], 0.0) + EPS, axis=(1, 2))

            # ---- strict accept prefix -----------------------------------
            fail_own = cand_valid & ~(ok_pre & accept)           # [B]
            if reclaim:
                prev_lo = jnp.zeros((B,), bool)
            else:
                # LEFTOVER DEMOTION (preempt exactness): a committing
                # lane whose victims free MORE than its own claims
                # consume leaves net capacity the sequential scan would
                # expose to every later preemptor — but a later lane's
                # optimistic solve never saw it (sparse: own pool only;
                # dense: chunk-start free without earlier claims), so
                # its placement can silently diverge where the accept's
                # over-subscription check has nothing to catch.  Lanes
                # after the first accepted leftover-producing lane are
                # demoted to conflict-retry; next chunk they re-run as
                # the LEADING lane, where inputs compose exactly and
                # the solve is bias-free (reference-exact).  Leftover
                # is rare in the steady state (a preemptor lands on its
                # own victims' capacity and consumes it), so chunks
                # stay wide; the demotion count is exported per cycle.
                lo_i = (ok_pre & accept & leftover_b).astype(jnp.int32)
                prev_lo = (jnp.cumsum(lo_i) - lo_i) > 0
            bad = fail_own | (cand_valid & prev_lo)              # [B]
            bad_cum = jnp.cumsum(bad.astype(jnp.int32))
            take = cand_valid & (bad_cum == 0)                   # [B]
            demoted = cand_valid & prev_lo & ok_pre & accept     # [B]
            # Only a GATE/placement failure of the first bad lane is
            # final — its inputs composed exactly (every earlier valid
            # lane took), and own-queue exclusion is exact here, so the
            # failure is genuine (insufficient admissible victims,
            # capacity, or queue gates) — never a range artifact.  An
            # accept failure there is a cross-lane capacity CONFLICT:
            # the lane retries next chunk, where, as the leading lane,
            # its accept is self-consistent.
            #
            # TERMINATION INVARIANT (the fuel bound relies on it): every
            # chunk retires >=1 lane, because a LEADING valid lane's
            # accept is implied by ok_pre — each accept component (node
            # floors vs its own extra pool, bind vs chunk-start idle,
            # queue caps, the reclaim fair-share term) is already
            # enforced by gate_b/_attempt_gang when no earlier lane
            # contributed deltas.  If you add an accept-ONLY check, also
            # gate it in gate_b, or the loop can spin identical chunks
            # until fuel exhausts.
            first_bad = bad & ((bad_cum - bad.astype(jnp.int32)) == 0)
            if sparse:
                # the optimistic own-pool solve hides earlier lanes'
                # freed capacity: a non-leading lane's gate/placement
                # failure may be that artifact, so only the LEADING
                # valid lane (whose inputs compose exactly) fails
                # terminally — everything else conflict-retries
                first_fail = first_bad & ~ok_pre & ~dup_b & lead
            else:
                # a lane demoted by an earlier leftover had polluted
                # inputs — its failure is never terminal
                first_fail = first_bad & ~ok_pre & ~dup_b & ~prev_lo
            any_take = jnp.any(take)
            star = jnp.argmax(jnp.where(take, lanes, -1))
            victims = (lane_of_pod <= star) & any_take
            # per-queue consumed pointers: the max committed budget among
            # accepted lanes allowed to evict from that queue
            if reclaim:
                M_v = jnp.max(jnp.where(take[None, :] & may,
                                        K_b[None, :], -1), axis=1)  # [Q]
            else:
                # accepted lanes advance their OWN queue's pointer to
                # their per-queue watermark
                M_v = jax.ops.segment_max(
                    jnp.where(take & cand_valid, K_wm, -1),
                    jnp.where(cand_valid, q_b, Q),
                    num_segments=Q + 1)[:Q]
            c2 = jnp.maximum(c, M_v)

            w = take.astype(free.dtype)
            sel = lambda arr, base_v: jnp.where(any_take, arr[star],
                                                base_v)
            if sparse:
                # commits reconstruct capacity deltas from the sparse
                # entries (claims) and the per-lane own freed (pools) —
                # the union of accepted DISJOINT lanes is a plain sum
                take_e = take[lane_e] & ent_ok.ravel()
                upd = jnp.zeros((n.n + 1, R_), free.dtype).at[
                    node_e].add(
                    jnp.where(take_e[:, None], req_b[lane_e], 0.0),
                    mode="drop")
                new_free = free - upd[:n.n]
                new_extra = extra + jnp.einsum("b,bnr->nr", w, freed_n_b)
                new_qa = (qa - jnp.einsum("b,bqr->qr", w, freed_q_b)
                          + jnp.einsum("b,bqr->qr", w, d_qa))
            else:
                new_free = free - jnp.einsum("b,bnr->nr", w, d_free)
                new_extra = sel(extra_b, extra)
                new_qa = (sel(qa_eff_b, qa)
                          + jnp.einsum("b,bqr->qr", w, d_qa))
            res = res.replace(
                free=new_free,
                device_free=(dev - jnp.einsum(
                    "b,bnd->nd", w,
                    jnp.where(okm, dev[None] - dev2_b, 0.0))
                    if (not sparse) and pcfg.track_devices else dev),
                extended_free=(ext - jnp.einsum(
                    "b,bne->ne", w,
                    jnp.where(okm, ext[None] - ext2_b, 0.0))
                    if (not sparse) and pcfg.extended else ext),
                releasing_extra=new_extra,
                device_releasing_extra=(sel(extra_dev_b, extra_dev)
                                        if track_dev else extra_dev),
                extended_releasing_extra=(sel(ext_extra_b, ext_extra)
                                          if track_ext else ext_extra),
                queue_allocated=new_qa,
                queue_allocated_nonpreemptible=(
                    qan + jnp.einsum("b,bqr->qr", w, d_qan)),
                placements=res.placements.at[cand_g].set(
                    jnp.where(take[:, None], nodes_b,
                              res.placements[cand_g])),
                placement_device=res.placement_device.at[cand_g].set(
                    jnp.where(take[:, None], devt_b,
                              res.placement_device[cand_g])),
                pipelined=res.pipelined.at[cand_g].set(
                    jnp.where(take[:, None], pipe_b,
                              res.pipelined[cand_g])),
                allocated=res.allocated.at[cand_g].set(
                    res.allocated[cand_g] | take),
                attempted=res.attempted.at[cand_g].set(
                    res.attempted[cand_g] | take | first_fail),
                fit_reason=res.fit_reason.at[cand_g].set(
                    jnp.where(first_fail, 3, res.fit_reason[cand_g])),
                victim=res.victim | victims,
                wavefront_stats=res.wavefront_stats
                .at[ROW, 0].add(1)
                .at[ROW, 1].add(jnp.sum(cand_valid.astype(jnp.int32)))
                .at[ROW, 2].add(B)
                .at[ROW, 4].add(jnp.sum(demoted.astype(jnp.int32))),
            )
            if anti:
                res = res.replace(anti_used=anti_mark_placements(
                    state, res.anti_used, dom_static, cand_g,
                    jnp.where(take[:, None], nodes_b, -1), take))
            done_b = take | first_fail
            remaining = remaining.at[cand_g].set(
                remaining[cand_g] & ~done_b)
            if depth is not None:
                q_att = q_att + jax.ops.segment_sum(
                    done_b.astype(jnp.int32), q_b, num_segments=Q)
                remaining = remaining & (q_att[gq] < depth)
            if reclaim:
                # live strategy-viability drop (see the sequential path)
                qa_l = res.queue_allocated
                under_g = jax.vmap(
                    lambda qi, tr: _ancestor_gate(
                        q.parent, qi, num_levels, qa_l, q.quota, tr))(
                            gq, task_req_g)
                lqs2 = jnp.maximum(lq_tab, 0)
                no_lq = lq_tab < 0
                over_fs_vc = no_lq | jnp.any(
                    qa_l[lqs2] > fair_share[lqs2] + EPS, -1)
                over_qt_vc = no_lq | jnp.any(
                    qa_l[lqs2] > quota_eff_q[lqs2] + EPS, -1)
                diff = (qidx[:, None] != qidx[None, :])
                has_v = (cnt_q > 0)[:, None] & diff
                ev_fs_c = jnp.any(has_v & over_fs_vc, axis=0)
                ev_qt_c = jnp.any(has_v & over_qt_vc, axis=0)
                remaining = remaining & (
                    ev_fs_c[gq] | (under_g & ev_qt_c[gq]))
            return res, remaining, c2, q_att, fuel - 1

        def run(res0):
            if fell_back:
                # runtime overflow of the compact unit tables — counted
                # so the sparse-path fallback rate is observable
                res0 = res0.replace(
                    wavefront_stats=res0.wavefront_stats
                    .at[ROW, 3].add(1))
            res, _, _, _, fuel_left = lax.while_loop(
                lambda cr: jnp.any(cr[1]) & (cr[4] > 0), chunk,
                (res0, remaining0, jnp.full((Q,), -1, jnp.int32),
                 jnp.zeros((Q,), jnp.int32), jnp.asarray(G, jnp.int32)))
            if _DEBUG_CHUNKS:
                # stash the chunk count in the last fit_reason slot
                # (scratch diagnostics only — that slot is snapshot
                # padding in practice)
                res = res.replace(fit_reason=res.fit_reason.at[-1].set(
                    jnp.asarray(G, jnp.int32) - fuel_left))
            return res

        return run

    if not sparse_able:
        return make_run(False, False)(result)
    if KU >= M:
        # no queue can ever expose more units than running pods exist:
        # the dense fallback is statically unreachable, so skip the
        # cond (small tier-1 shapes trace ONE loop, not two)
        return make_run(True, False)(result)
    cnt_units_q = jax.ops.segment_sum(
        has_leaf.astype(jnp.int32), jnp.where(has_leaf, leaf_safe, Q),
        num_segments=Q + 1)[:Q]
    return lax.cond(jnp.any(cnt_units_q > KU),
                    make_run(False, True), make_run(True, False), result)


#: scratch diagnostics flag (set True to expose chunk counts)
_DEBUG_CHUNKS = False


def run_victim_action(
    state: ClusterState,
    fair_share: jax.Array,
    result: AllocationResult,
    *,
    num_levels: int,
    mode: str,                   # "reclaim" | "preempt" | "consolidate"
    config: VictimConfig = VictimConfig(),
) -> AllocationResult:
    """The reclaim / preempt / consolidation action: scan pending
    unallocated gangs in fairness order, solving victim scenarios for each.

    Functional equivalent of ``reclaim.Execute`` / ``preempt.Execute`` /
    ``consolidation.Execute``.  Successful preemptors are committed as
    *pipelined* placements (they wait for their victims' pods to
    terminate — the reference pipelines preemptors onto releasing
    resources the same way); consolidation victims additionally get a
    planned re-placement node in ``victim_move``.
    """
    if mode not in ("reclaim", "preempt", "consolidate"):
        raise ValueError(f"unknown victim action mode: {mode!r}")
    g, q, r = state.gangs, state.queues, state.running
    G = g.g
    total = state.total_capacity
    chain = _chain_membership(q.parent, num_levels)
    depth = (config.queue_depth_preempt
             if mode == "preempt" and config.queue_depth_preempt is not None
             else config.queue_depth)
    statics = victim_statics(state)
    job_rank0 = frozen_job_rank(state, result.queue_allocated, fair_share)
    quota_eff_q = jnp.where(q.quota <= UNLIMITED + 0.5, jnp.inf, q.quota)
    anti = config.placement.anti_groups
    if anti:
        dom_static, _TA = anti_domain_tables(state)
    if mode == "reclaim":
        # [victim leaf, reclaimer leaf] leveled-queue table for the live
        # strategy-viability drop inside `step`
        qidx = jnp.arange(q.q)
        lq_tab = jax.vmap(lambda v: jax.vmap(
            lambda c: _leveled_queue(chain, q.depth, v, c))(qidx))(qidx)

    def step(carry):
        res, remaining, q_att, fuel = carry
        gi = ordering.select_next_gang(
            g, q, res.queue_allocated, fair_share, total, remaining)
        runnable = remaining[gi] & g.valid[gi] & (g.backoff[gi] <= 0) \
            & ~res.allocated[gi]

        dmask = (~anti_forbid_nodes(state, res.anti_used, dom_static, gi)
                 if anti else None)
        if anti and config.placement.attract_groups:
            dmask = dmask & attract_allow_nodes(
                state, res.anti_used, dom_static, gi)

        def attempt(_):
            return solve_for_preemptor(
                state, gi, res, fair_share, chain,
                num_levels=num_levels, mode=mode, config=config,
                statics=statics, job_rank=job_rank0, domain_mask=dmask)

        def skip(_):
            T = g.t
            return (jnp.asarray(False), jnp.zeros_like(res.victim),
                    jnp.full((T,), -1, jnp.int32),
                    jnp.full((T,), -1, jnp.int32), jnp.zeros((T,), bool),
                    jnp.full((state.running.m,), -1, jnp.int32),
                    res.free, res.device_free, res.releasing_extra,
                    res.device_releasing_extra, res.queue_allocated,
                    res.queue_allocated_nonpreemptible, res.extended_free,
                    res.extended_releasing_extra)

        (success, victims, nodes_t, dev_t, pipe_t, moves,
         free2, dev2, extra2, extra_dev2, qa2, qan2, ext2,
         ext_extra2) = lax.cond(runnable, attempt, skip, None)
        res = res.replace(
            extended_free=jnp.where(success, ext2, res.extended_free),
            extended_releasing_extra=jnp.where(
                success, ext_extra2, res.extended_releasing_extra),
            free=jnp.where(success, free2, res.free),
            device_free=jnp.where(success, dev2, res.device_free),
            releasing_extra=jnp.where(success, extra2, res.releasing_extra),
            device_releasing_extra=jnp.where(
                success, extra_dev2, res.device_releasing_extra),
            queue_allocated=jnp.where(success, qa2, res.queue_allocated),
            queue_allocated_nonpreemptible=jnp.where(
                success, qan2, res.queue_allocated_nonpreemptible),
            placements=res.placements.at[gi].set(
                jnp.where(success, nodes_t, res.placements[gi])),
            placement_device=res.placement_device.at[gi].set(
                jnp.where(success, dev_t, res.placement_device[gi])),
            # tasks on victim/releasing capacity pipeline; tasks that fit
            # genuinely idle capacity bind now (stmt.Allocate vs Pipeline)
            pipelined=res.pipelined.at[gi].set(
                jnp.where(success, pipe_t, res.pipelined[gi])),
            allocated=res.allocated.at[gi].set(res.allocated[gi] | success),
            attempted=res.attempted.at[gi].set(res.attempted[gi] | runnable),
            victim=res.victim | victims,
            victim_move=jnp.where(success & (moves >= 0), moves,
                                  res.victim_move),
        )
        if anti:
            # a victim-action placement claims its domains too, so a
            # later conflicting gang (in this or a later action of the
            # cycle) cannot co-land with a reclaim-placed preemptor
            res = res.replace(anti_used=anti_mark_placements(
                state, res.anti_used, dom_static, gi, nodes_t, success))
        remaining = remaining.at[gi].set(False)
        if depth is not None:
            # per-QUEUE attempt budget (ref QueueDepthPerAction: "max
            # number of jobs to try for action per queue") — exhausted
            # queues drain from the remaining set
            q_att = q_att.at[g.queue[gi]].add(
                runnable.astype(jnp.int32))
            remaining = remaining & (
                q_att[g.queue] < depth)
        if mode == "reclaim":
            # Live strategy-viability drop — SOUND because within the
            # action victim-queue shares only fall and reclaimer
            # allocation only grows, so a (victim queue, reclaimer) pair
            # that stops being strategy-evictable never recovers.  A
            # reclaimer gang stays in `remaining` only while some other
            # leaf queue with candidates is still evictable for it; once
            # shares exhaust, the loop ends in O(successes) steps instead
            # of attempting every remaining pending gang.
            # (cnt_q / task_req_g / gq / lq_tab / quota_eff_q are bound
            # later in the enclosing scope, before the while_loop traces.)
            qa_l = res.queue_allocated
            under_g = jax.vmap(
                lambda qi, tr: _ancestor_gate(
                    q.parent, qi, num_levels, qa_l, q.quota, tr))(
                        gq, task_req_g)                            # [G]
            lqs = jnp.maximum(lq_tab, 0)
            no_lq = lq_tab < 0
            over_fs_vc = no_lq | jnp.any(
                qa_l[lqs] > fair_share[lqs] + EPS, -1)             # [Q, Q]
            over_qt_vc = no_lq | jnp.any(
                qa_l[lqs] > quota_eff_q[lqs] + EPS, -1)
            diff = (jnp.arange(q.q)[:, None] != jnp.arange(q.q)[None, :])
            has_v = (cnt_q > 0)[:, None] & diff
            ev_fs_c = jnp.any(has_v & over_fs_vc, axis=0)          # [Q]
            ev_qt_c = jnp.any(has_v & over_qt_vc, axis=0)
            remaining = remaining & (
                ev_fs_c[gq] | (under_g & ev_qt_c[gq]))
        return res, remaining, q_att, fuel - 1

    remaining0 = g.valid & (g.backoff <= 0) & ~result.allocated

    # ---- vectorized viability prefilter ---------------------------------
    # The per-gang scan is the expensive part (a fairness re-sort per
    # step); gangs that cannot possibly preempt are dropped upfront.
    # Sound because queue allocation only GROWS within the action, so the
    # capacity/fair-share gates (re-checked live per attempt) only get
    # stricter — a gang failing them at action start can never pass later.
    base = (r.valid & ~r.releasing & (r.node >= 0) & r.preemptible
            & (r.gang >= 0))
    rq = jnp.where(base, r.queue, q.q)
    cnt_q = jax.ops.segment_sum(base.astype(jnp.int32), rq,
                                num_segments=q.q + 1)[:q.q]       # [Q]
    total_cnt = jnp.sum(cnt_q)
    gq = jnp.maximum(g.queue, 0)
    if mode == "reclaim":
        has_cand = (total_cnt - cnt_q[gq]) > 0
    elif mode == "consolidate":
        own = jax.ops.segment_sum(
            base.astype(jnp.int32), jnp.where(base, r.gang, G),
            num_segments=G + 1)[:G]
        has_cand = (total_cnt - own) > 0
    else:  # preempt: a lower-priority candidate in the gang's own queue
        minprio = jax.ops.segment_min(
            jnp.where(base, r.priority, BIG), rq,
            num_segments=q.q + 1)[:q.q]
        has_cand = minprio[gq] < g.priority
    task_req_g = jnp.sum(
        jnp.where(g.task_valid[:, :, None], g.task_req, 0.0), axis=1)
    gate_np = jax.vmap(
        lambda qi, tr: _ancestor_gate(
            q.parent, qi, num_levels,
            result.queue_allocated_nonpreemptible, q.quota, tr)
    )(gq, task_req_g)
    viable = has_cand & jnp.where(~g.preemptible, gate_np, True)
    if mode == "reclaim":
        # the fair-share gate must use a LOWER bound of future queue
        # allocation — reclaim evictions SHRINK allocation as the action
        # proceeds, so gating on the live value would wrongly exclude
        # reclaimers whose chain drops under fair share once victims
        # free up.  Lower bound: current allocation minus everything any
        # candidate could ever free along the chain.
        cand_leaf = jax.ops.segment_sum(
            jnp.where(base[:, None], r.req, 0.0), rq,
            num_segments=q.q + 1)[:q.q]                        # [Q, R]
        freeable = jnp.einsum("qa,qr->ar", chain.astype(cand_leaf.dtype),
                              cand_leaf)
        qa_lower = jnp.maximum(result.queue_allocated - freeable, 0.0)
        viable = viable & jax.vmap(
            lambda qi, tr: _ancestor_gate(
                q.parent, qi, num_levels, qa_lower,
                fair_share, tr))(gq, task_req_g)
    elif mode == "consolidate":
        viable = viable & g.preemptible
        # conservation gate: moving victims frees NOTHING in aggregate —
        # a consolidation preemptor must fit the cluster's total spare
        # capacity, or no rearrangement can ever place it.  On a
        # saturated cluster this empties the action outright.
        spare = jnp.sum(jnp.where(
            state.nodes.valid[:, None],
            result.free + state.nodes.releasing + result.releasing_extra,
            0.0), axis=0)
        viable = viable & jnp.all(task_req_g <= spare[None, :] + EPS,
                                  axis=-1)
    remaining0 = remaining0 & viable

    if (config.batch_size > 1 and mode in ("reclaim", "preempt")
            and (mode != "reclaim" or config.chunk_reclaim)):
        return _run_victim_action_chunked(
            state, fair_share, result, num_levels=num_levels, mode=mode,
            config=config, remaining0=remaining0, chain=chain,
            statics=statics, job_rank=job_rank0,
            lq_tab=lq_tab if mode == "reclaim" else None,
            cnt_q=cnt_q, task_req_g=task_req_g)
    res, _, _, _ = lax.while_loop(
        lambda c: jnp.any(c[1]) & (c[3] > 0), step,
        (result, remaining0, jnp.zeros((q.q,), jnp.int32),
         jnp.asarray(G, jnp.int32)))
    return res


@functools.partial(jax.jit,
                   static_argnames=("num_levels", "mode", "config"))
def run_victim_action_jit(state, fair_share, result, *, num_levels,
                          mode, config=VictimConfig()):
    return run_victim_action(state, fair_share, result,
                             num_levels=num_levels, mode=mode,
                             config=config)


# kai-wire compile watcher: per-(entry, signature) cache-miss
# attribution (runtime/compile_watch.py)
run_victim_action_jit = compile_watch.watch("run_victim_action",
                                            run_victim_action_jit)

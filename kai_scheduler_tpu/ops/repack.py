"""kai-repack — proactive constraint-based defragmentation solver.

Consolidation moves victims *reactively*, one blocked gang at a time;
the "Priority Matters" packing paper (PAPERS.md, arxiv 2511.08373)
treats the cluster as a constraint-based bin-packing instance solved
*proactively*.  This kernel is that solver for the rack-stranded shape
the kai-pulse fragmentation gauge detects (``ops/analytics.py``): free
capacity that could serve a rack-required gang in aggregate, but that
no single rack domain can host.

One jitted pass over the device-resident snapshot:

1. **target gang** — the oldest starving pending gang (host-owned
   ``pending_age`` counters, the same vector the analytics kernel
   consumes) whose required topology level IS the configured rack level,
   and whose quorum is cluster-feasible by raw free units but
   rack-stranded (the predicate mirrors the analytics ladder, probed
   with the gang's own unit request through the allocate
   ``resource_fit_mask`` predicate).
2. **min-migration rack selection** — movable running pods (valid,
   preemptible, not releasing — the victim filter) are ordered by the
   canonical victim key (priority asc, newest first, index tie-break).
   Because a node's unit count only grows as pods leave it, each pod's
   *marginal unit gain* at its position in its node's eviction order is
   a fixed quantity; per-rack prefix sums of those gains give the EXACT
   number of canonical-order migrations each rack needs to host the
   gang — no per-rack simulation loop.  The rack needing the fewest
   migrations (lowest domain id tie-break) wins, subject to the
   migration budget (``RepackConfig.max_migrations``, already clamped
   to ``VictimConfig.max_victim_pods`` by the Session-side caller).
3. **re-placement** — the selected victims are re-placed OUTSIDE the
   target rack by canonical ascending-node first fit (the uniform
   kernel's replica→node canonicalization: interchangeable work takes
   nodes in ascending id order), each move respecting the pod's
   node-filter class (taints/affinity — the consolidation-move rule).
4. **sparse claim verification** — the plan's (node, delta) claim
   segments are re-verified with the shared
   ``sparse_accept_first_bad``/``sparse_entry_tables`` protocol from
   ``ops/allocate.py`` (one implementation; the allocate chunk and the
   victim wavefront are the other two consumers) and the plan truncates
   at the first over-subscribed lane — by construction the sequential
   fill never over-subscribes, so a truncation here means the plan is
   unsound and it is discarded whole.

The emitted :class:`RepackPlan` is fixed-shape and bounded: at most
``max_migrations`` (pod → node) moves.  The host turns a feasible plan
into evictions-with-move-targets that commit through the SAME pipelined
rebind path as consolidation moves (``Session.pipelined_rebind``), so
repack introduces no second bind semantics.

Rack-domain single source of truth: the kernel derives the rack level
from the embedded :class:`~.analytics.AnalyticsConfig` —
:class:`RepackConfig` deliberately has NO ``rack_level`` field of its
own, so the trigger gauge and the solver can never disagree about what
a rack is (``tests/test_repack.py`` pins this by construction).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..runtime import compile_watch
from ..state.cluster_state import ClusterState
from . import analytics as pulse
from .allocate import EPS, sparse_accept_first_bad

#: i32 sentinel for "no migration count" (well above any plan width)
_BIG = jnp.int32(1 << 30)


@dataclasses.dataclass(frozen=True)
class RepackConfig:
    """Static knobs of the repack solver (hashable — rides the jit
    signature like ``AllocateConfig``)."""

    #: the rack-domain + unit-probe knobs, shared verbatim with the
    #: kai-pulse analytics kernel — the ONE place the rack level lives
    analytics: pulse.AnalyticsConfig = pulse.AnalyticsConfig()
    #: migration budget AND plan width: the caller passes
    #: ``min(SchedulerConfig.repack_max_migrations,
    #: VictimConfig.max_victim_pods)`` so the repack plan can never
    #: out-migrate what the victim machinery would allow
    max_migrations: int = 64


class RepackPlan(struct.PyTreeNode):
    """The fixed-shape bounded migration plan one repack solve emits."""

    move_pod: jax.Array       # i32 [P] running-pod index, -1 unused
    move_node: jax.Array      # i32 [P] destination node index, -1 unused
    num_moves: jax.Array      # i32 []  moves in a feasible plan (else 0)
    feasible: jax.Array       # bool [] plan fully frees the target rack
    target_gang: jax.Array    # i32 []  gang the plan unblocks, -1 none
    target_rack: jax.Array    # i32 []  dense rack-domain id, -1 none
    needed: jax.Array         # f32 []  unit pods the gang still needs
    rack_units_before: jax.Array  # f32 [] target-rack units pre-plan
    rack_units_after: jax.Array   # f32 [] target-rack units post-plan
    total_units: jax.Array    # f32 []  cluster-wide units for the gang


def plan_repack(state: ClusterState, pending_age: jax.Array,
                dest_free: jax.Array, *,
                config: RepackConfig) -> RepackPlan:
    """One whole-cluster min-migration repack solve (see module doc).

    ``pending_age`` (f32 [G]) is the host-owned pending-cycles counter
    per gang slot — the same vector ``cluster_analytics`` consumes, so
    the trigger's starvation signal and the solver's target choice read
    one clock.  ``dest_free`` (f32 [N, R]) is the pool migration
    DESTINATIONS draw on: the scheduler passes the cycle's
    POST-decision idle pool (``AllocationResult.free``), so a plan
    fired alongside the action pipeline never re-places a victim onto
    capacity this cycle's own allocate/consolidation decisions just
    consumed (a rebind onto stolen capacity would fail in the binder
    after evicting the pod).  The rack-strandedness analysis stays on
    the PRE-decision snapshot pool — the signal the trigger gauge read.
    """
    n, g, r = state.nodes, state.gangs, state.running
    N, L = n.n, n.topology.shape[1]
    M = r.m
    P = max(1, int(config.max_migrations))
    rl = min(max(config.analytics.rack_level, 0), L - 1)

    # --- target gang: oldest starving rack-required pending gang ---------
    cand = g.valid & (g.required_level == rl)
    age_key = jnp.where(cand, pending_age, -1.0)
    target = jnp.argmax(age_key).astype(jnp.int32)
    has_target = age_key[target] > 0.0
    unit = g.task_req[target, 0]                     # [R] uniform replica
    needed = jnp.maximum(g.min_needed[target], 0).astype(jnp.float32)

    # --- cluster-feasible-but-rack-stranded, probed with the gang's unit
    free0 = jnp.maximum(n.free, 0.0)
    units0 = pulse._unit_pods_per_node(free0, n.valid, unit)       # [N]
    total_units = jnp.sum(units0)
    seg = pulse.rack_domain_ids(state, rl)                         # [N]
    junk = N * L + N
    SEGS = junk + 1
    have = jax.ops.segment_sum(units0, seg, num_segments=SEGS)
    max_rack = jnp.max(have.at[junk].set(0.0))
    candidacy = (has_target & (needed > 0)
                 & (total_units >= needed) & (max_rack < needed))

    # --- movable pods + canonical victim order ---------------------------
    # the consolidation-mode victim filter (``victim_candidates``,
    # ops/victims.py): preemptible running pods of other gangs, with
    # minruntime still protecting — a gang whose runtime sits inside
    # its queue's resolved preempt-minruntime window (consolidation's
    # protection branch) exposes no movable pods.  gang_runtime is the
    # ``victim_statics`` formula (-1 = never started => NOT protected).
    G = g.g
    gang_runtime = jax.ops.segment_max(
        jnp.where(r.valid & (r.gang >= 0), r.runtime_s, -1.0),
        jnp.where(r.gang >= 0, r.gang, G), num_segments=G + 1)[:G]
    mrt_g = state.queues.preempt_min_runtime_eff[jnp.maximum(g.queue, 0)]
    prot_g = (gang_runtime >= 0) & (gang_runtime < mrt_g)        # [G]
    movable = (r.valid & ~r.releasing & r.preemptible & (r.node >= 0)
               & (r.gang >= 0) & (r.gang != target)
               & ~prot_g[jnp.clip(r.gang, 0, G - 1)])
    node_m = jnp.maximum(r.node, 0)
    # canonical victim key: priority asc, newest (smallest runtime)
    # first; lexsort is stable, so pod index breaks the remaining ties
    order = jnp.lexsort((r.runtime_s, r.priority.astype(jnp.float32)))
    crank = jnp.zeros((M,), jnp.int32).at[order].set(
        jnp.arange(M, dtype=jnp.int32))

    # --- fixed per-pod marginal unit gains -------------------------------
    # sort movable pods by (node, canonical rank): the per-node prefix
    # of freed requests gives each pod's unit gain AT ITS POSITION in
    # its node's eviction order — a fixed quantity, since unit counts
    # only grow as capacity frees (see module doc)
    nkey = jnp.where(movable, node_m, N)
    p1 = jnp.lexsort((crank, nkey))
    mov1 = movable[p1]
    req1 = jnp.where(mov1[:, None], r.req[p1], 0.0)            # [M, R]
    cs = jnp.cumsum(req1, axis=0)
    ns = nkey[p1]
    first = jnp.concatenate([jnp.ones((1,), bool), ns[1:] != ns[:-1]])
    sidx = lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(M), -1))
    freed_incl = cs - (cs - req1)[sidx]          # per-node inclusive
    nsafe = jnp.minimum(ns, N - 1)
    base_free = free0[nsafe]                                   # [M, R]
    nvalid = (ns < N) & n.valid[nsafe]
    u_incl = pulse._unit_pods_per_node(base_free + freed_incl,
                                       nvalid & mov1, unit)
    u_excl = pulse._unit_pods_per_node(base_free + freed_incl - req1,
                                       nvalid & mov1, unit)
    gain = jnp.zeros((M,), jnp.float32).at[p1].set(
        jnp.where(mov1, u_incl - u_excl, 0.0))

    # --- per-rack min-migration counts -----------------------------------
    dkey = jnp.where(movable, seg[node_m], junk)
    p2 = jnp.lexsort((crank, dkey))
    mov2 = movable[p2]
    gain2 = jnp.where(mov2, gain[p2], 0.0)
    cg = jnp.cumsum(gain2)
    ds = dkey[p2]
    first2 = jnp.concatenate([jnp.ones((1,), bool), ds[1:] != ds[:-1]])
    sidx2 = lax.associative_scan(
        jnp.maximum, jnp.where(first2, jnp.arange(M), -1))
    cum_d = cg - (cg - gain2)[sidx2]             # per-rack inclusive
    rank_in_rack = (jnp.arange(M) - sidx2).astype(jnp.int32)
    dsafe = jnp.minimum(ds, junk)
    reach = have[dsafe] + cum_d
    crosses = (mov2 & (ds < junk) & (reach >= needed)
               & (rank_in_rack < P))
    k_cand = jnp.where(crosses, rank_in_rack + 1, _BIG)
    k_d = jax.ops.segment_min(k_cand, dsafe, num_segments=SEGS)
    k_d = k_d.at[junk].set(_BIG)
    best = jnp.argmin(k_d).astype(jnp.int32)     # lowest id breaks ties
    k_star = k_d[best]
    feasible_rack = k_star < _BIG

    # --- victim selection (first k_star of the best rack, canonical) -----
    sel2 = mov2 & (ds == best) & (rank_in_rack < k_star)
    slot = jnp.where(sel2, rank_in_rack, P)      # [M] plan slot or junk
    slot_pod = jnp.full((P + 1,), -1, jnp.int32).at[slot].set(
        jnp.where(sel2, p2.astype(jnp.int32), -1))[:P]
    rack_after = have[best] + jnp.sum(jnp.where(sel2, gain2, 0.0))

    # --- destination assignment: canonical ascending-node first fit ------
    dest_ok = n.valid & (seg != best)
    free_dest0 = jnp.where(dest_ok[:, None],
                           jnp.maximum(dest_free, 0.0), 0.0)
    X = n.filter_masks.shape[0]

    def fill(free_d, p_slot):
        pod = slot_pod[p_slot]
        psafe = jnp.maximum(pod, 0)
        vreq = r.req[psafe]
        fc = jnp.clip(r.filter_class[psafe], 0, X - 1)
        fit = (dest_ok & n.filter_masks[fc]
               & jnp.all(free_d + EPS >= vreq[None, :], axis=1))
        found = jnp.any(fit) & (pod >= 0)
        node = jnp.where(found, jnp.argmax(fit).astype(jnp.int32), -1)
        free_d = jnp.where(
            found, free_d.at[jnp.maximum(node, 0)].add(-vreq), free_d)
        return free_d, node

    _, nodes_p = lax.scan(fill, free_dest0, jnp.arange(P))
    placed = nodes_p >= 0
    all_placed = jnp.all(placed == (slot_pod >= 0))

    # --- sparse (node, delta) claim re-verification ----------------------
    # the shared accept protocol (ops/allocate.py — third consumer after
    # the allocate chunk and the victim wavefront): every move is a
    # pipelined rebind claim against the destination idle pool; the plan
    # truncates at the first over-subscribing lane.  The sequential fill
    # above never over-subscribes, so a truncation marks the plan
    # unsound and it is discarded whole (feasible=False).
    req_b = jnp.where((slot_pod >= 0)[:, None],
                      r.req[jnp.maximum(slot_pod, 0)], 0.0)    # [P, R]
    first_bad, _, _ = sparse_accept_first_bad(
        nodes_p[:, None], placed[:, None], placed[:, None], req_b,
        free_dest0, free_dest0, N)
    verified = first_bad >= P

    feasible = (candidacy & feasible_rack & all_placed & verified
                & (k_star > 0))
    move_pod = jnp.where(feasible & placed, slot_pod, -1)
    move_node = jnp.where(feasible & placed, nodes_p, -1)
    # scalar outputs gate like target_gang/target_rack: a no-candidate
    # or no-freeable-rack firing must not publish index-0 junk values
    # to /debug/repack
    rack_ok = candidacy & feasible_rack
    return RepackPlan(
        move_pod=move_pod, move_node=move_node,
        num_moves=jnp.where(feasible,
                            jnp.sum((move_pod >= 0).astype(jnp.int32)),
                            0).astype(jnp.int32),
        feasible=feasible,
        target_gang=jnp.where(candidacy, target, -1).astype(jnp.int32),
        target_rack=jnp.where(rack_ok, best, -1).astype(jnp.int32),
        needed=jnp.where(candidacy, needed, 0.0),
        rack_units_before=jnp.where(rack_ok, have[best], 0.0),
        rack_units_after=jnp.where(rack_ok, rack_after, 0.0),
        total_units=jnp.where(candidacy, total_units, 0.0))


# kai-wire compile watcher: per-(entry, signature) cache-miss
# attribution (runtime/compile_watch.py)
plan_repack_jit = compile_watch.watch(
    "repack",
    functools.partial(jax.jit, static_argnames=("config",))(plan_repack))

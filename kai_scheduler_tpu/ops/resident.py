"""kai-resident — packed journal deltas + the scatter-apply kernel.

ROADMAP item 1's endgame: the cluster snapshot stays **resident on the
device across cycles** and patched cycles ship only what changed.  The
``IncrementalSnapshotter`` keeps maintaining its host-side numpy mirror
(the fallback / verify source of truth), but instead of re-uploading
whole changed leaves it emits a **packed journal delta** — fixed-shape
sparse ``(flat element index, value)`` segments, one pair of arrays per
leaf dtype class — which the jitted scatter-apply below writes into the
device-resident :class:`~..state.cluster_state.ClusterState` **in
place** (the fused cycle entry donates the state buffers via
``donate_argnums``, so the update never copies the snapshot).

Delta format
------------

The pytree *structure* of a delta is fixed — one ``(idx, val)`` pair
per dtype class present in the snapshot (``float32`` / ``int32`` /
``bool`` for every production snapshot) — so the only thing that varies
cycle-to-cycle is the padded segment length per class.  Lengths bucket
to powers of two with a floor (:data:`MIN_BUCKET`), so a steady-churn
cluster settles onto ONE abstract signature and the fused cycle entry
compiles once per snapshot shape bucket.

Element addressing is a **virtual concatenation** per group, where a
group is ``(section, dtype class)`` — ``nodes``/``queues``/``gangs``/
``running`` × ``float32``/``int32``/``bool``: leaves are numbered in
pytree-flatten order, and each leaf's elements occupy
``[offset, offset + leaf.size)`` of its group's flat index space
(:func:`leaf_layout` — derived purely from the tree paths and
shapes/dtypes, so the host packer and the traced kernel can never
disagree).  Padding slots carry ``idx == -1``; the scatter rebases
every entry per leaf and maps anything outside the leaf's range to
``leaf.size``, which jax's ``mode="drop"`` scatter discards — so one
fixed-shape segment table serves every leaf of its group with no
per-leaf shapes in the signature.  Grouping by section keeps the
scatter work proportional to ``Σ (leaves in section × section's
segment length)`` instead of ``total leaves × total length`` — a
running-section burst (e.g. ``runtime_s`` moving on every tick) is
scanned only by running-section leaves.

The host packer (:func:`pack_delta`) diffs the new mirror against the
previous one element-wise (NaN-stable on float leaves, identity-
short-circuited like the classic ship path) and returns both the delta
and a merged mirror that reuses the previous cycle's arrays for
unchanged leaves, so the next diff short-circuits on ``is``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MIN_BUCKET", "DeltaShapeError", "leaf_layout", "pack_delta",
           "apply_delta", "empty_delta", "delta_nbytes"]

#: minimum padded segment length per (section, dtype-class) group —
#: small enough that a quiet cycle's delta stays a few KB, large
#: enough that ordinary churn jitter in near-floor groups never
#: crosses a bucket boundary (each distinct bucket tuple is a fresh
#: XLA compile of the fused cycle entry)
MIN_BUCKET = 256


class DeltaShapeError(ValueError):
    """A leaf changed shape/dtype between mirrors — not patchable (the
    caller falls back to the full rebuild)."""


def _bucket(n: int) -> int:
    """Padded segment length: 0 stays 0 (a class with no changes ships
    zero bytes), anything else pads to a pow2 with a floor."""
    if n <= 0:
        return 0
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _group_key(path, leaf) -> str:
    """``section.dtypeclass`` — the segment-table a leaf belongs to."""
    head = path[0] if path else None
    section = getattr(head, "name", None) or str(head)
    return f"{section}.{np.dtype(leaf.dtype).name}"


def leaf_layout(paths_leaves) -> list[tuple[str, int]]:
    """``(group key, flat offset)`` per leaf, in flatten order.

    ``paths_leaves`` is ``tree_flatten_with_path(state)[0]``.  Offsets
    are running sums of ``leaf.size`` per group — a pure function of
    the snapshot's tree paths and shapes/dtypes, shared by the host
    packer and the traced scatter so their element addressing is
    identical by construction.
    """
    cursor: dict[str, int] = {}
    out = []
    for path, leaf in paths_leaves:
        key = _group_key(path, leaf)
        off = cursor.get(key, 0)
        out.append((key, off))
        cursor[key] = off + int(leaf.size)
    return out


def _groups(paths_leaves) -> list[tuple[str, str]]:
    """Sorted ``(group key, dtype name)`` pairs present in the state."""
    seen: dict[str, str] = {}
    for path, leaf in paths_leaves:
        seen.setdefault(_group_key(path, leaf),
                        np.dtype(leaf.dtype).name)
    return sorted(seen.items())


def empty_delta(state) -> dict:
    """A structurally-valid no-op delta for ``state`` (zero-size
    segments in every group) — the trace probe's canonical argument and
    the shape template fallback paths reuse."""
    pl = jax.tree_util.tree_flatten_with_path(state)[0]
    return {
        "idx": {k: np.zeros((0,), np.int32) for k, _d in _groups(pl)},
        "val": {k: np.zeros((0,), np.dtype(d)) for k, d in _groups(pl)},
    }


def delta_nbytes(delta: dict) -> int:
    """Total bytes the delta puts on the wire (idx + val segments)."""
    return int(sum(int(a.nbytes)
                   for part in delta.values() for a in part.values()))


def pack_delta(old_state, new_state,
               min_buckets: dict | None = None
               ) -> tuple[dict, object, dict]:
    """Diff two host mirrors into a packed journal delta.

    Returns ``(delta, merged_state, stats)``: the fixed-structure delta
    dict, a merged mirror whose unchanged leaves keep the OLD array
    objects (so next cycle's compares short-circuit on identity), and
    ``stats`` with ``leaves`` / ``elements`` / ``bytes`` (the packed
    delta size — the number the wire assertion pins upload bytes to)
    plus ``buckets`` (the padded length chosen per group).

    ``min_buckets`` is the **hysteresis floor** per group — the caller
    (the snapshotter) feeds back the previous cycle's chosen buckets so
    segment lengths only ever GROW: without it, a group whose changed
    count wobbles across a pow2 boundary would flip the fused entry's
    abstract signature cycle-to-cycle, and every flip is a full XLA
    recompile of the 17k-eqn resident program.  With it, the signature
    converges after one cycle and changes again only on genuine growth.

    Raises :class:`DeltaShapeError` when any leaf changed shape or
    dtype — the caller must fall back to the full rebuild (on the patch
    path this cannot happen: capacity overflows already force
    ``_Fallback`` before assembly).
    """
    paths_new, treedef = jax.tree_util.tree_flatten_with_path(new_state)
    paths_old = jax.tree_util.tree_flatten_with_path(old_state)[0]
    old_leaves = [leaf for _p, leaf in paths_old]
    layout = leaf_layout(paths_old)
    idx_acc: dict[str, list] = {}
    val_acc: dict[str, list] = {}
    merged = []
    changed_leaves = 0
    elements = 0
    for ((path, new), old, (cls, off)) in zip(paths_new, old_leaves,
                                              layout):
        if new is old:
            merged.append(old)
            continue
        if (getattr(new, "shape", None) != old.shape
                or new.dtype != old.dtype):
            raise DeltaShapeError(
                f"leaf {jax.tree_util.keystr(path)}: "
                f"{getattr(new, 'shape', None)}/{new.dtype} != "
                f"{old.shape}/{old.dtype}")
        diff = new != old
        if new.dtype.kind == "f":
            # NaN-stable: an unset-sentinel NaN must not read as a
            # changed element forever (same rule as the classic ship)
            diff &= ~(np.isnan(new) & np.isnan(old))
        flat = np.flatnonzero(diff)
        if not len(flat):
            merged.append(old)
            continue
        changed_leaves += 1
        elements += len(flat)
        idx_acc.setdefault(cls, []).append(
            flat.astype(np.int32) + np.int32(off))
        val_acc.setdefault(cls, []).append(new.ravel()[flat])
        merged.append(new)
    delta: dict = {"idx": {}, "val": {}}
    buckets: dict[str, int] = {}
    min_buckets = min_buckets or {}
    for key, dtype_name in _groups(paths_old):
        idx_parts = idx_acc.get(key, [])
        n = int(sum(len(p) for p in idx_parts))
        k = max(_bucket(n), int(min_buckets.get(key, 0)))
        buckets[key] = k
        idx = np.full((k,), -1, np.int32)
        val = np.zeros((k,), np.dtype(dtype_name))
        if n:
            idx[:n] = np.concatenate(idx_parts)
            val[:n] = np.concatenate(val_acc[key])
        delta["idx"][key] = idx
        delta["val"][key] = val
    stats = {"leaves": changed_leaves, "elements": elements,
             "bytes": delta_nbytes(delta), "buckets": buckets}
    return delta, jax.tree_util.tree_unflatten(treedef, merged), stats


def apply_delta(state, delta: dict):
    """Scatter a packed journal delta into the device-resident state.

    Pure and trace-safe — the fused cycle entry inlines it under
    ``donate_argnums`` so the writes land in the donated snapshot
    buffers.  Every leaf scans its class's whole segment table: entries
    outside the leaf's ``[offset, offset + size)`` range (including the
    ``idx == -1`` padding) rebase out of bounds and are dropped by the
    scatter, so the per-leaf work is a fixed-shape masked scatter with
    no dynamic shapes anywhere.
    """
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    layout = leaf_layout(paths_leaves)
    out = []
    for (_path, leaf), (cls, off) in zip(paths_leaves, layout):
        idx = delta["idx"][cls]
        val = delta["val"][cls]
        if idx.shape[0] == 0:
            out.append(leaf)
            continue
        size = int(leaf.size)
        local = idx - jnp.int32(off)
        ok = (local >= 0) & (local < size)
        # out-of-range (other leaves' entries + padding) → index `size`,
        # dropped by mode="drop"; negative padding never wraps
        local = jnp.where(ok, local, size)
        flat = jnp.reshape(leaf, (-1,)).at[local].set(
            val.astype(leaf.dtype), mode="drop")
        out.append(jnp.reshape(flat, leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, out)
